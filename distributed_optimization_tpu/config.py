"""Typed experiment configuration.

The reference keeps configuration as module-level constants assembled into a
plain dict (reference ``main.py:6-38``) threaded through every layer. Here the
same keys become a frozen dataclass with validation, plus new framework knobs
(backend selection, algorithm, topology, mesh shape, eval cadence) that the
reference does not have. ``to_dict``/``from_dict`` keep the reference's key
names so configs round-trip with the reference's experiment setup.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any

# Algorithms the framework implements. The reference only has 'centralized'
# (reference trainer.py:7-74) and 'dsgd' (trainer.py:76-197); the rest are the
# planned capability extensions named in BASELINE.json, plus push_sum (SGP —
# stochastic gradient push over directed graphs, Nedić-Olshevsky 2016 /
# Assran et al. 2019), the asymmetric-link continuation of the reference's
# MH-gossip family (reference trainer.py:118-126 builds the symmetric case).
ALGORITHMS = ("centralized", "dsgd", "gradient_tracking", "extra", "admm",
              "choco", "push_sum")

TOPOLOGIES = ("ring", "grid", "fully_connected", "erdos_renyi", "chain", "star",
              "directed_ring", "directed_erdos_renyi")

# Directed topologies carry column-stochastic (not doubly stochastic) mixing:
# plain gossip algorithms would drift toward the graph's Perron weighting
# instead of the true average, so only push_sum — which debiases by the
# tracked mass — may run on them.
DIRECTED_TOPOLOGIES = ("directed_ring", "directed_erdos_renyi")

PROBLEM_TYPES = ("logistic", "quadratic", "huber", "softmax")

BACKENDS = ("jax", "numpy", "cpp")

# Gossip-compression operators (CHOCO-SGD); implemented in ops/compression.py,
# which derives from this constant (config stays jax-free).
COMPRESSIONS = ("none", "top_k", "random_k", "qsgd")

# Algorithms the shared error-feedback compressed-gossip machinery covers
# (ops/compression.py::ErrorFeedbackGossip): CHOCO is the original
# formulation; dsgd and gradient_tracking route their gossip exchanges
# through the same per-worker estimate + compressor carry when
# ``compression != 'none'`` (ISSUE-6 tentpole — the gather path's
# production currency is bytes moved per round).
COMPRESSED_ALGORITHMS = ("choco", "dsgd", "gradient_tracking")

# Byzantine attack models (parallel/adversary.py derives from this constant):
# a static, seed-deterministic set of `n_byzantine` workers replaces its
# OUTGOING model each gossip round with an adversarial payload — sign_flip
# sends −scale·x, large_noise sends x + scale·N(0, I) redrawn per (seed, t),
# alie sends the colluders' shared "a little is enough" vector
# honest_mean − scale·honest_std (Baruch et al. 2019), hiding inside the
# honest spread to evade norm/outlier filters.
ATTACKS = ("none", "sign_flip", "large_noise", "alie")

# Rejoin policies after a crash-recovery outage (parallel/faults.py
# REJOIN_POLICIES mirrors this constant; config stays jax-free).
REJOINS = ("frozen", "neighbor_restart")

# Execution modes (docs/ASYNC.md): 'sync' is the bulk-synchronous scan
# over rounds (every path before ISSUE-9); 'async' scans over a
# precomputed EVENT schedule (parallel/events.py) — AD-PSGD-style
# bounded-staleness gossip where each event is one worker's local
# gradient step at its realized staleness plus a pairwise-average
# exchange, and stragglers are modeled as LATENCY, not drops.
EXECUTIONS = ("sync", "async")

# Latency models for the asynchronous event schedule's per-worker
# compute-time draws (parallel/events.py LATENCY_MODELS mirrors this
# constant; config stays numpy/jax-free). All are normalized to mean
# ``latency_mean``; ``latency_tail`` is the shape knob (lognormal
# log-std, pareto alpha) for the heavy-tailed straggler regimes.
LATENCY_MODELS = ("constant", "exponential", "lognormal", "pareto")

# Robust neighbor-aggregation rules (ops/robust_aggregation.py) replacing
# plain W @ x gossip: coordinate-wise trimmed mean / median over the closed
# neighborhood, and self-centered clipping (ClippedGossip, He-Karimireddy-
# Jaggi 2022). 'gossip' is the plain (vulnerable) MH average; a robust rule
# with robust_b == 0 degrades to exactly plain gossip.
AGGREGATIONS = ("gossip", "trimmed_mean", "median", "clipped_gossip")

# Algorithms that accept ``local_steps`` > 1 (τ gradient steps per gossip
# round — the federated local-update regime of Koloskova et al. '20's
# unified theory; docs/PERF.md §14). Only mix-based rules whose round
# structure survives extra purely-local descents qualify: D-SGD (plain
# local SGD between gossips) and gradient tracking (tracker-corrected
# local steps, K-GT style). EXTRA/ADMM/CHOCO/push-sum each pin a
# one-exchange-per-descent recursion that τ local steps would silently
# break.
LOCAL_STEP_ALGORITHMS = ("dsgd", "gradient_tracking")

# Topologies with a neighbor-table-native (matrix-free) constructor
# (parallel/topology.py): the graph is built directly as a padded
# [N, k_max] neighbor table without ever materializing the dense [N, N]
# adjacency or mixing matrix — the representation that lifts the worker
# axis to N in the tens of thousands (the dense path's [N, N] float64
# state is ~800 MB at N = 10k). fully_connected/star are deliberately
# excluded: their k_max is N−1, so the "table" would be the quadratic
# object the path exists to avoid (build_topology rejects them loudly).
NEIGHBOR_TOPOLOGIES = ("ring", "grid", "chain", "erdos_renyi")

# N at which ``topology_impl='auto'`` switches to the matrix-free neighbor
# path (and mixing_impl='auto' to the k_max-bounded gather operator on
# matrix-backed irregular graphs): the dense-mixing measurements stop at
# N = 4096 — the axis cap docs/perf/sparse_mixing.json records — and the
# federated-scale bench (docs/perf/federated.json) measures the gather
# route winning on CPU well below it while being the only route that
# completes at N >= 10k.
MATRIX_FREE_AUTO_N = 4096

# N at which ``topology_sampler='auto'`` switches the matrix-free
# Erdős–Rényi constructor to the O(N·k_max) sparse sampler. Below it the
# O(N²)-draw dense-stream sampler stays the realization (it is the
# bitwise reference the sparse sampler's law is tested against, and at
# small N the quadratic draw cost is immaterial); above it the quadratic
# stream replay is the recorded reason ER-at-100k was skipped in
# docs/perf/worker_mesh.json, so 'auto' routes to sparse.
SPARSE_SAMPLER_AUTO_N = 65_536

# Per-replica scalar axes ``jax_backend.run_batch`` can sweep alongside the
# seed axis (each replica r behaves exactly like a sequential run of
# ``config.replace(seed=seeds[r], **{field: values[r]})``). Only scalars
# that enter the compiled program as data — the LR schedule's eta0, the
# clipping radius, the edge-drop threshold — batch this way; structural
# fields (topology, n_workers, algorithm, ...) change the traced program
# itself and are rejected with a pointer to running separate sweeps.
SWEEPABLE_FIELDS = ("learning_rate_eta0", "clip_tau", "edge_drop_prob")

# Topologies whose edge structure is a random draw from a seed; only these
# consume ``resolved_topology_seed`` when building the graph, so only they
# contribute it to the structural hash below (a ring is the same compiled
# program whatever the seed says).
RANDOM_TOPOLOGIES = ("erdos_renyi", "directed_erdos_renyi")

# Default Huber transition point δ: fixed at the synthetic data's noise scale
# (make_regression noise=10.0, utils/data.py), i.e. the kink sits at ~1σ of the
# residuals at the optimum — the classical choice. δ is data-scale-dependent,
# so it is a config field (``huber_delta``); this constant is the SINGLE
# source of the default, consumed by ops/losses.py, ops/losses_np.py, and
# (via the C ABI's huber_delta argument) native/src/gossip_core.cpp.
DEFAULT_HUBER_DELTA = 10.0


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """All hyperparameters for one experiment.

    Field names match the reference's config-dict keys (reference
    ``main.py:25-38``) where a counterpart exists.
    """

    # --- reference-parity fields (main.py:6-21 defaults) ---
    n_workers: int = 25
    local_batch_size: int = 16
    n_iterations: int = 10_000
    learning_rate_eta0: float = 0.05
    l2_regularization_lambda: float = 1e-4
    strong_convexity_mu: float = 1e-4
    problem_type: str = "quadratic"
    n_samples: int = 12_500
    n_features: int = 80
    n_informative_features: int = 50
    classification_sep: float = 0.7
    suboptimality_threshold: float = 0.08

    # --- new framework knobs (no reference counterpart) ---
    backend: str = "jax"  # 'jax' (TPU/XLA north star) | 'numpy' (fidelity oracle)
    algorithm: str = "dsgd"
    topology: str = "ring"
    # LR schedule: 'auto' = the reference's eta0/sqrt(t+1) decay
    # (trainer.py:17-19) for SGD-family algorithms, constant eta0 for
    # gradient_tracking/extra/admm (their linear-convergence regimes).
    lr_schedule: str = "auto"  # 'auto' | 'sqrt_decay' | 'constant'
    admm_c: float = 0.5  # ADMM edge-penalty coefficient
    # DLM proximal-linearization weight; must dominate the loss gradient's
    # Lipschitz constant for stability (L ≈ 4 for the standardized quadratic
    # data here, ≈ 0.25 for logistic). 5.0 is safe for both study problems.
    admm_rho: float = 5.0
    # CHOCO-SGD (compressed gossip) knobs: the compression operator applied
    # to transmitted model differences (see COMPRESSIONS), its parameter
    # (coordinates kept for top_k/random_k; quantization BITS for qsgd), and
    # the consensus step size gamma. Stability needs roughly gamma <= the
    # operator's contraction factor delta: k/d for top_k/random_k,
    # 1/(1+min(d/s^2, sqrt(d)/s)) with s = 2^bits for qsgd (reported as
    # Compressor.delta by ops.compression.make_compressor).
    compression: str = "none"
    compression_k: int = 0
    choco_gamma: float = 0.3
    # Class count for the multinomial softmax family (problem_type='softmax'
    # only — the compute-bound objective tier, models/softmax.py). The
    # parameter is a [n_features, n_classes] matrix flattened to d·K for the
    # mixing/algorithm layers; K also scales the per-edge gossip payload.
    n_classes: int = 10
    # Huber transition point δ (problem_type='huber' only); see
    # DEFAULT_HUBER_DELTA for the default's rationale. Threaded through all
    # three tiers: jax closures (models/huber.py), numpy twins
    # (losses_np delta kwarg), and the native core (C ABI argument).
    huber_delta: float = DEFAULT_HUBER_DELTA
    # Data partition across workers: 'sorted' = the study's contiguous
    # sort-by-target split (maximal non-IID skew, reference utils.py
    # parity); 'shuffled' = seed-deterministic IID split — the bounded-
    # heterogeneity control (used by the Byzantine benches: screening
    # rules provably pay a bias ∝ attack fraction × heterogeneity, so the
    # breakdown point is only visible without the sorted skew).
    partition: str = "sorted"
    seed: int = 203  # reference seeds np.random.seed(203) at main.py:24
    # Seed for the TOPOLOGY's random structure (Erdős–Rényi edge draws)
    # when it should NOT follow ``seed``: −1 (default) derives the graph
    # from ``seed`` as always; >= 0 pins the graph independently, so a
    # seed sweep (``replicas`` / run_batch) varies run randomness —
    # sampling, faults, adversary draws — over ONE fixed graph instance.
    # The replica-batched path pins this automatically (the graph is
    # structural: a per-replica graph cannot batch), making each batched
    # replica exactly equivalent to a sequential run of its per-replica
    # config. Deterministic topologies ignore it.
    topology_seed: int = -1
    # Seed for the DATASET's random draws (sklearn generators + the
    # 'shuffled' partition) when it should NOT follow ``seed``: −1
    # (default) derives the data from ``seed`` as the reference does; >= 0
    # pins the problem instance independently, so seed variants name runs
    # over ONE shared dataset. This is the serving layer's coalescing knob
    # (docs/SERVING.md): requests that differ only in ``seed`` can share a
    # run_batch cohort — and therefore one compiled program execution —
    # only when they agree on the dataset, which a pinned data_seed makes
    # explicit (the same contract the CLI's --seeds path has always used
    # implicitly by generating the dataset from the base seed once).
    data_seed: int = -1
    eval_every: int = 1  # full-data objective eval cadence (reference: every iter)
    erdos_renyi_p: float = 0.4  # edge probability for the ER topology
    # Failure injection (SURVEY.md §5.3): per-iteration iid probability that
    # each edge of the topology drops; gossip runs over the surviving graph
    # with MH weights recomputed on realized degrees. 0 = no faults.
    edge_drop_prob: float = 0.0
    # Straggler/node-failure injection: per-iteration iid probability that a
    # node sits the round out — it exchanges nothing and takes no local
    # step (its state is frozen for that iteration). 0 = none.
    straggler_prob: float = 0.0
    # --- temporally-correlated fault processes (docs/CHURN.md) ---
    # Bursty link failures: per-edge two-state Markov chain (Gilbert-
    # Elliott) at the SAME marginal drop rate edge_drop_prob but with mean
    # burst length burst_len/(1 - edge_drop_prob) — burst_len times the iid
    # chain's. 0 = the memoryless iid sampler (default); 1 reduces BITWISE
    # to it (different code path, identical draws/thresholds); > 1
    # correlates failures in time. Requires edge_drop_prob > 0.
    burst_len: float = 0.0
    # Crash-recovery node churn replacing iid stragglers: geometric up/down
    # holding times with mean up-time `mttf` rounds and mean outage `mttr`
    # rounds (stationary downtime mttr/(mttf+mttr)); a down node exchanges
    # nothing and takes no local step for the WHOLE outage. Both 0 = off;
    # both must be >= 1 and set together, and exclude straggler_prob
    # (mttf=1/q, mttr=1/(1-q) reduces bitwise to straggler_prob=q).
    mttf: float = 0.0
    mttr: float = 0.0
    # What a node resumes with after an outage: 'frozen' = its stale
    # pre-crash state (the staleness stress test); 'neighbor_restart' =
    # warm restart of its model row from the realized-neighborhood average
    # on the rejoin round (trades exact average preservation for a
    # consensus reset after long outages). Only meaningful with churn.
    rejoin: str = "frozen"
    # Byzantine adversary injection (docs/BYZANTINE.md): `n_byzantine`
    # workers (a static seed-deterministic set) replace their OUTGOING
    # models with an `attack` payload each gossip round. attack_scale is the
    # payload magnitude: the sign-flip multiplier, the large-noise sigma, or
    # ALIE's z (how many honest standard deviations the colluders shift).
    # Composes with edge_drop_prob/straggler_prob (attacks over failing
    # links) and is decentralized-only, like the fault machinery.
    attack: str = "none"
    n_byzantine: int = 0
    attack_scale: float = 1.0
    # --- federated execution regime (docs/PERF.md §14) ---
    # τ local SGD steps per gossip round (Koloskova et al. '20 local
    # updates): each scan iteration is one ROUND — the algorithm's normal
    # gossip-fused first descent plus τ−1 purely-local descents (tracker-
    # corrected for gradient_tracking), all fused inside the same compiled
    # scan body. Per-round comms is unchanged, so τ is the dominant
    # communication-reduction lever: τ gradient steps per exchanged model
    # ⇒ up to τ× fewer floats per unit of progress (measured in
    # docs/perf/federated.json). 1 = the existing one-step round, bitwise.
    local_steps: int = 1
    # Per-round partial participation (client sampling): each round, every
    # worker independently participates with this probability, presampled
    # into the run's fault timeline ([horizon, N] masks — the same
    # machinery as stragglers/churn, distinct key stream). A sampled-out
    # worker exchanges nothing and takes no local step that round (its
    # state is frozen); gossip reweights on the realized subgraph via the
    # realized-adjacency composition, so participation composes with
    # churn, bursty links and the Byzantine layer. 1.0 = everyone, every
    # round — bitwise the no-sampling program (no fault machinery traced).
    participation_rate: float = 1.0
    # --- event-driven asynchronous execution (docs/ASYNC.md) ---
    # 'sync' | 'async'. 'async' replaces the bulk-synchronous round scan
    # with a scan over a precomputed event schedule
    # (parallel/events.py::build_event_timeline): n_iterations then counts
    # per-worker gradient steps (N events per "round", the same total
    # gradient budget as the synchronous run), eval_every keeps its
    # round-based meaning, and wall-clock comparisons use the schedule's
    # simulated VIRTUAL clock. All four fields are structural for the
    # serving cache: the event schedule is baked into the traced program.
    execution: str = "sync"
    # Latency distribution of the per-worker compute-time draws (see
    # LATENCY_MODELS); only meaningful with execution='async'.
    latency_model: str = "constant"
    # Mean compute time per gradient step in virtual seconds (every model
    # is matched-mean, so the tail knob never changes expected compute).
    latency_mean: float = 1.0
    # Heavy-tail straggler knob: lognormal log-std (> 0) or pareto shape
    # alpha (> 1); must stay 0 for constant/exponential (no tail shape).
    latency_tail: float = 0.0
    # 'auto' | 'dense' | 'neighbor'. Topology representation: 'dense'
    # builds the [N, N] adjacency + mixing matrix (every pre-federated
    # path); 'neighbor' is the matrix-free form — a padded [N, k_max]
    # neighbor table with gather-form MH mixing, matrix-free spectral-gap
    # diagnostics, and O(N·k_max·d) per-round work/memory, the only
    # representation that fits N in the tens of thousands. 'auto' picks
    # 'neighbor' on the jax backend above MATRIX_FREE_AUTO_N workers for
    # NEIGHBOR_TOPOLOGIES when no dense-only feature (edge-fault
    # processes, Byzantine screening, matching schedules, matrix-backed
    # mixing impls) is requested; 'dense' otherwise.
    topology_impl: str = "auto"
    # Robust neighbor aggregation (defense): which rule honest workers use
    # to combine received neighbor models, and its per-neighborhood attack
    # budget b (values trimmed from each tail / messages assumed Byzantine).
    # The backend validates 2·b <= min node degree (otherwise trimming can
    # exhaust a neighborhood); robust_b == 0 degrades every rule to exactly
    # plain MH gossip. clip_tau: fixed clipping radius for clipped_gossip
    # (0 = adaptive: each node clips its b largest-norm neighbor
    # differences down to the (deg−b)-th smallest norm).
    aggregation: str = "gossip"
    robust_b: int = 0
    clip_tau: float = 0.0
    # 'auto' | 'dense' | 'gather' | 'fused'. Execution form of the robust
    # rule on the jax backend (the numpy oracle has one per-node form):
    # 'dense' sorts the [N, N, d] closed-neighborhood tensor over the full
    # node axis — O(N²·d·log N) regardless of topology; 'gather'
    # precomputes a static [N, k_max] padded neighbor table, gathers
    # neighbor models and per-incident-edge liveness bits, and screens
    # over the k_max axis — O(N·k_max·d·log k_max), ~N/k_max-fold less
    # work on degree-bounded graphs (measured 69-75x e2e for trimmed
    # mean/median on an N=256 ring, docs/perf/robust_scale.json); 'fused'
    # runs the gather math as ONE pallas kernel (gather + screen + mix,
    # plus the SGD update for dsgd) so the [N, k_max, d] neighbor stack
    # never materializes in HBM (ops/pallas_kernels.py; count rules need
    # the closed neighborhood to fit the in-kernel sort network,
    # k_max+1 <= FUSED_MAX_SORT_WIDTH). 'auto' picks from the measured
    # crossover and promotes to 'fused' when the backend reports it
    # eligible — static topology, fused-supported rule, no telemetry
    # activity probe (see resolved_robust_impl).
    robust_impl: str = "auto"
    # Gossip schedule: 'synchronous' averages with all (surviving) neighbors
    # per iteration; 'one_peer' is Boyd-style randomized gossip — each node
    # exchanges with at most ONE mutually-proposing random neighbor, W_t =
    # 0.5(I + P_t), composable with edge/straggler injection; 'round_robin'
    # cycles deterministic matchings that cover the edge set every P
    # iterations (ring/chain/even-sided grid).
    gossip_schedule: str = "synchronous"
    # 'auto' | 'dense' | 'stencil' | 'shard_map' | 'pallas' | 'sparse'.
    # 'auto' picks the measured winner: stencil where the graph embeds as
    # mesh shifts, else dense (round 5: the 7-dim pallas sweep found no
    # reproducible win — docs/perf/pallas_regimes.json — and the CSR sparse
    # form measured slower than dense at every cell —
    # docs/perf/sparse_mixing.json; both remain explicit opt-ins).
    mixing_impl: str = "auto"
    # 'auto' | 'gather' | 'dense'. Mini-batch realization on the jax backend:
    # 'gather' materializes [N, b, d] batches (top_k + row gathers), 'dense'
    # computes the weighted gradient over the full padded shard with 1/b
    # weights on the sampled rows — same sampled subsets, no top_k/gather.
    # 'auto' picks from measurement (see resolved_sampling_impl).
    sampling_impl: str = "auto"
    # XLA scan unrolling for the jax backend's training loop. Swept on the
    # real chip (examples/bench_breakdown.py → docs/perf/breakdown.json):
    # 1/2/4/8 measure within noise of each other, 16+ regress and cost more
    # compile time. 0 = auto: 8 on accelerators (within noise of best,
    # +0.9s compile vs unroll=1), 1 on CPU (where compile cost dwarfs the
    # tiny kernels' dispatch savings).
    scan_unroll: int = 0
    dtype: str = "float32"
    matmul_precision: str = "highest"  # jax.lax Precision for parity-sensitive math
    record_consensus: bool = True
    # Flight-recorder trace buffers (telemetry.py, docs/OBSERVABILITY.md):
    # record per-eval-row run-health series — per-worker grad/param norms,
    # non-finite sentinel counts, fault-layer liveness, robust-aggregation
    # activity — inside the compiled scan (stacked outputs only; the scan
    # carry and the optimization dataflow are untouched, so trajectories
    # are bitwise-identical with telemetry on or off). Off by default: the
    # recording costs one extra gradient per eval point (measured overhead
    # bound in docs/perf/telemetry.json).
    telemetry: bool = False
    # Replica-batched execution (jax backend): run this many independent
    # seed replicates — seeds seed, seed+1, ..., seed+replicas−1 — through
    # ONE vmapped compiled program ([R, N, d] state, [R, n_evals] metrics)
    # instead of sequential compiled runs, and report mean ± std over the
    # replica axis. 1 = the single-trajectory path (unchanged). Each
    # replica is trajectory-equivalent to a sequential run with its seed
    # (tests pin ≤ 1e-12 in f64 through the fault and Byzantine layers).
    replicas: int = 1
    # Tensor parallelism for the compute-bound softmax tier: shard the
    # [d, K] classifier over a 'model' mesh axis of this many devices
    # (parallel/tensor_parallel.py — D-SGD + ring + softmax + full local
    # batches only; every other combination is rejected below with the
    # reason). 1 = pure data parallelism (unchanged).
    tp_degree: int = 1
    # Sharded worker mesh (docs/PERF.md §16): split the WORKER axis into
    # this many contiguous row blocks, one per device — state rows
    # [N/P, d], neighbor tables [N/P, k_max] and fault-timeline columns
    # all live per-shard, and each gossip round exchanges only the
    # boundary rows a shard's neighbor table references (a ppermute halo
    # exchange; parallel/collectives.py::make_halo_mixing_op). This is
    # the representation that lifts matrix-free N past one device's RAM:
    # per-device memory is O(N/P·(d + k_max)), and the sharded-vs-
    # unsharded trajectories are BITWISE identical at matched N (the
    # halo gather computes the exact per-row op sequence of the
    # single-device gather path). 0 = unsharded (every pre-mesh
    # program, unchanged); >= 2 = the device count, which must divide
    # n_workers. jax backend + neighbor-table topologies only; on CPU
    # hosts simulate devices via
    # XLA_FLAGS=--xla_force_host_platform_device_count=P.
    worker_mesh: int = 0
    # 'auto' | 'dense' | 'sparse'. Which Erdős–Rényi constructor realizes
    # the matrix-free graph: 'dense' replays the [N, N] uniform stream
    # bit-for-bit (O(N²) draws — the historical reference, and the oracle
    # the sparse sampler is tested against below the cutoff); 'sparse'
    # draws O(N·k_max) (forward-tail binomial degrees + tail-sampled
    # partners — the million-node path). The two realize the SAME
    # G(n, p) law but DIFFERENT graphs per (seed, p), so the resolved
    # value is part of the structural identity (structural_dict). 'auto'
    # picks 'sparse' above SPARSE_SAMPLER_AUTO_N on the matrix-free ER
    # path, 'dense' otherwise. Only meaningful for topology='erdos_renyi'
    # (rejected elsewhere rather than silently ignored).
    topology_sampler: str = "auto"
    # 'off' | 'double_buffer'. Halo-exchange overlap on the worker mesh
    # (docs/PERF.md §17): 'off' runs PR 11's exchange unchanged
    # (bitwise-pinned); 'double_buffer' issues the boundary-row ppermutes
    # FIRST and computes the self + in-block partial sums while they are
    # in flight (the standard stencil latency-hiding idiom — XLA's
    # scheduler overlaps collectives with independent compute on
    # accelerators; CPU single-stream may tie). The halo contributions
    # are added after the in-block partial, a different summation order,
    # so double_buffer is NOT bitwise vs off — it is a distinct
    # structural program. Plain-gossip mesh path only (no compression,
    # faults, or robust screening).
    halo_overlap: str = "off"

    def __post_init__(self) -> None:
        if self.problem_type not in PROBLEM_TYPES:
            raise ValueError(f"Unknown problem type: {self.problem_type}")
        if self.algorithm not in ALGORITHMS:
            raise ValueError(f"Unknown algorithm: {self.algorithm}")
        if self.topology not in TOPOLOGIES:
            raise ValueError(f"Unknown topology: {self.topology}")
        if self.backend not in BACKENDS:
            raise ValueError(f"Unknown backend: {self.backend}")
        if self.mixing_impl not in ("auto", "dense", "stencil", "shard_map",
                                    "pallas", "sparse", "gather"):
            raise ValueError(f"Unknown mixing impl: {self.mixing_impl}")
        if self.sampling_impl not in ("auto", "gather", "dense"):
            raise ValueError(f"Unknown sampling impl: {self.sampling_impl}")
        if self.lr_schedule not in ("auto", "sqrt_decay", "constant"):
            raise ValueError(f"Unknown lr schedule: {self.lr_schedule}")
        if self.compression not in COMPRESSIONS:
            raise ValueError(f"Unknown compression: {self.compression}")
        if self.compression != "none":
            if self.algorithm not in COMPRESSED_ALGORITHMS:
                raise ValueError(
                    f"compression={self.compression!r} only takes effect "
                    f"with the error-feedback gossip algorithms "
                    f"{COMPRESSED_ALGORITHMS}; other algorithms exchange "
                    "full vectors and would silently ignore it"
                )
            if self.compression_k <= 0:
                raise ValueError(
                    "compression_k (coordinates kept, or qsgd bits) must be "
                    f"positive when compression={self.compression!r}"
                )
            if (
                self.edge_drop_prob > 0.0
                or self.straggler_prob > 0.0
                or self.mttf > 0.0
                or self.gossip_schedule != "synchronous"
            ):
                raise ValueError(
                    "compressed gossip does not compose with time-varying "
                    "graphs: a dropped exchange leaves the neighbor's copy "
                    "of the shared error-feedback estimate stale, which "
                    "the single shared X̂ leaf cannot represent (per-edge "
                    "[N, N, d] staleness state would be needed) — run "
                    "faults uncompressed, or compression on a static graph"
                )
            if self.attack != "none" or self.aggregation != "gossip":
                raise ValueError(
                    "compressed gossip does not compose with Byzantine "
                    "injection / robust aggregation: screening operates "
                    "on transmitted models, but error-feedback exchanges "
                    "compressed DIFFERENCES against a shared estimate — "
                    "a screened-out update still mutates every neighbor's "
                    "X̂ copy, silently breaking the defense's contract"
                )
        if self.huber_delta <= 0.0:
            raise ValueError(f"huber_delta must be positive, got {self.huber_delta}")
        if self.n_classes < 2:
            raise ValueError(
                f"n_classes must be >= 2, got {self.n_classes}"
            )
        if (
            self.algorithm == "choco" or self.compression != "none"
        ) and not 0.0 < self.choco_gamma <= 1.0:
            raise ValueError(
                f"choco_gamma must be in (0, 1], got {self.choco_gamma}"
            )
        if self.partition not in ("sorted", "shuffled"):
            raise ValueError(f"Unknown partition: {self.partition}")
        if self.attack not in ATTACKS:
            raise ValueError(f"Unknown attack: {self.attack}")
        if self.aggregation not in AGGREGATIONS:
            raise ValueError(f"Unknown aggregation: {self.aggregation}")
        if self.n_byzantine < 0:
            raise ValueError(
                f"n_byzantine must be >= 0, got {self.n_byzantine}"
            )
        if (self.attack == "none") != (self.n_byzantine == 0):
            raise ValueError(
                f"attack={self.attack!r} and n_byzantine="
                f"{self.n_byzantine} must be set together: an attack needs "
                "attackers, and Byzantine workers need a payload to send"
            )
        if self.attack != "none":
            if self.n_byzantine >= self.n_workers:
                raise ValueError(
                    f"n_byzantine ({self.n_byzantine}) must leave at least "
                    f"one honest worker out of {self.n_workers}"
                )
            if self.attack_scale <= 0.0:
                raise ValueError(
                    f"attack_scale must be positive, got {self.attack_scale}"
                )
        elif self.attack_scale != 1.0:
            raise ValueError(
                f"attack_scale={self.attack_scale} only takes effect with "
                "an attack; attack='none' would silently ignore it"
            )
        if self.robust_b < 0:
            raise ValueError(f"robust_b must be >= 0, got {self.robust_b}")
        if self.robust_b > 0 and self.aggregation == "gossip":
            raise ValueError(
                f"robust_b={self.robust_b} only takes effect with a robust "
                "aggregation rule; plain 'gossip' has no screening step and "
                "would silently ignore it"
            )
        if self.robust_impl not in ("auto", "dense", "gather", "fused"):
            raise ValueError(f"Unknown robust impl: {self.robust_impl}")
        if self.robust_impl != "auto" and not (
            self.aggregation != "gossip" and self.robust_b > 0
        ):
            raise ValueError(
                f"robust_impl={self.robust_impl!r} selects the execution "
                "form of a robust aggregation rule; without one (a non-"
                "gossip aggregation and robust_b > 0) it would be silently "
                "ignored"
            )
        if self.clip_tau < 0.0:
            raise ValueError(f"clip_tau must be >= 0, got {self.clip_tau}")
        if self.clip_tau > 0.0 and self.aggregation != "clipped_gossip":
            raise ValueError(
                f"clip_tau only applies to aggregation='clipped_gossip'; "
                f"{self.aggregation!r} would silently ignore it"
            )
        if self.aggregation != "gossip" and self.gossip_schedule != "synchronous":
            raise ValueError(
                f"aggregation={self.aggregation!r} screens MULTIPLE received "
                "neighbor messages per round; matching schedules "
                f"({self.gossip_schedule!r}) deliver at most one, so no "
                "trimming/clipping budget is realizable — use 'synchronous'"
            )
        if not 0.0 <= self.edge_drop_prob < 1.0:
            raise ValueError(
                f"edge_drop_prob must be in [0, 1), got {self.edge_drop_prob}"
            )
        if not 0.0 <= self.straggler_prob < 1.0:
            raise ValueError(
                f"straggler_prob must be in [0, 1), got {self.straggler_prob}"
            )
        if self.burst_len != 0.0 and self.burst_len < 1.0:
            raise ValueError(
                f"burst_len must be 0 (iid edge drops) or >= 1 (mean burst "
                f"multiplier), got {self.burst_len}"
            )
        if self.burst_len != 0.0 and self.edge_drop_prob == 0.0:
            raise ValueError(
                f"burst_len={self.burst_len} shapes the edge-failure "
                "process and needs edge_drop_prob > 0; without a drop rate "
                "it would be silently ignored"
            )
        if (self.mttf > 0.0) != (self.mttr > 0.0):
            raise ValueError(
                f"mttf ({self.mttf}) and mttr ({self.mttr}) must be set "
                "together: crash-recovery churn needs both a mean up-time "
                "and a mean outage length"
            )
        if self.mttf < 0.0 or self.mttr < 0.0:
            raise ValueError(
                f"mttf/mttr must be >= 0, got ({self.mttf}, {self.mttr})"
            )
        if self.mttf > 0.0:
            if self.mttf < 1.0 or self.mttr < 1.0:
                raise ValueError(
                    "mttf/mttr are mean holding times in rounds and must "
                    f"be >= 1, got ({self.mttf}, {self.mttr})"
                )
            if self.straggler_prob > 0.0:
                raise ValueError(
                    "crash-recovery churn (mttf/mttr) replaces iid "
                    "stragglers; set straggler_prob=0 (the iid model is "
                    "churn at mttf=1/q, mttr=1/(1-q))"
                )
            if self.gossip_schedule != "synchronous":
                raise ValueError(
                    "crash-recovery churn requires "
                    "gossip_schedule='synchronous': rejoin policies act on "
                    "the realized neighborhood, which matching schedules "
                    f"({self.gossip_schedule!r}, at most one partner per "
                    "round) cannot supply"
                )
        if self.rejoin not in REJOINS:
            raise ValueError(f"Unknown rejoin policy: {self.rejoin}")
        if self.rejoin == "neighbor_restart" and (
            self.attack != "none"
            or (self.aggregation != "gossip" and self.robust_b > 0)
        ):
            raise ValueError(
                "rejoin='neighbor_restart' does not compose with Byzantine "
                "injection / robust aggregation: the warm restart averages "
                "neighbors' raw model rows, bypassing both the attack "
                "payloads and the screening rule — it would model an "
                "unrealistically safe rejoin at exactly the moment an "
                "adversary controls the unscreened average. Use "
                "rejoin='frozen' under attack"
            )
        if self.rejoin != "frozen" and self.mttf == 0.0:
            raise ValueError(
                f"rejoin={self.rejoin!r} only takes effect with "
                "crash-recovery churn (mttf/mttr); without outages there "
                "are no rejoin rounds and it would be silently ignored"
            )
        if self.local_steps < 1:
            raise ValueError(
                f"local_steps must be >= 1, got {self.local_steps}"
            )
        if self.local_steps > 1:
            if self.algorithm not in LOCAL_STEP_ALGORITHMS:
                raise ValueError(
                    f"local_steps={self.local_steps} is unsupported for "
                    f"{self.algorithm!r}: τ local descents between gossip "
                    "exchanges only compose with the mix-based rules "
                    f"{LOCAL_STEP_ALGORITHMS} (EXTRA/ADMM/CHOCO/push-sum "
                    "pin a one-exchange-per-descent recursion that extra "
                    "local steps would silently break)"
                )
            if self.compression != "none":
                raise ValueError(
                    "local_steps > 1 does not compose with compressed "
                    "gossip: the error-feedback estimate exchange assumes "
                    "one descent per transmitted difference — τ local "
                    "steps between exchanges would leave the shared X̂ "
                    "tracking a state it never saw"
                )
            if self.backend == "cpp":
                raise ValueError(
                    "local_steps > 1 is unsupported on the cpp backend "
                    "(its native kernel hard-codes the one-step round); "
                    "use backend='jax' or 'numpy'"
                )
            if self.tp_degree > 1:
                raise ValueError(
                    "local_steps > 1 does not compose with tp_degree > 1: "
                    "the tensor-parallel path runs its own sharded "
                    "one-step ring stencil"
                )
        if not 0.0 < self.participation_rate <= 1.0:
            raise ValueError(
                f"participation_rate must be in (0, 1], got "
                f"{self.participation_rate}"
            )
        if self.participation_rate < 1.0:
            if self.algorithm == "centralized":
                raise ValueError(
                    "participation_rate models per-round client sampling "
                    "of peer exchanges; the centralized pattern has no "
                    "peer edges — it applies to decentralized algorithms "
                    "only"
                )
            if self.gossip_schedule != "synchronous":
                raise ValueError(
                    "participation_rate < 1 requires "
                    "gossip_schedule='synchronous': the sampled subgraph "
                    "reweights the whole realized neighborhood, which "
                    f"matching schedules ({self.gossip_schedule!r}) "
                    "cannot supply"
                )
            if self.compression != "none":
                raise ValueError(
                    "participation_rate < 1 does not compose with "
                    "compressed gossip (same reason as edge faults: a "
                    "sampled-out round leaves neighbors' error-feedback "
                    "estimates stale) — sample participation uncompressed"
                )
            if self.backend == "cpp":
                raise ValueError(
                    "participation_rate < 1 is unsupported on the cpp "
                    "backend; use backend='jax' (or the numpy oracle)"
                )
            if self.tp_degree > 1:
                raise ValueError(
                    "participation_rate < 1 does not compose with "
                    "tp_degree > 1: the TP ring stencil is a fixed "
                    "boundary exchange, not a per-round realized graph"
                )
        if self.topology_impl not in ("auto", "dense", "neighbor"):
            raise ValueError(f"Unknown topology impl: {self.topology_impl}")
        if self.topology_impl == "neighbor":
            if self.topology == "fully_connected":
                raise ValueError(
                    "topology_impl='neighbor' with 'fully_connected' would "
                    "allocate an [N, N-1] neighbor table — the quadratic "
                    "object the matrix-free path exists to avoid; use "
                    "topology_impl='dense' (k_max = N−1 leaves nothing "
                    "for a degree-bounded route to win)"
                )
            if self.topology not in NEIGHBOR_TOPOLOGIES:
                raise ValueError(
                    f"topology_impl='neighbor' supports "
                    f"{NEIGHBOR_TOPOLOGIES}; {self.topology!r} has no "
                    "matrix-free constructor"
                )
            if self.backend != "jax":
                raise ValueError(
                    "topology_impl='neighbor' is a jax-backend capability "
                    "(gather-form mixing); the numpy/cpp oracles run the "
                    "dense matrix form — use topology_impl='dense'"
                )
            if self.mixing_impl not in ("auto", "gather", "stencil"):
                raise ValueError(
                    f"topology_impl='neighbor' never materializes the "
                    f"[N, N] matrices that mixing_impl="
                    f"{self.mixing_impl!r} consumes — use 'auto', "
                    "'gather', or 'stencil'. To run the gather path over "
                    "real collectives, shard the worker axis instead: "
                    "worker_mesh >= 2 lowers gather mixing to a ppermute "
                    "halo exchange (the sharded-gather path; "
                    "docs/PERF.md §16) — mixing_impl='shard_map' is the "
                    "dense-representation stencil form only"
                )
            if (
                self.attack != "none"
                or (self.aggregation != "gossip" and self.robust_b > 0)
            ) and self.robust_impl not in ("auto", "gather"):
                # ISSUE-9 satellite: the matrix-free path ACCEPTS Byzantine
                # screening in its gather form (the neighbor table IS the
                # gather path's input); only the [N, N]-materializing
                # execution forms stay dense-only.
                raise ValueError(
                    f"topology_impl='neighbor' runs robust aggregation in "
                    f"gather form over the [N, k_max] table; robust_impl="
                    f"{self.robust_impl!r} materializes dense/VMEM objects "
                    "the matrix-free path never builds — use 'auto' or "
                    "'gather'"
                )
            if self.gossip_schedule != "synchronous":
                raise ValueError(
                    "topology_impl='neighbor' requires "
                    "gossip_schedule='synchronous' (matching schedules "
                    "sample partners from the dense adjacency)"
                )
            if self.tp_degree > 1:
                raise ValueError(
                    "topology_impl='neighbor' does not compose with "
                    "tp_degree > 1 (the TP path pins its own ring "
                    "stencil over a device mesh)"
                )
        if self.worker_mesh < 0 or self.worker_mesh == 1:
            raise ValueError(
                f"worker_mesh must be 0 (unsharded) or >= 2 devices, got "
                f"{self.worker_mesh} (1 would name the unsharded program "
                "— leave it 0)"
            )
        if self.worker_mesh >= 2:
            if self.backend != "jax":
                raise ValueError(
                    "worker_mesh shards the worker axis over a jax device "
                    f"mesh; backend={self.backend!r} has no mesh — use "
                    "backend='jax'"
                )
            if self.algorithm == "centralized":
                raise ValueError(
                    "worker_mesh shards the gossip neighbor tables; the "
                    "centralized pattern has no peer graph to shard — it "
                    "applies to decentralized algorithms only"
                )
            if self.n_workers % self.worker_mesh != 0:
                raise ValueError(
                    f"worker_mesh={self.worker_mesh} must divide n_workers "
                    f"({self.n_workers}): shards are equal contiguous row "
                    "blocks (pad N or pick a divisor)"
                )
            if self.topology not in NEIGHBOR_TOPOLOGIES:
                raise ValueError(
                    f"worker_mesh runs the neighbor-table halo-exchange "
                    f"path; topology {self.topology!r} has no matrix-free "
                    f"constructor (supported: {NEIGHBOR_TOPOLOGIES})"
                )
            if self.topology_impl == "dense":
                raise ValueError(
                    "worker_mesh shards the [N, k_max] neighbor tables; "
                    "topology_impl='dense' materializes the [N, N] "
                    "matrices the sharded path never builds — use "
                    "'auto' or 'neighbor'"
                )
            if self.mixing_impl not in ("auto", "gather"):
                raise ValueError(
                    f"worker_mesh lowers gather mixing to a ppermute halo "
                    f"exchange at shard edges; mixing_impl="
                    f"{self.mixing_impl!r} has no sharded form — use "
                    "'auto' or 'gather'"
                )
            if self.execution == "async":
                raise ValueError(
                    "worker_mesh does not compose with execution='async': "
                    "the event path is a totally ordered sequential "
                    "schedule a worker mesh cannot partition"
                )
            if self.gossip_schedule != "synchronous":
                raise ValueError(
                    "worker_mesh requires gossip_schedule='synchronous' "
                    "(matching schedules sample partners from the dense "
                    "adjacency)"
                )
            if self.edge_drop_prob > 0.0:
                raise ValueError(
                    "worker_mesh does not yet compose with per-edge fault "
                    "processes (edge_drop_prob/burst_len): the missing "
                    "piece is per-shard slicing of the [horizon, E] edge "
                    "chains through shard-local (node, slot) -> edge-id "
                    "tables — node processes (stragglers, churn, "
                    "participation) compose through the halo today"
                )
            if self.attack == "alie":
                raise ValueError(
                    "worker_mesh does not compose with attack='alie': the "
                    "colluders' shared payload is a global honest-moment "
                    "reduction whose sharded accumulation order diverges "
                    "from the single-device stream, breaking the bitwise "
                    "parity contract — use sign_flip or large_noise"
                )
            if self.rejoin == "neighbor_restart":
                raise ValueError(
                    "worker_mesh does not yet compose with "
                    "rejoin='neighbor_restart': the missing piece is the "
                    "halo-averaged warm restart (the rejoin average needs "
                    "boundary rows) — use rejoin='frozen'"
                )
            if self.robust_impl not in ("auto", "gather"):
                raise ValueError(
                    f"worker_mesh screens Byzantine messages in halo-"
                    f"gather form over the sharded tables; robust_impl="
                    f"{self.robust_impl!r} materializes dense/VMEM "
                    "objects the sharded path never builds — use 'auto' "
                    "or 'gather'"
                )
            if self.telemetry and (
                self.aggregation != "gossip" and self.robust_b > 0
            ):
                raise ValueError(
                    "worker_mesh does not yet compose with the telemetry "
                    "robust-activity probe: the missing piece is a "
                    "shard-local screening-fraction twin (the unsharded "
                    "probe gathers the global [N, k_max, d] stack) — "
                    "record telemetry without a robust rule, or run the "
                    "robust study unsharded"
                )
            if self.tp_degree > 1:
                raise ValueError(
                    "worker_mesh and tp_degree > 1 are mutually "
                    "exclusive: the TP path pins its own 2-D (workers, "
                    "model) mesh"
                )
        if self.topology_sampler not in ("auto", "dense", "sparse"):
            raise ValueError(
                f"Unknown topology sampler: {self.topology_sampler!r} "
                "(expected 'auto', 'dense', or 'sparse')"
            )
        if self.topology_sampler != "auto" and self.topology != "erdos_renyi":
            raise ValueError(
                f"topology_sampler={self.topology_sampler!r} selects the "
                "matrix-free Erdős–Rényi constructor; topology="
                f"{self.topology!r} has exactly one realization and would "
                "silently ignore it — leave topology_sampler='auto'"
            )
        if (
            self.topology_sampler == "sparse"
            and self.topology_impl == "dense"
        ):
            raise ValueError(
                "topology_sampler='sparse' only exists on the matrix-free "
                "path: topology_impl='dense' replays the [N, N] uniform "
                "stream as its own sampler — use topology_impl='auto' or "
                "'neighbor'"
            )
        if self.halo_overlap not in ("off", "double_buffer"):
            raise ValueError(
                f"Unknown halo overlap mode: {self.halo_overlap!r} "
                "(expected 'off' or 'double_buffer')"
            )
        if self.halo_overlap == "double_buffer":
            if self.worker_mesh < 2:
                raise ValueError(
                    "halo_overlap='double_buffer' overlaps the worker-mesh "
                    "halo exchange with local gather math; without "
                    "worker_mesh >= 2 there is no exchange to overlap — "
                    "leave halo_overlap='off'"
                )
            if self.compression != "none":
                raise ValueError(
                    "halo_overlap='double_buffer' does not compose with "
                    "compressed gossip: the compressed exchange ships "
                    "error-feedback estimate rows whose halo copies must "
                    "land before the mix reads them — run overlap studies "
                    "with compression='none'"
                )
            if (
                self.straggler_prob > 0.0
                or self.mttf > 0.0
                or self.participation_rate < 1.0
                or self.attack != "none"
                or (self.aggregation != "gossip" and self.robust_b > 0)
            ):
                raise ValueError(
                    "halo_overlap='double_buffer' restructures the PLAIN "
                    "gossip mixing body only; the fault/robust mesh paths "
                    "run their own liveness + model exchanges and would "
                    "silently ignore it — run overlap studies on the "
                    "plain path"
                )
        if self.execution not in EXECUTIONS:
            raise ValueError(f"Unknown execution mode: {self.execution}")
        if self.latency_model not in LATENCY_MODELS:
            raise ValueError(f"Unknown latency model: {self.latency_model}")
        if self.execution == "sync":
            if (
                self.latency_model != "constant"
                or self.latency_mean != 1.0
                or self.latency_tail != 0.0
            ):
                raise ValueError(
                    "latency_model/latency_mean/latency_tail shape the "
                    "asynchronous event schedule; execution='sync' would "
                    "silently ignore them — set execution='async'"
                )
        else:  # execution == 'async' (docs/ASYNC.md)
            if self.latency_mean <= 0.0:
                raise ValueError(
                    f"latency_mean must be positive, got {self.latency_mean}"
                )
            if self.latency_model == "lognormal" and self.latency_tail <= 0.0:
                raise ValueError(
                    "latency_model='lognormal' needs latency_tail > 0 "
                    "(the log-std tail knob)"
                )
            if self.latency_model == "pareto" and self.latency_tail <= 1.0:
                raise ValueError(
                    "latency_model='pareto' needs latency_tail > 1 (the "
                    "shape alpha; alpha <= 1 has no finite mean)"
                )
            if (
                self.latency_model in ("constant", "exponential")
                and self.latency_tail != 0.0
            ):
                raise ValueError(
                    f"latency_tail only shapes the lognormal/pareto tails; "
                    f"latency_model={self.latency_model!r} would silently "
                    "ignore it"
                )
            if self.backend == "cpp":
                raise ValueError(
                    "execution='async' is unsupported on the cpp backend "
                    "(its native kernel hard-codes the synchronous round); "
                    "use backend='jax' or the numpy oracle"
                )
            if self.algorithm not in ("dsgd", "gradient_tracking"):
                raise ValueError(
                    f"execution='async' is unsupported for "
                    f"{self.algorithm!r}: an event applies ONE worker's "
                    "update at its realized staleness — only dsgd's "
                    "pairwise-average descent and gradient tracking's "
                    "per-event tracker telescoping have an event form; "
                    "EXTRA/ADMM's static-W fixed points, CHOCO's shared "
                    "estimates and push-sum's mass pair do not — use "
                    "algorithm='dsgd' or 'gradient_tracking'"
                )
            if self.topology in DIRECTED_TOPOLOGIES:
                raise ValueError(
                    "execution='async' realizes mutual pairwise exchanges; "
                    f"directed topology {self.topology!r} has one-way links"
                )
            # gossip_schedule has an event-axis meaning (ISSUE-17):
            # 'synchronous'/'one_peer' both name the timeline's sampled
            # mutual matchings (the schedule IS one-peer per event) and
            # 'round_robin' cycles the deterministic phase partners.
            # Round-indexed fault knobs (edge_drop/straggler/mttf/
            # participation) are realized on the event axis by
            # parallel.events.realize_event_faults — a crashed worker's
            # event fires as a no-op (mid-flight gradient lost), thinning
            # skips events at the matched rate, and rejoin policies
            # re-enter per docs/CHURN.md — so they compose here.
            if self.attack != "none" or (
                self.aggregation != "gossip" and self.robust_b > 0
            ):
                raise ValueError(
                    "execution='async' does not compose with Byzantine "
                    "injection / robust aggregation: screening needs "
                    "multiple received messages per aggregation, but an "
                    "event delivers exactly one pairwise exchange — no "
                    "trimming/clipping budget is realizable"
                )
            if self.compression != "none":
                raise ValueError(
                    "execution='async' does not compose with compressed "
                    "gossip: the error-feedback estimate exchange assumes "
                    "synchronized rounds, which the event schedule removes"
                )
            if self.tp_degree > 1 or self.replicas > 1:
                raise ValueError(
                    "execution='async' is a sequential scan over a totally "
                    "ordered event schedule; the tensor-parallel mesh and "
                    "the replica vmap axis have no event form — run "
                    "tp_degree=1, replicas=1"
                )
            if self.topology_impl == "neighbor":
                raise ValueError(
                    "execution='async' scans events over the dense-"
                    "representation topology (its regime is modest N with "
                    "long horizons, not the matrix-free 10k+ axis); use "
                    "topology_impl='dense' or 'auto'"
                )
        if self.gossip_schedule not in ("synchronous", "one_peer",
                                        "round_robin"):
            raise ValueError(
                f"Unknown gossip schedule: {self.gossip_schedule}"
            )
        if self.gossip_schedule == "round_robin" and (
            self.edge_drop_prob > 0.0 or self.straggler_prob > 0.0
        ):
            raise ValueError(
                "round_robin is a deterministic schedule; combine failure "
                "injection with 'synchronous' or 'one_peer' instead"
            )
        if self.dtype not in ("float32", "float64", "bfloat16"):
            raise ValueError(f"Unknown dtype: {self.dtype}")
        if self.matmul_precision not in ("default", "high", "highest"):
            raise ValueError(f"Unknown matmul precision: {self.matmul_precision}")
        if self.n_workers <= 0:
            raise ValueError("n_workers must be positive")
        if self.n_informative_features > self.n_features:
            raise ValueError(
                f"n_informative_features ({self.n_informative_features}) cannot "
                f"exceed n_features ({self.n_features})"
            )
        if self.eval_every <= 0:
            raise ValueError("eval_every must be positive")
        if self.scan_unroll < 0:
            raise ValueError("scan_unroll must be >= 0 (0 = auto)")
        if self.n_iterations % self.eval_every != 0:
            raise ValueError(
                f"eval_every ({self.eval_every}) must divide n_iterations "
                f"({self.n_iterations})"
            )
        if self.topology == "grid":
            side = int(math.isqrt(self.n_workers))
            if side * side != self.n_workers:
                raise ValueError(
                    f"grid topology requires a perfect-square worker count, got {self.n_workers}"
                )
        if (
            self.topology in DIRECTED_TOPOLOGIES
            and self.gossip_schedule != "synchronous"
        ):
            raise ValueError(
                f"gossip_schedule={self.gossip_schedule!r} realizes mutual "
                "pairwise matchings, an undirected construction; directed "
                f"topology {self.topology!r} has one-way links — use "
                "'synchronous' (edge_drop_prob/straggler_prob compose with "
                "it via column-stochastic renormalization of surviving "
                "out-links)"
            )
        if (
            self.topology in DIRECTED_TOPOLOGIES
            and self.algorithm != "push_sum"
        ):
            raise ValueError(
                f"topology {self.topology!r} is directed: its mixing matrix "
                "is column-stochastic, not doubly stochastic, so "
                f"{self.algorithm!r} would converge to the graph's Perron "
                "weighting instead of the true average — use "
                "algorithm='push_sum', which debiases by the tracked "
                "push-sum mass"
            )
        if self.topology_seed < -1:
            raise ValueError(
                f"topology_seed must be -1 (follow seed) or >= 0, got "
                f"{self.topology_seed}"
            )
        if self.data_seed < -1:
            raise ValueError(
                f"data_seed must be -1 (follow seed) or >= 0, got "
                f"{self.data_seed}"
            )
        if self.replicas < 1:
            raise ValueError(
                f"replicas must be >= 1, got {self.replicas}"
            )
        if self.replicas > 1:
            if self.backend != "jax":
                raise ValueError(
                    f"replicas={self.replicas} batches seed replicates "
                    "through one vmapped XLA program, which only the jax "
                    "backend compiles; the numpy/cpp backends run one "
                    "trajectory at a time — use backend='jax' or loop "
                    "single runs"
                )
            if self.mixing_impl in ("shard_map", "pallas"):
                raise ValueError(
                    f"replicas={self.replicas} is incompatible with "
                    f"mixing_impl={self.mixing_impl!r}: the replica axis "
                    "vmaps the whole compiled program, but shard_map "
                    "stencils pin a fixed device mesh and the pallas "
                    "kernels address unbatched VMEM blocks — use 'auto', "
                    "'dense', 'stencil', 'sparse', or 'gather' (the "
                    "sharded-gather worker_mesh route instead dispatches "
                    "replicas as sequential mesh runs — see "
                    "jax_backend.run_batch)"
                )
            if self.algorithm == "choco":
                raise ValueError(
                    "replicas > 1 is unsupported for 'choco': its step "
                    "rule derives the compressor stream from config.seed "
                    "internally, which a batched per-replica seed axis "
                    "cannot reach — replicas would silently share "
                    "compression draws; run seeds sequentially instead"
                )
            if self.compression != "none":
                raise ValueError(
                    "replicas > 1 is unsupported with compressed gossip: "
                    "the error-feedback step derives its compressor "
                    "stream from config.seed internally, which a batched "
                    "per-replica seed axis cannot reach — replicas would "
                    "silently share compression draws; run seeds "
                    "sequentially instead"
                )
            if self.robust_impl == "fused":
                raise ValueError(
                    "replicas > 1 is incompatible with "
                    "robust_impl='fused': the replica axis vmaps the "
                    "whole compiled program, but the fused pallas kernel "
                    "addresses unbatched VMEM blocks — use 'auto', "
                    "'gather', or 'dense'"
                )
        if self.tp_degree < 1:
            raise ValueError(
                f"tp_degree must be >= 1, got {self.tp_degree}"
            )
        if self.tp_degree > 1:
            if self.backend != "jax":
                raise ValueError(
                    "tp_degree > 1 shards the model over a jax device "
                    f"mesh; backend={self.backend!r} has no mesh — use "
                    "backend='jax'"
                )
            if self.problem_type != "softmax":
                raise ValueError(
                    f"tp_degree={self.tp_degree} shards the softmax "
                    "[d, K] classifier over class columns; problem_type="
                    f"{self.problem_type!r} has a flat parameter vector "
                    "with no model axis to shard — use "
                    "problem_type='softmax'"
                )
            if self.algorithm != "dsgd" or self.topology != "ring":
                raise ValueError(
                    "the tensor-parallel path implements D-SGD ring "
                    "gossip on the class-sharded slice (the compute "
                    f"tier's measured configuration); algorithm="
                    f"{self.algorithm!r} topology={self.topology!r} is "
                    "unsupported — use algorithm='dsgd', topology='ring'"
                )
            if self.n_classes % self.tp_degree != 0:
                raise ValueError(
                    f"tp_degree={self.tp_degree} must divide n_classes "
                    f"({self.n_classes}): the [d, K] matrix shards in "
                    "equal class-column blocks"
                )
            if (
                self.edge_drop_prob > 0.0
                or self.straggler_prob > 0.0
                or self.mttf > 0.0
                or self.gossip_schedule != "synchronous"
                or self.attack != "none"
                or self.aggregation != "gossip"
            ):
                raise ValueError(
                    "tp_degree > 1 does not compose with fault injection, "
                    "matching schedules, or Byzantine machinery: the TP "
                    "ring stencil is a fixed boundary ppermute over the "
                    "workers mesh axis, not a per-iteration realized "
                    "graph — run those studies on the data-parallel path"
                )
            if self.compression != "none":
                raise ValueError(
                    "tp_degree > 1 does not compose with compressed "
                    "gossip: the TP path runs its own sharded ring "
                    "stencil, which carries no error-feedback estimate — "
                    "run compression studies on the data-parallel path"
                )
            if self.replicas > 1:
                raise ValueError(
                    "tp_degree > 1 and replicas > 1 are mutually "
                    "exclusive: the TP path pins a 2-D (workers, model) "
                    "device mesh that the replica vmap axis cannot wrap"
                )
            if self.mixing_impl not in ("auto", "stencil"):
                raise ValueError(
                    f"tp_degree > 1 realizes ring gossip as its own "
                    f"boundary-exchange stencil; mixing_impl="
                    f"{self.mixing_impl!r} would be silently ignored — "
                    "use 'auto'"
                )

    def resolved_topology_seed(self) -> int:
        """The seed random topologies actually build from: ``topology_seed``
        when pinned (>= 0), else ``seed``."""
        return self.topology_seed if self.topology_seed >= 0 else self.seed

    def resolved_data_seed(self) -> int:
        """The seed the dataset actually generates from: ``data_seed`` when
        pinned (>= 0), else ``seed``."""
        return self.data_seed if self.data_seed >= 0 else self.seed

    def resolved_topology_impl(self) -> str:
        """Resolve topology_impl='auto' (docs/PERF.md §14).

        The neighbor-table-native (matrix-free) representation activates
        automatically on the jax backend above ``MATRIX_FREE_AUTO_N``
        workers for the topologies that have a matrix-free constructor,
        provided no dense-only feature is requested — exactly the
        conditions an explicit ``topology_impl='neighbor'`` validates
        loudly. Below the threshold (or off the jax backend, or with a
        dense-only feature in play) 'auto' keeps the dense form: at small
        N the [N, N] matrices are cheap and every measured fast path
        (stencil mixing, the fused robust kernels, dense fault machinery)
        assumes them.
        """
        if self.topology_impl != "auto":
            return self.topology_impl
        if self.worker_mesh >= 2:
            # The sharded worker mesh is neighbor-table-native: shards
            # hold [N/P, k_max] table blocks and halo-exchange boundary
            # rows (docs/PERF.md §16). __post_init__ already rejected
            # every dense-only feature for worker_mesh >= 2, so 'auto'
            # resolves to the matrix-free form at ANY N.
            return "neighbor"
        dense_only_feature = (
            self.backend != "jax"
            or self.topology not in NEIGHBOR_TOPOLOGIES
            or self.mixing_impl not in ("auto", "gather", "stencil")
            # Byzantine screening DOES run matrix-free now (gather form,
            # ISSUE-9 satellite) but stays an explicit opt-in: auto keeps
            # defense studies on the dense path where every execution
            # form (dense/gather/fused) is comparable. Edge-fault
            # processes are no longer dense-only — the [horizon, E]
            # chains index through the (node, slot)→edge-id table.
            or self.attack != "none"
            or (self.aggregation != "gossip" and self.robust_b > 0)
            or self.gossip_schedule != "synchronous"
            or self.execution == "async"
            or self.tp_degree > 1
        )
        if not dense_only_feature and self.n_workers >= MATRIX_FREE_AUTO_N:
            return "neighbor"
        return "dense"

    def resolved_topology_sampler(self) -> str:
        """Resolve topology_sampler='auto' (docs/PERF.md §17).

        The sparse O(N·k_max) Erdős–Rényi sampler activates automatically
        above ``SPARSE_SAMPLER_AUTO_N`` workers on the matrix-free ER
        path — the regime where the dense sampler's O(N²) stream replay
        is the recorded blocker. Below the cutoff (or off the matrix-free
        ER path entirely) 'auto' keeps the dense-stream sampler: it is
        the bitwise reference every pre-existing ER artifact realized,
        and the graph IS the structural identity, so auto must never
        silently re-realize small-N graphs. Non-ER topologies resolve to
        'dense' (the only realization; __post_init__ rejects explicit
        non-auto values for them).
        """
        if self.topology_sampler != "auto":
            return self.topology_sampler
        if (
            self.topology == "erdos_renyi"
            and self.resolved_topology_impl() == "neighbor"
            and self.n_workers > SPARSE_SAMPLER_AUTO_N
        ):
            return "sparse"
        return "dense"

    def structural_dict(self) -> dict[str, Any]:
        """The canonical view of everything that changes the TRACED program.

        Two configs with equal structural dicts compile to the same XLA
        program shape on the replica-batched path, where the per-replica
        scalars are data: ``seed`` feeds PRNG keys / fault timelines /
        Byzantine sets (all traced inputs), ``data_seed`` only picks the
        dataset VALUES (also traced inputs), and the ``SWEEPABLE_FIELDS``
        (eta0, clip_tau, edge_drop_prob) enter as swept per-replica scalars.
        Everything else — and the structural BOUNDARIES inside the
        sweepables — stays: ``edge_drop_prob == 0`` means no fault
        machinery is traced at all, and ``clip_tau == 0`` selects the
        adaptive-radius clipping program, so those zero/nonzero indicators
        are recorded even though the values are not. Random topologies
        contribute their resolved seed (the realized graph is baked into
        the program as mixing constants); deterministic topologies do not.

        This is the serving layer's cache/coalescing identity
        (docs/SERVING.md): the executable cache keys compiled programs on
        ``structural_hash()`` (plus call-level facts like the cohort size
        and data shapes), and the request coalescer groups pending requests
        whose structural hash AND dataset agree into one ``run_batch``
        cohort.

        The federated fields are STRUCTURAL, deliberately (tested in
        tests/test_federated.py): ``local_steps`` changes the traced scan
        body (τ unrolled/fori local descents), ``participation_rate``
        both gates the fault machinery in or out AND bakes a different
        presampled participation timeline shape decision, and
        ``topology_impl`` selects between the dense-matrix and
        gather-table programs. All three therefore stay in the dict
        verbatim (``topology_impl`` as its RESOLVED value, so
        'auto'-at-large-N and an explicit 'neighbor' of the same program
        share a cohort) — two requests differing in any of them MISS each
        other's cached executables rather than silently colliding into
        one cohort.
        """
        d = self.to_dict()
        d["seed"] = None
        d["data_seed"] = None
        for f in SWEEPABLE_FIELDS:
            d[f] = None
        d["topology_seed"] = (
            self.resolved_topology_seed()
            if self.topology in RANDOM_TOPOLOGIES
            else None
        )
        d["topology_impl"] = self.resolved_topology_impl()
        # The ER sampler realizes a DIFFERENT graph per identity (same
        # law, different draws), and the realized graph is baked into the
        # compiled program — so the RESOLVED sampler is structural, like
        # topology_seed. Deterministic topologies have one realization
        # and contribute None (a ring is the same program under any
        # sampler name).
        d["topology_sampler"] = (
            self.resolved_topology_sampler()
            if self.topology == "erdos_renyi"
            else None
        )
        d["edge_faults_traced"] = self.edge_drop_prob > 0.0
        d["clip_tau_fixed"] = self.clip_tau > 0.0
        return d

    def structural_hash(self) -> str:
        """Stable content hash of ``structural_dict`` (sorted-key JSON,
        sha256, 16 hex chars — the same convention as telemetry's
        ``config_hash``)."""
        blob = json.dumps(self.structural_dict(), sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def replica_seeds(self) -> list[int]:
        """The per-replica seed vector a replicated run sweeps: seed,
        seed+1, ..., seed+replicas−1 (length 1 for single runs)."""
        return [self.seed + r for r in range(self.replicas)]

    def resolved_sampling_impl(self, platform: str, n_local: int) -> str:
        """Resolve sampling_impl='auto' from measured data.

        On the real chip (docs/perf/breakdown.json §sampling) the dense
        weighted-gradient form wins decisively when shards are small — the
        latency-bound regime where top_k+gather dominate the iteration:
        2.5x at N=256 (L=49), 10x at N=1024 (L=13) — while the gather path
        wins for large shards (N=25, L=500: 1.8x) where the full-shard pass
        costs real FLOPs; the two tie within chip noise for L ~ 100-250.
        Rule: dense on accelerators when the padded shard length is <= 64
        rows; gather otherwise (and always on CPU, where the extra FLOPs are
        not latency-hidden).
        """
        if self.sampling_impl != "auto":
            return self.sampling_impl
        if platform != "cpu" and n_local <= 64:
            return "dense"
        return "gather"

    def resolved_robust_impl(
        self, k_max: int, *, fused_eligible: bool = False
    ) -> str:
        """Resolve robust_impl='auto' from the topology's maximum degree.

        The gather form does (k_max+1)/N of the dense sort work but adds
        the [N, k_max, d] model gather; measured
        (docs/perf/robust_scale.json) it wins at every k_max < N−1 —
        ~70x on an N=256 ring, and still ~1.7x at N=64 Erdős–Rényi
        k_max=40 — and only stops paying at k_max = N−1 (fully
        connected), where it sorts the same closed axis as dense plus the
        gather and the two measure a tie. Rule: gather iff k_max+1 < N
        (dense keeps the fully-connected case: nothing to gain, and the
        [N, k_max+1, d] gather buffer matches dense's memory anyway).

        ``fused_eligible``: the BACKEND's report that the single-kernel
        pallas form can take this configuration (static topology, a
        fused-supported rule at this k_max, no telemetry activity probe
        — jax_backend._bind_byzantine computes it); when set, the gather
        branch promotes to 'fused' — same math, one VMEM-resident kernel
        instead of gather→sort→mix ops bouncing through HBM. An explicit
        robust_impl is never overridden.
        """
        if self.robust_impl != "auto":
            return self.robust_impl
        if k_max + 1 >= self.n_workers:
            return "dense"
        return "fused" if fused_eligible else "gather"

    def resolved_scan_unroll(self, platform: str) -> int:
        if self.scan_unroll > 0:
            return self.scan_unroll
        return 1 if platform == "cpu" else 8

    def resolved_lr_schedule(self) -> str:
        if self.lr_schedule != "auto":
            return self.lr_schedule
        # SGD-family rules (plain stochastic gossip descent, incl. SGP's
        # gradient-push) take the reference's decaying step; the
        # bias-corrected / dual methods run their constant-step regimes.
        return (
            "sqrt_decay"
            if self.algorithm in ("centralized", "dsgd", "push_sum")
            else "constant"
        )

    # The regularizer actually used for the gradient/objective: the reference
    # uses lambda for logistic and mu (== lambda by default) for quadratic
    # (reference worker.py:36-42, main.py:20-21).
    @property
    def reg_param(self) -> float:
        # Convex problems (logistic, huber) use lambda; the strongly convex
        # quadratic uses mu (== lambda by default), mirroring the reference.
        return (
            self.strong_convexity_mu
            if self.problem_type == "quadratic"
            else self.l2_regularization_lambda
        )

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ExperimentConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def construction_error(cls, fields: dict[str, Any]) -> "str | None":
        """The validation message constructing these fields would raise, or
        None when they build a valid config.

        The scenario engine's ground truth (docs/SCENARIOS.md): the
        declarative validity table in ``scenarios/validity.py`` mirrors
        ``__post_init__``'s composition rules for structured querying, and
        its agreement with THIS function — verdict for verdict over every
        sampled cell of the composition matrix — is what keeps the two
        from silently drifting apart.
        """
        try:
            cls(**fields)
        except (TypeError, ValueError) as e:
            return str(e)
        return None

    def replace(self, **kwargs: Any) -> "ExperimentConfig":
        return dataclasses.replace(self, **kwargs)
