"""Native (C++) host-simulator backend, loaded via ctypes.

The framework's native runtime tier for host-side execution: the reference's
two algorithms (centralized SGD and D-SGD with a dense mixing matrix —
reference ``trainer.py:7-74``/``76-197``) plus matrix/node-form recursions
of the extensions (DIGing gradient tracking, EXTRA, DLM decentralized ADMM,
CHOCO-SGD with deterministic compressors, and push-sum SGP over directed
graphs — the same recursions the numpy oracle implements, giving a third
independent implementation for cross-tier verification; round 5 adds the
softmax family, whose flat [d·K] matrix parameters flow through every
recursion unchanged), compiled from
``native/src/gossip_core.cpp`` into a shared library (OpenMP-parallel
worker loop, stable closed-form objectives). Fidelity-sensitive work stays on
the numpy oracle (exact reference semantics, injectable batches); this tier
exists for fast large-N host simulation and as the C++ runtime the TPU tier
delegates host-side bulk work to.

The library builds on demand with g++ (cached under ``native/build/``); a
CMakeLists.txt is provided for standalone builds. No pybind11 — plain C ABI
+ ctypes, per the environment's binding constraints.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import time
from typing import Optional

import numpy as np

from distributed_optimization_tpu.backends.base import BackendRunResult
from distributed_optimization_tpu.metrics import (
    RunHistory,
    centralized_floats_per_iteration,
    decentralized_floats_per_iteration,
)
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.utils.data import HostDataset

_SUPPORTED = ("centralized", "dsgd", "gradient_tracking", "extra", "admm",
              "choco", "push_sum")
_ALGO_CODES = {"centralized": 0, "dsgd": 1, "gradient_tracking": 2,
               "extra": 3, "admm": 4, "choco": 5, "push_sum": 6}
_COMPRESSION_CODES = {"none": 0, "top_k": 1}

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "src", "gossip_core.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libgossip_core.so")

_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    """The native core could not be built/loaded on this host."""


def _build_library() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    base = ["g++", "-std=c++17", "-O3", "-fPIC", "-shared", _SRC, "-o", _LIB_PATH]
    attempts = (base[:1] + ["-fopenmp"] + base[1:], base)  # OpenMP, then without
    errors = []
    for cmd in attempts:
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=300
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise NativeBuildError(f"failed to run g++: {e}") from e
        if proc.returncode == 0:
            return _LIB_PATH
        errors.append(proc.stderr.strip())
    raise NativeBuildError(
        "g++ failed to build the native core:\n" + "\n---\n".join(errors)
    )


def load_library(rebuild: bool = False) -> ctypes.CDLL:
    """Build (if needed) and load the native core; idempotent."""
    global _lib
    if _lib is not None and not rebuild:
        return _lib
    if rebuild or not os.path.exists(_LIB_PATH) or (
        os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
    ):
        _build_library()
    lib = ctypes.CDLL(_LIB_PATH)
    f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
    lib.run_simulation.restype = ctypes.c_int
    lib.run_simulation.argtypes = [
        f64p, f64p, i64p,                      # X, y, offsets
        ctypes.c_int64, ctypes.c_int64,        # n_workers, d
        ctypes.c_int64, f64p,                  # n_classes (1 = scalar), W
        ctypes.c_int, ctypes.c_int,            # algorithm, problem
        ctypes.c_int64, ctypes.c_int64,        # T, batch_size
        ctypes.c_double, ctypes.c_int,         # eta0, sqrt_decay
        ctypes.c_double, ctypes.c_double,      # reg, huber_delta
        ctypes.c_double, ctypes.c_double,      # admm_c, admm_rho
        ctypes.c_int, ctypes.c_int64,          # compression, comp_k
        ctypes.c_double,                       # choco_gamma
        ctypes.c_uint64,                       # seed
        ctypes.c_int64, ctypes.c_int,          # eval_every, collect_metrics
        f64p, f64p, f64p, f64p,                # out_models/gap/cons/times
    ]
    _lib = lib
    return lib


def run(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    collect_metrics: bool = True,
) -> BackendRunResult:
    if config.algorithm not in _SUPPORTED:
        raise ValueError(
            f"cpp backend implements {_SUPPORTED} (the reference's "
            "algorithms plus matrix/node-form GT/EXTRA/ADMM/CHOCO); "
            f"{config.algorithm!r} is a jax-backend capability"
        )
    if (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.gossip_schedule != "synchronous"
    ):
        raise ValueError(
            "failure injection / one-peer gossip is implemented on the "
            "jax backend and the numpy oracle, not the native core"
        )
    if config.attack != "none" or (
        config.aggregation != "gossip" and config.robust_b > 0
    ):
        raise ValueError(
            "Byzantine injection / robust aggregation is implemented on "
            "the jax backend and the numpy oracle (docs/BYZANTINE.md), "
            "not the native core"
        )
    if config.algorithm == "choco" and config.compression not in _COMPRESSION_CODES:
        raise ValueError(
            "the cpp CHOCO tier supports the deterministic compressors "
            "(none, top_k); random_k/qsgd draw from the jax counter-based "
            "PRNG inside the step, which an independent native "
            "implementation cannot reproduce (same stance as the numpy "
            "oracle)"
        )
    if config.compression != "none" and config.algorithm != "choco":
        raise ValueError(
            "error-feedback compressed dsgd/gradient_tracking is "
            "implemented on the jax backend and the numpy oracle; the "
            "native core's compression path covers CHOCO only — running "
            "it here would silently exchange full vectors"
        )
    lib = load_library()

    n = config.n_workers
    d = dataset.n_features
    # Trained parameter dimension: the softmax family's flat [d·K] matrix
    # (class labels travel in the float64 y array — exact for any K),
    # n_features for the scalar GLMs. Mirrors the jax backend's
    # problem.param_dim and the numpy oracle's branch.
    n_classes = config.n_classes if config.problem_type == "softmax" else 1
    d_model = d * n_classes
    T = config.n_iterations
    eval_every = config.eval_every
    n_evals = T // eval_every
    centralized = config.algorithm == "centralized"

    # Concatenate shards in worker order (contiguous offsets).
    sizes = [len(idx) for idx in dataset.shard_indices]
    offsets = np.zeros(n + 1, dtype=np.int64)
    offsets[1:] = np.cumsum(sizes)
    order = np.concatenate(dataset.shard_indices)
    X = np.ascontiguousarray(dataset.X_full[order], dtype=np.float64)
    y = np.ascontiguousarray(dataset.y_full[order], dtype=np.float64)

    if centralized:
        W = np.zeros((1, 1), dtype=np.float64)
        floats_per_iter = centralized_floats_per_iteration(n, d_model)
        spectral_gap = None
    else:
        from distributed_optimization_tpu.algorithms import get_algorithm

        topo = build_topology(
            config.topology, n, erdos_renyi_p=config.erdos_renyi_p,
            seed=config.resolved_topology_seed(),
        )
        W = np.ascontiguousarray(topo.mixing_matrix, dtype=np.float64)
        algo = get_algorithm(config.algorithm)
        if algo.comm_payload is not None:
            # Compressed gossip transmits the compressor's payload per edge
            # (same accounting as the jax and numpy backends).
            floats_per_iter = topo.floats_per_iteration * algo.comm_payload(
                config, d_model
            )
        else:
            # GT gossips both x and y per iteration (gossip_rounds=2).
            floats_per_iter = decentralized_floats_per_iteration(
                topo, d_model, algo.gossip_rounds
            )
        spectral_gap = topo.spectral_gap

    out_models = np.zeros((n, d_model), dtype=np.float64)
    out_gap = np.full(n_evals, np.nan)
    out_cons = np.full(n_evals, np.nan)
    out_times = np.full(n_evals, np.nan)

    start = time.perf_counter()
    rc = lib.run_simulation(
        X, y, offsets, n, d, n_classes, W,
        _ALGO_CODES[config.algorithm],
        {"logistic": 0, "quadratic": 1, "huber": 2,
         "softmax": 3}[config.problem_type],
        T, config.local_batch_size,
        config.learning_rate_eta0,
        1 if config.resolved_lr_schedule() == "sqrt_decay" else 0,
        config.reg_param, config.huber_delta,
        config.admm_c, config.admm_rho,
        _COMPRESSION_CODES.get(config.compression, 0),
        config.compression_k or 0, config.choco_gamma,
        config.seed, eval_every,
        1 if collect_metrics else 0,
        out_models, out_gap, out_cons, out_times,
    )
    run_seconds = time.perf_counter() - start
    if rc != 0:
        raise RuntimeError(f"native core rejected arguments (code {rc})")

    track_consensus = (
        collect_metrics and not centralized and config.record_consensus
    )
    history = RunHistory(
        objective=out_gap - f_opt,
        consensus_error=out_cons if track_consensus else None,
        # The core stamps steady_clock at every eval boundary (parity with
        # the reference's per-iteration time.time() samples, trainer.py:63).
        time=out_times,
        time_measured=True,
        eval_iterations=np.arange(eval_every, T + 1, eval_every),
        total_floats_transmitted=floats_per_iter * T,
        iters_per_second=T / run_seconds if run_seconds > 0 else float("inf"),
        spectral_gap=spectral_gap,
    )
    return BackendRunResult(
        history=history,
        final_models=out_models,
        final_avg_model=out_models.mean(axis=0),
    )
