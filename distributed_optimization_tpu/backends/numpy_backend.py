"""The numpy fidelity-oracle backend: reference-semantics simulator.

Mirrors the reference's single-process execution model (SURVEY.md §0) —
host-side float64 numpy, per-iteration Python loop, dense ``W @ models``
gossip, full-dataset objective evaluated on the host every iteration — so it

1. anchors metric/convergence parity with the reference's published numbers,
2. provides the CPU iters/sec baseline the north-star speedup is measured
   against (BASELINE.json), and
3. serves as the equivalence oracle for the JAX backend (identical injected
   batches must produce matching trajectories — SURVEY.md §4c).

Covers the two algorithms the reference implements (centralized SGD,
D-SGD) via the same shared step rules the JAX backend uses, plus
INDEPENDENT matrix-form host implementations of every extension written
directly from the published recursions rather than through the shared
``Algorithm.step`` rules — gradient tracking (Nedić-Olshevsky-Shi 2017,
DIGing), EXTRA (Shi-Ling-Wu-Yin 2015 eq. 2.13), decentralized linearized
ADMM (Ling-Shi-Wu-Ribeiro 2015, DLM; half-Laplacian matrix form), CHOCO-SGD
(Koloskova-Stich-Jaggi 2019, Algorithm 2 matrix form), and push-sum SGP
(Nedić-Olshevsky 2016; Assran et al. 2019, Algorithm 1) — so all seven
algorithms have a long-horizon fixed-point / trajectory oracle for the
JAX backend (SURVEY.md §4c backend-equivalence strategy). The only CHOCO
restriction: randomized compressors (random_k, qsgd) draw from the JAX
counter-based PRNG inside the step, which a host oracle cannot reproduce
without importing the very code under test — the deterministic compressors
(none, top_k) are supported and are the measured configurations.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import numpy as np

from distributed_optimization_tpu.algorithms import get_algorithm
from distributed_optimization_tpu.algorithms.base import StepContext
from distributed_optimization_tpu.backends.base import BackendRunResult
from distributed_optimization_tpu.metrics import (
    RunHistory,
    centralized_floats_per_iteration,
    consensus_error,
    decentralized_floats_per_iteration,
    honest_consensus_error,
    honest_mean,
)
from distributed_optimization_tpu.ops import losses_np
from distributed_optimization_tpu.ops.robust_aggregation import (
    robust_activity_np,
    robust_aggregate_np,
    validate_budget,
)
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel.adversary import byzantine_mask
from distributed_optimization_tpu.utils.data import HostDataset

_SUPPORTED = (
    "centralized", "dsgd", "gradient_tracking", "extra", "admm", "choco",
    "push_sum",
)

# Algorithms with a dedicated matrix-form host implementation below,
# independent of the shared ``Algorithm.step`` rules the JAX backend runs.
_MATRIX_FORM = ("gradient_tracking", "extra", "admm", "choco", "push_sum")


def run_async(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    batch_schedule: Optional[np.ndarray] = None,
    collect_metrics: bool = True,
    state0: Optional[dict] = None,
    start_event: int = 0,
    n_events: Optional[int] = None,
    return_state: bool = False,
    checkpoint=None,
    _fault_timeline=None,
) -> BackendRunResult:
    """Per-event float64 twin of the jax scan-over-events path.

    The event SCHEDULE and the event-axis fault realization come from the
    shared host-side builders (``parallel/events.py`` — the
    fault-timeline convention: both backends agree on who fires when,
    with whom, at what staleness, and which events are lost to crashes or
    thinning), while the per-event update math — pairwise average,
    stale-read gradient step, the DIGing tracker telescoping, τ fused
    local descents, rejoin warm restarts, the read-snapshot bookkeeping —
    is an independent float64 implementation written from the published
    recursions. Batch draws: ``batch_schedule`` injects per-event indices
    into the firing worker's shard (``[E, b]``, or ``[E, τ, b]`` with
    local_steps τ > 1 — the oracle-equivalence convention; standalone
    runs draw from a host Generator, which the jax counter-based stream
    cannot and need not reproduce). ``state0``/``start_event``/
    ``n_events`` continue a previous slice exactly like the jax twin;
    ``checkpoint`` runs the same event-indexed ``RunCheckpointer``
    contract (one chunk per eval row, bitwise resume).
    """
    from distributed_optimization_tpu.backends.async_scan import (
        _async_trace,
        _validate_slice,
        event_faults_for,
        timeline_for,
    )

    n = config.n_workers
    reg = config.reg_param
    d, objective, gradient, shards, shard_sizes = _problem_setup(
        config, dataset
    )

    topo, timeline = timeline_for(config)
    E = timeline.n_events
    n_events, events_per_eval = _validate_slice(
        config, E, start_event, n_events
    )
    algo_gt = config.algorithm == "gradient_tracking"
    tau = int(config.local_steps)
    telemetry_on = bool(config.telemetry)
    if checkpoint is not None:
        if telemetry_on:
            raise ValueError(
                "telemetry trace buffers are not checkpointed: a resumed "
                "run would report a hole — run telemetry without "
                "checkpointing, or checkpoint without telemetry"
            )
        if state0 is not None or start_event != 0:
            raise ValueError(
                "checkpointed async runs manage their own continuation "
                "cursor (the RunCheckpointer chunk); don't combine "
                "checkpoint= with state0/start_event"
            )
    if batch_schedule is not None:
        batch_schedule = np.asarray(batch_schedule)
        if len(batch_schedule) != E:
            # Same contract (and message shape) as the jax twin: the
            # schedule is indexed by ABSOLUTE event id, so a
            # window-length schedule on a continued slice is the caller
            # bug this catches.
            raise ValueError(
                f"async batch_schedule carries {len(batch_schedule)} "
                f"event rows; the schedule has {E} events (one index "
                "row per event into the firing worker's shard)"
            )
        if tau == 1:
            if batch_schedule.ndim != 2:
                raise ValueError(
                    f"async batch_schedule must be [E, b] at local_steps="
                    f"1; got shape {batch_schedule.shape}"
                )
        elif batch_schedule.ndim != 3 or batch_schedule.shape[1] != tau:
            raise ValueError(
                f"async batch_schedule must be [E, {tau}, b] at "
                f"local_steps={tau} (one [b] row per local descent); got "
                f"shape {batch_schedule.shape}"
            )
    n_evals = n_events // events_per_eval
    rounds_slice = n_events // n
    start_round = start_event // n

    _, fault_real, restart_rows = event_faults_for(
        config, topo, timeline, _fault_timeline
    )
    faults_on = fault_real is not None
    restart_on = restart_rows is not None
    partner_src = fault_real.partner if faults_on else timeline.partner

    carry_leaves = ("x", "x_read") + (("y", "g_prev") if algo_gt else ())
    if state0 is None:
        if start_event != 0:
            raise ValueError(
                "continuing from start_event > 0 needs the previous "
                f"slice's final_state ({list(carry_leaves)}) as state0"
            )
        state = {k: np.zeros((n, d)) for k in carry_leaves}
    else:
        if set(state0) != set(carry_leaves):
            raise ValueError(
                f"async state0 leaves {sorted(state0)} do not match the "
                f"event-path carry {list(carry_leaves)}"
            )
        state = {
            k: np.array(v, dtype=np.float64, copy=True)
            for k, v in state0.items()
        }

    # Standalone batch draws are COUNTER-BASED in (seed, worker, local
    # step[, local descent]) — one fresh Generator per event, like the
    # jax twin's folded keys (independent stream, same contract): a draw
    # never depends on the event interleaving or on how the run is
    # split, which is what makes the continuation path bitwise without
    # an injected schedule. τ = 1 keeps the original 4-word counter so
    # healthy runs replay the PR 9 stream exactly.
    def event_batch(i: int, k: int, m: Optional[int]) -> np.ndarray:
        b = min(config.local_batch_size, shard_sizes[i])
        if b <= 0:
            return np.empty(0, dtype=np.int64)
        words = [config.seed & 0xFFFFFFFF, 0xA57E, i, k]
        if m is not None:
            words.append(m)
        erng = np.random.default_rng(words)
        return erng.choice(shard_sizes[i], size=b, replace=False)

    eta0 = config.learning_rate_eta0
    sqrt_decay = config.resolved_lr_schedule() == "sqrt_decay"
    track_consensus = collect_metrics and config.record_consensus
    gap_hist = np.full(n_evals, np.nan)
    cons_hist = np.full(n_evals, np.nan)
    time_hist = np.empty(n_evals)

    # Event-indexed checkpointing (ISSUE-17): one chunk per eval row,
    # shared RunCheckpointer contract with the jax twin (truncated-chunk
    # fallback, config sidecar, bitwise resume — all RNG is
    # counter-based, so the replayed tail is the uninterrupted run's).
    ckptr = None
    start_chunk = 0
    if checkpoint is not None:
        from distributed_optimization_tpu.utils.checkpoint import (
            RunCheckpointer,
        )

        ckptr = RunCheckpointer(checkpoint)
        restored = None
        # Horizon-global event schedule: n_iterations is NOT resumable on
        # the event clock (async_scan's sidecar convention).
        if checkpoint.resume:
            ckptr.validate_or_record_config(
                config, resumable_keys=frozenset(),
            )
            restored = ckptr.restore()
        else:
            ckptr.reset(config, resumable_keys=frozenset())
        if restored is not None:
            state_np, gaps_r, conss_r, _fl, times_r, start_chunk = restored
            if start_chunk > n_evals:
                raise ValueError(
                    f"checkpoint at chunk {start_chunk} exceeds this "
                    f"run's horizon ({n_evals} eval chunks); raise "
                    "n_iterations to extend the checkpointed progress"
                )
            if set(state_np) != set(carry_leaves):
                raise ValueError(
                    f"checkpointed state leaves {sorted(state_np)} do "
                    f"not match the event-path carry {list(carry_leaves)}"
                )
            state = {
                k: np.array(v, dtype=np.float64, copy=True)
                for k, v in state_np.items()
            }
            gap_hist[:start_chunk] = np.asarray(gaps_r)[:start_chunk]
            if len(conss_r):
                cons_hist[:start_chunk] = np.asarray(conss_r)[:start_chunk]
            time_hist[:start_chunk] = np.asarray(times_r)[:start_chunk]

    x, x_read = state["x"], state["x_read"]
    if algo_gt:
        y, g_prev = state["y"], state["g_prev"]
    g_norm = np.zeros(n) if telemetry_on else None
    tele_rows: dict[str, list] = {
        "param_norm": [], "grad_norm": [], "nonfinite": [],
    }

    def local_chain(x_start, corr, eta, e, i):
        """τ local descents fused into one event (the jax twin's
        ``local_chain``): z_{m+1} = z_m − η(corr + g(z_m))."""
        Xi, yi = shards[i]
        z = x_start.copy()
        gsum = np.zeros_like(x_start)
        k = int(timeline.local_step[e])
        for m in range(tau):
            if batch_schedule is not None:
                idx = np.asarray(batch_schedule[e][m])
            else:
                idx = event_batch(i, k, m)
            gm = gradient(z, Xi[idx], yi[idx], reg)
            gsum += gm
            z = z - eta * (corr + gm)
        return z - x_start, gsum / tau

    t_base = float(time_hist[start_chunk - 1]) if start_chunk else 0.0
    save_seconds = 0.0
    start = time.perf_counter()
    for off in range(start_chunk * events_per_eval, n_events):
        e = start_event + off
        i = int(timeline.worker[e])
        # Mid-flight crash / thinned firing: the event is a no-op — but
        # the eval-row bookkeeping below still runs (a window whose
        # CLOSING event is a no-op must still emit its row).
        fired = not (faults_on and not fault_real.fire[e])
        if fired:
            j = int(partner_src[e])
            k = int(timeline.local_step[e])
            eta = eta0 / np.sqrt(k + 1.0) if sqrt_decay else eta0
            xi, read_i = x[i], x_read[i]
            if restart_on and fault_real.rejoin[e]:
                # neighbor_restart rejoin: warm-start from the realized
                # alive neighborhood average (x only; GT tracker rows
                # untouched).
                warm = restart_rows[e] @ x
                xi = warm
                read_i = warm
            matched = j != i
            avg = 0.5 * (xi + x[j]) if matched else None
            base_i = avg if matched else xi
            if algo_gt:
                # DIGing tracker telescoping at the stale read: the
                # network sum of y tracks the sum of g_prev EXACTLY at
                # every event.
                avg_y = 0.5 * (y[i] + y[j]) if matched else None
                base_y = avg_y if matched else y[i]
                if tau == 1:
                    Xi, yi_s = shards[i]
                    if batch_schedule is not None:
                        idx = np.asarray(batch_schedule[e])
                    else:
                        idx = event_batch(i, k, None)
                    g = gradient(read_i, Xi[idx], yi_s[idx], reg)
                    new_y_i = base_y + g - g_prev[i]
                    new_i = base_i - eta * new_y_i
                else:
                    delta, g = local_chain(
                        read_i, base_y - g_prev[i], eta, e, i
                    )
                    new_y_i = base_y + g - g_prev[i]
                    new_i = base_i + delta
                if matched:
                    y[j] = avg_y
                y[i] = new_y_i
                g_prev[i] = g
            else:
                if tau == 1:
                    Xi, yi_s = shards[i]
                    if batch_schedule is not None:
                        idx = np.asarray(batch_schedule[e])
                    else:
                        idx = event_batch(i, k, None)
                    g = gradient(read_i, Xi[idx], yi_s[idx], reg)
                    # D-PSGD ordering: average the live pair, then the
                    # firing worker descends along its stale-read
                    # gradient.
                    new_i = base_i - eta * g
                else:
                    delta, g = local_chain(read_i, 0.0, eta, e, i)
                    new_i = base_i + delta
            if matched:
                x[j] = avg
            x[i] = new_i
            x_read[i] = x[i].copy()
            if telemetry_on:
                g_norm[i] = float(np.linalg.norm(g))
        if (off + 1) % events_per_eval == 0:
            row = (off + 1) // events_per_eval - 1
            if collect_metrics:
                xbar = x.mean(axis=0)
                gap_hist[row] = (
                    objective(xbar, dataset.X_full, dataset.y_full, reg)
                    - f_opt
                )
                if track_consensus:
                    cons_hist[row] = consensus_error(x)
            if telemetry_on:
                tele_rows["param_norm"].append(
                    np.linalg.norm(x, axis=1).astype(np.float32)
                )
                tele_rows["grad_norm"].append(
                    g_norm.astype(np.float32).copy()
                )
                tele_rows["nonfinite"].append(
                    np.float32((~np.isfinite(x)).sum())
                )
            time_hist[row] = (
                t_base + time.perf_counter() - start - save_seconds
            )
            if ckptr is not None and (
                (row + 1) % checkpoint.every_evals == 0
                or row + 1 == n_evals
            ):
                t_save = time.perf_counter()
                ckptr.save(
                    row + 1,
                    {k: v.copy() for k, v in state.items()},
                    gap_hist[:row + 1], cons_hist[:row + 1],
                    (), time_hist[:row + 1],
                )
                save_seconds += time.perf_counter() - t_save
    run_seconds = time.perf_counter() - start - save_seconds

    # Comms accounting: only FIRED live exchanges move data — 2·d floats
    # for the model pair, 4·d for gradient tracking (tracker rows ride
    # alongside). Solo, degraded, and non-firing events move nothing.
    matched_eff = (
        fault_real.matched_fired if faults_on else timeline.matched()
    )
    matched_slice = int(
        np.sum(matched_eff[start_event:start_event + n_events])
    )
    per_exchange = (4.0 if algo_gt else 2.0) * d

    trace = None
    if telemetry_on:
        trace = _async_trace(
            config, timeline, fault_real, matched_eff, tele_rows,
            start_event, n_evals, events_per_eval,
        )

    history = RunHistory(
        objective=gap_hist,
        consensus_error=cons_hist if track_consensus else None,
        time=time_hist,
        time_measured=True,
        eval_iterations=np.arange(
            start_round + config.eval_every,
            start_round + rounds_slice + 1,
            config.eval_every,
        ),
        total_floats_transmitted=per_exchange * matched_slice,
        iters_per_second=(
            rounds_slice / run_seconds if run_seconds > 0 else float("inf")
        ),
        spectral_gap=topo.spectral_gap,
        trace=trace,
    )
    return BackendRunResult(
        history=history,
        final_models=x,
        final_avg_model=x.mean(axis=0),
        final_state=(
            dict(state) if return_state else None
        ),
    )


def _problem_setup(config, dataset: HostDataset):
    """Shared host problem prelude for the sync and async oracle paths:
    (d, objective, gradient, shards, shard_sizes). ``d`` is the TRAINED
    dimension — the softmax family's flat [d·K] matrix, ``n_features``
    for the scalar GLMs (mirrors jax_backend's ``problem.param_dim``
    without importing the jax problem registry)."""
    d = dataset.n_features
    if config.problem_type == "softmax":
        d = dataset.n_features * config.n_classes
    objective = losses_np.OBJECTIVES[config.problem_type]
    gradient = losses_np.GRADIENTS[config.problem_type]
    if config.problem_type == "huber":
        objective = functools.partial(objective, delta=config.huber_delta)
        gradient = functools.partial(gradient, delta=config.huber_delta)
    shards = [dataset.shard(i) for i in range(config.n_workers)]
    shard_sizes = [Xi.shape[0] for Xi, _ in shards]
    return d, objective, gradient, shards, shard_sizes


def _topk_rows(v: np.ndarray, k: int) -> np.ndarray:
    """Per-row top-k-by-magnitude compressor (Koloskova et al. '19 §2, the
    deterministic contraction): keep the k largest |v| entries per row, zero
    the rest. Ties break toward the lower index (a stable descending sort),
    matching ``lax.top_k`` so the two backends select identical supports."""
    out = np.zeros_like(v)
    for r in range(v.shape[0]):
        keep = np.argsort(-np.abs(v[r]), kind="stable")[:k]
        out[r, keep] = v[r, keep]
    return out


def run(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    batch_schedule: Optional[np.ndarray] = None,
    collect_metrics: bool = True,
) -> BackendRunResult:
    if config.execution == "async":
        # Event-driven asynchronous gossip (docs/ASYNC.md): per-event
        # float64 twin of the jax scan-over-events path.
        return run_async(
            config, dataset, f_opt, batch_schedule=batch_schedule,
            collect_metrics=collect_metrics,
        )
    if config.algorithm not in _SUPPORTED:
        raise ValueError(
            f"numpy backend implements {_SUPPORTED} (the reference's "
            "algorithms plus matrix-form oracles for the exact first-order "
            f"extensions); {config.algorithm!r} is a jax-backend capability"
        )
    if config.gossip_schedule != "synchronous":
        raise ValueError(
            "matching-based gossip (one_peer/round_robin) is a jax-backend "
            "capability; the numpy oracle covers the synchronous schedule "
            "(fault-free or with synchronous failure injection)"
        )
    algo = get_algorithm(config.algorithm)
    # Synchronous failure injection IS oracle-supported (iid edge drops,
    # bursty Gilbert-Elliott links, iid stragglers, crash-recovery churn):
    # the fault SCHEDULE comes from the shared host-side timeline builder —
    # the same convention as the Byzantine set below, so both backends
    # agree on which edges/nodes fail — while every piece of mask/weight
    # MATH (realized MH / column-stochastic weights, the freeze, the
    # rejoin restart, the realized-floats accounting) is an independent
    # float64 twin of the jax path.
    faults_active = (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.participation_rate < 1.0
    )
    if faults_active:
        if not algo.is_decentralized:
            raise ValueError(
                "fault injection models peer exchanges and applies only to "
                "decentralized algorithms; the centralized pattern has no "
                "peer edges"
            )
        if not algo.supports_edge_faults:
            raise ValueError(
                f"time-varying gossip is unsupported for "
                f"{config.algorithm!r} (see jax_backend for the rationale "
                "per algorithm)"
            )
        if config.mttf > 0.0 and not algo.supports_churn:
            raise ValueError(
                f"crash-recovery churn is unsupported for "
                f"{config.algorithm!r}; use 'dsgd' or 'gradient_tracking' "
                "(see jax_backend for the rationale per algorithm)"
            )
    byz_active = config.attack != "none" or (
        config.aggregation != "gossip" and config.robust_b > 0
    )
    if byz_active:
        if not algo.supports_byzantine:
            raise ValueError(
                f"Byzantine injection / robust aggregation is unsupported "
                f"for {config.algorithm!r}; use 'dsgd' or "
                "'gradient_tracking' (see jax_backend for the rationale "
                "per algorithm)"
            )
        if config.attack == "large_noise":
            raise ValueError(
                "the numpy oracle supports the deterministic attacks "
                "(sign_flip, alie); large_noise draws from the jax "
                "counter-based PRNG inside the step, which an independent "
                "host implementation cannot reproduce without importing "
                "the code under test"
            )
    T = config.n_iterations
    n = config.n_workers
    reg = config.reg_param
    d, objective, gradient, shards, shard_sizes = _problem_setup(
        config, dataset
    )

    if config.compression in ("random_k", "qsgd"):
        raise ValueError(
            "the numpy error-feedback oracle supports the deterministic "
            "compressors (none, top_k); random_k/qsgd draw from the jax "
            "counter-based PRNG inside the step, which an independent host "
            "implementation cannot reproduce without importing the code "
            "under test"
        )
    # Compressed dsgd shares CHOCO's matrix recursion (it IS the CHOCO
    # update registered under dsgd — see algorithms/dsgd.py); compressed
    # gradient tracking extends the GT matrix form with per-leaf
    # error-feedback estimates. Both therefore take the matrix-form
    # branch below instead of the shared Algorithm.step rules.
    compressed = config.compression != "none"
    if algo.is_decentralized:
        topo = build_topology(
            config.topology, n, erdos_renyi_p=config.erdos_renyi_p,
            seed=config.resolved_topology_seed(),
        )
        W = topo.mixing_matrix
        A = topo.adjacency
        degrees = topo.degrees[:, None]
        if algo.comm_payload is not None:
            # Compressed gossip transmits the compressor's payload per edge
            # (same accounting as the jax backend).
            floats_per_iter = topo.floats_per_iteration * algo.comm_payload(
                config, d
            )
        else:
            floats_per_iter = decentralized_floats_per_iteration(
                topo, d, algo.gossip_rounds
            )
        spectral_gap = topo.spectral_gap
    else:
        topo, W, A = None, None, None
        degrees = np.zeros((n, 1))
        floats_per_iter = centralized_floats_per_iteration(n, d)
        spectral_gap = None

    # --- failure injection (mirrors jax_backend; docs/CHURN.md). `live`
    # holds the CURRENT iteration's realized (W_t, A_t); the gossip
    # closures below read through it so one definition serves the static
    # and the time-varying case. The weight recomputation rules are
    # independent numpy twins of parallel/faults.py's jax forms.
    timeline = None
    live = {"W": W, "A": A}
    realized_degree_total = 0.0
    if faults_active:
        from distributed_optimization_tpu.parallel.faults import (
            build_fault_timeline,
        )

        timeline = build_fault_timeline(
            topo, T, config.seed,
            edge_drop_prob=config.edge_drop_prob,
            burst_len=config.burst_len if config.burst_len >= 1.0 else 1.0,
            straggler_prob=(
                0.0 if config.mttf > 0.0 else config.straggler_prob
            ),
            mttf=config.mttf, mttr=config.mttr,
            participation_rate=config.participation_rate,
        )

        def _up_row(t: int) -> Optional[np.ndarray]:
            """Composed [N] bool availability at round t: churn/straggler-up
            AND sampled-in (participation) — the independent float64 twin
            of the jax path's composed ``active(t)``. None when no node
            process is active."""
            up = None
            if timeline.node_up is not None:
                up = timeline.node_up[t]
            if timeline.part_up is not None:
                up = (
                    timeline.part_up[t] if up is None
                    else up & timeline.part_up[t]
                )
            return up

        def _realized_A(t: int) -> np.ndarray:
            if timeline.edge_up is not None:
                A_t = np.zeros((n, n))
                ei = timeline.edge_index[:, 0]
                ej = timeline.edge_index[:, 1]
                vals = timeline.edge_up[t].astype(np.float64)
                A_t[ei, ej] = vals
                if not topo.directed:
                    A_t[ej, ei] = vals
            else:
                A_t = np.asarray(A, dtype=np.float64).copy()
            up = _up_row(t)
            if up is not None:
                m = up.astype(np.float64)
                A_t *= m[:, None] * m[None, :]  # down node exchanges nothing
            return A_t

        def _mh_weights(A_t: np.ndarray) -> np.ndarray:
            # Metropolis-Hastings on realized degrees: symmetric + doubly
            # stochastic for every draw; an isolated row collapses to I.
            deg = A_t.sum(axis=1)
            pair = 1.0 / (1.0 + np.maximum(deg[:, None], deg[None, :]))
            W_t = A_t * pair
            return W_t + np.diag(1.0 - W_t.sum(axis=1))

        def _column_stochastic(A_t: np.ndarray) -> np.ndarray:
            # Surviving-out-link renormalization (directed / push-sum fault
            # model): columns sum to 1 for every realization.
            out_deg = A_t.sum(axis=0)
            W_t = A_t / (1.0 + out_deg)[None, :]
            return W_t + np.diag(1.0 - W_t.sum(axis=0))

        _realized_weights = (
            _column_stochastic if topo.directed else _mh_weights
        )

    # --- Byzantine machinery (mirrors jax_backend; docs/BYZANTINE.md).
    # The Byzantine SET comes from the shared host-side sampler so both
    # backends agree on who lies; the corruption and the robust rules are
    # independent numpy twins. Byzantine rows keep the benign W-mix of the
    # TRUE stack (attackers run honest dynamics internally and lie only on
    # the wire — same convention as parallel/adversary.py).
    byz = None
    if byz_active:
        byz = byzantine_mask(n, config.n_byzantine, config.seed)
        robust_name = (
            config.aggregation
            if config.aggregation != "gossip" and config.robust_b > 0
            else None
        )
        if robust_name is not None:
            validate_budget(
                int(topo.degrees.min()), config.robust_b, config.aggregation
            )
        scale = config.attack_scale

        def corrupt_np(v: np.ndarray) -> np.ndarray:
            if config.attack == "none":
                return v
            out = np.array(v, dtype=np.float64, copy=True)
            if config.attack == "sign_flip":
                out[byz] = -scale * v[byz]
            else:  # alie: shared honest_mean − scale·honest_std payload
                mu = v[~byz].mean(axis=0)
                sd = v[~byz].std(axis=0)
                out[byz] = mu - scale * sd
            return out

        def byz_mix(v: np.ndarray) -> np.ndarray:
            # Reads the realized (W_t, A_t) through `live`, so attacks and
            # screening run over the same per-iteration graph as the
            # mixing (the realized_adjacency composition of the jax path).
            va = corrupt_np(v)
            if robust_name is not None:
                honest_agg = robust_aggregate_np(
                    robust_name, live["A"], va, config.robust_b,
                    config.clip_tau,
                )
            else:
                honest_agg = live["W"] @ va
            if not byz.any():  # pure-defense run: no benign branch needed
                return honest_agg
            return np.where(byz[:, None], live["W"] @ v, honest_agg)

    rng = np.random.default_rng(config.seed)
    eta0 = config.learning_rate_eta0
    sqrt_decay = config.resolved_lr_schedule() == "sqrt_decay"

    def sample_indices(t: int, i: int) -> np.ndarray:
        if batch_schedule is not None:
            return batch_schedule[t, i]
        ni = shard_sizes[i]
        b = min(config.local_batch_size, ni)
        if b <= 0:
            return np.empty(0, dtype=np.int64)
        return rng.choice(ni, size=b, replace=False)

    # Last-drawn batch indices per worker — the flight recorder's gradient
    # probe reuses them, so it measures the SAME batch realization the eval
    # iteration's step consumed (jax_backend parity: its probe re-derives
    # that batch from the counter-based (key, t)) WITHOUT consuming any
    # extra host-RNG draws — telemetry must not perturb the trajectory.
    last_idx: dict[int, np.ndarray] = {}

    def make_grad(t: int):
        def grad(params: np.ndarray, slot: int) -> np.ndarray:
            out = np.zeros((n, d))
            for i in range(n):
                Xi, yi = shards[i]
                idx = sample_indices(t, i)
                last_idx[i] = idx
                out[i] = gradient(params[i], Xi[idx], yi[idx], reg)
            return out

        return grad

    if config.algorithm in _MATRIX_FORM or (
        config.algorithm == "dsgd" and compressed
    ):
        # Independent matrix recursions (NOT algo.init/algo.step): state
        # leaves written out explicitly from the published update equations.
        zeros = np.zeros((n, d))
        if config.algorithm == "gradient_tracking" and compressed:
            # Compressed DIGing (the jax rule's independent float64 twin,
            # algorithms/gradient_tracking.py): BOTH gossip rounds replace
            # W v with the error-feedback exchange v + γ(W X̂⁺ − X̂⁺) over
            # per-leaf estimate memories; Q = identity or per-row top-k
            # (randomized compressors rejected above). Compression
            # excludes faults/Byzantine by config, so W is static here.
            gamma = config.choco_gamma
            k_comp = config.compression_k
            compress = (
                (lambda v: v) if config.compression == "none"
                else (lambda v: _topk_rows(v, k_comp))
            )
            state = {"x": zeros.copy(), "y": zeros.copy(),
                     "g": zeros.copy(), "xhat": zeros.copy(),
                     "yhat": zeros.copy()}

            def matrix_step(state, t, eta, grad_at):
                xhat_new = state["xhat"] + compress(
                    state["x"] - state["xhat"]
                )
                x_new = (
                    state["x"] + gamma * (W @ xhat_new - xhat_new)
                    - eta * state["y"]
                )
                g_new = grad_at(x_new)
                yhat_new = state["yhat"] + compress(
                    state["y"] - state["yhat"]
                )
                y_new = (
                    state["y"] + gamma * (W @ yhat_new - yhat_new)
                    + g_new - state["g"]
                )
                return {"x": x_new, "y": y_new, "g": g_new,
                        "xhat": xhat_new, "yhat": yhat_new}

        elif config.algorithm == "gradient_tracking":
            # DIGing: x_{t+1} = W x_t − η y_t;  y_{t+1} = W y_t + g_{t+1} − g_t
            # with y_0 = g_prev = 0 (first step is a pure gossip step).
            # Under Byzantine injection both gossip rounds go through the
            # corrupt/screen composition, exactly like the jax rule; under
            # faults the realized W_t is read through `live`.
            gossip = byz_mix if byz is not None else (lambda v: live["W"] @ v)
            state = {"x": zeros.copy(), "y": zeros.copy(), "g": zeros.copy()}
            tau_gt = config.local_steps

            def matrix_step(state, t, eta, grad_at):
                x_new = gossip(state["x"]) - eta * state["y"]
                g_new = grad_at(x_new)
                y_new = gossip(state["y"]) + g_new - state["g"]
                # Federated local updates (config.local_steps = τ): τ−1
                # extra LOCAL descents along the tracker-corrected
                # direction y_new + (g(v) − g_new) — the independent
                # float64 twin of the jax rule's K-GT-style recursion
                # (algorithms/gradient_tracking.py). τ = 1 adds no ops.
                for _ in range(1, tau_gt):
                    x_new = x_new - eta * (y_new + grad_at(x_new) - g_new)
                return {"x": x_new, "y": y_new, "g": g_new}

        elif config.algorithm == "extra":
            # EXTRA (Shi et al. 2015):
            #   x_1     = W x_0 − η g(x_0)
            #   x_{t+1} = (I+W) x_t − (I+W)/2 x_{t−1} − η (g(x_t) − g(x_{t−1}))
            # ``Wx_prev`` carries the previous iteration's W @ x, so each
            # step performs exactly one dense mix (same comms accounting as
            # the jax rule, which also reuses the carried mix).
            state = {"x": zeros.copy(), "x_prev": zeros.copy(),
                     "Wx_prev": zeros.copy(), "g": zeros.copy(),
                     "started": False}

            def matrix_step(state, t, eta, grad_at):
                x = state["x"]
                g = grad_at(x)
                Wx = W @ x
                if not state["started"]:
                    x_new = Wx - eta * g
                else:
                    x_new = (
                        x + Wx
                        - 0.5 * (state["x_prev"] + state["Wx_prev"])
                        - eta * (g - state["g"])
                    )
                return {"x": x_new, "x_prev": x, "Wx_prev": Wx, "g": g,
                        "started": True}

        elif config.algorithm == "admm":
            # DLM (Ling-Shi-Wu-Ribeiro 2015), half-Laplacian matrix form.
            # Edge-consensus ADMM (x_i = z_e = x_j per edge) with
            # zero-initialized duals eliminates z to the edge midpoint; the
            # aggregated node dual Φ (rows φ_i = Σ_{e∋i} λ_{e,i}) and a
            # linearized f with proximal weight ρ give, with D = deg diag,
            # A = adjacency, L⁺ = (D+A)/2 (signless half-Laplacian),
            # L⁻ = (D−A)/2 (half-Laplacian):
            #   X_{k+1} = (ρI + cD)⁻¹ (ρ X_k + c L⁺ X_k − ∇F(X_k) − Φ_k)
            #   Φ_{k+1} = Φ_k + c L⁻ X_{k+1}
            # The diagonal system solves row-wise; step size is the penalty
            # pair (c, ρ), not η (constant by construction — the lr schedule
            # is irrelevant here, as in the jax rule).
            c_pen, rho = config.admm_c, config.admm_rho
            D = np.diag(topo.degrees.astype(np.float64))
            L_plus = 0.5 * (D + A)
            L_minus = 0.5 * (D - A)
            diag_inv = 1.0 / (rho + c_pen * topo.degrees)[:, None]
            state = {"x": zeros.copy(), "phi": zeros.copy()}

            def matrix_step(state, t, eta, grad_at):
                x, phi = state["x"], state["phi"]
                g = grad_at(x)
                x_new = diag_inv * (
                    rho * x + c_pen * (L_plus @ x) - g - phi
                )
                return {"x": x_new, "phi": phi + c_pen * (L_minus @ x_new)}

        elif config.algorithm == "push_sum":
            # Push-sum SGP (Nedić-Olshevsky 2016; Assran et al. 2019 Alg. 1)
            # with COLUMN-stochastic A (directed graphs; a doubly stochastic
            # W is the degenerate case with mass ≡ 1):
            #   num_{t+1} = A (num_t − η ∇F(z_t))
            #   w_{t+1}   = A w_t,  w_0 = 1
            #   z_{t+1}   = num_{t+1} / w_{t+1}
            # Gradients at the de-biased z. The 'x' leaf holds z so metrics
            # and final_models see the estimates (same layout as the jax
            # rule). Columns of A summing to 1 conserve Σ num and Σ w = N.
            state = {"x": zeros.copy(), "num": zeros.copy(),
                     "w": np.ones((n, 1))}

            def matrix_step(state, t, eta, grad_at):
                g = grad_at(state["x"])
                num_new = live["W"] @ (state["num"] - eta * g)
                w_new = live["W"] @ state["w"]
                return {"x": num_new / w_new, "num": num_new, "w": w_new}

        else:  # choco, and compressed dsgd (the identical recursion)
            # CHOCO-SGD (Koloskova et al. 2019, Algorithm 2 matrix form):
            #   X_{t+½} = X_t − η ∇F(X_t)
            #   X̂_{t+1} = X̂_t + Q(X_{t+½} − X̂_t)      ← the transmitted bits
            #   X_{t+1} = X_{t+½} + γ (W − I) X̂_{t+1}
            # Q = identity ('none') or per-row top-k; randomized compressors
            # are rejected above. Compressed dsgd routes here too: the
            # error-feedback D-SGD step IS this update (only the lr
            # schedule differs, and eta arrives resolved from the config).
            gamma = config.choco_gamma
            k_comp = config.compression_k
            compress = (
                (lambda v: v) if config.compression == "none"
                else (lambda v: _topk_rows(v, k_comp))
            )
            state = {"x": zeros.copy(), "xhat": zeros.copy()}

            def matrix_step(state, t, eta, grad_at):
                x, xhat = state["x"], state["xhat"]
                x_half = x - eta * grad_at(x)
                xhat_new = xhat + compress(x_half - xhat)
                return {
                    "x": x_half + gamma * (W @ xhat_new - xhat_new),
                    "xhat": xhat_new,
                }

    else:
        matrix_step = None
        state = {k: np.asarray(v, dtype=np.float64) for k, v in
                 algo.init(
                     np.zeros((n, d)), config,
                     neighbor_sum=(lambda v: A @ v) if A is not None else None,
                 ).items()}

    eval_every = config.eval_every
    n_evals = T // eval_every
    track_consensus = (
        collect_metrics and algo.is_decentralized and config.record_consensus
    )
    gap_hist = np.full(n_evals, np.nan)
    cons_hist = np.full(n_evals, np.nan)
    time_hist = np.empty(n_evals)
    trace_lists: Optional[dict[str, list]] = (
        {k: [] for k in ("grad_norm", "param_norm", "nodes_up",
                         "nonfinite", "live_edges", "clip_frac")}
        if config.telemetry else None
    )

    def trace_row(x: np.ndarray, t: int) -> None:
        """One flight-recorder row (telemetry.TRACE_FIELDS) — independent
        float64 twin of the jax backend's in-scan probe, same keys/shapes/
        float32 rows, recorded from the post-step state at the eval
        boundary."""
        gnorm = np.zeros(n)
        for i in range(n):
            Xi, yi = shards[i]
            idx = last_idx.get(i)
            if idx is None:  # no step ran yet (T == 0 edge)
                idx = np.arange(shard_sizes[i])
            gnorm[i] = np.linalg.norm(gradient(x[i], Xi[idx], yi[idx], reg))
        nonf = 0
        for v in state.values():
            if isinstance(v, np.ndarray) and np.issubdtype(
                v.dtype, np.floating
            ):
                nonf += int(np.sum(~np.isfinite(v)))
        if algo.is_decentralized:
            live_edges = float(np.asarray(live["A"]).sum())
        else:
            live_edges = 0.0
        up_row = _up_row(t) if timeline is not None else None
        nodes = (
            up_row.astype(np.float32)
            if up_row is not None
            else np.ones(n, dtype=np.float32)
        )
        cf = 0.0
        if byz is not None and robust_name is not None:
            cf = robust_activity_np(
                robust_name, live["A"], corrupt_np(x), config.robust_b,
                config.clip_tau,
            )
        trace_lists["grad_norm"].append(gnorm.astype(np.float32))
        trace_lists["param_norm"].append(
            np.linalg.norm(x, axis=1).astype(np.float32)
        )
        trace_lists["nodes_up"].append(nodes)
        trace_lists["nonfinite"].append(np.float32(nonf))
        trace_lists["live_edges"].append(np.float32(live_edges))
        trace_lists["clip_frac"].append(np.float32(cf))

    start = time.perf_counter()

    for t in range(T):
        eta = eta0 / np.sqrt(t + 1.0) if sqrt_decay else eta0
        if faults_active:
            A_t = _realized_A(t)
            live["A"] = A_t
            live["W"] = _realized_weights(A_t)
            realized_degree_total += A_t.sum()
            if (
                config.rejoin == "neighbor_restart"
                and timeline.rejoin is not None
                and timeline.rejoin[t].any()
            ):
                # Warm restart BEFORE the step (mirrors jax_backend): a
                # rejoining node's model row becomes its realized-
                # neighborhood average; isolated rejoiners stay stale.
                deg = A_t.sum(axis=1)
                take = timeline.rejoin[t] & (deg > 0)
                if take.any():
                    x_r = state["x"].copy()
                    nbr = (A_t @ state["x"]) / np.maximum(deg, 1.0)[:, None]
                    x_r[take] = nbr[take]
                    state = {**state, "x": x_r}
        prev_state = state
        if matrix_step is not None:
            grad_fn = make_grad(t)
            state = matrix_step(state, t, eta, lambda p: grad_fn(p, 0))
        else:
            ctx = StepContext(
                grad=make_grad(t),
                mix=(
                    byz_mix
                    if byz is not None
                    else (lambda v: live["W"] @ v)
                    if W is not None
                    else (lambda v: v)
                ),
                neighbor_sum=(
                    (lambda v: live["A"] @ v)
                    if A is not None
                    else (lambda v: v * 0)
                ),
                eta=eta,
                t=t,
                degrees=degrees,
                config=config,
            )
            state = algo.step(state, ctx)
        if timeline is not None and (
            timeline.node_up is not None or timeline.part_up is not None
        ):
            # A down/sampled-out node takes no step at all: freeze its
            # rows across every state leaf — for churn, across the WHOLE
            # outage, so a 'frozen' rejoin resumes the stale pre-crash
            # state for free.
            up = _up_row(t)
            state = {
                k: np.where(
                    up.reshape((-1,) + (1,) * (v.ndim - 1)), v, prev_state[k]
                )
                for k, v in state.items()
            }
        if (t + 1) % eval_every == 0:
            k = (t + 1) // eval_every - 1
            x = state["x"]
            if collect_metrics:
                # Honest-only metrics under attack (docs/BYZANTINE.md).
                xbar = honest_mean(x, byz) if byz is not None else x.mean(axis=0)
                gap_hist[k] = (
                    objective(xbar, dataset.X_full, dataset.y_full, reg) - f_opt
                )
                if track_consensus:
                    cons_hist[k] = (
                        honest_consensus_error(x, byz)
                        if byz is not None
                        else consensus_error(x)
                    )
            if trace_lists is not None:
                trace_row(x, t)
            time_hist[k] = time.perf_counter() - start

    run_seconds = time.perf_counter() - start

    trace = None
    if trace_lists is not None:
        trace = {
            k: np.asarray(v, dtype=np.float32)
            for k, v in trace_lists.items()
        }

    history = RunHistory(
        objective=gap_hist,
        consensus_error=cons_hist if track_consensus else None,
        time=time_hist,
        time_measured=True,  # real per-eval perf_counter samples
        eval_iterations=np.arange(eval_every, T + 1, eval_every),
        # Honest comms accounting under faults: floats actually exchanged
        # over realized edges (same edge payload convention as the jax
        # backend's realized_degree_sum path).
        total_floats_transmitted=(
            realized_degree_total * d * algo.gossip_rounds
            if faults_active
            else floats_per_iter * T
        ),
        iters_per_second=T / run_seconds if run_seconds > 0 else float("inf"),
        spectral_gap=spectral_gap,
        trace=trace,
    )
    final = state["x"]
    return BackendRunResult(
        history=history,
        final_models=final,
        final_avg_model=(
            honest_mean(final, byz) if byz is not None else final.mean(axis=0)
        ),
    )
