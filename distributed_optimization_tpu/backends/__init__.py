"""Execution backends: 'jax' (TPU/XLA north star) and 'numpy' (fidelity oracle)."""

from distributed_optimization_tpu.backends.base import BackendRunResult, run_algorithm  # noqa: F401
