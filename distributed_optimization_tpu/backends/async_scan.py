"""Scan-over-events execution: the jax backend's asynchronous gossip path.

Where the synchronous paths scan over ROUNDS (every iteration advances all
N workers behind a barrier), this path scans over the EVENTS of a
precomputed ``parallel/events.py`` timeline: each scan trip is one
worker's local D-PSGD update at its realized staleness plus a
pairwise-average gossip exchange — AD-PSGD-style asynchronous
decentralized SGD (Lian et al. '17) with stragglers modeled as LATENCY in
the schedule rather than as dropped rounds.

Execution shape: the event schedule is static host data (pure in
(topology, horizon, seed, latency model) — the ``build_fault_timeline``
trick), threaded through jit as arrays, so the whole run compiles to ONE
XLA program: an outer ``lax.scan`` over eval chunks whose body scans the
chunk's ``eval_every * N`` events and computes the full-data metrics once,
exactly on cadence. Per-event work is O(b·d + d): a single-worker batch
gather, one gradient, and two dynamic row writes — there is no [N, N]
object and no per-event host sync anywhere.

Staleness mechanics inside the scan: the carry holds the live model stack
``x`` AND the per-worker read snapshots ``x_read`` (the model each worker
captured when it started its in-flight gradient). An event's gradient is
evaluated at ``x_read[i]`` while the averaging acts on the LIVE rows —
the gap between the two is exactly the realized staleness the timeline
records per event (surfaced as a histogram in ``health_summary``).

Resume-exactness: the timeline is rebuilt identically from the config,
batch draws are counter-based in (seed, worker, local_step), and the
carry is just ``{x, x_read}`` — so a run split at any eval boundary via
``state0``/``start_event`` replays the identical tail events bitwise
(tests/test_async.py pins it through a save/restore round-trip on both
backends).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.backends.base import BackendRunResult
from distributed_optimization_tpu.metrics import RunHistory
from distributed_optimization_tpu.models import get_problem
from distributed_optimization_tpu.ops.sampling import sample_batch_indices
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel.events import build_event_timeline
from distributed_optimization_tpu.serving.cache import (
    resolve_cache,
    sequential_cache_key,
)
from distributed_optimization_tpu.telemetry import cost_from_lowered
from distributed_optimization_tpu.utils.data import HostDataset, stack_shards

# PRNG stream tag for the event path's batch draws: per-event keys are
# fold_in(fold_in(fold_in(key(seed), TAG), worker), local_step) — a
# distinct stream from every synchronous sampler, counter-based in the
# worker's OWN step count so a draw never depends on the interleaving.
_ASYNC_BATCH_TAG = 0xA57E


@functools.lru_cache(maxsize=8)
def _cached_timeline(
    topology, n, er_p, topo_seed, horizon, seed, latency_model,
    latency_mean, latency_tail,
):
    topo = build_topology(
        topology, n, erdos_renyi_p=er_p, seed=topo_seed,
    )
    return topo, build_event_timeline(
        topo, horizon, seed,
        latency_model=latency_model, latency_mean=latency_mean,
        latency_tail=latency_tail,
    )


def timeline_for(config):
    """The event timeline this config's async run executes — identical for
    the backends, the telemetry health block, and the bench (the
    (seed, horizon)-pure contract). Schedules are deterministic in the
    key below, so a small LRU makes the rebuilds one run triggers (jax or
    numpy execution, then the health/RunTrace derivation, possibly a
    serving manifest) share ONE build of the O(E) host unroll."""
    return _cached_timeline(
        config.topology, config.n_workers, config.erdos_renyi_p,
        config.resolved_topology_seed(), config.n_iterations, config.seed,
        config.latency_model, config.latency_mean, config.latency_tail,
    )


def _validate_slice(config, E: int, start_event: int, n_events: Optional[int]):
    """Resolve and validate the executed [start, start+n) event window.

    Eval boundaries are every ``eval_every * N`` events, so both ends must
    land on one — otherwise the continuation's metric rows would not line
    up with the one-shot run's.
    """
    n = config.n_workers
    events_per_eval = config.eval_every * n
    if n_events is None:
        n_events = E - start_event
    if not 0 <= start_event < E or start_event + n_events > E or n_events <= 0:
        raise ValueError(
            f"event window [{start_event}, {start_event + n_events}) is "
            f"outside the schedule's {E} events"
        )
    if start_event % events_per_eval or n_events % events_per_eval:
        raise ValueError(
            f"event window must align to eval boundaries "
            f"(eval_every * N = {events_per_eval} events): got start="
            f"{start_event}, length={n_events}"
        )
    return n_events, events_per_eval


def _async_progress_emitter(config, progress_cb, timeline, start_event):
    """Heartbeat closure for the event path: realized staleness quantiles
    over the executed window (the live form of the ``async_summary``
    health block) ride every event, and the chunk's staleness slice is
    bulk-observed into the process metrics registry
    (``dopt_async_staleness`` / ``dopt_async_events_total``)."""
    from distributed_optimization_tpu.log import get_logger
    from distributed_optimization_tpu.observability.metrics_registry import (
        metrics_registry,
    )
    from distributed_optimization_tpu.observability.progress import (
        ProgressEvent,
        progress_heartbeat_counter,
    )

    log = get_logger("progress")
    counter = progress_heartbeat_counter()
    reg = metrics_registry()
    ev_total = reg.counter(
        "dopt_async_events_total",
        "Asynchronous gossip events executed",
    )
    stale_hist = reg.histogram(
        "dopt_async_staleness",
        "Realized per-event staleness (writes between read and fire)",
        buckets=(0, 1, 2, 4, 8, 16, 32, 64),
    )
    E_total = timeline.n_events

    def emit(events_done, rounds_done, gap, cons, elapsed, chunk_events):
        lo = start_event + events_done - chunk_events
        hi = start_event + events_done
        window = np.asarray(
            timeline.staleness[start_event:hi], dtype=np.float64
        )
        ev_total.inc(chunk_events)
        stale_hist.observe_many(timeline.staleness[lo:hi])
        ev = ProgressEvent(
            kind="async",
            iteration=int(rounds_done),
            n_iterations=int(timeline.n_rounds),
            wall_seconds=float(elapsed),
            gap=gap,
            consensus=cons,
            event_index=int(hi),
            n_events=int(E_total),
            staleness_p50=float(np.percentile(window, 50)),
            staleness_p90=float(np.percentile(window, 90)),
            staleness_max=float(window.max()),
        )
        counter.inc()
        try:
            progress_cb(ev)
        except Exception:  # observability never kills the run
            log.exception("progress callback failed; continuing run")

    return emit


def run_async(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    batch_schedule: Optional[np.ndarray] = None,
    collect_metrics: bool = True,
    measure_compile: bool = True,
    return_state: bool = False,
    state0: Optional[dict] = None,
    start_event: int = 0,
    n_events: Optional[int] = None,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BackendRunResult:
    """Run one asynchronous experiment (``config.execution == 'async'``).

    ``progress_cb``/``progress_every`` (ISSUE-10; host-loop granularity
    fixed in ISSUE-13): when set, the run executes as SEGMENTS of
    ``progress_every`` eval chunks, each segment one compiled call of the
    SAME outer-scan body the fused program runs (the event arrays are
    traced inputs, so one executable serves every same-size segment),
    with one ``ProgressEvent`` per boundary carrying live staleness
    quantiles over the executed window. The host syncs once per
    HEARTBEAT, not once per eval chunk — the original per-chunk loop
    measured 12.3% overhead on the bench container
    (docs/perf/observatory.json pre-fix), the segmented form is gated at
    ≤5%. ``None`` changes nothing (one fused program).

    ``monitors`` (ISSUE-13): a ``MonitorBank`` joining the heartbeat
    chain (staleness blowup, divergence, non-finite sentinels); under
    ``halt_on='fatal'`` the run stops at the next segment boundary with
    the executed prefix as a partial result.

    ``batch_schedule [E_total, b]`` injects fixed per-EVENT batch indices
    into the firing worker's shard (the oracle-equivalence convention —
    the async twin of the synchronous ``[T, N, b]`` schedule).
    ``state0``/``start_event``/``n_events`` continue a previous slice from
    its ``final_state`` ({x, x_read} leaves): the schedule and the
    counter-based batch draws are functions of the config alone, so the
    continuation is exactly the one-shot program split in two (bitwise —
    the resume-exactness contract). ``executable_cache`` follows the
    sequential path's convention (docs/SERVING.md); the window facts are
    part of the key.
    """
    from distributed_optimization_tpu.backends.base import x64_scope

    with x64_scope(config):
        return _run_async(
            config, dataset, f_opt, batch_schedule=batch_schedule,
            collect_metrics=collect_metrics,
            measure_compile=measure_compile, return_state=return_state,
            state0=state0, start_event=start_event, n_events=n_events,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors,
        )


def _run_async(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    batch_schedule,
    collect_metrics: bool,
    measure_compile: bool,
    return_state: bool,
    state0,
    start_event: int,
    n_events,
    executable_cache,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BackendRunResult:
    if progress_every < 1:
        raise ValueError(
            f"progress_every must be >= 1 eval-chunks, got {progress_every}"
        )
    problem = get_problem(
        config.problem_type, huber_delta=config.huber_delta,
        n_classes=config.n_classes,
    )
    reg = config.reg_param
    n = config.n_workers
    device_data = stack_shards(dataset, dtype=np.dtype(config.dtype))
    d_model = problem.param_dim(device_data.n_features)
    dtype = device_data.X.dtype

    topo, timeline = timeline_for(config)
    E = timeline.n_events
    n_events, events_per_eval = _validate_slice(
        config, E, start_event, n_events
    )
    n_evals = n_events // events_per_eval
    rounds_slice = n_events // n
    start_round = start_event // n

    sl = slice(start_event, start_event + n_events)
    ev_chunks = {
        "worker": jnp.asarray(
            timeline.worker[sl].reshape(n_evals, events_per_eval)
        ),
        "partner": jnp.asarray(
            timeline.partner[sl].reshape(n_evals, events_per_eval)
        ),
        "local_step": jnp.asarray(
            timeline.local_step[sl].reshape(n_evals, events_per_eval)
        ),
    }
    sched_sig = None
    if batch_schedule is not None:
        batch_schedule = np.asarray(batch_schedule)
        if batch_schedule.shape[0] != E:
            raise ValueError(
                f"async batch_schedule carries {batch_schedule.shape[0]} "
                f"event rows; the schedule has {E} events (one [b] index "
                "row per event into the firing worker's shard)"
            )
        ev_chunks["schedule"] = jnp.asarray(
            batch_schedule[sl].reshape(
                n_evals, events_per_eval, batch_schedule.shape[1]
            ),
            dtype=jnp.int32,
        )
        sched_sig = tuple(batch_schedule.shape)

    # --- initial carry ------------------------------------------------
    x0 = jnp.zeros((n, d_model), dtype=dtype)
    if state0 is None:
        if start_event != 0:
            raise ValueError(
                "continuing from start_event > 0 needs the previous "
                "slice's final_state ({x, x_read}) as state0"
            )
        st0 = {"x": x0, "x_read": x0}
    else:
        if set(state0) != {"x", "x_read"}:
            raise ValueError(
                f"async state0 leaves {sorted(state0)} do not match the "
                "event-path carry ['x', 'x_read']"
            )
        st0 = {
            k: jnp.asarray(v).astype(dtype) for k, v in state0.items()
        }
        for k, v in st0.items():
            if v.shape != (n, d_model):
                raise ValueError(
                    f"state0[{k!r}] has shape {v.shape}; expected "
                    f"{(n, d_model)}"
                )

    from distributed_optimization_tpu.backends.jax_backend import (
        _make_eta_fn,
        make_full_objective_fn,
    )

    eta_fn = _make_eta_fn(config)
    full_objective = make_full_objective_fn(problem, reg)
    batch_size = config.local_batch_size
    L = device_data.X.shape[1]
    full_batch = batch_schedule is None and batch_size >= L
    track_consensus = collect_metrics and config.record_consensus
    key = jax.random.fold_in(jax.random.key(config.seed), _ASYNC_BATCH_TAG)

    data_args = {
        "X": jnp.asarray(device_data.X),
        "y": jnp.asarray(device_data.y),
        "n_valid": jnp.asarray(device_data.n_valid),
        "ev": ev_chunks,
    }

    def make_chunk_body(data):
        X, y, n_valid = data["X"], data["y"], data["n_valid"]

        def event_grad(x_read_i, ev):
            i, k = ev["worker"], ev["local_step"]
            Xi, yi, ni = X[i], y[i], n_valid[i]
            if "schedule" in ev:
                idx = ev["schedule"]
                Xb, yb = Xi[idx], yi[idx]
                wts = jnp.full(
                    idx.shape, 1.0 / idx.shape[0], dtype=dtype
                )
            elif full_batch:
                mask = (jnp.arange(L) < ni).astype(dtype)
                wts = mask / jnp.maximum(ni.astype(dtype), 1.0)
                Xb, yb = Xi, yi
            else:
                wkey = jax.random.fold_in(jax.random.fold_in(key, i), k)
                idx, w = sample_batch_indices(wkey, L, ni, batch_size)
                Xb, yb = Xi[idx], yi[idx]
                wts = w.astype(dtype)
            return problem.gradient_weighted(x_read_i, Xb, yb, wts, reg)

        def event_step(carry, ev):
            x, x_read = carry["x"], carry["x_read"]
            i, j = ev["worker"], ev["partner"]
            g = event_grad(x_read[i], ev)
            eta = eta_fn(ev["local_step"]).astype(dtype)
            xi, xj = x[i], x[j]
            matched = j != i
            avg = (0.5 * (xi + xj)).astype(dtype)
            # D-PSGD ordering (Lian et al. '17 Alg. 1): average the live
            # rows, then worker i descends along its (stale) gradient;
            # the passive partner only averages. Writing j before i keeps
            # the solo case (j == i, isolated node) a plain local step.
            new_i = (jnp.where(matched, avg, xi) - eta * g).astype(dtype)
            new_j = jnp.where(matched, avg, xj)
            x = x.at[j].set(new_j)
            x = x.at[i].set(new_i)
            # Worker i immediately re-reads and starts its next gradient.
            x_read = x_read.at[i].set(new_i)
            return {"x": x, "x_read": x_read}, None

        def chunk_body(carry, ev_row):
            carry, _ = jax.lax.scan(event_step, carry, ev_row)
            out = {}
            if collect_metrics:
                x = carry["x"]
                xbar = jnp.mean(x, axis=0)
                out["gap"] = full_objective(xbar, X, y, n_valid) - f_opt
                if track_consensus:
                    out["cons"] = jnp.mean(
                        jnp.sum((x - xbar[None, :]) ** 2, axis=1)
                    )
            return carry, out

        return chunk_body

    def run_scan(state, data):
        return jax.lax.scan(make_chunk_body(data), state, data["ev"])

    exec_cache = resolve_cache(executable_cache)
    n_done_evals = n_evals
    if progress_cb is not None or monitors is not None:
        # Progress streaming (ISSUE-10; segment-fused in ISSUE-13): the
        # run executes as SEGMENTS of ``progress_every`` eval chunks,
        # each segment one compiled call of the SAME outer scan over its
        # chunk rows — the event arrays are traced inputs, so one
        # executable serves every same-size segment, and the per-segment
        # scans compose to exactly the fused program's computation
        # (bitwise, asserted in tests/test_observatory.py /
        # tests/test_monitors.py). The host syncs once per heartbeat
        # instead of once per chunk — the ISSUE-10 per-chunk loop's
        # measured 12.3% overhead was pure dispatch latency this buys
        # back (docs/perf/observatory.json).
        from distributed_optimization_tpu.backends.jax_backend import (
            _fanout_progress,
        )

        cb = _fanout_progress(progress_cb, monitors)
        emit = _async_progress_emitter(config, cb, timeline, start_event)
        halt_check = (
            monitors.should_halt
            if monitors is not None and monitors.halt_on != "never"
            else None
        )
        seg_chunks = min(max(int(progress_every), 1), n_evals)
        sizes = {seg_chunks}
        if n_evals % seg_chunks:
            sizes.add(n_evals % seg_chunks)

        def seg_scan(state, data):
            return jax.lax.scan(make_chunk_body(data), state, data["ev"])

        compiled_by_size = {}
        compile_seconds = 0.0
        for size in sorted(sizes):
            cache_key = cached = None
            if exec_cache is not None:
                cache_key = sequential_cache_key(
                    config, f_opt, device_data,
                    schedule_signature=(
                        "async-seg", events_per_eval, int(size), sched_sig,
                    ),
                    collect_metrics=collect_metrics,
                )
                cached = exec_cache.get(cache_key)
            if cached is not None:
                compiled_by_size[size] = cached.executable
                continue
            data_c = dict(data_args)
            data_c["ev"] = {k: v[:size] for k, v in ev_chunks.items()}
            t0c = time.perf_counter()
            with jax.default_matmul_precision(config.matmul_precision):
                lowered = jax.jit(seg_scan).lower(st0, data_c)
                cost = cost_from_lowered(lowered)
                compiled_by_size[size] = lowered.compile()
            cold_seconds = time.perf_counter() - t0c
            if measure_compile:
                compile_seconds += cold_seconds
            if exec_cache is not None:
                exec_cache.put(
                    cache_key, compiled_by_size[size], cost=cost,
                    compile_seconds=cold_seconds,
                )

        t1 = time.perf_counter()
        state = st0
        gap_list: list[float] = []
        cons_list: list[float] = []
        done = 0
        while done < n_evals:
            this_chunks = min(seg_chunks, n_evals - done)
            data_c = dict(data_args)
            data_c["ev"] = {
                k: v[done:done + this_chunks] for k, v in ev_chunks.items()
            }
            state, outs = compiled_by_size[this_chunks](state, data_c)
            jax.block_until_ready(state)
            if "gap" in outs:
                gap_list.extend(
                    float(g) for g in np.asarray(outs["gap"])
                )
            if "cons" in outs:
                cons_list.extend(
                    float(c) for c in np.asarray(outs["cons"])
                )
            done += this_chunks
            emit(
                done * events_per_eval,
                start_round + done * config.eval_every,
                gap_list[-1] if gap_list else None,
                cons_list[-1] if cons_list else None,
                time.perf_counter() - t1,
                this_chunks * events_per_eval,
            )
            if halt_check is not None and halt_check():
                # Early-halt policy (ISSUE-13): stop at this segment
                # boundary; the executed event prefix is the fused
                # program's prefix (the continuation contract).
                break
        final_state = state
        run_seconds = time.perf_counter() - t1
        n_done_evals = done
        if monitors is not None and done < n_evals:
            monitors.note_halt(
                start_round + done * config.eval_every
            )
        gap_hist = (
            np.asarray(gap_list, dtype=np.float64)
            if gap_list else np.full(n_done_evals, np.nan)
        )
        cons_hist = (
            np.asarray(cons_list, dtype=np.float64) if cons_list else None
        )
    else:
        # AOT compile with the sequential path's cache convention: the
        # event arrays and the carry are traced inputs, so the key only
        # needs the full config hash + the window/schedule trace facts.
        cache_key = cached = None
        if exec_cache is not None:
            cache_key = sequential_cache_key(
                config, f_opt, device_data,
                schedule_signature=(
                    "async", start_event, n_events, state0 is not None,
                    sched_sig,
                ),
                collect_metrics=collect_metrics,
            )
            cached = exec_cache.get(cache_key)
        if cached is not None:
            compiled = cached.executable
            compile_seconds = 0.0
        else:
            t0c = time.perf_counter()
            with jax.default_matmul_precision(config.matmul_precision):
                lowered = jax.jit(run_scan).lower(st0, data_args)
                cost = cost_from_lowered(lowered)
                compiled = lowered.compile()
            cold_seconds = time.perf_counter() - t0c
            compile_seconds = cold_seconds if measure_compile else 0.0
            if exec_cache is not None:
                exec_cache.put(
                    cache_key, compiled, cost=cost,
                    compile_seconds=cold_seconds,
                )

        t1 = time.perf_counter()
        final_state, ys = compiled(st0, data_args)
        final_state = jax.block_until_ready(final_state)
        run_seconds = time.perf_counter() - t1

        gap_hist = (
            np.asarray(ys["gap"], dtype=np.float64)
            if "gap" in ys else np.full(n_evals, np.nan)
        )
        cons_hist = (
            np.asarray(ys["cons"], dtype=np.float64) if "cons" in ys else None
        )
    # Comms accounting: every matched event moves one pairwise exchange —
    # both models cross the wire, 2·d floats (a solo event moves none).
    # Halted runs bill only the executed event prefix.
    done_events = n_done_evals * events_per_eval
    done_rounds = done_events // n
    sl_done = slice(start_event, start_event + done_events)
    matched_slice = int(np.sum(timeline.matched()[sl_done]))
    total_floats = 2.0 * d_model * matched_slice

    history = RunHistory(
        objective=gap_hist,
        consensus_error=cons_hist,
        time=np.linspace(
            run_seconds / max(n_done_evals, 1), run_seconds, n_done_evals
        ),
        time_measured=False,
        # Round-based iteration numbering (N events per round), so
        # iters-to-ε stays comparable with the synchronous paths.
        eval_iterations=np.arange(
            start_round + config.eval_every,
            start_round + done_rounds + 1,
            config.eval_every,
        ),
        total_floats_transmitted=total_floats,
        iters_per_second=(
            done_rounds / run_seconds if run_seconds > 0 else float("nan")
        ),
        compile_seconds=compile_seconds,
        spectral_gap=topo.spectral_gap,
    )
    final_models = np.asarray(final_state["x"]).astype(np.float64)
    return BackendRunResult(
        history=history,
        final_models=final_models,
        final_avg_model=final_models.mean(axis=0),
        final_state=(
            {
                k: np.asarray(v).astype(np.float64)
                for k, v in final_state.items()
            }
            if return_state
            else None
        ),
    )
