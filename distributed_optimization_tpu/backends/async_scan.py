"""Scan-over-events execution: the jax backend's asynchronous gossip path.

Where the synchronous paths scan over ROUNDS (every iteration advances all
N workers behind a barrier), this path scans over the EVENTS of a
precomputed ``parallel/events.py`` timeline: each scan trip is one
worker's local D-PSGD update at its realized staleness plus a
pairwise-average gossip exchange — AD-PSGD-style asynchronous
decentralized SGD (Lian et al. '17) with stragglers modeled as LATENCY in
the schedule rather than as dropped rounds.

Execution shape: the event schedule is static host data (pure in
(topology, horizon, seed, latency model) — the ``build_fault_timeline``
trick), threaded through jit as arrays, so the whole run compiles to ONE
XLA program: an outer ``lax.scan`` over eval chunks whose body scans the
chunk's ``eval_every * N`` events and computes the full-data metrics once,
exactly on cadence. Per-event work is O(b·d + d): a single-worker batch
gather, one gradient, and two dynamic row writes — there is no [N, N]
object and no per-event host sync anywhere.

Staleness mechanics inside the scan: the carry holds the live model stack
``x`` AND the per-worker read snapshots ``x_read`` (the model each worker
captured when it started its in-flight gradient). An event's gradient is
evaluated at ``x_read[i]`` while the averaging acts on the LIVE rows —
the gap between the two is exactly the realized staleness the timeline
records per event (surfaced as a histogram in ``health_summary``).

Fault processes on the event clock (ISSUE-17): when the config's
round-indexed fault knobs are active, the SAME chains
``timeline_for_config`` builds for the synchronous paths are realized on
the event axis by ``parallel.events.realize_event_faults`` — a crashed
worker's event fires as a NO-OP (the in-flight gradient is lost, the
pairing partner degrades to a self-loop), a sampled-out worker's events
are thinned at the matched per-round rate, dead edges degrade the
exchange, and recovery re-enters under the PR 3 rejoin policies
(``frozen`` resumes the pre-crash row; ``neighbor_restart`` warm-starts
from the realized alive neighborhood average). At constant latency the
event realization collapses BITWISE onto the round-clock realization
(tests pin it), and with every knob off the fault arrays are never
threaded at all — the compiled program is literally the healthy one.

Gradient tracking per event (DIGing, Nedić/Olshevsky/Shi '17): the carry
gains tracker rows ``y`` and last-reported gradients ``g_prev``; an
event's initiator refreshes its tracker by telescoping its new stale-read
gradient against the previous one (``y_i ← avg_y + g(x_read_i) −
g_prev_i``) so the network mean of ``y`` equals the mean of ``g_prev``
EXACTLY at every event, at any staleness and under any fault composition
(the tracking invariant the tests pin; the bench records how far the
tracked mean drifts from the LIVE mean gradient as staleness grows).

Resume-exactness: the timeline is rebuilt identically from the config,
batch draws are counter-based in (seed, worker, local_step[, local
descent]), and the carry is just the algorithm state — so a run split at
any eval boundary via ``state0``/``start_event`` (or an event-indexed
``RunCheckpointer`` chunk) replays the identical tail events bitwise
(tests/test_async.py pins it through a save/restore round-trip on both
backends).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.backends.base import BackendRunResult
from distributed_optimization_tpu.metrics import RunHistory
from distributed_optimization_tpu.models import get_problem
from distributed_optimization_tpu.ops.sampling import sample_batch_indices
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel.events import build_event_timeline
from distributed_optimization_tpu.serving.cache import (
    resolve_cache,
    sequential_cache_key,
)
from distributed_optimization_tpu.telemetry import cost_from_lowered
from distributed_optimization_tpu.utils.data import HostDataset, stack_shards

# PRNG stream tag for the event path's batch draws: per-event keys are
# fold_in(fold_in(fold_in(key(seed), TAG), worker), local_step) — a
# distinct stream from every synchronous sampler, counter-based in the
# worker's OWN step count so a draw never depends on the interleaving.
# With local_steps τ > 1 the m-th local descent (m = 0..τ−1) folds m in
# once more; τ = 1 keeps the original unfolded key so the healthy program
# is bitwise the PR 9 one.
_ASYNC_BATCH_TAG = 0xA57E


@functools.lru_cache(maxsize=8)
def _cached_timeline(
    topology, n, er_p, topo_seed, horizon, seed, latency_model,
    latency_mean, latency_tail, gossip_schedule,
):
    topo = build_topology(
        topology, n, erdos_renyi_p=er_p, seed=topo_seed,
    )
    return topo, build_event_timeline(
        topo, horizon, seed,
        latency_model=latency_model, latency_mean=latency_mean,
        latency_tail=latency_tail, gossip_schedule=gossip_schedule,
    )


def timeline_for(config):
    """The event timeline this config's async run executes — identical for
    the backends, the telemetry health block, and the bench (the
    (seed, horizon)-pure contract). Schedules are deterministic in the
    key below, so a small LRU makes the rebuilds one run triggers (jax or
    numpy execution, then the health/RunTrace derivation, possibly a
    serving manifest) share ONE build of the O(E) host unroll."""
    return _cached_timeline(
        config.topology, config.n_workers, config.erdos_renyi_p,
        config.resolved_topology_seed(), config.n_iterations, config.seed,
        config.latency_model, config.latency_mean, config.latency_tail,
        config.gossip_schedule,
    )


def event_faults_for(config, topo, timeline, fault_timeline=None):
    """Realize the config's fault chains on the event axis.

    Returns ``(fault_timeline, realization, restart_rows)`` —
    ``(None, None, None)`` when no fault knob is active, so the healthy
    path never threads fault arrays at all (the crash-free bitwise
    contract is structural, not numeric). ``fault_timeline`` overrides
    the config-derived chains (the equivalence tests inject hand-built
    masks); ``restart_rows`` is the ``[E, N]`` warm-restart weight table,
    present only under ``rejoin='neighbor_restart'`` with realized rejoin
    events.
    """
    from distributed_optimization_tpu.parallel.events import (
        realize_event_faults,
        rejoin_restart_rows,
    )
    from distributed_optimization_tpu.parallel.faults import (
        config_faults_active,
        timeline_for_config,
    )

    if fault_timeline is None:
        if not config_faults_active(config):
            return None, None, None
        fault_timeline = timeline_for_config(
            config, topo, timeline.n_rounds
        )
    realization = realize_event_faults(timeline, fault_timeline)
    restart = None
    if config.rejoin == "neighbor_restart" and bool(
        realization.rejoin.any()
    ):
        restart = rejoin_restart_rows(
            timeline, fault_timeline, realization, topo
        )
    return fault_timeline, realization, restart


def _validate_slice(config, E: int, start_event: int, n_events: Optional[int]):
    """Resolve and validate the executed [start, start+n) event window.

    Eval boundaries are every ``eval_every * N`` events, so both ends must
    land on one — otherwise the continuation's metric rows would not line
    up with the one-shot run's.
    """
    n = config.n_workers
    events_per_eval = config.eval_every * n
    if n_events is None:
        n_events = E - start_event
    if not 0 <= start_event < E or start_event + n_events > E or n_events <= 0:
        raise ValueError(
            f"event window [{start_event}, {start_event + n_events}) is "
            f"outside the schedule's {E} events"
        )
    if start_event % events_per_eval or n_events % events_per_eval:
        raise ValueError(
            f"event window must align to eval boundaries "
            f"(eval_every * N = {events_per_eval} events): got start="
            f"{start_event}, length={n_events}"
        )
    return n_events, events_per_eval


def _async_progress_emitter(config, progress_cb, timeline, start_event):
    """Heartbeat closure for the event path: realized staleness quantiles
    over the executed window (the live form of the ``async_summary``
    health block) ride every event, and the chunk's staleness slice is
    bulk-observed into the process metrics registry
    (``dopt_async_staleness`` / ``dopt_async_events_total``)."""
    from distributed_optimization_tpu.log import get_logger
    from distributed_optimization_tpu.observability.metrics_registry import (
        metrics_registry,
    )
    from distributed_optimization_tpu.observability.progress import (
        ProgressEvent,
        progress_heartbeat_counter,
    )

    log = get_logger("progress")
    counter = progress_heartbeat_counter()
    reg = metrics_registry()
    ev_total = reg.counter(
        "dopt_async_events_total",
        "Asynchronous gossip events executed",
    )
    stale_hist = reg.histogram(
        "dopt_async_staleness",
        "Realized per-event staleness (writes between read and fire)",
        buckets=(0, 1, 2, 4, 8, 16, 32, 64),
    )
    E_total = timeline.n_events

    def emit(events_done, rounds_done, gap, cons, elapsed, chunk_events):
        lo = start_event + events_done - chunk_events
        hi = start_event + events_done
        window = np.asarray(
            timeline.staleness[start_event:hi], dtype=np.float64
        )
        ev_total.inc(chunk_events)
        stale_hist.observe_many(timeline.staleness[lo:hi])
        ev = ProgressEvent(
            kind="async",
            iteration=int(rounds_done),
            n_iterations=int(timeline.n_rounds),
            wall_seconds=float(elapsed),
            gap=gap,
            consensus=cons,
            event_index=int(hi),
            n_events=int(E_total),
            staleness_p50=float(np.percentile(window, 50)),
            staleness_p90=float(np.percentile(window, 90)),
            staleness_max=float(window.max()),
        )
        counter.inc()
        try:
            progress_cb(ev)
        except Exception:  # observability never kills the run
            log.exception("progress callback failed; continuing run")

    return emit


def run_async(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    batch_schedule: Optional[np.ndarray] = None,
    collect_metrics: bool = True,
    measure_compile: bool = True,
    return_state: bool = False,
    state0: Optional[dict] = None,
    start_event: int = 0,
    n_events: Optional[int] = None,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
    checkpoint=None,
    _fault_timeline=None,
) -> BackendRunResult:
    """Run one asynchronous experiment (``config.execution == 'async'``).

    ``progress_cb``/``progress_every`` (ISSUE-10; host-loop granularity
    fixed in ISSUE-13): when set, the run executes as SEGMENTS of
    ``progress_every`` eval chunks, each segment one compiled call of the
    SAME outer-scan body the fused program runs (the event arrays are
    traced inputs, so one executable serves every same-size segment),
    with one ``ProgressEvent`` per boundary carrying live staleness
    quantiles over the executed window. The host syncs once per
    HEARTBEAT, not once per eval chunk — the original per-chunk loop
    measured 12.3% overhead on the bench container
    (docs/perf/observatory.json pre-fix), the segmented form is gated at
    ≤5%. ``None`` changes nothing (one fused program).

    ``monitors`` (ISSUE-13): a ``MonitorBank`` joining the heartbeat
    chain (staleness blowup, divergence, non-finite sentinels); under
    ``halt_on='fatal'`` the run stops at the next segment boundary with
    the executed prefix as a partial result.

    ``checkpoint`` (ISSUE-17): a ``utils.checkpoint.CheckpointOptions``;
    the run then executes through the segmented machinery saving one
    event-indexed ``RunCheckpointer`` chunk every ``every_evals`` eval
    boundaries (chunk cursor = eval rows done = ``eval_every * N`` events
    each), and ``resume=True`` restores the latest intact chunk (the PR 3
    truncated-chunk fallback) and replays the tail bitwise — the
    schedule, fault realization, and counter-based batch draws all
    rebuild from the config alone.

    ``batch_schedule`` injects fixed per-EVENT batch indices into the
    firing worker's shard (the oracle-equivalence convention — the async
    twin of the synchronous ``[T, N, b]`` schedule): ``[E_total, b]``
    rows, or ``[E_total, τ, b]`` when ``local_steps=τ > 1`` (one row per
    local descent). ``state0``/``start_event``/``n_events`` continue a
    previous slice from its ``final_state`` leaves: the continuation is
    exactly the one-shot program split in two (bitwise — the
    resume-exactness contract). ``executable_cache`` follows the
    sequential path's convention (docs/SERVING.md); the window facts are
    part of the key. ``_fault_timeline`` injects a hand-built
    ``FaultTimeline`` in place of the config-derived chains
    (equivalence tests only; disables the executable cache).
    """
    from distributed_optimization_tpu.backends.base import x64_scope

    with x64_scope(config):
        return _run_async(
            config, dataset, f_opt, batch_schedule=batch_schedule,
            collect_metrics=collect_metrics,
            measure_compile=measure_compile, return_state=return_state,
            state0=state0, start_event=start_event, n_events=n_events,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors, checkpoint=checkpoint,
            _fault_timeline=_fault_timeline,
        )


def _run_async(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    batch_schedule,
    collect_metrics: bool,
    measure_compile: bool,
    return_state: bool,
    state0,
    start_event: int,
    n_events,
    executable_cache,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
    checkpoint=None,
    _fault_timeline=None,
) -> BackendRunResult:
    if progress_every < 1:
        raise ValueError(
            f"progress_every must be >= 1 eval-chunks, got {progress_every}"
        )
    if checkpoint is not None:
        if config.telemetry:
            raise ValueError(
                "telemetry trace buffers are not checkpointed: a resumed "
                "run would report a hole — run telemetry without "
                "checkpointing, or checkpoint without telemetry"
            )
        if state0 is not None or start_event != 0:
            raise ValueError(
                "checkpointed async runs manage their own continuation "
                "cursor (the RunCheckpointer chunk); don't combine "
                "checkpoint= with state0/start_event"
            )
    problem = get_problem(
        config.problem_type, huber_delta=config.huber_delta,
        n_classes=config.n_classes,
    )
    reg = config.reg_param
    n = config.n_workers
    device_data = stack_shards(dataset, dtype=np.dtype(config.dtype))
    d_model = problem.param_dim(device_data.n_features)
    dtype = device_data.X.dtype

    topo, timeline = timeline_for(config)
    E = timeline.n_events
    n_events, events_per_eval = _validate_slice(
        config, E, start_event, n_events
    )
    n_evals = n_events // events_per_eval
    start_round = start_event // n

    algo_gt = config.algorithm == "gradient_tracking"
    tau = int(config.local_steps)
    telemetry_on = bool(config.telemetry)

    # Event-axis fault realization (None triple when every knob is off —
    # the healthy program then never sees a fault array: the crash-free
    # bitwise gate is structural).
    _, fault_real, restart_rows = event_faults_for(
        config, topo, timeline, _fault_timeline
    )
    faults_on = fault_real is not None
    restart_on = restart_rows is not None

    sl = slice(start_event, start_event + n_events)
    partner_src = fault_real.partner if faults_on else timeline.partner
    ev_chunks = {
        "worker": jnp.asarray(
            timeline.worker[sl].reshape(n_evals, events_per_eval)
        ),
        "partner": jnp.asarray(
            partner_src[sl].reshape(n_evals, events_per_eval)
        ),
        "local_step": jnp.asarray(
            timeline.local_step[sl].reshape(n_evals, events_per_eval)
        ),
    }
    if faults_on:
        ev_chunks["fire"] = jnp.asarray(
            fault_real.fire[sl].reshape(n_evals, events_per_eval)
        )
    if restart_on:
        ev_chunks["rejoin"] = jnp.asarray(
            fault_real.rejoin[sl].reshape(n_evals, events_per_eval)
        )
        ev_chunks["restart_w"] = jnp.asarray(
            restart_rows[sl].reshape(n_evals, events_per_eval, n),
            dtype=dtype,
        )
    sched_sig = None
    if batch_schedule is not None:
        batch_schedule = np.asarray(batch_schedule)
        if batch_schedule.shape[0] != E:
            raise ValueError(
                f"async batch_schedule carries {batch_schedule.shape[0]} "
                f"event rows; the schedule has {E} events (one index "
                "row per event into the firing worker's shard)"
            )
        if tau == 1:
            if batch_schedule.ndim != 2:
                raise ValueError(
                    f"async batch_schedule must be [E, b] at local_steps="
                    f"1; got shape {batch_schedule.shape}"
                )
        elif batch_schedule.ndim != 3 or batch_schedule.shape[1] != tau:
            raise ValueError(
                f"async batch_schedule must be [E, {tau}, b] at "
                f"local_steps={tau} (one [b] row per local descent); got "
                f"shape {batch_schedule.shape}"
            )
        ev_chunks["schedule"] = jnp.asarray(
            batch_schedule[sl].reshape(
                (n_evals, events_per_eval) + batch_schedule.shape[1:]
            ),
            dtype=jnp.int32,
        )
        sched_sig = tuple(batch_schedule.shape)

    # --- initial carry ------------------------------------------------
    # The algorithm leaves are the resume-contract surface; the telemetry
    # scratch row g_norm (last fired gradient norm per worker) is carried
    # too but excluded from state0/final_state — it feeds the trace
    # buffers only and never touches the optimization dataflow.
    x0 = jnp.zeros((n, d_model), dtype=dtype)
    carry_leaves = ("x", "x_read") + (
        ("y", "g_prev") if algo_gt else ()
    )
    if state0 is None:
        if start_event != 0:
            raise ValueError(
                "continuing from start_event > 0 needs the previous "
                f"slice's final_state ({list(carry_leaves)}) as state0"
            )
        st0 = {k: x0 for k in carry_leaves}
    else:
        if set(state0) != set(carry_leaves):
            raise ValueError(
                f"async state0 leaves {sorted(state0)} do not match the "
                f"event-path carry {list(carry_leaves)}"
            )
        st0 = {
            k: jnp.asarray(v).astype(dtype) for k, v in state0.items()
        }
        for k, v in st0.items():
            if v.shape != (n, d_model):
                raise ValueError(
                    f"state0[{k!r}] has shape {v.shape}; expected "
                    f"{(n, d_model)}"
                )
    if telemetry_on:
        st0 = dict(st0)
        st0["g_norm"] = jnp.zeros((n,), dtype=dtype)

    from distributed_optimization_tpu.backends.jax_backend import (
        _make_eta_fn,
        make_full_objective_fn,
    )

    eta_fn = _make_eta_fn(config)
    full_objective = make_full_objective_fn(problem, reg)
    batch_size = config.local_batch_size
    L = device_data.X.shape[1]
    full_batch = batch_schedule is None and batch_size >= L
    track_consensus = collect_metrics and config.record_consensus
    key = jax.random.fold_in(jax.random.key(config.seed), _ASYNC_BATCH_TAG)

    data_args = {
        "X": jnp.asarray(device_data.X),
        "y": jnp.asarray(device_data.y),
        "n_valid": jnp.asarray(device_data.n_valid),
        "ev": ev_chunks,
    }

    def make_chunk_body(data):
        X, y_data, n_valid = data["X"], data["y"], data["n_valid"]

        def event_grad(x_at, ev, m):
            """Stale-read minibatch gradient for the m-th local descent
            (m is a Python int; None ≡ the τ=1 single descent, which
            keeps the original PR 9 key so the healthy program is
            bitwise unchanged)."""
            i, k = ev["worker"], ev["local_step"]
            Xi, yi, ni = X[i], y_data[i], n_valid[i]
            if "schedule" in ev:
                idx = ev["schedule"] if m is None else ev["schedule"][m]
                Xb, yb = Xi[idx], yi[idx]
                wts = jnp.full(
                    idx.shape, 1.0 / idx.shape[0], dtype=dtype
                )
            elif full_batch:
                mask = (jnp.arange(L) < ni).astype(dtype)
                wts = mask / jnp.maximum(ni.astype(dtype), 1.0)
                Xb, yb = Xi, yi
            else:
                wkey = jax.random.fold_in(jax.random.fold_in(key, i), k)
                if m is not None:
                    wkey = jax.random.fold_in(wkey, m)
                idx, w = sample_batch_indices(wkey, L, ni, batch_size)
                Xb, yb = Xi[idx], yi[idx]
                wts = w.astype(dtype)
            return problem.gradient_weighted(x_at, Xb, yb, wts, reg)

        def local_chain(x_start, corr, eta, ev):
            """τ local descents fused into one event (Koloskova '20's
            local-update axis on the event clock): z_{m+1} = z_m −
            η(corr + g(z_m)); returns (z_τ − z_0, mean gradient)."""
            z = x_start
            gsum = jnp.zeros_like(x_start)
            for m in range(tau):
                gm = event_grad(z, ev, m)
                gsum = gsum + gm
                z = (z - eta * (corr + gm)).astype(dtype)
            g_mean = (gsum / tau).astype(dtype)
            return (z - x_start).astype(dtype), g_mean

        def event_step(carry, ev):
            x, x_read = carry["x"], carry["x_read"]
            i, j = ev["worker"], ev["partner"]
            eta = eta_fn(ev["local_step"]).astype(dtype)
            xi, read_i = x[i], x_read[i]
            if restart_on:
                # neighbor_restart rejoin: the re-entering worker warm-
                # starts from its realized alive neighborhood's average
                # (the precomputed weight row; x only — the GT tracker
                # rows are untouched, preserving the tracking invariant).
                warm = (ev["restart_w"] @ x).astype(dtype)
                rj = ev["rejoin"]
                xi = jnp.where(rj, warm, xi)
                read_i = jnp.where(rj, warm, read_i)
            xj = x[j]
            matched = j != i
            avg = (0.5 * (xi + xj)).astype(dtype)
            base_i = jnp.where(matched, avg, xi)
            # D-PSGD ordering (Lian et al. '17 Alg. 1): average the live
            # rows, then worker i descends along its (stale) gradient;
            # the passive partner only averages. Writing j before i keeps
            # the solo case (j == i: isolated, or degraded by a dead
            # partner/edge) a plain local step.
            if algo_gt:
                y, g_prev = carry["y"], carry["g_prev"]
                yi, yj, gpi = y[i], y[j], g_prev[i]
                avg_y = (0.5 * (yi + yj)).astype(dtype)
                base_y = jnp.where(matched, avg_y, yi)
                if tau == 1:
                    g_ev = event_grad(read_i, ev, None)
                    new_y_i = (base_y + g_ev - gpi).astype(dtype)
                    new_i = (base_i - eta * new_y_i).astype(dtype)
                else:
                    corr = (base_y - gpi).astype(dtype)
                    delta, g_ev = local_chain(read_i, corr, eta, ev)
                    new_y_i = (base_y + g_ev - gpi).astype(dtype)
                    new_i = (base_i + delta).astype(dtype)
                new_y_j = jnp.where(matched, avg_y, yj)
            else:
                if tau == 1:
                    g_ev = event_grad(read_i, ev, None)
                    new_i = (base_i - eta * g_ev).astype(dtype)
                else:
                    delta, g_ev = local_chain(
                        read_i, jnp.zeros((), dtype=dtype), eta, ev
                    )
                    new_i = (base_i + delta).astype(dtype)
            new_j = jnp.where(matched, avg, xj)
            if faults_on:
                # A non-firing event is a total no-op: the crashed (or
                # sampled-out) worker's in-flight gradient is lost and
                # nobody's row moves.
                fire = ev["fire"]
                new_i = jnp.where(fire, new_i, x[i])
                new_j = jnp.where(fire, new_j, x[j])
                new_read = jnp.where(fire, new_i, x_read[i])
            else:
                new_read = new_i
            x = x.at[j].set(new_j)
            x = x.at[i].set(new_i)
            # Worker i immediately re-reads and starts its next gradient.
            x_read = x_read.at[i].set(new_read)
            out = {"x": x, "x_read": x_read}
            if algo_gt:
                if faults_on:
                    new_y_i = jnp.where(fire, new_y_i, y[i])
                    new_y_j = jnp.where(fire, new_y_j, y[j])
                    new_gp = jnp.where(fire, g_ev, gpi)
                else:
                    new_gp = g_ev
                y = y.at[j].set(new_y_j)
                y = y.at[i].set(new_y_i)
                out["y"] = y
                out["g_prev"] = g_prev.at[i].set(new_gp)
            if telemetry_on:
                gn = carry["g_norm"]
                g_n = jnp.sqrt(jnp.sum(g_ev * g_ev)).astype(dtype)
                if faults_on:
                    g_n = jnp.where(fire, g_n, gn[i])
                out["g_norm"] = gn.at[i].set(g_n)
            return out, None

        def chunk_body(carry, ev_row):
            carry, _ = jax.lax.scan(event_step, carry, ev_row)
            out = {}
            if collect_metrics:
                x = carry["x"]
                xbar = jnp.mean(x, axis=0)
                out["gap"] = full_objective(xbar, X, y_data, n_valid) - f_opt
                if track_consensus:
                    out["cons"] = jnp.mean(
                        jnp.sum((x - xbar[None, :]) ** 2, axis=1)
                    )
            if telemetry_on:
                x = carry["x"]
                out["param_norm"] = jnp.sqrt(jnp.sum(x * x, axis=1))
                out["grad_norm"] = carry["g_norm"]
                out["nonfinite"] = jnp.sum(
                    ~jnp.isfinite(x), dtype=jnp.int32
                )
            return carry, out

        return chunk_body

    def run_scan(state, data):
        return jax.lax.scan(make_chunk_body(data), state, data["ev"])

    # An injected fault timeline bypasses the config, which is the whole
    # executable-cache key — never cache those programs.
    exec_cache = (
        resolve_cache(executable_cache) if _fault_timeline is None else None
    )

    # Comms accounting rows (host precompute): only FIRED live exchanges
    # move data — both models cross the wire (2·d floats), and gradient
    # tracking ships the tracker row alongside (4·d). Solo, degraded,
    # and non-firing events move nothing.
    matched_eff = (
        fault_real.matched_fired if faults_on else timeline.matched()
    )
    per_exchange = (4.0 if algo_gt else 2.0) * float(d_model)
    floats_rows = per_exchange * matched_eff[sl].reshape(
        n_evals, events_per_eval
    ).sum(axis=1).astype(np.float64)

    tele_rows: dict[str, list] = {
        "param_norm": [], "grad_norm": [], "nonfinite": [],
    }

    def _collect_tele(outs, rows):
        if telemetry_on and rows:
            tele_rows["param_norm"].extend(
                np.asarray(outs["param_norm"], dtype=np.float32)[:rows]
            )
            tele_rows["grad_norm"].extend(
                np.asarray(outs["grad_norm"], dtype=np.float32)[:rows]
            )
            tele_rows["nonfinite"].extend(
                np.asarray(outs["nonfinite"], dtype=np.float32)[:rows]
            )

    n_done_evals = n_evals
    time_rows = None
    start_chunk = 0
    if progress_cb is not None or monitors is not None or checkpoint is not None:
        # Progress streaming (ISSUE-10; segment-fused in ISSUE-13) and
        # event-indexed checkpointing (ISSUE-17): the run executes as
        # SEGMENTS of eval chunks, each segment one compiled call of the
        # SAME outer scan over its chunk rows — the event arrays are
        # traced inputs, so one executable serves every same-size
        # segment, and the per-segment scans compose to exactly the
        # fused program's computation (bitwise, asserted in
        # tests/test_observatory.py / tests/test_monitors.py /
        # tests/test_async_faults.py). The host syncs once per segment
        # boundary instead of once per chunk.
        from distributed_optimization_tpu.backends.jax_backend import (
            _fanout_progress,
            _fetch_to_host,
        )

        emit = halt_check = None
        if progress_cb is not None or monitors is not None:
            cb = _fanout_progress(progress_cb, monitors)
            emit = _async_progress_emitter(
                config, cb, timeline, start_event
            )
            halt_check = (
                monitors.should_halt
                if monitors is not None and monitors.halt_on != "never"
                else None
            )

        # Checkpoint cursor: one chunk = one eval row = eval_every * N
        # events. Resume restores the latest intact chunk (truncated
        # chunks fall back — the RunCheckpointer contract) and the loop
        # below replays only the tail.
        ckptr = None
        gap_list: list[float] = []
        cons_list: list[float] = []
        time_list: list[float] = []
        if checkpoint is not None:
            from distributed_optimization_tpu.utils.checkpoint import (
                RunCheckpointer,
            )

            ckptr = RunCheckpointer(checkpoint)
            restored = None
            # The event schedule is horizon-GLOBAL (events interleave
            # across rounds by completion time), so extending
            # n_iterations would replay a different event prefix than
            # the saved chunks executed — pin it in the sidecar.
            if checkpoint.resume:
                ckptr.validate_or_record_config(
                    config, resumable_keys=frozenset(),
                )
                restored = ckptr.restore()
            else:
                ckptr.reset(config, resumable_keys=frozenset())
            if restored is not None:
                state_np, gaps_r, conss_r, _fl, times_r, start_chunk = (
                    restored
                )
                if start_chunk > n_evals:
                    raise ValueError(
                        f"checkpoint at chunk {start_chunk} exceeds this "
                        f"run's horizon ({n_evals} eval chunks); raise "
                        "n_iterations to extend the checkpointed progress"
                    )
                if set(state_np) != set(carry_leaves):
                    raise ValueError(
                        f"checkpointed state leaves {sorted(state_np)} do "
                        f"not match the event-path carry "
                        f"{list(carry_leaves)}"
                    )
                st0 = {
                    k: jnp.asarray(v).astype(dtype)
                    for k, v in state_np.items()
                }
                gap_list = [float(g) for g in gaps_r]
                cons_list = [float(c) for c in conss_r]
                time_list = [float(t) for t in times_r]

        if checkpoint is not None:
            seg_pref = checkpoint.every_evals
            if progress_cb is not None or monitors is not None:
                seg_pref = min(seg_pref, max(int(progress_every), 1))
        else:
            seg_pref = max(int(progress_every), 1)
        remaining = n_evals - start_chunk
        seg_chunks = min(seg_pref, max(remaining, 1))
        sizes = {seg_chunks} if remaining else set()
        if remaining % seg_chunks:
            sizes.add(remaining % seg_chunks)

        def seg_scan(state, data):
            return jax.lax.scan(make_chunk_body(data), state, data["ev"])

        compiled_by_size = {}
        compile_seconds = 0.0
        for size in sorted(sizes):
            cache_key = cached = None
            if exec_cache is not None:
                cache_key = sequential_cache_key(
                    config, f_opt, device_data,
                    schedule_signature=(
                        "async-seg", events_per_eval, int(size), sched_sig,
                    ),
                    collect_metrics=collect_metrics,
                )
                cached = exec_cache.get(cache_key)
            if cached is not None:
                compiled_by_size[size] = cached.executable
                continue
            data_c = dict(data_args)
            data_c["ev"] = {k: v[:size] for k, v in ev_chunks.items()}
            t0c = time.perf_counter()
            with jax.default_matmul_precision(config.matmul_precision):
                lowered = jax.jit(seg_scan).lower(st0, data_c)
                cost = cost_from_lowered(lowered)
                compiled_by_size[size] = lowered.compile()
            cold_seconds = time.perf_counter() - t0c
            if measure_compile:
                compile_seconds += cold_seconds
            if exec_cache is not None:
                exec_cache.put(
                    cache_key, compiled_by_size[size], cost=cost,
                    compile_seconds=cold_seconds,
                )

        t1 = time.perf_counter()
        state = st0
        save_seconds = 0.0
        prev_elapsed = 0.0
        t_base = time_list[-1] if time_list else 0.0
        done = start_chunk
        halted = False
        while done < n_evals:
            this_chunks = min(seg_chunks, n_evals - done)
            data_c = dict(data_args)
            data_c["ev"] = {
                k: v[done:done + this_chunks] for k, v in ev_chunks.items()
            }
            state, outs = compiled_by_size[this_chunks](state, data_c)
            jax.block_until_ready(state)
            if "gap" in outs:
                gap_list.extend(
                    float(g) for g in np.asarray(outs["gap"])
                )
            if "cons" in outs:
                cons_list.extend(
                    float(c) for c in np.asarray(outs["cons"])
                )
            _collect_tele(outs, this_chunks)
            done += this_chunks
            elapsed = time.perf_counter() - t1 - save_seconds
            time_list.extend(
                t_base + prev_elapsed
                + (elapsed - prev_elapsed) * (r + 1) / this_chunks
                for r in range(this_chunks)
            )
            prev_elapsed = elapsed
            if emit is not None:
                emit(
                    done * events_per_eval,
                    start_round + done * config.eval_every,
                    gap_list[-1] if gap_list else None,
                    cons_list[-1] if cons_list else None,
                    elapsed,
                    this_chunks * events_per_eval,
                )
            if halt_check is not None and halt_check():
                # Early-halt policy (ISSUE-13): stop at this segment
                # boundary; the executed event prefix is the fused
                # program's prefix (the continuation contract).
                halted = True
            if ckptr is not None and (
                done % checkpoint.every_evals == 0
                or done == n_evals or halted
            ):
                # Save I/O excluded from the interpolated run stamps —
                # it is checkpoint cost, not optimization time.
                t_save = time.perf_counter()
                ckptr.save(
                    done, _fetch_to_host(state), gap_list, cons_list,
                    floats_rows[:done], time_list,
                )
                save_seconds += time.perf_counter() - t_save
            if halted:
                break
        final_state = state
        run_seconds = time.perf_counter() - t1 - save_seconds
        n_done_evals = done
        time_rows = np.asarray(time_list, dtype=np.float64)
        if monitors is not None and halted:
            monitors.note_halt(
                start_round + done * config.eval_every
            )
        gap_hist = (
            np.asarray(gap_list, dtype=np.float64)
            if gap_list else np.full(n_done_evals, np.nan)
        )
        cons_hist = (
            np.asarray(cons_list, dtype=np.float64) if cons_list else None
        )
    else:
        # AOT compile with the sequential path's cache convention: the
        # event arrays and the carry are traced inputs, so the key only
        # needs the full config hash + the window/schedule trace facts.
        cache_key = cached = None
        if exec_cache is not None:
            cache_key = sequential_cache_key(
                config, f_opt, device_data,
                schedule_signature=(
                    "async", start_event, n_events, state0 is not None,
                    sched_sig,
                ),
                collect_metrics=collect_metrics,
            )
            cached = exec_cache.get(cache_key)
        if cached is not None:
            compiled = cached.executable
            compile_seconds = 0.0
        else:
            t0c = time.perf_counter()
            with jax.default_matmul_precision(config.matmul_precision):
                lowered = jax.jit(run_scan).lower(st0, data_args)
                cost = cost_from_lowered(lowered)
                compiled = lowered.compile()
            cold_seconds = time.perf_counter() - t0c
            compile_seconds = cold_seconds if measure_compile else 0.0
            if exec_cache is not None:
                exec_cache.put(
                    cache_key, compiled, cost=cost,
                    compile_seconds=cold_seconds,
                )

        t1 = time.perf_counter()
        final_state, ys = compiled(st0, data_args)
        final_state = jax.block_until_ready(final_state)
        run_seconds = time.perf_counter() - t1

        gap_hist = (
            np.asarray(ys["gap"], dtype=np.float64)
            if "gap" in ys else np.full(n_evals, np.nan)
        )
        cons_hist = (
            np.asarray(ys["cons"], dtype=np.float64) if "cons" in ys else None
        )
        _collect_tele(ys, n_evals)
    # Halted runs bill only the executed event prefix.
    done_events = n_done_evals * events_per_eval
    done_rounds = done_events // n
    total_floats = float(floats_rows[:n_done_evals].sum())

    trace = None
    if telemetry_on:
        trace = _async_trace(
            config, timeline, fault_real, matched_eff, tele_rows,
            start_event, n_done_evals, events_per_eval,
        )

    history = RunHistory(
        objective=gap_hist,
        consensus_error=cons_hist,
        time=(
            time_rows if time_rows is not None else np.linspace(
                run_seconds / max(n_done_evals, 1), run_seconds,
                n_done_evals,
            )
        ),
        time_measured=False,
        # Round-based iteration numbering (N events per round), so
        # iters-to-ε stays comparable with the synchronous paths.
        eval_iterations=np.arange(
            start_round + config.eval_every,
            start_round + done_rounds + 1,
            config.eval_every,
        ),
        total_floats_transmitted=total_floats,
        iters_per_second=(
            (done_rounds - start_chunk * config.eval_every) / run_seconds
            if run_seconds > 0 else float("nan")
        ),
        compile_seconds=compile_seconds,
        spectral_gap=topo.spectral_gap,
        trace=trace,
    )
    final_state = dict(final_state)
    final_state.pop("g_norm", None)
    final_models = np.asarray(final_state["x"]).astype(np.float64)
    return BackendRunResult(
        history=history,
        final_models=final_models,
        final_avg_model=final_models.mean(axis=0),
        final_state=(
            {
                k: np.asarray(v).astype(np.float64)
                for k, v in final_state.items()
            }
            if return_state
            else None
        ),
    )


def _async_trace(
    config, timeline, fault_real, matched_eff, tele_rows, start_event,
    n_rows, events_per_eval,
):
    """Flight-recorder buffers for the event path (``TRACE_FIELDS``
    schema): the in-scan rows (param/grad norms, non-finite sentinel)
    come from the scan outputs; the fault-layer rows are derived host-
    side from the SAME realization the scan executed — ``nodes_up`` is
    the per-worker event-fire fraction over each eval window (1.0 =
    every event fired) and ``live_edges`` the mean per-round count of
    live directed exchange endpoints."""
    n = config.n_workers
    sl = slice(start_event, start_event + n_rows * events_per_eval)
    worker = timeline.worker[sl].reshape(n_rows, events_per_eval)
    if fault_real is not None:
        fire = fault_real.fire[sl].reshape(n_rows, events_per_eval)
    else:
        fire = np.ones((n_rows, events_per_eval), dtype=bool)
    nodes_up = np.ones((n_rows, n), dtype=np.float32)
    for r in range(n_rows):
        fired = np.bincount(
            worker[r], weights=fire[r].astype(np.float64), minlength=n
        )
        total = np.bincount(worker[r], minlength=n)
        nodes_up[r] = np.where(
            total > 0, fired / np.maximum(total, 1), 1.0
        ).astype(np.float32)
    live = matched_eff[sl].reshape(n_rows, events_per_eval).sum(axis=1)
    live_edges = (
        2.0 * live.astype(np.float64) / float(config.eval_every)
    ).astype(np.float32)
    return {
        "param_norm": np.asarray(tele_rows["param_norm"], dtype=np.float32),
        "grad_norm": np.asarray(tele_rows["grad_norm"], dtype=np.float32),
        "nonfinite": np.asarray(tele_rows["nonfinite"], dtype=np.float32),
        "nodes_up": nodes_up,
        "live_edges": live_edges,
        "clip_frac": np.zeros(n_rows, dtype=np.float32),
    }
