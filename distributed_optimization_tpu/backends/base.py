"""Backend dispatch and the shared run-result container.

Mirrors the reference's trainer contract — ``run(...) -> (history, final
model)`` plus a ``total_floats_transmitted`` attribute (reference
``trainer.py:33,74,154,197``, read at ``simulator.py:81``) — as one dataclass
returned by every backend, so the simulator layer is backend-agnostic (the
``--backend`` selection named in BASELINE.json's north star).
"""

from __future__ import annotations

import contextlib
import dataclasses

import numpy as np

from distributed_optimization_tpu.metrics import RunHistory


def x64_scope(config):
    """Scoped ``enable_x64`` for float64 configs.

    Without it jax silently truncates every array to float32, defeating
    the fidelity dtype — the single definition of that stance, shared by
    every jax execution path (jax_backend, tensor_parallel).
    """
    import jax

    from distributed_optimization_tpu.parallel._compat import enable_x64

    return (
        enable_x64()
        if config.dtype == "float64" and not jax.config.jax_enable_x64
        else contextlib.nullcontext()
    )


@dataclasses.dataclass
class BackendRunResult:
    history: RunHistory
    final_models: np.ndarray  # [N, d] per-worker models after T iterations
    final_avg_model: np.ndarray  # [d] network average (the reported model)
    # Full final algorithm state (every leaf, e.g. gradient tracking's
    # y/g_prev), host-fetched. Populated only on request
    # (jax_backend.run(return_state=True)) — used by invariant-level tests
    # (e.g. GT's tracking invariant under failure injection).
    final_state: dict | None = None

    @property
    def total_floats_transmitted(self) -> float:
        return self.history.total_floats_transmitted


def run_algorithm(config, dataset, f_opt, **kwargs) -> BackendRunResult:
    """Run ``config.algorithm`` on ``config.backend`` over ``dataset``.

    ``dataset`` is a HostDataset; backends derive their preferred layout.
    Extra kwargs are backend-specific (mesh=..., batch_schedule=..., ...).
    """
    if config.backend == "jax":
        if config.tp_degree > 1:
            # Tensor parallelism (round-5 capability, product-surfaced in
            # round 6): the config validated the supported combination
            # (softmax + dsgd + ring); the TP module validates the
            # dataset-dependent full-batch requirement and the mesh fit.
            from distributed_optimization_tpu.parallel import tensor_parallel

            return tensor_parallel.run_tp_backend(
                config, dataset, f_opt, **kwargs
            )
        from distributed_optimization_tpu.backends import jax_backend

        return jax_backend.run(config, dataset, f_opt, **kwargs)
    if config.backend == "numpy":
        from distributed_optimization_tpu.backends import numpy_backend

        return numpy_backend.run(config, dataset, f_opt, **kwargs)
    if config.backend == "cpp":
        from distributed_optimization_tpu.backends import cpp_backend

        return cpp_backend.run(config, dataset, f_opt, **kwargs)
    raise ValueError(f"Unknown backend: {config.backend!r}")


def run_algorithm_batch(config, dataset, f_opt, **kwargs):
    """Run R seed replicates of ``config`` as ONE vmapped program.

    Returns a ``jax_backend.BatchRunResult`` (per-replica trajectories +
    aggregate sweep throughput). Only the jax backend compiles a batched
    program; the config validation already rejects ``replicas > 1``
    elsewhere, and a direct call with another backend gets the same
    explanation.
    """
    if config.backend != "jax":
        raise ValueError(
            "replica-batched execution vmaps the jax scan; backend="
            f"{config.backend!r} runs one trajectory at a time — use "
            "backend='jax' or loop single runs"
        )
    from distributed_optimization_tpu.backends import jax_backend

    return jax_backend.run_batch(config, dataset, f_opt, **kwargs)
