"""The JAX/XLA execution backend — the TPU-native north star.

Where the reference runs T × N Python-level worker iterations with per-iter
host-side full-dataset metric evaluations (reference ``trainer.py:41-71``,
``161-193``), this backend compiles the ENTIRE run into one XLA program:

- state is an ``[N, d]``-stacked pytree sharded over the worker mesh axis;
- one iteration = one pure function: per-worker minibatch sampling
  (counter-based keys) → per-worker gradients (vmapped, MXU matmuls) →
  gossip collective (ppermute stencil / psum / dense contraction) → step;
- the T-iteration loop is a single ``jax.lax.scan``; suboptimality and
  consensus metrics accumulate on-device in the scan outputs and are fetched
  ONCE at the end (the reference pays a host round-trip per iteration);
- compile and execute are measured separately via AOT lowering, so iters/sec
  reflects steady-state throughput.

Reference call-stack parity: this file replaces SURVEY.md §3.2/§3.3's hot
loops end to end.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.algorithms import get_algorithm
from distributed_optimization_tpu.algorithms.base import StepContext
from distributed_optimization_tpu.backends.base import BackendRunResult
from distributed_optimization_tpu.metrics import (
    RunHistory,
    centralized_floats_per_iteration,
    decentralized_floats_per_iteration,
)
from distributed_optimization_tpu.models import get_problem
from distributed_optimization_tpu.ops.mixing import make_mixing_op
from distributed_optimization_tpu.ops.sampling import (
    sample_worker_batch_weights,
    sample_worker_batches,
)
from distributed_optimization_tpu.ops.robust_aggregation import (
    make_gather_robust_activity,
    make_gather_robust_aggregator,
    make_robust_activity,
    make_robust_aggregator,
    validate_budget,
)
from distributed_optimization_tpu.telemetry import cost_from_lowered
from distributed_optimization_tpu.serving.cache import (
    batch_cache_key,
    resolve_cache,
    sequential_cache_key,
)
from distributed_optimization_tpu.parallel.adversary import (
    make_adversary,
    make_byzantine_mixing,
)
from distributed_optimization_tpu.parallel.faults import (
    make_faulty_mixing,
    make_round_robin_mixing,
)
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel.collectives import make_shard_map_mixing_op
from distributed_optimization_tpu.parallel.mesh import (
    make_worker_mesh,
    replicate,
    shard_over_workers,
)
from distributed_optimization_tpu.utils.data import HostDataset, stack_shards


# Forcing --sampling-impl dense beyond this padded shard length warns: the
# [L, L] ranking matrix is quadratic and the measured crossover to gather is
# ~L=250 (docs/perf/breakdown.json). Single source for the backend warning
# and the CLI help.
DENSE_SAMPLING_WARN_ROWS = 256


def make_full_objective_fn(problem, reg):
    """Full-dataset objective of a single model w, computed from the stacked
    per-worker shards (so it shards over the mesh and reduces with one psum).

    Equals the reference's objective over the concatenated dataset
    (trainer.py:67,189): padding rows carry zero weight and every real row
    weighs 1/total, so Σ_workers Σ_rows w_il·loss_il is the global mean.

    X/y/n_valid are arguments (not captured) so the traced computation never
    closes over globally-sharded arrays — closing over arrays that span
    non-addressable devices is an error in multi-process runs.
    """

    def full_objective(w, X, y, n_valid):
        L = X.shape[1]
        mask = (jnp.arange(L)[None, :] < n_valid[:, None]).astype(X.dtype)
        total = jnp.maximum(jnp.sum(n_valid).astype(X.dtype), 1.0)
        weights = mask / total  # [N, L]
        per_worker = jax.vmap(
            lambda Xi, yi, wi: problem.objective_weighted(w, Xi, yi, wi, 0.0)
        )(X, y, weights)
        return jnp.sum(per_worker) + 0.5 * reg * jnp.dot(w, w)

    return full_objective


def _fetch_to_host(tree):
    """Bring possibly sharded device arrays to host numpy.

    In a multi-process (multi-host) run the worker axis spans
    non-addressable devices, so a plain np.asarray would raise; gather the
    full value on every host first. Single-process runs skip the gather.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tree = multihost_utils.process_allgather(tree, tiled=True)
    return jax.tree.map(np.asarray, tree)


def _make_eta_fn(config, eta0=None):
    """LR schedule closure; ``eta0`` overrides the config scalar — the
    replica-batched path passes a per-replica traced value (a swept axis)."""
    if eta0 is None:
        eta0 = config.learning_rate_eta0
    if config.resolved_lr_schedule() == "sqrt_decay":
        # Parity: reference trainer.py:17-19, eta0 / sqrt(t + 1).
        return lambda t: eta0 / jnp.sqrt(t + 1.0)
    return lambda t: jnp.asarray(eta0)


def _fanout_progress(progress_cb, monitors):
    """Compose the user progress callback with a ``MonitorBank`` observer
    (ISSUE-13): each consumer is shielded individually, so a broken user
    callback cannot starve the monitors of heartbeats (or vice versa).
    Returns None when both are absent — progress off stays the pre-PR
    code path."""
    cbs = []
    if progress_cb is not None:
        cbs.append(progress_cb)
    if monitors is not None:
        cbs.append(monitors.observe)
    if not cbs:
        return None
    if len(cbs) == 1:
        return cbs[0]
    from distributed_optimization_tpu.log import get_logger

    log = get_logger("progress")

    def fan(ev):
        for cb in cbs:
            try:
                cb(ev)
            except Exception:  # observability never kills the run
                log.exception("progress consumer failed; continuing run")

    return fan


def _progress_emitter(
    config, progress_cb, *, t0: int = 0, kind="chunk", with_bhat=True,
):
    """Heartbeat closure for the round-based paths (ISSUE-10 progress
    streaming; ``observability/progress.py``).

    Returns ``emit(done_evals, gap_list, cons_list, elapsed, **extra)`` or
    None when progress is off. The emitter derives the live B̂ view once
    (host-side timeline rebuild, bitwise the backend's realization — the
    ``realized_bhat`` convention, cost-capped) and shields the run from a
    broken callback: observability must never kill optimization.
    ``with_bhat=False`` suppresses the live B̂: the replica-batched path
    realizes R DISTINCT fault timelines (one per replica seed), so a
    single heartbeat has no B̂ that is true for the cohort — emitting the
    base config's would misattribute replica 0's realization to everyone.

    When the live-B̂ probe is ACTIVE but reports None — the executed
    prefix's union graph is disconnected, so no finite B exists — the
    event carries ``extra={"bhat_disconnected": True}``: a bare
    ``bhat=None`` is ambiguous (it also means "not applicable"), and the
    connectivity-loss monitor must be able to tell assumption violation
    from absence (ISSUE-13).
    """
    if progress_cb is None:
        return None
    from distributed_optimization_tpu.log import get_logger
    from distributed_optimization_tpu.observability.progress import (
        ProgressEvent,
        make_live_bhat,
        progress_heartbeat_counter,
    )

    log = get_logger("progress")
    live_bhat = make_live_bhat(config) if with_bhat else None
    counter = progress_heartbeat_counter()
    horizon = t0 + config.n_iterations

    def emit(done_evals, gap_list, cons_list, elapsed, **extra):
        iteration = t0 + done_evals * config.eval_every
        gap = float(gap_list[-1]) if len(gap_list) else None
        cons = float(cons_list[-1]) if cons_list is not None and len(
            cons_list
        ) else None
        bhat = None
        if live_bhat is not None:
            bhat = live_bhat(iteration)
            if bhat is None:
                extra = dict(extra)
                extra["extra"] = {
                    **(extra.get("extra") or {}), "bhat_disconnected": True,
                }
        ev = ProgressEvent(
            kind=kind,
            iteration=int(iteration),
            n_iterations=int(horizon),
            wall_seconds=float(elapsed),
            gap=gap,
            consensus=cons,
            bhat=bhat,
            **extra,
        )
        counter.inc()
        try:
            progress_cb(ev)
        except Exception:  # observability never kills the run
            log.exception("progress callback failed; continuing run")

    return emit


@dataclasses.dataclass(frozen=True)
class _StepPieces:
    """Everything the per-iteration step/eval closures bind to.

    One bundle serves BOTH execution paths: ``_run`` fills it from the
    config's own seed-derived randomness (concrete arrays), and
    ``run_batch`` fills it per replica inside the vmapped trace (leaves
    may be tracers carrying the replica axis) — so a batched replica runs
    the IDENTICAL program as a sequential run, just under ``vmap``.
    """

    algo: object
    problem: object
    reg: float
    config: object
    batch_size: int
    sampling_impl: str
    key: object          # per-run sampling PRNG key
    eta_fn: object
    degrees: object
    mix_op: object       # MixingOp or None (centralized)
    faulty: object       # FaultyMixing or None
    byz_mix: object      # composed Byzantine mix or None
    adversary: object    # Adversary or None
    honest_w: object     # [N] f32 honest mask or None
    fused_mix_step: object
    full_objective: object
    f_opt: float
    collect_metrics: bool
    track_consensus: bool
    edge_payload: object
    # Single-kernel robust D-SGD update ``(t, x, g, eta) -> x_new``
    # (robust_impl='fused' + dsgd; _bind_byzantine). Bound per-iteration
    # into ``ctx.fused_mix_step`` so the algorithm's canonical
    # mix-then-step collapses into one pallas pass.
    fused_robust_step: object = None
    # --- flight recorder (config.telemetry; telemetry.TRACE_FIELDS) ---
    telemetry: bool = False
    # ``activity(t, x) -> scalar``: robust-aggregation screening fraction
    # over the realized graph at t (corruption composed upstream, like the
    # aggregate itself); None when no robust rule is active.
    robust_activity: object = None
    # Nominal Σ_i deg_i of the static topology (the fault-free live_edges
    # row; 0.0 for centralized runs).
    static_degree_sum: float = 0.0
    # Sharded compressed-exchange wire form (q, x̂⁺, halo) -> (W x̂⁺, halo⁺)
    # (collectives.make_halo_compressed_mixing_op); only set on the
    # worker-mesh path with compression != 'none'.
    compressed_mix: object = None


def _make_step_eval(p: _StepPieces, data):
    """Bind the step/eval/floats closures to the data pytree passed through
    jit (shared by the sequential and replica-batched paths — see
    ``_StepPieces``)."""
    X, y, n_valid = data["X"], data["y"], data["n_valid"]
    schedule = data.get("schedule")
    batch_size = p.batch_size
    faulty, mix_op, byz_mix, adversary = (
        p.faulty, p.mix_op, p.byz_mix, p.adversary
    )

    # Full-batch fast path: sampling b >= L rows without replacement IS
    # the whole shard with 1/n_i weights (the reference's b=min(b, n_i)
    # semantics, worker.py:21), so skip the per-iteration RNG + top_k +
    # gather entirely — in the compute-bound tier the gather alone would
    # otherwise copy the full [N, L, d] every iteration, doubling HBM
    # traffic for no semantic effect.
    full_batch = schedule is None and batch_size >= X.shape[1]
    if full_batch:
        Lr = X.shape[1]
        fmask = (
            jnp.arange(Lr)[None, :] < n_valid[:, None]
        ).astype(X.dtype)
        full_wts = fmask / jnp.maximum(
            n_valid[:, None].astype(X.dtype), 1.0
        )

    def grad_fn_factory(t):
        def grad(params, slot):
            if schedule is not None:
                idx = schedule[t]  # [N, b] injected batch indices
                Xb = jnp.take_along_axis(X, idx[:, :, None], axis=1)
                yb = jnp.take_along_axis(y, idx, axis=1)
                wts = jnp.full(idx.shape, 1.0 / idx.shape[1], dtype=X.dtype)
            elif full_batch:
                Xb, yb, wts = X, y, full_wts
            elif p.sampling_impl == "dense":
                # Dense-weights sampling: no top_k, no gather — the
                # weighted gradient runs over the full padded shard with
                # 1/b weights on the sampled rows (same subsets as the
                # gather path for the same key; see ops/sampling.py).
                slot_key = jax.random.fold_in(p.key, slot)
                Xb, yb = X, y
                wts = sample_worker_batch_weights(
                    slot_key, t, n_valid, X.shape[1], batch_size
                ).astype(X.dtype)
            else:
                slot_key = jax.random.fold_in(p.key, slot)
                Xb, yb, wts = sample_worker_batches(
                    slot_key, t, X, y, n_valid, batch_size
                )
                wts = wts.astype(X.dtype)  # keep bf16 carries unpromoted
            return jax.vmap(
                p.problem.gradient_weighted, in_axes=(0, 0, 0, 0, None)
            )(params, Xb, yb, wts, p.reg)

        return grad

    def step(state, t):
        if faulty is not None and faulty.rejoin_restart is not None:
            # neighbor_restart rejoin policy: BEFORE the step at the
            # rejoin round, a node coming back from an outage replaces
            # its stale model row with the realized-neighborhood
            # average (auxiliary leaves stay frozen-stale — only the
            # model is warm-restarted). The restarted value is what it
            # gossips this round.
            state = {
                **state, "x": faulty.rejoin_restart(t, state["x"])
            }
        if faulty is not None:
            mix_fn = lambda v: faulty.mix(t, v)  # noqa: E731
            nbr_fn = lambda v: faulty.neighbor_sum(t, v)  # noqa: E731
        elif mix_op is not None:
            mix_fn, nbr_fn = mix_op.apply, mix_op.neighbor_sum
        else:
            mix_fn, nbr_fn = (lambda v: v), (lambda v: v * 0)
        if byz_mix is not None:
            # Corrupt outgoing models, then (robustly) aggregate — the
            # composed per-iteration mix from parallel/adversary.py.
            # neighbor_sum sees the corrupted stack too (consistency;
            # no byzantine-supported algorithm consumes it today).
            base_nbr = nbr_fn
            mix_fn = lambda v: byz_mix(t, v)  # noqa: E731
            if adversary is not None:
                nbr_fn = lambda v: base_nbr(  # noqa: E731
                    adversary.corrupt(t, v)
                )
        fused_mix_step = p.fused_mix_step
        if p.fused_robust_step is not None:
            # robust_impl='fused' + dsgd: the whole corrupt → screen →
            # mix → SGD update runs as one pallas kernel for iteration t.
            fused_mix_step = (
                lambda xx, gg, ee, _t=t: p.fused_robust_step(  # noqa: E731
                    _t, xx, gg, ee
                )
            )
        ctx = StepContext(
            grad=grad_fn_factory(t),
            mix=mix_fn,
            neighbor_sum=nbr_fn,
            # Cast to the run dtype so low-precision carries (bfloat16)
            # aren't silently promoted by the f32 schedule scalar.
            eta=p.eta_fn(t).astype(X.dtype),
            t=t,
            degrees=p.degrees,
            config=p.config,
            fused_mix_step=fused_mix_step,
            compressed_mix=p.compressed_mix,
        )
        new_state = p.algo.step(state, ctx)
        if faulty is not None and (
            faulty.straggler_prob > 0.0 or faulty.churn_active
            or faulty.participation_active
        ):
            # A straggler/crashed/sampled-out node takes no step at all:
            # freeze its rows across every state leaf (each leaf leads
            # with the worker axis) — for churn, across the WHOLE
            # outage, so a 'frozen' rejoin resumes the stale pre-crash
            # state for free. Its mixing row already degenerated to
            # identity via the dropped edges.
            m = faulty.active(t)
            new_state = jax.tree.map(
                lambda new, old: jnp.where(
                    m.reshape((-1,) + (1,) * (new.ndim - 1)) > 0, new, old
                ),
                new_state,
                state,
            )
        return new_state, None

    def trace_row(state, t):
        """One flight-recorder row (telemetry.TRACE_FIELDS) at iteration t:
        pure observability computed from the post-step state, feeding the
        scan's stacked OUTPUTS only — the carry and the step dataflow are
        untouched, so trajectories are bitwise-identical with telemetry on
        or off (tests/test_telemetry.py pins it). The gradient uses the
        same (key, t) batch realization the iteration-t step consumed."""
        x = state["x"]
        acc = jnp.promote_types(jnp.float32, x.dtype)
        g = grad_fn_factory(t)(x, 0).astype(acc)
        nonfinite = jnp.zeros((), dtype=jnp.float32)
        for leaf in jax.tree.leaves(state):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                nonfinite = nonfinite + jnp.sum(
                    ~jnp.isfinite(leaf)
                ).astype(jnp.float32)
        if faulty is not None:
            nodes_up = faulty.active(t)
            live_edges = faulty.realized_degree_sum(t).astype(jnp.float32)
        else:
            nodes_up = jnp.ones(x.shape[0], dtype=jnp.float32)
            live_edges = jnp.asarray(p.static_degree_sum, dtype=jnp.float32)
        clip_frac = (
            p.robust_activity(t, x).astype(jnp.float32)
            if p.robust_activity is not None
            else jnp.zeros((), dtype=jnp.float32)
        )
        return {
            "grad_norm": jnp.sqrt(jnp.sum(g * g, axis=-1)).astype(
                jnp.float32
            ),
            "param_norm": jnp.sqrt(
                jnp.sum(x.astype(acc) ** 2, axis=-1)
            ).astype(jnp.float32),
            "nodes_up": nodes_up,
            "nonfinite": nonfinite,
            "live_edges": live_edges,
            "clip_frac": clip_frac,
        }

    def _zero_trace(state):
        n = state["x"].shape[0]
        z = jnp.zeros((), dtype=jnp.float32)
        zn = jnp.zeros(n, dtype=jnp.float32)
        return {
            "grad_norm": zn, "param_norm": zn, "nodes_up": zn,
            "nonfinite": z, "live_edges": z, "clip_frac": z,
        }

    def eval_metrics(state, t_last, cadence_known=False):
        """Per-eval metrics + flight-recorder row at iteration ``t_last``.

        ``cadence_known=True`` promises t_last IS an eval boundary (the
        chunked/hoisted forms); the inline fused scan computes its eval
        every trip and discards off-cadence rows, so there the trace row —
        whose gradient probe is NOT latency-hidden the way the stacked-
        output eval is — hides behind a ``lax.cond`` on the boundary
        predicate instead of running every trip (measured 36% → <10%
        steady overhead on the CPU container; docs/perf/telemetry.json).
        """
        out = {}
        if p.telemetry:
            if cadence_known:
                out["trace"] = trace_row(state, t_last)
            else:
                on_boundary = (t_last + 1) % p.config.eval_every == 0
                out["trace"] = jax.lax.cond(
                    on_boundary,
                    lambda s: trace_row(s, t_last),
                    _zero_trace,
                    state,
                )
        if p.collect_metrics:
            x = state["x"]
            if adversary is not None:
                # Honest-only metrics (docs/BYZANTINE.md): the gap is
                # f(x̄_honest) − f* on the unchanged global objective,
                # consensus is the honest spread — Byzantine rows are
                # adversary-controlled and would poison both.
                hw = p.honest_w.astype(x.dtype)
                nh = jnp.sum(hw)
                xbar = jnp.sum(x * hw[:, None], axis=0) / nh
                out["gap"] = p.full_objective(xbar, X, y, n_valid) - p.f_opt
                if p.track_consensus:
                    out["cons"] = (
                        jnp.sum(
                            hw * jnp.sum((x - xbar[None, :]) ** 2, axis=1)
                        )
                        / nh
                    )
            else:
                xbar = jnp.mean(x, axis=0)
                out["gap"] = p.full_objective(xbar, X, y, n_valid) - p.f_opt
                if p.track_consensus:
                    out["cons"] = jnp.mean(
                        jnp.sum((x - xbar[None, :]) ** 2, axis=1)
                    )
        return out

    def floats_for(ts):
        # Honest comms accounting under faults: floats actually
        # exchanged over realized edges for these iterations (recomputed
        # from the fault keys, so it costs one tiny mask redraw per
        # iteration, no extra communication).
        return (
            jnp.sum(jax.vmap(faulty.realized_degree_sum)(ts))
            * p.edge_payload
        )

    return step, eval_metrics, floats_for


def _flat_scan_cadence(scan_unroll: int, eval_every: int):
    """(micro, trips_per_eval, flat_unroll) for the flat fused scan.

    ``micro`` is the largest divisor of ``eval_every`` within the unroll
    budget, so some scan trip lands exactly on every eval boundary. One
    derivation shared by the sequential and replica-batched paths — their
    eval cadence must not be able to drift apart.
    """
    micro = next(
        d for d in range(min(scan_unroll, eval_every), 0, -1)
        if eval_every % d == 0
    )
    return micro, eval_every // micro, max(1, scan_unroll // micro)


def _build_faulty(config, algo, topo, T, *, drop_prob=None, keys=None,
                  timeline=None, horizon=None, halo_mesh=None):
    """Time-varying gossip wiring shared by ``_run`` and ``run_batch``.

    Returns a ``FaultyMixing`` (or None for a static graph) after the
    algorithm-support validation. The keyword overrides are the replica-
    batched hooks: ``drop_prob`` a per-replica (possibly traced) scalar,
    ``keys`` pre-derived per-replica PRNG keys, ``timeline`` a prebuilt
    per-replica ``FaultTimeline`` view, ``horizon`` the timeline length
    (t0 + T for continued batches; defaults to T). ``halo_mesh``: the
    worker-mesh route (``config.worker_mesh >= 2``) — node-process fault
    mixing then runs sharded with per-shard timeline slices
    (``parallel/faults.py::make_halo_faulty_mixing``).
    """
    time_varying = (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.participation_rate < 1.0
        or config.gossip_schedule != "synchronous"
        or drop_prob is not None
    )
    if not time_varying:
        return None
    if not algo.supports_edge_faults:
        raise ValueError(
            f"time-varying gossip is unsupported for {algo.name!r}: "
            "the step rule is not faithful under per-iteration "
            "graphs — participation sampling included (ADMM pairs "
            "neighbor sums with static degrees; CHOCO's shared "
            "estimate state cannot represent undelivered updates; "
            "EXTRA's fixed-point argument requires a static W)"
        )
    if config.mttf > 0.0 and not algo.supports_churn:
        raise ValueError(
            f"crash-recovery churn is unsupported for {algo.name!r}: "
            "multi-round outages freeze a node's whole state and "
            "may warm-restart its model on rejoin, which only "
            "mix-based rules tolerate (push-sum's (num, w) mass "
            "pair cannot be restarted consistently; EXTRA/ADMM/"
            "CHOCO already reject time-varying graphs) — use "
            "'dsgd' or 'gradient_tracking'"
        )
    if config.gossip_schedule == "round_robin":
        return make_round_robin_mixing(topo)
    return make_faulty_mixing(
        topo,
        config.edge_drop_prob if drop_prob is None else drop_prob,
        config.seed,
        straggler_prob=config.straggler_prob,
        one_peer=config.gossip_schedule == "one_peer",
        burst_len=config.burst_len,
        mttf=config.mttf, mttr=config.mttr,
        rejoin=config.rejoin,
        horizon=T if horizon is None else horizon,
        keys=keys, timeline=timeline,
        participation_rate=config.participation_rate,
        mesh=halo_mesh,
    )


def _bind_byzantine(config, algo, topo, faulty, mix_op, *, clip_tau=None,
                    byz=None, noise_key=None, allow_fused=True,
                    fused_auto_ok=True, halo_mesh=None):
    """Byzantine adversary + robust-aggregation wiring shared by ``_run``
    and ``run_batch`` (docs/BYZANTINE.md). Returns ``(adversary, byz_mix,
    activity_t, fused_step_t)`` — all None when the config is benign.
    ``activity_t(t, x)`` is the flight recorder's screening-fraction probe
    (the telemetry twin of the robust rule, over the same realized graph
    and the same corrupted stack; None without a robust rule).
    ``fused_step_t(t, x, g, eta)`` is the single-kernel robust D-SGD
    update (gather + screen + mix + SGD in one pallas pass,
    ``robust_impl='fused'`` + dsgd only) — when set, the step binds it as
    ``ctx.fused_mix_step`` and the whole per-iteration update runs
    VMEM-resident. The keyword overrides are the replica-batched hooks:
    ``clip_tau`` a per-replica (possibly traced) radius,
    ``byz``/``noise_key`` the per-replica Byzantine set and large-noise
    stream; ``allow_fused=False`` keeps the vmapped path off the pallas
    kernel entirely (it addresses unbatched VMEM blocks);
    ``fused_auto_ok=False`` only stops AUTO from promoting to it (the
    sharded-mesh case: the kernel would be GSPMD-replicated instead of
    partitioned — an explicit robust_impl='fused' is still honored).
    """
    byzantine_active = config.attack != "none" or (
        config.aggregation != "gossip" and config.robust_b > 0
    )
    if not byzantine_active:
        return None, None, None, None
    if not algo.supports_byzantine:
        raise ValueError(
            f"Byzantine injection / robust aggregation is "
            f"unsupported for {algo.name!r}: only step rules whose "
            "updates go through the gossip mix alone compose with "
            "screened aggregation (EXTRA's fixed point needs the "
            "static linear W; ADMM pairs neighbor sums with static "
            "degrees; CHOCO's shared estimates cannot represent "
            "screened-out updates; push-sum's debiasing needs the "
            "column-stochastic mass conservation screening breaks) "
            "— use 'dsgd' or 'gradient_tracking'"
        )
    adversary = make_adversary(
        config.n_workers, config.attack, config.n_byzantine,
        config.attack_scale, config.seed, byz=byz, noise_key=noise_key,
    )
    robust_aggregate_t = None
    activity_src = None
    fused_update = None
    if config.aggregation != "gossip" and config.robust_b > 0:
        from distributed_optimization_tpu.ops.pallas_kernels import (
            fused_robust_supported,
            make_fused_robust_aggregator,
            make_fused_robust_dsgd_step,
        )

        validate_budget(
            int(topo.degrees.min()), config.robust_b,
            config.aggregation,
        )
        ct = config.clip_tau if clip_tau is None else clip_tau
        k_max_topo = int(topo.degrees.max())
        # The screened-rule execution form (docs/BYZANTINE.md
        # "Degree-bounded gather path"): 'gather' screens over the
        # static [N, k_max] neighbor table — O(N·k_max·d·log k_max)
        # — instead of the dense [N, N, d] node-axis sort; 'fused'
        # runs the gather math as ONE pallas kernel so the
        # [N, k_max, d] neighbor stack never materializes in HBM;
        # 'auto' routes by the measured crossover and promotes to
        # fused only when the production shape is eligible: static
        # topology (no per-round liveness recompute to overlap),
        # fused-supported rule at this k_max, and no telemetry
        # activity probe (the probe would re-run the un-fused
        # screening maths alongside). An EXPLICIT 'fused' is honored
        # beyond the auto gate (time-varying liveness feeds the
        # kernel per step — the parity tests force exactly that),
        # but never inside the vmapped replica batch.
        fused_eligible = (
            allow_fused
            and fused_auto_ok
            and faulty is None
            and not config.telemetry
            # Matrix-free topologies run the gather form only: the fused
            # kernel is measured on the dense-representation shapes.
            and not topo.is_matrix_free
            # The fused-kernel measurement covers the one-step round; with
            # τ local steps auto stays on gather (an EXPLICIT 'fused'
            # still runs — the kernel is the round's first descent and the
            # τ−1 local steps follow outside it).
            and config.local_steps == 1
            and fused_robust_supported(config.aggregation, k_max_topo, ct)
        )
        robust_impl = config.resolved_robust_impl(
            k_max_topo, fused_eligible=fused_eligible
        )
        if robust_impl == "fused" and not allow_fused:
            raise ValueError(
                "robust_impl='fused' cannot run inside the replica-"
                "batched program: the pallas kernel addresses unbatched "
                "VMEM blocks — use 'auto', 'gather', or 'dense'"
            )
        if topo.is_matrix_free and robust_impl != "gather":
            # Unreachable through config validation (neighbor topologies
            # never have k_max + 1 >= N, so 'auto' resolves to gather and
            # explicit dense/fused are rejected up front) — guard anyway
            # so a future resolver change fails loudly, not silently
            # through a None adjacency.
            raise ValueError(
                f"matrix-free robust aggregation runs in gather form; "
                f"resolved robust_impl={robust_impl!r} needs the dense "
                "[N, N] adjacency"
            )
        if halo_mesh is not None:
            # Sharded worker mesh (docs/PERF.md §16): screening runs in
            # halo-gather form — corrupted boundary rows travel over the
            # same ppermute exchange as benign gossip, each shard screens
            # its own closed neighborhoods locally. Node-process faults
            # compose through the availability row; config already
            # rejected everything without a sharded form (edge chains,
            # alie, dense/fused impls, the telemetry activity probe)
            # with the missing piece named.
            if robust_impl != "gather":
                raise ValueError(
                    f"worker_mesh screens in halo-gather form; resolved "
                    f"robust_impl={robust_impl!r} has no sharded twin"
                )
            from distributed_optimization_tpu.parallel.collectives import (
                make_halo_robust_aggregator_t,
            )

            robust_aggregate_t = make_halo_robust_aggregator_t(
                config.aggregation, config.robust_b, topo, halo_mesh,
                ct, faulty.active if faulty is not None else None,
            )
        elif robust_impl in ("gather", "fused"):
            from distributed_optimization_tpu.parallel.topology import (
                neighbor_tables_for,
            )

            # Native tables for matrix-free topologies (the satellite:
            # Byzantine screening accepted on the neighbor path), derived
            # from the dense adjacency otherwise — identical layout.
            nbr_idx, nbr_mask = neighbor_tables_for(topo)
            if robust_impl == "fused":
                gather_agg = make_fused_robust_aggregator(
                    config.aggregation, config.robust_b, nbr_idx, ct,
                )
            else:
                gather_agg = make_gather_robust_aggregator(
                    config.aggregation, config.robust_b, nbr_idx, ct,
                )
            if faulty is not None:
                live_fn = faulty.make_neighbor_liveness(
                    nbr_idx, nbr_mask
                )
            else:
                static_live = jnp.asarray(
                    nbr_mask, dtype=jnp.float32
                )
                live_fn = lambda t: static_live  # noqa: E731
            robust_aggregate_t = (
                lambda t, v: gather_agg(live_fn(t), v)  # noqa: E731
            )
            if robust_impl == "fused" and algo.name == "dsgd":
                # D-SGD's whole update fuses: the −η·g lands inside
                # the same kernel (make_fused_robust_dsgd_step);
                # composed with the adversary below.
                fused_update = (
                    make_fused_robust_dsgd_step(
                        config.aggregation, config.robust_b, nbr_idx,
                        ct,
                    ),
                    live_fn,
                )
            # The activity probe stays the (un-fused) gather twin for
            # both forms — observability only, off the auto-fused path.
            gather_act = make_gather_robust_activity(
                config.aggregation, config.robust_b, nbr_idx, ct,
            )
            activity_src = (
                lambda t, v: gather_act(live_fn(t), v)  # noqa: E731
            )
        else:
            dense_agg = make_robust_aggregator(
                config.aggregation, config.robust_b, ct
            )
            if faulty is not None:
                adj_fn = faulty.realized_adjacency
            else:
                static_A = jnp.asarray(
                    topo.adjacency, dtype=jnp.float32
                )
                adj_fn = lambda t: static_A  # noqa: E731
            robust_aggregate_t = (
                lambda t, v: dense_agg(adj_fn(t), v)  # noqa: E731
            )
            dense_act = make_robust_activity(
                config.aggregation, config.robust_b, ct
            )
            activity_src = (
                lambda t, v: dense_act(adj_fn(t), v)  # noqa: E731
            )
    if faulty is not None:
        base_mix_t = faulty.mix
    else:
        base_mix_t = lambda t, v: mix_op.apply(v)  # noqa: E731
    byz_mix = make_byzantine_mixing(
        adversary, base_mix_t, aggregate_t=robust_aggregate_t,
    )
    fused_step_t = None
    if fused_update is not None:
        fused_kernel, fused_live = fused_update

        def fused_step_t(t, x, g, eta):
            # The single-kernel twin of ``byz_mix(t, x) − η·g`` for D-SGD
            # (make_byzantine_mixing composition, SGD folded in): honest
            # rows screen the corrupted stack in-kernel; Byzantine rows
            # keep the benign mix of the TRUE stack (the attacker-runs-
            # honest-dynamics threat model) — elementwise the same values
            # as select-then-subtract, so the fused path stays bitwise.
            xc = adversary.corrupt(t, x) if adversary is not None else x
            out = fused_kernel(fused_live(t), xc, g, eta)
            if adversary is not None:
                m = jnp.asarray(
                    adversary.byzantine, dtype=jnp.float32
                ).reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
                out = jnp.where(m > 0, base_mix_t(t, x) - eta * g, out)
            return out

    activity_t = None
    if activity_src is not None:
        # The probe sees exactly what the screening rule sees: the stack
        # AS TRANSMITTED (attack payloads applied) over the realized graph.
        if adversary is not None:
            activity_t = (
                lambda t, v: activity_src(t, adversary.corrupt(t, v))  # noqa: E731
            )
        else:
            activity_t = activity_src
    return adversary, byz_mix, activity_t, fused_step_t


def _run_chunked(
    chunk, state0, data_args, checkpoint, mesh, config, n_evals,
    measure_compile, progress_hook=None, progress_every=1, halt_check=None,
):
    """Host-driven chunk loop: measured per-eval timestamps, optional orbax
    checkpointing (``checkpoint=None`` runs the loop purely for timing).
    ``chunk(state, ts, data_args)`` takes the sharded data pytree as an
    argument (multi-process safe; see ``make_chunk``).

    One 'chunk' = ``eval_every`` fused iterations (the same compiled body the
    single-scan path uses); the host only intervenes at eval boundaries, so
    steady-state throughput matches the fused path up to one host sync per
    ``eval_every`` iterations. Each chunk records a real ``perf_counter``
    timestamp — the measured wall-clock the reference samples per iteration
    (trainer.py:63,181), at eval granularity. Returns (final_state, gap_hist,
    cons_hist, time_hist, realized_floats, executed_iters, compile_seconds,
    run_seconds, trace, cost) — ``executed_iters`` counts only iterations
    run in THIS process, so resumed runs report honest throughput;
    ``time_hist`` is cumulative across installments (restored timestamps
    carry an offset); ``trace``/``cost`` are the flight-recorder buffers
    and XLA cost analysis (None when ``config.telemetry`` is off).
    """
    from distributed_optimization_tpu.parallel.mesh import (
        replicate as _replicate,
        shard_over_workers as _shard,
    )
    from distributed_optimization_tpu.utils.checkpoint import RunCheckpointer

    eval_every = config.eval_every
    ckptr = None
    if checkpoint is not None:
        ckptr = RunCheckpointer(checkpoint)
        if checkpoint.resume:
            ckptr.validate_or_record_config(config)
        else:
            # Explicit fresh start: clear stale chunks (they would poison a
            # later resume) and rewrite the sidecar instead of validating.
            ckptr.reset(config)
    ts_row0 = _replicate(mesh, jnp.arange(eval_every, dtype=jnp.int32))

    t0 = time.perf_counter()
    with jax.default_matmul_precision(config.matmul_precision):
        lowered = jax.jit(chunk).lower(state0, ts_row0, data_args)
        cost = cost_from_lowered(lowered) if config.telemetry else None
        compiled = lowered.compile()
    compile_seconds = time.perf_counter() - t0 if measure_compile else 0.0

    state = state0
    gap_list: list[float] = []
    cons_list: list[float] = []
    floats_list: list[float] = []
    time_list: list[float] = []
    trace_lists: dict[str, list] = {}
    start_chunk = 0
    if ckptr is not None and checkpoint.resume:
        restored = ckptr.restore()
        if restored is not None:
            state_np, gaps, conss, floats, times, start_chunk = restored
            if start_chunk > n_evals:
                raise ValueError(
                    f"checkpoint at chunk {start_chunk} exceeds this run's "
                    f"horizon of {n_evals} chunks (n_iterations shrank below "
                    "the checkpointed progress)"
                )
            state = _shard(mesh, jax.tree.map(np.asarray, state_np))
            gap_list = [float(v) for v in gaps]
            cons_list = [float(v) for v in conss]
            floats_list = [float(v) for v in floats]
            time_list = [float(v) for v in times]

    # Cumulative-time offset from previous installments of a resumed run.
    time_offset = time_list[-1] if time_list else 0.0
    t1 = time.perf_counter()
    save_seconds = 0.0  # cumulative orbax-save time, excluded from stamps
    done = start_chunk
    for c in range(start_chunk, n_evals):
        ts = _replicate(
            mesh,
            jnp.arange(c * eval_every, (c + 1) * eval_every, dtype=jnp.int32),
        )
        state, out = compiled(state, ts, data_args)
        if "gap" in out:
            gap_list.append(float(out["gap"]))
        if "cons" in out:
            cons_list.append(float(out["cons"]))
        if "floats" in out:
            floats_list.append(float(out["floats"]))
        if "trace" in out:
            for k, v in out["trace"].items():
                trace_lists.setdefault(k, []).append(np.asarray(v))
        # The metric fetches above already forced the chunk to completion;
        # sync explicitly anyway so the timestamp is honest when metrics
        # collection is off. Earlier saves' durations are subtracted — they
        # are checkpoint I/O, not optimization time (round-5 advisor fix,
        # matching the segmented path's accounting).
        jax.block_until_ready(state)
        time_list.append(time_offset + time.perf_counter() - t1 - save_seconds)
        done = c + 1
        if progress_hook is not None and (
            done % progress_every == 0 or done == n_evals
        ):
            # The chunk loop is already host-synced per eval, so the
            # heartbeat costs only the callback itself — but the cadence
            # contract (one heartbeat per progress_every eval-chunks) is
            # the same as the segmented/batched/async paths'.
            progress_hook(done, gap_list, cons_list, time_list[-1])
        if ckptr is not None and (
            done % checkpoint.every_evals == 0 or done == n_evals
        ):
            t_save = time.perf_counter()
            ckptr.save(
                done, _fetch_to_host(state),
                gap_list, cons_list, floats_list, time_list,
            )
            save_seconds += time.perf_counter() - t_save
        if halt_check is not None and halt_check():
            # Early-halt policy (ISSUE-13): a fatal anomaly stops the run
            # at this eval-chunk boundary — the executed prefix is the
            # full run's prefix (same compiled chunk, same carries), the
            # remaining chunks just never execute.
            break
    run_seconds = time.perf_counter() - t1 - save_seconds

    gap_hist = np.asarray(gap_list, dtype=np.float64)
    cons_hist = np.asarray(cons_list, dtype=np.float64) if cons_list else None
    time_hist = np.asarray(time_list, dtype=np.float64)
    realized_floats = float(np.sum(floats_list)) if floats_list else None
    executed_iters = (done - start_chunk) * eval_every
    trace = (
        {k: np.stack(v) for k, v in trace_lists.items()}
        if trace_lists else None
    )
    return (state, gap_hist, cons_hist, time_hist, realized_floats,
            executed_iters, compile_seconds, run_seconds, trace, cost)


def _run_segmented_fused(
    make_seg_scan, harvest, state0, data_args, checkpoint, mesh, config,
    n_evals, measure_compile, *, progress_hook=None, progress_every=1,
    exec_cache=None, cache_key_fn=None, halt_check=None,
):
    """Segmented execution of the flat fused scan (round 4 — VERDICT r3
    item 5; generalized for ISSUE-10 progress streaming).

    The round-2/3 design forced every checkpointed run through the
    host-driven chunk loop — one compiled call + host sync per eval chunk —
    which the round-3 root-cause measurements put at 2.2× slower than the
    flat fused scan at coarse cadence (docs/PERF.md §root-cause). Here a
    checkpointed run executes ``checkpoint.every_evals`` eval-chunks per
    compiled call through the SAME flat microchunk scan the fused path
    uses (iteration indices offset by a traced ``t0``, so one executable
    serves every segment), with the orbax save between segments. The host
    intervenes once per SAVE, not once per eval; per-eval wall-clock inside
    a segment is interpolated (``time_measured=False``) — opt into
    ``measure_timestamps=True`` for real per-eval samples via the chunk
    loop, accepting its measured cost.

    Progress streaming (ISSUE-10) runs THIS path with ``checkpoint=None``:
    segments of ``progress_every`` eval-chunks, a heartbeat
    (``progress_hook(done_evals, gap_list, cons_list, elapsed)``) after
    each — the identical compiled program split at eval boundaries, so
    trajectories are bitwise the one-shot run's (the continuation
    contract, asserted in tests/test_observatory.py). With progress on
    the segmented executables are cacheable (``exec_cache`` +
    ``cache_key_fn(size)``): the serving daemon heartbeats every request,
    so the progress path must amortize compiles like the one-shot path.

    Returns (final_state, gap_hist, cons_hist, time_hist, realized_floats,
    executed_iters, compile_seconds, run_seconds, trace, cost);
    ``executed_iters`` counts only iterations run in THIS process (resumed
    runs report honest throughput); ``trace``/``cost`` are the flight-
    recorder buffers and XLA cost analysis (None when ``config.telemetry``
    is off — and always None for checkpointed runs, which reject
    telemetry upstream).
    """
    from distributed_optimization_tpu.parallel.mesh import (
        replicate as _replicate,
        shard_over_workers as _shard,
    )

    eval_every = config.eval_every
    ckptr = None
    if checkpoint is not None:
        from distributed_optimization_tpu.utils.checkpoint import (
            RunCheckpointer,
        )

        ckptr = RunCheckpointer(checkpoint)
        if checkpoint.resume:
            ckptr.validate_or_record_config(config)
        else:
            ckptr.reset(config)

    state = state0
    gap_list: list[float] = []
    cons_list: list[float] = []
    floats_list: list[float] = []
    time_list: list[float] = []
    trace_lists: dict[str, list] = {}
    start_chunk = 0
    if ckptr is not None and checkpoint.resume:
        restored = ckptr.restore()
        if restored is not None:
            state_np, gaps, conss, floats, times, start_chunk = restored
            if start_chunk > n_evals:
                raise ValueError(
                    f"checkpoint at chunk {start_chunk} exceeds this run's "
                    f"horizon of {n_evals} chunks (n_iterations shrank below "
                    "the checkpointed progress)"
                )
            state = _shard(mesh, jax.tree.map(np.asarray, state_np))
            gap_list = [float(v) for v in gaps]
            cons_list = [float(v) for v in conss]
            floats_list = [float(v) for v in floats]
            time_list = [float(v) for v in times]

    remaining = n_evals - start_chunk
    seg_evals = (
        min(checkpoint.every_evals, max(remaining, 1))
        if checkpoint is not None
        else min(max(int(progress_every), 1), max(remaining, 1))
    )

    # AOT-compile every segment size this run needs (the full segment plus
    # a possible trailing remainder) before the timer starts, so compile and
    # steady-state stay separable. One executable serves all same-size
    # segments because the iteration offset is a traced argument.
    sizes = set()
    if remaining > 0:
        sizes.add(min(seg_evals, remaining))
        if remaining % seg_evals:
            sizes.add(remaining % seg_evals)
    t0c = time.perf_counter()
    t0_probe = _replicate(mesh, jnp.asarray(0, dtype=jnp.int32))
    compiled_by_size = {}
    cost = None
    cold_compile = 0.0
    with jax.default_matmul_precision(config.matmul_precision):
        for size in sorted(sizes):
            key = cache_key_fn(size) if (
                exec_cache is not None and cache_key_fn is not None
            ) else None
            cached = exec_cache.get(key) if key is not None else None
            if cached is not None:
                compiled_by_size[size] = cached.executable
                if config.telemetry and cost is None:
                    cost = cached.cost
                continue
            t_cold = time.perf_counter()
            lowered = jax.jit(make_seg_scan(size)).lower(
                state, t0_probe, data_args
            )
            size_cost = (
                cost_from_lowered(lowered) if config.telemetry else None
            )
            if cost is None:
                cost = size_cost
            compiled_by_size[size] = lowered.compile()
            this_cold = time.perf_counter() - t_cold
            cold_compile += this_cold
            if key is not None:
                exec_cache.put(
                    key, compiled_by_size[size], cost=size_cost,
                    compile_seconds=this_cold,
                )
    compile_seconds = cold_compile if measure_compile else 0.0

    time_offset = time_list[-1] if time_list else 0.0
    t1 = time.perf_counter()
    save_seconds = 0.0  # cumulative orbax-save time, excluded from stamps
    done = start_chunk
    while done < n_evals:
        this_evals = min(seg_evals, n_evals - done)
        t0_iter = _replicate(
            mesh, jnp.asarray(done * eval_every, dtype=jnp.int32)
        )
        state, ys = compiled_by_size[this_evals](state, t0_iter, data_args)
        gap, cons, floats, trace_seg = harvest(ys, this_evals)
        if gap is not None:
            gap_list.extend(gap.tolist())
        if cons is not None:
            cons_list.extend(cons.tolist())
        if floats is not None:
            floats_list.extend(floats.tolist())
        if trace_seg is not None:
            for k, v in trace_seg.items():
                trace_lists.setdefault(k, []).append(np.asarray(v))
        jax.block_until_ready(state)
        done += this_evals
        # Per-eval timestamps are interpolated within the segment (the scan
        # runs without host syncs); only the segment boundary is a real
        # sample. The restored cumulative offset carries across installments
        # like the chunk loop's. Earlier segments' orbax-save durations are
        # subtracted (round-5 advisor fix: they are checkpoint I/O, not
        # optimization time — without this every segment after the first
        # folded prior saves into its stamps and into run_seconds, so
        # checkpointed iters/sec silently included checkpoint I/O).
        seg_end = time_offset + time.perf_counter() - t1 - save_seconds
        prev = time_list[-1] if time_list else time_offset
        time_list.extend(
            np.linspace(prev + (seg_end - prev) / this_evals, seg_end,
                        this_evals).tolist()
        )
        if progress_hook is not None:
            progress_hook(done, gap_list, cons_list, seg_end)
        if ckptr is not None:
            t_save = time.perf_counter()
            ckptr.save(
                done, _fetch_to_host(state),
                gap_list, cons_list, floats_list, time_list,
            )
            save_seconds += time.perf_counter() - t_save
        if halt_check is not None and halt_check():
            # Early-halt policy (ISSUE-13): a fatal anomaly fired on this
            # segment's heartbeat — stop at the boundary. The executed
            # prefix is the one-shot program's prefix (the continuation
            # contract); the remaining segments never execute.
            break
    run_seconds = time.perf_counter() - t1 - save_seconds

    gap_hist = np.asarray(gap_list, dtype=np.float64) if gap_list else None
    cons_hist = np.asarray(cons_list, dtype=np.float64) if cons_list else None
    time_hist = np.asarray(time_list, dtype=np.float64)
    realized_floats = float(np.sum(floats_list)) if floats_list else None
    executed_iters = (done - start_chunk) * eval_every
    trace = (
        {k: np.concatenate(v, axis=0) for k, v in trace_lists.items()}
        if trace_lists else None
    )
    return (state, gap_hist, cons_hist, time_hist, realized_floats,
            executed_iters, compile_seconds, run_seconds, trace, cost)


def run(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    mesh=None,
    use_mesh: bool = True,
    batch_schedule: Optional[np.ndarray] = None,
    collect_metrics: bool = True,
    measure_compile: bool = True,
    checkpoint=None,
    measure_timestamps: Optional[bool] = None,
    return_state: bool = False,
    hoisted_min_ratio: Optional[float] = None,
    eval_hoist_limit: Optional[int] = None,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BackendRunResult:
    """Run one experiment on the JAX backend; returns histories + final models.

    ``monitors`` (ISSUE-13 anomaly sentinel): an
    ``observability.monitors.MonitorBank`` observing the run's heartbeats
    online. With a bank installed the run executes through the SAME
    segmented progress machinery as ``progress_cb`` (off, and on with
    nothing firing, are bitwise the one-shot program — the progress
    contract), detectors fire structured anomalies into the bank, and
    under ``halt_on='fatal'`` a fatal anomaly stops the run at the next
    chunk boundary with the executed prefix returned as a partial
    result (``monitors.halted_at`` records where). Trace-derived
    detectors are fed the flight-recorder buffers after the run when
    ``config.telemetry`` is on.

    ``progress_cb`` (ISSUE-10 live observatory): a host callback receiving
    one ``observability.progress.ProgressEvent`` every ``progress_every``
    eval-chunks on ALL paths — the fused paths then execute as segments
    of the SAME compiled scan split at eval boundaries (trajectories stay
    bitwise-identical to the one-shot program, asserted); the measured
    chunked loop and the async event loop are host-synced per eval
    already and just invoke the callback at the same cadence. ``None``
    (default) changes nothing: same code path, same compiled program —
    the ``config.telemetry`` discipline.

    ``executable_cache`` controls AOT compile reuse (docs/SERVING.md): the
    default ``None`` consults the process-wide
    ``serving.cache.process_executable_cache()`` — a repeated identical run
    in one process re-executes the cached compiled program instead of
    re-tracing and re-compiling it (bitwise-identical results; the cache
    key pins the full config, f*, data/mesh signatures and the jax
    environment, so anything that could change the program misses).
    ``False`` forces a cold compile (benches that MEASURE compile cost use
    this); an ``ExecutableCache`` instance scopes reuse explicitly (the
    serving layer passes its own). Only the fused no-checkpoint path
    caches; the chunked/segmented forms always compile. On a cache hit
    ``history.compile_seconds`` is 0.0.

    ``hoisted_min_ratio`` / ``eval_hoist_limit`` override the module-level
    eval-cadence-form defaults (HOISTED_MIN_RATIO / EVAL_HOIST_LIMIT) for
    THIS run only — e.g. ``hoisted_min_ratio=0.0`` forces the hoisted
    exact-cadence form, ``eval_hoist_limit=0`` forces inline; ``None``
    keeps the measured defaults.

    ``measure_timestamps=True`` executes eval-chunks under a host-driven loop
    recording a real ``perf_counter`` timestamp per eval (one host sync per
    ``eval_every`` iterations) instead of the fully fused scan; the returned
    history then carries measured wall-clock (``time_measured=True``) rather
    than a linspace interpolation of the total run time. The default
    (``None`` == ``False``) is the fused scan at every cadence: since the
    round-3 flat restructuring fixed the nested-loop pipelining defect, the
    fused path is the fastest at EVERY eval cadence (measured 2.2× the
    chunked loop at eval_every=50k — docs/PERF.md "root cause" section), so
    the former coarse-cadence auto-routing is gone; measured timestamps are
    purely opt-in.

    A float64 config runs under a scoped ``enable_x64`` — without it jax
    silently truncates every array to float32, defeating the fidelity dtype.
    """
    from distributed_optimization_tpu.backends.base import x64_scope

    if config.execution == "async":
        # Event-driven asynchronous gossip (docs/ASYNC.md): a scan over
        # the precomputed event schedule instead of rounds. The
        # round-based execution knobs below have no event form — reject
        # loudly rather than silently ignoring them.
        from distributed_optimization_tpu.backends import async_scan

        if measure_timestamps:
            raise ValueError(
                "execution='async' reports the event schedule's simulated "
                "VIRTUAL clock (telemetry.async health block), not "
                "host-driven per-eval timestamps"
            )
        if mesh is not None:
            raise ValueError(
                "execution='async' runs unsharded: events are a totally "
                "ordered sequential schedule, which a worker mesh cannot "
                "partition"
            )
        return async_scan.run_async(
            config, dataset, f_opt, batch_schedule=batch_schedule,
            collect_metrics=collect_metrics,
            measure_compile=measure_compile, return_state=return_state,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors, checkpoint=checkpoint,
        )
    with x64_scope(config):
        return _run(
            config, dataset, f_opt, mesh=mesh, use_mesh=use_mesh,
            batch_schedule=batch_schedule, collect_metrics=collect_metrics,
            measure_compile=measure_compile, checkpoint=checkpoint,
            measure_timestamps=measure_timestamps,
            return_state=return_state,
            hoisted_min_ratio=hoisted_min_ratio,
            eval_hoist_limit=eval_hoist_limit,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors,
        )


# Eval-cadence forms for the fused scan (round 5 — VERDICT r4 item 6).
# The flat microchunk computes the full-dataset eval INLINE every `micro`
# iterations regardless of cadence. Round 3 called that "measured-free at
# this scale" (n_samples=12.5k) and left larger datasets open; round 5
# measured the alternatives across n_samples = 12.5k…2M and eval-dominance
# ratios 0.19…48.8 (docs/perf/eval_cadence.json,
# examples/bench_eval_cadence.py). Result: INLINE WON EVERY CELL. The
# inline eval feeds only the scan's stacked outputs (never the carry), so
# XLA overlaps it with subsequent steps — the discarded off-cadence evals
# stay substantially latency-hidden even at n=2M, where inline beat the
# exact-cadence HOISTED form 6x and the host-driven chunk loop 6x.
#
# The two exact-cadence alternatives both lose to per-boundary dispatch
# costs on this tunneled chip:
# - HOISTED (a Python-unrolled SEQUENCE of eval-free flat scans with the
#   eval between them — one XLA program, no nested/conditional control
#   flow in any hot loop body, eval exactly on cadence): each extra scan
#   region costs ~180 ms dispatch/sync (S=12.5k: hoisted ~31k vs inline
#   ~75k iters/sec with 5 regions), which no measured eval size amortizes.
# - chunk loop (measure_timestamps=True): one host round-trip per eval,
#   ~300 ms each — measured 311 vs 78,077 iters/sec at the headline scale.
#   Never a routing target; it exists for real per-eval timestamps.
#
# HOISTED_MIN_RATIO therefore defaults to infinity: the hoisted machinery
# stays (exact-cadence semantics, resume-exact, tested — and on LOCAL TPU
# hardware, where a scan region does not cost 180 ms of tunnel sync, the
# crossover would land where the naive FLOP model predicts), but nothing
# selects it by default on infrastructure where it measured slower
# everywhere. These module constants are IMMUTABLE defaults: override per
# run via the ``hoisted_min_ratio`` / ``eval_hoist_limit`` kwargs of
# ``run()`` (tests and examples/bench_eval_cadence.py force forms that
# way — nothing mutates the globals, so concurrent runs cannot race on
# them). EVAL_HOIST_LIMIT bounds program size (64 unrolled scan+eval
# segments).
EVAL_HOIST_LIMIT = 64
HOISTED_MIN_RATIO = float("inf")


# Mixing-impl history (why there is no TPU-specific resolver here): round 1
# (gather era) the fused pallas ring kernel won decisively at the headline
# shape; round 2 (dense sampling) pallas and stencil tied within chip
# noise; round 3 (flat fused scan) stencil measured ~10% ahead at d=81 and
# pallas ~13% ahead at d=1024 — one session each, which became a "d >= 512"
# auto-gate. Round 5 settled it with the interleaved 7-dim sweep the
# round-3 bracket asked for (d ∈ {81..1024},
# ``docs/perf/pallas_regimes.json``): the e2e pallas/stencil ratio bounces
# 0.78–1.29 with NO trend across adjacent dims — pure co-tenant noise — and
# the round-3 d=1024 win does not replicate (0.78 in the sweep). There is
# no crossover to gate on, so ``mixing_impl`` passes straight through to
# ``make_mixing_op`` ('auto' → stencil where the graph embeds as mesh
# shifts, else dense) and the VMEM kernels are explicit opt-in
# (``mixing_impl='pallas'``, f32 whole-array envelope only — Mosaic's
# dynamic_rotate cannot compile bf16, and operands live unblocked in VMEM,
# so the softmax tier's flat d·K models are out of range).


def _run(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    mesh=None,
    use_mesh: bool = True,
    batch_schedule: Optional[np.ndarray] = None,
    collect_metrics: bool = True,
    measure_compile: bool = True,
    checkpoint=None,
    measure_timestamps: Optional[bool] = None,
    return_state: bool = False,
    hoisted_min_ratio: Optional[float] = None,
    eval_hoist_limit: Optional[int] = None,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BackendRunResult:
    """Backend implementation (see ``run``).

    ``mesh``: an explicit ``jax.sharding.Mesh`` (1-D, axis 'workers');
    ``use_mesh=True`` builds one over all visible devices that evenly divide
    N. ``batch_schedule [T, N, b]`` injects fixed batch indices (equivalence
    testing vs the numpy oracle — SURVEY.md §4c). ``checkpoint``: a
    ``utils.checkpoint.CheckpointOptions``; when given, the run executes the
    flat fused scan in SEGMENTS of ``every_evals`` eval-chunks with an orbax
    save (and resume) between segments — add ``measure_timestamps=True`` to
    instead use the host-driven chunk loop with real per-eval timestamps,
    at its measured 2.2× coarse-cadence cost (docs/PERF.md §root-cause).
    """
    if config.telemetry and checkpoint is not None:
        raise ValueError(
            "telemetry trace buffers are not checkpointed: a resumed run "
            "would silently emit a truncated trace — record telemetry "
            "without checkpointing, or checkpoint without telemetry"
        )
    if progress_every < 1:
        raise ValueError(
            f"progress_every must be >= 1 eval-chunks, got {progress_every}"
        )
    # Monitors ride the progress machinery (ISSUE-13): the bank's observe
    # joins the callback chain, and under halt_on='fatal' the segmented
    # loops consult should_halt() at every chunk boundary.
    progress_emit = _progress_emitter(
        config, _fanout_progress(progress_cb, monitors)
    )
    halt_check = (
        monitors.should_halt
        if monitors is not None and monitors.halt_on != "never" else None
    )
    algo = get_algorithm(config.algorithm)
    problem = get_problem(
        config.problem_type, huber_delta=config.huber_delta,
        n_classes=config.n_classes,
    )
    reg = config.reg_param
    T = config.n_iterations
    n = config.n_workers

    device_data = stack_shards(dataset, dtype=np.dtype(config.dtype))
    # The trained parameter dimension: n_features for the scalar GLMs,
    # n_features·K for softmax (flattened [d, K] matrix). Everything the
    # model vector touches — state init, gossip payload accounting, the
    # mixing-impl gate — sizes off this, not off the feature count.
    d_model = problem.param_dim(device_data.n_features)

    # --- topology & collectives (centralized needs none) ---
    halo_mesh = None
    compressed_mix = None
    if algo.is_decentralized:
        topo = build_topology(
            config.topology, n, erdos_renyi_p=config.erdos_renyi_p,
            seed=config.resolved_topology_seed(),
            impl=config.resolved_topology_impl(),
            sampler=config.resolved_topology_sampler(),
        )
        if config.worker_mesh >= 2:
            # Sharded worker mesh (ISSUE-11 tentpole, docs/PERF.md §16):
            # exactly config.worker_mesh devices, contiguous row blocks.
            # The halo-exchange gather path IS the mixing operator; state,
            # data, and timeline columns shard over the same mesh below.
            if mesh is not None:
                from distributed_optimization_tpu.parallel.mesh import (
                    WORKER_AXIS as _WAXIS,
                )

                if (
                    _WAXIS not in mesh.shape
                    or mesh.shape[_WAXIS] != config.worker_mesh
                    or mesh.size != config.worker_mesh
                ):
                    raise ValueError(
                        f"worker_mesh={config.worker_mesh} needs a 1-D "
                        f"mesh with a {_WAXIS!r} axis of exactly that "
                        f"size (the halo plan, timeline slices and ICI "
                        f"accounting are all built for that P); got "
                        f"axes {dict(mesh.shape)}"
                    )
            else:
                from distributed_optimization_tpu.parallel.mesh import (
                    make_sized_worker_mesh,
                )

                mesh = make_sized_worker_mesh(config.worker_mesh)
            halo_mesh = mesh
            from distributed_optimization_tpu.parallel.collectives import (
                make_halo_compressed_mixing_op,
                make_halo_mixing_op,
            )

            mix_op = make_halo_mixing_op(
                topo, mesh, dtype=device_data.X.dtype,
                overlap=config.halo_overlap,
            )
            if config.compression != "none":
                # Compressed halo exchange (ISSUE-18): the error-feedback
                # algorithms route their wire rounds through this instead
                # of mix_op.apply — only q boundary rows cross devices,
                # with the receiver-side estimate copies persisted in the
                # *_halo state leaves seeded below.
                compressed_mix = make_halo_compressed_mixing_op(
                    topo, mesh, dtype=device_data.X.dtype
                )
        elif (
            mesh is None and use_mesh and len(jax.devices()) > 1
            and not topo.is_matrix_free
        ):
            # The shard_map grid stencil — and the GSPMD grid stencil the
            # auto path resolves to — block grid ROWS over devices, so the
            # mesh size must divide the row count, not just N (the
            # ISSUE-11 satellite: auto and explicit shard_map now apply
            # the SAME row-divisibility rule, so both resolve to the same
            # mesh instead of auto landing on a device count the row
            # reshape cannot split). The matrix-free path runs unsharded
            # unless worker_mesh asks for the halo route above: gather
            # indices under plain GSPMD would all-gather.
            if topo.grid_shape is not None and config.mixing_impl in (
                "shard_map", "stencil", "auto"
            ):
                mesh = make_worker_mesh(topo.grid_shape[0])
            else:
                mesh = make_worker_mesh(n)
        # No platform-specific resolution (see the mixing-impl history note
        # above the run() helpers): make_mixing_op resolves 'auto'.
        mixing_impl = config.mixing_impl
        if halo_mesh is not None:
            pass  # the halo gather op above IS the resolved mixing form
        elif mixing_impl == "shard_map":
            if mesh is None:
                raise ValueError("shard_map mixing requires a device mesh")
            mix_op = make_shard_map_mixing_op(topo, mesh)
        else:
            mix_op = make_mixing_op(
                topo, impl=mixing_impl, dtype=device_data.X.dtype
            )
        degrees = jnp.asarray(topo.degrees, dtype=device_data.X.dtype)[:, None]
        # Per-edge payload: d · gossip_rounds for full-vector exchange, or the
        # algorithm's override (compressed gossip transmits less).
        if algo.comm_payload is not None:
            edge_payload = algo.comm_payload(config, d_model)
            floats_per_iter = topo.floats_per_iteration * edge_payload
        else:
            edge_payload = d_model * algo.gossip_rounds
            floats_per_iter = decentralized_floats_per_iteration(
                topo, d_model, algo.gossip_rounds
            )
        spectral_gap = topo.spectral_gap
        time_varying = (
            config.edge_drop_prob > 0.0
            or config.straggler_prob > 0.0
            or config.mttf > 0.0
            or config.participation_rate < 1.0
            or config.gossip_schedule != "synchronous"
        )
        byzantine_active = config.attack != "none" or (
            config.aggregation != "gossip" and config.robust_b > 0
        )
        if config.mixing_impl == "shard_map":
            if time_varying:
                raise ValueError(
                    "fault injection / matching-based gossip requires dense "
                    "or stencil mixing: the shard_map stencils assume the "
                    "static uniform-weight topology"
                )
            if byzantine_active:
                raise ValueError(
                    "Byzantine injection / robust aggregation requires "
                    "dense or stencil mixing: the shard_map stencils "
                    "assume the static uniform-weight benign topology"
                )
        # Time-varying gossip and the Byzantine adversary + robust
        # aggregation composition (docs/BYZANTINE.md) — wiring shared with
        # the replica-batched path (``_build_faulty``/``_bind_byzantine``).
        # Byzantine is active when there is an attack to simulate OR a
        # robust rule with a positive budget to defend with; robust_b == 0
        # keeps the plain gossip path bitwise (a robust rule degrades to
        # MH gossip at zero budget by definition).
        faulty = _build_faulty(config, algo, topo, T, halo_mesh=halo_mesh)
        adversary, byz_mix, robust_activity, fused_robust_step = (
            _bind_byzantine(
                config, algo, topo, faulty, mix_op,
                # Auto only promotes to the fused kernel on unsharded
                # runs: under a worker mesh GSPMD would replicate the
                # pallas call (no partitioning rule) where the gather
                # ops shard — explicit robust_impl='fused' still runs.
                fused_auto_ok=mesh is None,
                halo_mesh=halo_mesh,
            )
        )
        # == adjacency.sum() for both orientations; degree-based so the
        # matrix-free representation needs no [N, N] array.
        static_degree_sum = float(np.asarray(topo.degrees).sum())
        if halo_mesh is not None:
            # Real-collective traffic accounting (ISSUE-11): the halo
            # plan is static, so bytes over ICI per device per round are
            # exact — surfaced as per-device gauges in the PR-10 metrics
            # registry (scraped at /metrics). One pricing source:
            # ``telemetry.ici_summary`` (also the report's bytes-over-ICI
            # line), fed the already-built topology per its one-build
            # convention, so /metrics and the report can never disagree.
            from distributed_optimization_tpu.observability.metrics_registry import (  # noqa: E501
                metrics_registry,
            )
            from distributed_optimization_tpu.telemetry import ici_summary

            _ici = ici_summary(
                config, topo=topo, d_features=device_data.n_features
            )
            _reg = metrics_registry()
            _g = _reg.gauge(
                "dopt_worker_mesh_ici_bytes_per_round",
                "Halo-exchange bytes each device ships per gossip round "
                "(static plan: rotation-padded wire rows x per-config "
                "row payload)",
            )
            _g.reset()  # a smaller mesh must not leave stale devices
            for _p, _bytes in enumerate(
                _ici["bytes_per_device_per_round"]
            ):
                _g.set(float(_bytes), device=str(_p))
            _reg.gauge(
                "dopt_worker_mesh_devices",
                "Worker-mesh shard count of the most recent sharded run",
            ).set(float(config.worker_mesh))
            _halo_g = _reg.gauge(
                "dopt_worker_mesh_halo_rows",
                "Boundary rows each device fetches per gossip round",
            )
            _halo_g.reset()
            for _p, _rows in enumerate(_ici["halo_rows_per_device"]):
                _halo_g.set(float(_rows), device=str(_p))
    else:
        if (
            config.edge_drop_prob > 0.0
            or config.straggler_prob > 0.0
            or config.mttf > 0.0
            or config.gossip_schedule != "synchronous"
            or config.attack != "none"
            or (config.aggregation != "gossip" and config.robust_b > 0)
        ):
            raise ValueError(
                "fault injection / matching-based gossip / Byzantine "
                "injection model peer exchanges and apply only to "
                "decentralized algorithms; the centralized pattern has no "
                "peer edges"
            )
        byzantine_active = False
        adversary = None
        byz_mix = None
        robust_activity = None
        fused_robust_step = None
        static_degree_sum = 0.0
        topo = None
        mix_op = None
        faulty = None
        edge_payload = None
        degrees = jnp.zeros((n, 1), dtype=device_data.X.dtype)
        floats_per_iter = centralized_floats_per_iteration(n, d_model)
        spectral_gap = None
        if mesh is None and use_mesh and len(jax.devices()) > 1:
            mesh = make_worker_mesh(n)

    # --- device placement (sharded over the worker axis where it matters) ---
    X = shard_over_workers(mesh, jnp.asarray(device_data.X))
    y = shard_over_workers(mesh, jnp.asarray(device_data.y))
    n_valid = shard_over_workers(mesh, jnp.asarray(device_data.n_valid))
    x0 = shard_over_workers(
        mesh, jnp.zeros((n, d_model), dtype=device_data.X.dtype)
    )
    state0 = algo.init(
        x0, config,
        neighbor_sum=mix_op.neighbor_sum if mix_op is not None else None,
    )
    if compressed_mix is not None:
        # Seed the persistent receiver-side halo copies (one per estimate
        # leaf; [P·(h_max+1), d] row-sharded, zeros — agreeing with the
        # zero xhat memories, which is what the bitwise induction vs the
        # unsharded exchange starts from). A resumed state that already
        # carries the leaves passes through untouched.
        for _leaf in ("xhat", "yhat"):
            if _leaf in state0 and f"{_leaf}_halo" not in state0:
                state0[f"{_leaf}_halo"] = shard_over_workers(
                    mesh,
                    jnp.zeros(
                        (compressed_mix.halo_rows, d_model),
                        dtype=device_data.X.dtype,
                    ),
                )
    key = jax.random.key(config.seed)

    schedule = None
    if batch_schedule is not None:
        schedule = replicate(mesh, jnp.asarray(batch_schedule, dtype=jnp.int32))

    full_objective = make_full_objective_fn(problem, reg)
    eta_fn = _make_eta_fn(config)
    batch_size = config.local_batch_size
    sampling_impl = config.resolved_sampling_impl(
        jax.devices()[0].platform, device_data.X.shape[1]
    )
    if (
        config.sampling_impl == "dense"
        and device_data.X.shape[1] > DENSE_SAMPLING_WARN_ROWS
    ):
        import warnings

        # The auto rule gates dense to L <= 64 and the measured crossover to
        # gather is around L ~ 250 (docs/perf/breakdown.json); an explicit
        # force beyond that silently pays the [L, L] ranking matrix.
        warnings.warn(
            f"--sampling-impl dense builds an [L, L] per-worker ranking "
            f"matrix every iteration (O(N·L²) work/memory); at L = "
            f"{device_data.X.shape[1]} rows the measured crossover favors "
            "'gather' — forcing dense anyway as requested",
            stacklevel=2,
        )

    # Sharded arrays are threaded through jit as ARGUMENTS, never captured:
    # a traced function that closes over an array spanning non-addressable
    # devices raises in multi-process runs (caught by
    # examples/multihost_smoke.py).
    data_args = {"X": X, "y": y, "n_valid": n_valid}
    if schedule is not None:
        data_args["schedule"] = schedule

    track_consensus = (
        collect_metrics and algo.is_decentralized and config.record_consensus
    )
    eval_every = config.eval_every
    # The chunked (host-driven) path nests a scan per chunk; split the unroll
    # budget so the total unrolled step bodies stay ~scan_unroll (not
    # scan_unroll²). The fused path below does NOT nest — see _flat_micro.
    scan_unroll = config.resolved_scan_unroll(jax.devices()[0].platform)
    inner_unroll = min(scan_unroll, eval_every)
    outer_unroll = max(1, scan_unroll // eval_every)

    honest_w = None
    if adversary is not None:
        honest_w = jnp.asarray(adversary.honest.astype(np.float32))

    # The pallas ring kernel fuses the whole canonical gossip-SGD update;
    # offer it to algorithms via the context (dsgd uses it). Disabled under
    # Byzantine injection: the fused W x − ηg bypasses the corrupt/screen
    # composition.
    fused_mix_step = None
    if (
        not byzantine_active
        and faulty is None
        and mix_op is not None
        and mix_op.impl == "pallas"
        and topo is not None
        and topo.name == "ring"
    ):
        from distributed_optimization_tpu.ops.pallas_kernels import (
            fused_ring_dsgd_step,
        )

        fused_mix_step = fused_ring_dsgd_step

    pieces = _StepPieces(
        algo=algo, problem=problem, reg=reg, config=config,
        batch_size=batch_size, sampling_impl=sampling_impl, key=key,
        eta_fn=eta_fn, degrees=degrees, mix_op=mix_op, faulty=faulty,
        byz_mix=byz_mix, adversary=adversary, honest_w=honest_w,
        fused_mix_step=fused_mix_step, full_objective=full_objective,
        f_opt=f_opt, collect_metrics=collect_metrics,
        track_consensus=track_consensus, edge_payload=edge_payload,
        fused_robust_step=fused_robust_step,
        telemetry=config.telemetry, robust_activity=robust_activity,
        static_degree_sum=static_degree_sum,
        compressed_mix=compressed_mix,
    )

    def make_step_eval(data):
        return _make_step_eval(pieces, data)

    def make_chunk(data):
        """One eval-chunk for the host-driven loop: ``eval_every`` iterations
        of pure optimization under a nested scan, then one on-device metric
        evaluation — the eval-cadence knob SURVEY.md §7 hard part (b) calls
        for (the reference evaluates every iteration; k=1 reproduces that
        exactly)."""
        step, eval_metrics, floats_for = make_step_eval(data)

        def chunk(state, ts):
            state, _ = jax.lax.scan(step, state, ts, unroll=inner_unroll)
            out = eval_metrics(state, ts[-1], cadence_known=True)
            if faulty is not None:
                out["floats"] = floats_for(ts)
            return state, out

        return chunk

    n_evals = T // eval_every

    # The default is the fused scan at every cadence (see ``run``'s
    # docstring: the flat restructuring removed the coarse-cadence defect
    # that round 2's auto-routing worked around); measured timestamps are
    # opt-in because the host-driven loop pays one tunnel round-trip per
    # eval chunk — never a routing target (see the eval-cadence note above
    # the run() helpers: measured 311 vs 78,077 iters/sec).
    if measure_timestamps is None:
        measure_timestamps = False

    # Quantities for the eval-cadence form choice (round 5 — see
    # EVAL_HOIST_LIMIT / HOISTED_MIN_RATIO above). Checkpointed runs hoist
    # per SEGMENT (each compiled scan covers every_evals eval-chunks), so
    # the hoist-availability gate uses the per-scan eval count, not the
    # run total.
    _micro_probe, _trips_per_eval, _flat_unroll = _flat_scan_cadence(
        scan_unroll, eval_every
    )
    per_scan_evals = (
        n_evals if checkpoint is None
        else min(checkpoint.every_evals, max(n_evals, 1))
    )
    total_samples = float(np.sum(device_data.n_valid))
    eval_dominance_ratio = total_samples / max(
        2.0 * _micro_probe * n
        * min(batch_size, device_data.X.shape[1]), 1.0
    )

    if not measure_timestamps:
        # FLAT fused scan (round-3 anomaly fix — mechanism and measurements
        # in docs/PERF.md §"root cause"): the run is ONE scan over
        # micro-chunks of ``micro`` Python-unrolled steps with the metric
        # eval computed INLINE every trip — never a scan nested inside a
        # scan, and no lax.cond in the body. Both alternatives measured
        # badly on the chip, for the same reason: non-flat control flow in
        # the hot loop body defeats XLA:TPU's inter-iteration pipelining.
        # The round-2 nested form (outer chunks × inner step scan) ran
        # identical fusions ~6.4× slower per execution inside the nested
        # while (device-trace evidence, co-tenant-free; 2.1× total device
        # time), and a cond-guarded eval re-serialized the loop harder
        # still (~23k vs ~47k iters/sec, same session). Computing the eval
        # every trip is measured-free at this scale (the full-data pass is
        # a few µs against a latency-bound step) and the off-cadence rows
        # are discarded host-side; ``micro`` is the largest divisor of
        # eval_every within the unroll budget so some trip lands exactly on
        # every eval boundary. At k=1 this degenerates to exactly the old
        # (always-fast) flat structure.
        #
        # Checkpointed runs (round 4 — VERDICT r3 item 5) run the SAME flat
        # scan in segments of ``checkpoint.every_evals`` eval-chunks with an
        # orbax save between segments, instead of paying the host-driven
        # chunk loop's 2.2× coarse-cadence tax for the whole run; the host
        # intervenes once per SAVE, not once per eval.
        micro = _micro_probe
        trips_per_eval = _trips_per_eval
        flat_unroll = _flat_unroll

        # Exact-cadence "hoisted" form (round 5 — VERDICT r4 item 6): a
        # Python-unrolled SEQUENCE of eval-free flat scans with the metric
        # eval computed between them. Applies only when the run is
        # measured eval-DOMINATED (the per-region dispatch tax otherwise
        # loses to inline's latency-hidden extra evals — see the
        # eval-cadence note above the run() helpers), the inline form
        # would compute more evals than the cadence asks for
        # (trips_per_eval > 1), and the program stays small (evals per
        # compiled scan <= the hoist limit). Checkpointed runs hoist per
        # SEGMENT, so coarse-cadence checkpointed runs on huge datasets
        # get exact-cadence evals even when the run's total eval count is
        # large.
        hoist_limit = (
            EVAL_HOIST_LIMIT if eval_hoist_limit is None else eval_hoist_limit
        )
        min_ratio = (
            HOISTED_MIN_RATIO if hoisted_min_ratio is None
            else hoisted_min_ratio
        )
        use_hoisted = (
            collect_metrics
            and trips_per_eval > 1
            and per_scan_evals <= hoist_limit
            and eval_dominance_ratio >= min_ratio
        )

        def make_microchunk(data):
            step, eval_metrics, floats_for = make_step_eval(data)

            def microchunk(state, ts_row):
                for j in range(micro):
                    state, _ = step(state, ts_row[j])
                out = eval_metrics(
                    state, ts_row[-1], cadence_known=trips_per_eval == 1
                )
                if faulty is not None:
                    out["floats"] = floats_for(ts_row)
                return state, out

            return microchunk

        def make_hoisted_scan(n_evals_in):
            """``n_evals_in`` eval-chunks as sequential flat scans inside
            one traced program; iteration indices offset by a (possibly
            traced) ``t0`` so one executable serves every same-size
            segment. No scan nests inside a scan and no cond guards the
            eval — the round-3 pipelining constraints hold; the eval just
            moves from the scan body to between scans, running EXACTLY
            once per cadence point."""

            def hoisted(state_init, t0, data):
                step, eval_metrics, floats_for = make_step_eval(data)

                def micro_only(state, ts_row):
                    for j in range(micro):
                        state, _ = step(state, ts_row[j])
                    return state, None

                state, outs = state_init, []
                for e in range(n_evals_in):
                    ts = (
                        t0 + e * eval_every
                        + jnp.arange(eval_every, dtype=jnp.int32)
                    ).reshape(trips_per_eval, micro)
                    state, _ = jax.lax.scan(
                        micro_only, state, ts, unroll=flat_unroll
                    )
                    out = eval_metrics(
                        state, ts.reshape(-1)[-1], cadence_known=True
                    )
                    if faulty is not None:
                        out["floats"] = floats_for(ts.reshape(-1))
                    outs.append(out)
                ys = jax.tree.map(lambda *vs: jnp.stack(vs), *outs)
                return state, ys

            return hoisted

        def make_inline_seg_scan(n_seg_evals):
            n_trips_seg = n_seg_evals * trips_per_eval

            def seg_scan(state_init, t0, data):
                microchunk = make_microchunk(data)
                ts = (
                    t0 + jnp.arange(n_trips_seg * micro, dtype=jnp.int32)
                ).reshape(n_trips_seg, micro)
                return jax.lax.scan(
                    microchunk, state_init, ts, unroll=flat_unroll
                )

            return seg_scan

        def _harvest_inline(ys, n_rows_evals):
            """On-cadence metric rows from a scan's stacked outputs (the
            off-cadence rows hold real inline-computed evals the requested
            cadence discards); faults' realized floats summed per eval.
            Trace-buffer rows select like the gap: the eval-boundary trip's
            row is the recorded one."""
            sel = slice(trips_per_eval - 1, None, trips_per_eval)
            gap = (
                np.asarray(ys["gap"][sel], dtype=np.float64)
                if "gap" in ys else None
            )
            cons = (
                np.asarray(ys["cons"][sel], dtype=np.float64)
                if "cons" in ys else None
            )
            floats = (
                np.asarray(ys["floats"], dtype=np.float64)
                .reshape(n_rows_evals, trips_per_eval).sum(axis=1)
                if "floats" in ys else None
            )
            trace = (
                {k: np.asarray(v)[sel] for k, v in ys["trace"].items()}
                if "trace" in ys else None
            )
            return gap, cons, floats, trace

        def _harvest_hoisted(ys, n_rows_evals):
            """Hoisted rows are already exactly per-eval."""
            return (
                np.asarray(ys["gap"], dtype=np.float64)
                if "gap" in ys else None,
                np.asarray(ys["cons"], dtype=np.float64)
                if "cons" in ys else None,
                np.asarray(ys["floats"], dtype=np.float64)
                if "floats" in ys else None,
                {k: np.asarray(v) for k, v in ys["trace"].items()}
                if "trace" in ys else None,
            )

        make_seg_scan = (
            make_hoisted_scan if use_hoisted else make_inline_seg_scan
        )
        _harvest = _harvest_hoisted if use_hoisted else _harvest_inline

        if checkpoint is None and progress_emit is None:
            def run_scan(state_init, data):
                t0_const = jnp.asarray(0, dtype=jnp.int32)
                return make_seg_scan(n_evals)(state_init, t0_const, data)

            # AOT executable reuse (docs/SERVING.md): the sequential
            # program bakes its PRNG key, scalars and f*, so the key is
            # the FULL config hash + call-level trace facts — a hit means
            # the identical experiment ran before in this process, and
            # re-executing its compiled program is bitwise the same.
            exec_cache = resolve_cache(executable_cache)
            cache_key = cached = None
            if exec_cache is not None:
                cache_key = sequential_cache_key(
                    config, f_opt, device_data,
                    schedule_signature=(
                        tuple(batch_schedule.shape)
                        if batch_schedule is not None else None
                    ),
                    collect_metrics=collect_metrics,
                    mesh_signature=(
                        tuple(str(d) for d in mesh.devices.flat)
                        if mesh is not None else None
                    ),
                    hoisted_min_ratio=hoisted_min_ratio,
                    eval_hoist_limit=eval_hoist_limit,
                )
                cached = exec_cache.get(cache_key)
            if cached is not None:
                compiled = cached.executable
                cost = cached.cost if config.telemetry else None
                compile_seconds = 0.0
            else:
                # AOT compile so compile time and steady-state execution
                # are separable (jax.profiler-style phase split, SURVEY.md
                # §5.1).
                t0 = time.perf_counter()
                with jax.default_matmul_precision(config.matmul_precision):
                    lowered = jax.jit(run_scan).lower(state0, data_args)
                    cost = (
                        cost_from_lowered(lowered)
                        if config.telemetry else None
                    )
                    compiled = lowered.compile()
                cold_seconds = time.perf_counter() - t0
                compile_seconds = cold_seconds if measure_compile else 0.0
                if exec_cache is not None:
                    exec_cache.put(
                        cache_key, compiled, cost=cost,
                        compile_seconds=cold_seconds,
                    )

            t1 = time.perf_counter()
            final_state, ys = compiled(state0, data_args)
            final_state = jax.block_until_ready(final_state)
            run_seconds = time.perf_counter() - t1
            executed_iters = T

            gap_hist, cons_hist, floats_per_eval, trace = _harvest(
                ys, n_evals
            )
            if gap_hist is None:
                gap_hist = np.full(n_evals, np.nan)
            realized_floats = (
                float(floats_per_eval.sum())
                if floats_per_eval is not None else None
            )
            # The fused scan runs on-device without per-eval host
            # timestamps; spread the measured total uniformly (interpolated
            # — the report labels it as such; pass measure_timestamps=True
            # for real samples).
            time_hist = np.linspace(
                run_seconds / max(n_evals, 1), run_seconds, n_evals
            )
        else:
            # Segmented execution: checkpointed runs (orbax save between
            # segments) and/or progress streaming (heartbeat between
            # segments) — the same flat scan split at eval boundaries.
            # Progress-only segments reuse cached executables (the
            # serving daemon heartbeats every request); checkpointed
            # runs keep the always-compile behavior.
            seg_cache = (
                resolve_cache(executable_cache) if checkpoint is None
                else None
            )
            cache_key_fn = None
            if seg_cache is not None:
                mesh_sig = (
                    tuple(str(d) for d in mesh.devices.flat)
                    if mesh is not None else None
                )
                sched_sig = (
                    tuple(batch_schedule.shape)
                    if batch_schedule is not None else None
                )

                def cache_key_fn(size):
                    return sequential_cache_key(
                        config, f_opt, device_data,
                        schedule_signature=sched_sig,
                        collect_metrics=collect_metrics,
                        mesh_signature=mesh_sig,
                        hoisted_min_ratio=hoisted_min_ratio,
                        eval_hoist_limit=eval_hoist_limit,
                        segment=("seg", int(size)),
                    )

            (final_state, gap_hist, cons_hist, time_hist, realized_floats,
             executed_iters, compile_seconds, run_seconds, trace, cost) = (
                _run_segmented_fused(
                    make_seg_scan, _harvest, state0, data_args, checkpoint,
                    mesh, config, n_evals, measure_compile,
                    progress_hook=progress_emit,
                    progress_every=progress_every,
                    exec_cache=seg_cache, cache_key_fn=cache_key_fn,
                    halt_check=halt_check,
                )
            )
            if gap_hist is None:
                gap_hist = np.full(len(time_hist), np.nan)
        # Per-eval wall-clock is interpolated on both fused paths (within
        # segments, for the checkpointed one) — time_measured stays False.
        time_measured = False
    else:
        def chunk_fn(state, ts, data):
            return make_chunk(data)(state, ts)

        (final_state, gap_hist, cons_hist, time_hist, realized_floats,
         executed_iters, compile_seconds, run_seconds, trace, cost) = (
            _run_chunked(
                chunk_fn, state0, data_args, checkpoint, mesh, config,
                n_evals, measure_compile, progress_hook=progress_emit,
                progress_every=progress_every, halt_check=halt_check,
            )
        )
        time_measured = True
        if not collect_metrics:
            gap_hist = np.full(len(time_hist), np.nan)
        if not track_consensus:
            cons_hist = None

    # Early-halt bookkeeping (ISSUE-13): a loop that stopped before the
    # horizon left fewer per-eval rows than n_evals. The histories stay
    # honestly partial (their eval axis names the executed prefix), the
    # bank records where, and the analytic floats accounting covers only
    # the executed iterations — a halted run must not bill the horizon.
    n_done_evals = len(time_hist)
    halted = monitors is not None and n_done_evals < n_evals
    if halted:
        monitors.note_halt(n_done_evals * eval_every)
    if monitors is not None and trace is not None:
        # The iteration axis starts at eval_every unconditionally: trace
        # buffers exist only under config.telemetry, which is rejected
        # with checkpointing above — a trace can never belong to a
        # resumed run whose rows would need a start-chunk offset.
        monitors.scan_trace(
            trace,
            np.arange(eval_every, T + 1, eval_every)[:n_done_evals],
        )

    total_floats = (
        realized_floats if realized_floats is not None
        else floats_per_iter * (n_done_evals * eval_every if halted else T)
    )
    final_models = _fetch_to_host(final_state["x"]).astype(np.float64)
    # The reported model under attack is the HONEST average — Byzantine
    # rows are adversary-controlled state, not part of the solution.
    final_avg = (
        final_models[adversary.honest].mean(axis=0)
        if adversary is not None
        else final_models.mean(axis=0)
    )

    history = RunHistory(
        objective=gap_hist,
        consensus_error=cons_hist,
        time=time_hist,
        time_measured=time_measured,
        # Truncated to the executed prefix when the run halted early.
        eval_iterations=np.arange(eval_every, T + 1, eval_every)[
            :n_done_evals
        ],
        total_floats_transmitted=total_floats,
        # Throughput counts only iterations executed in THIS process, so a
        # resumed run doesn't claim credit for checkpointed progress.
        iters_per_second=(
            executed_iters / run_seconds if run_seconds > 0 and executed_iters
            else float("nan")
        ),
        compile_seconds=compile_seconds,
        spectral_gap=spectral_gap,
        trace=trace,
        cost=cost,
    )
    return BackendRunResult(
        history=history,
        final_models=final_models,
        final_avg_model=final_avg,
        final_state=(
            {
                k: _fetch_to_host(v).astype(np.float64)
                for k, v in final_state.items()
            }
            if return_state
            else None
        ),
    )


# --------------------------------------------------------------------------
# Replica-batched execution (ISSUE-4 tentpole): R independent runs — seed
# replicates and/or swept scalar hyperparameters — as ONE vmapped compiled
# program. The headline hot loop is latency/dispatch-bound (BENCH_r05: a
# [256, 81] model stack at ~103k iters/sec leaves the vector lanes mostly
# idle), so stacking R runs into [R, N, d] buys aggregate sweep throughput
# for near-free: every seed replicate a suite row needs, and every
# robustness experiment's mean ± std over fault realizations, costs ~one
# run's wall-clock instead of R (measured: examples/bench_sweep.py →
# docs/perf/sweep.json, asserted ≥ 8× aggregate at R=32).
# --------------------------------------------------------------------------


@dataclasses.dataclass
class BatchRunResult:
    """R replica trajectories from one ``run_batch`` call.

    ``results[r]`` is a per-replica ``BackendRunResult`` whose history is
    trajectory-equivalent to a sequential ``run`` of
    ``config.replace(seed=seeds[r], **{f: sweep[f][r]})`` (pinned ≤ 1e-12
    in f64 by tests/test_batch.py, fault and Byzantine layers included).
    Per-replica ``iters_per_second`` is the aggregate divided by R (the
    batch time-slices the chip evenly); ``aggregate_iters_per_second`` is
    the batch's R·T / run_seconds — the sweep-throughput headline.
    ``final_states`` holds the raw stacked state pytree ([R, ...] leaves,
    run dtype) — pass it back as ``state0`` with ``t0`` advanced to
    continue the batch exactly (per-replica resume-exactness is tested).
    """

    results: list
    seeds: list
    sweep: Optional[dict]
    objective: np.ndarray  # [R, n_evals] suboptimality gaps
    consensus_error: Optional[np.ndarray]  # [R, n_evals] or None
    aggregate_iters_per_second: float
    run_seconds: float
    compile_seconds: float
    final_states: dict


def batch_unsupported_reason(config) -> Optional[str]:
    """Why ``run_batch`` cannot execute this config, or None when it can.

    The single source of the batched path's rejection logic:
    ``_run_batch`` raises exactly these strings, and the serving
    coalescer (``serving/coalescer.py``) consults the same function to
    route unbatchable requests down the sequential fallback instead of
    discovering the rejection mid-cohort.
    """
    if config.backend != "jax":
        return (
            "replica-batched execution vmaps the jax scan; backend="
            f"{config.backend!r} runs one trajectory at a time — use "
            "backend='jax' or loop single runs"
        )
    if config.algorithm == "choco":
        return (
            "run_batch does not support 'choco': its step rule derives "
            "the compressor stream from config.seed internally, which the "
            "batched per-replica seed axis cannot reach — replicas would "
            "silently share compression draws"
        )
    if config.mixing_impl in ("shard_map", "pallas"):
        return (
            f"run_batch is incompatible with mixing_impl="
            f"{config.mixing_impl!r}: shard_map stencils pin a device "
            "mesh and the pallas kernels address unbatched VMEM blocks — "
            "use 'auto', 'dense', 'stencil', or 'sparse'"
        )
    if config.robust_impl == "fused":
        return (
            "run_batch is incompatible with robust_impl='fused': the "
            "fused pallas kernel addresses unbatched VMEM blocks — use "
            "'auto', 'gather', or 'dense' (auto never promotes to fused "
            "inside the replica batch)"
        )
    if config.compression != "none":
        return (
            "run_batch does not support compressed gossip: the "
            "error-feedback step derives its compressor stream from "
            "config.seed internally, which the batched per-replica seed "
            "axis cannot reach — replicas would silently share "
            "compression draws"
        )
    if config.tp_degree > 1:
        return (
            "run_batch and tp_degree > 1 are mutually exclusive: the TP "
            "path pins a 2-D (workers, model) device mesh that the "
            "replica vmap axis cannot wrap"
        )
    if config.execution == "async":
        return (
            "run_batch does not support execution='async': the event "
            "path is a sequential scan over one totally ordered schedule "
            "per seed, and the per-replica schedules have different "
            "event ORDERS (the order is data, but the staleness replay "
            "is not) — run seeds sequentially"
        )
    if config.worker_mesh >= 2:
        return (
            "run_batch and worker_mesh are mutually exclusive: the "
            "replica axis vmaps one unsharded program (it fills the chip "
            "instead of the worker mesh), and the halo-exchange shard_map "
            "pins a fixed device mesh — run sharded seeds sequentially"
        )
    return None


def run_batch(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    seeds=None,
    sweep=None,
    collect_metrics: bool = True,
    measure_compile: bool = True,
    state0=None,
    t0: int = 0,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BatchRunResult:
    """Run R replicas of ``config`` as one vmapped XLA program.

    ``monitors`` (ISSUE-13): a ``MonitorBank`` observing the cohort
    heartbeats (which carry per-replica gaps — the divergence detector
    judges the WORST replica, so one sick replica cannot hide behind the
    cohort mean); under ``halt_on='fatal'`` the whole batch stops at the
    next segment boundary (the replica axis is one compiled program — it
    cannot halt per replica). Rides the same segmented machinery as
    ``progress_cb``; trajectories with nothing firing stay bitwise.

    ``progress_cb``/``progress_every`` (ISSUE-10): when set, the batched
    program executes as segments of ``progress_every`` eval-chunks (the
    continuation machinery — one executable serves every same-size
    segment, trajectories bitwise the one-shot call's) with one
    ``ProgressEvent`` per boundary carrying the replica-mean gap and the
    per-replica gaps. ``None`` changes nothing.

    ``seeds``: per-replica seed vector (default ``config.replica_seeds()``
    — seed, seed+1, ..., seed+replicas−1). ``sweep``: optional dict
    mapping a ``SWEEPABLE_FIELDS`` name to R per-replica values; replica r
    then behaves exactly like a sequential run of ``config.replace(
    seed=seeds[r], **{field: values[r]})``. ``state0``/``t0`` continue a
    previous batch from its ``final_states`` (iteration indices — and the
    counter-based sampling/fault draws with them — resume at t0, so the
    continuation is exactly the one-shot program split in two).

    Structural axes (topology, n_workers, algorithm, ...) cannot batch —
    they change the traced program — and are rejected; so are the config
    combinations whose execution cannot wrap in vmap (shard_map/pallas
    mixing, tensor parallelism, choco's internal seed derivation) — see
    ``batch_unsupported_reason``. The batched program runs unsharded (the
    replica axis fills the chip instead of the worker mesh) and always
    uses the fused flat scan.

    ``executable_cache`` controls AOT compile reuse (docs/SERVING.md; same
    convention as ``run``): seeds, swept scalars, fault timelines,
    Byzantine masks and f* are traced INPUTS of the batched program, so a
    cached executable is reusable across seed AND sweep variants of one
    structural config — the serving layer's whole amortization story. The
    default ``None`` consults the process-wide cache; ``False`` forces a
    cold compile.
    """
    from distributed_optimization_tpu.backends.base import x64_scope

    if config.worker_mesh >= 2:
        # Sequential-mesh dispatch (ISSUE-18 satellite): the halo-exchange
        # shard_map pins a fixed device mesh the replica vmap axis cannot
        # wrap, so a sharded cohort runs as R sequential mesh runs sharing
        # one AOT executable (seeds and swept scalars are traced inputs —
        # replica 2..R hit the executable cache replica 1 compiled).
        # ``batch_unsupported_reason`` still names worker_mesh so the
        # serving coalescer routes these down its sequential path; this
        # entry point dispatches them itself so ``replicas=R`` sweeps work
        # at N=100k (docs/perf/scenarios.json agreement gate).
        return _run_sequential_mesh_batch(
            config, dataset, f_opt, seeds=seeds, sweep=sweep,
            collect_metrics=collect_metrics,
            measure_compile=measure_compile, state0=state0, t0=t0,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors,
        )
    with x64_scope(config):
        return _run_batch(
            config, dataset, f_opt, seeds=seeds, sweep=sweep,
            collect_metrics=collect_metrics,
            measure_compile=measure_compile, state0=state0, t0=t0,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors,
        )


def _run_sequential_mesh_batch(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    seeds,
    sweep,
    collect_metrics: bool,
    measure_compile: bool,
    state0,
    t0: int,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BatchRunResult:
    """R sequential worker-mesh runs presented as one ``BatchRunResult``.

    Each replica r executes the IDENTICAL sharded program a direct
    ``run(config.replace(replicas=1, seed=seeds[r], ...))`` would — same
    halo exchange, same per-device bytes — so per-replica trajectories
    are exactly the sequential ones (not merely equivalent). The topology
    seed is pinned to the base config's resolved value so every replica
    gossips over the SAME graph, matching the batched path's convention.
    ``final_states`` leaves are host-fetched float64 ([R, ...] stacked);
    batch continuation (``state0``/``t0``) is not supported here — the
    sequential runs have no state-injection port yet.
    """
    from distributed_optimization_tpu.config import SWEEPABLE_FIELDS

    if state0 is not None or t0 != 0:
        raise ValueError(
            "worker_mesh batches run as R sequential mesh runs, which "
            "cannot resume from a stacked state0/t0 — continue each "
            "replica with its own sequential run instead"
        )
    if seeds is None:
        seeds = config.replica_seeds()
    seeds = [int(s) for s in seeds]
    if not seeds:
        raise ValueError("run_batch needs at least one replica seed")
    R = len(seeds)
    sweep = {k: list(v) for k, v in (sweep or {}).items()}
    for field, values in sweep.items():
        if field not in SWEEPABLE_FIELDS:
            raise ValueError(
                f"cannot sweep {field!r} across a replica cohort: only "
                f"the per-replica scalar axes ({', '.join(SWEEPABLE_FIELDS)}) "
                "sweep this way; structural axes change the program — run "
                "separate calls per value"
            )
        if len(values) != R:
            raise ValueError(
                f"sweep[{field!r}] has {len(values)} values for {R} "
                "replicas; every swept axis must match the seed vector's "
                "length"
            )

    topo_seed = config.resolved_topology_seed()
    results = []
    compile_seconds = 0.0
    run_seconds = 0.0
    for r in range(R):
        overrides = {f: v[r] for f, v in sweep.items()}
        rep_cfg = config.replace(
            replicas=1, seed=seeds[r], topology_seed=topo_seed, **overrides
        )
        res = run(
            rep_cfg, dataset, f_opt,
            collect_metrics=collect_metrics,
            measure_compile=measure_compile,
            executable_cache=executable_cache,
            progress_cb=progress_cb, progress_every=progress_every,
            monitors=monitors, return_state=True,
        )
        compile_seconds += float(res.history.compile_seconds or 0.0)
        ips = float(res.history.iters_per_second)
        run_seconds += (
            config.n_iterations / ips if ips > 0 else float("nan")
        )
        results.append(res)
        if monitors is not None and monitors.halt_on != "never" and (
            monitors.should_halt()
        ):
            break

    objective = np.stack(
        [np.asarray(res.history.objective, dtype=np.float64)
         for res in results]
    )
    cons = (
        np.stack([
            np.asarray(res.history.consensus_error, dtype=np.float64)
            for res in results
        ])
        if all(res.history.consensus_error is not None for res in results)
        else None
    )
    final_states = {
        k: np.stack([res.final_state[k] for res in results])
        for k in results[0].final_state
    }
    done_R = len(results)
    aggregate_ips = (
        done_R * config.n_iterations / run_seconds
        if run_seconds > 0 else float("nan")
    )
    return BatchRunResult(
        results=results,
        seeds=seeds[:done_R],
        sweep=sweep or None,
        objective=objective,
        consensus_error=cons,
        aggregate_iters_per_second=aggregate_ips,
        run_seconds=run_seconds,
        compile_seconds=compile_seconds,
        final_states=final_states,
    )


def _run_batch(
    config,
    dataset: HostDataset,
    f_opt: float,
    *,
    seeds,
    sweep,
    collect_metrics: bool,
    measure_compile: bool,
    state0,
    t0: int,
    executable_cache=None,
    progress_cb=None,
    progress_every: int = 1,
    monitors=None,
) -> BatchRunResult:
    from distributed_optimization_tpu.config import SWEEPABLE_FIELDS
    from distributed_optimization_tpu.parallel.adversary import (
        _BYZ_NOISE_TAG,
        byzantine_mask,
    )
    from distributed_optimization_tpu.parallel.faults import (
        FaultTimeline,
        stack_fault_timelines,
        timeline_for_config,
    )

    # --- resolve and validate the replica axis -------------------------
    if seeds is None:
        seeds = config.replica_seeds()
    seeds = [int(s) for s in seeds]
    if not seeds:
        raise ValueError("run_batch needs at least one replica seed")
    R = len(seeds)
    sweep = {k: list(v) for k, v in (sweep or {}).items()}
    for field, values in sweep.items():
        if field not in SWEEPABLE_FIELDS:
            raise ValueError(
                f"cannot sweep {field!r} inside one batched program: only "
                f"per-replica scalars that enter the compiled program as "
                f"data batch this way ({', '.join(SWEEPABLE_FIELDS)}); "
                "structural axes change the traced program itself — run "
                "separate (possibly batched) calls per value"
            )
        if len(values) != R:
            raise ValueError(
                f"sweep[{field!r}] has {len(values)} values for {R} "
                "replicas; every swept axis must match the seed vector's "
                "length"
            )
    # The backend field routes dispatch (run_algorithm_batch), not this
    # entry point — a direct call compiles on jax regardless, so only the
    # execution-structure rejections apply here.
    unbatchable = batch_unsupported_reason(config.replace(backend="jax"))
    if unbatchable is not None:
        raise ValueError(unbatchable)
    if t0 < 0:
        raise ValueError(f"t0 must be >= 0, got {t0}")
    if not get_algorithm(config.algorithm).is_decentralized and (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.gossip_schedule != "synchronous"
        or config.attack != "none"
        or (config.aggregation != "gossip" and config.robust_b > 0)
        or "edge_drop_prob" in sweep
    ):
        # Mirror the sequential path's centralized rejection: silently
        # running a benign program here would break the replica-r ==
        # run(rep_cfgs[r]) contract (the sequential run raises).
        raise ValueError(
            "fault injection / matching-based gossip / Byzantine "
            "injection model peer exchanges and apply only to "
            "decentralized algorithms; the centralized pattern has no "
            "peer edges"
        )
    if "edge_drop_prob" in sweep and not all(
        0.0 < float(v) < 1.0 for v in sweep["edge_drop_prob"]
    ):
        raise ValueError(
            "swept edge_drop_prob values must all be in (0, 1): the "
            "batched fault threshold is traced data, so every replica "
            "must run the fault-sampling path (p = 0 rows belong in a "
            "separate fault-free batch)"
        )
    if "clip_tau" in sweep:
        if config.aggregation != "clipped_gossip" or config.robust_b <= 0:
            raise ValueError(
                "sweeping clip_tau requires aggregation='clipped_gossip' "
                "with robust_b > 0 — otherwise the radius is silently "
                "ignored"
            )
        if not all(float(v) > 0.0 for v in sweep["clip_tau"]):
            raise ValueError(
                "swept clip_tau values must all be > 0: the adaptive "
                "radius (clip_tau=0) is a different traced program — run "
                "it as its own batch"
            )
    # Per-replica sequential-equivalent configs: this DEFINES the batched
    # semantics (replica r == run(rep_cfgs[r])) and validates every cell
    # through the frozen dataclass's own cross-field checks. The topology
    # seed is pinned to the base config's resolved value — the graph is
    # structural (a per-replica graph cannot batch), so a seed sweep
    # varies run randomness over ONE fixed graph instance, and each
    # rep_cfg names exactly that run.
    rep_cfgs = [
        config.replace(
            seed=s,
            topology_seed=config.resolved_topology_seed(),
            **{f: type(getattr(config, f))(vals[r])
               for f, vals in sweep.items()},
        )
        for r, s in enumerate(seeds)
    ]

    algo = get_algorithm(config.algorithm)
    problem = get_problem(
        config.problem_type, huber_delta=config.huber_delta,
        n_classes=config.n_classes,
    )
    reg = config.reg_param
    T = config.n_iterations
    n = config.n_workers
    horizon = t0 + T  # fault timelines are prefix-stable in the horizon

    device_data = stack_shards(dataset, dtype=np.dtype(config.dtype))
    d_model = problem.param_dim(device_data.n_features)

    # --- static (replica-shared) topology & mixing ---------------------
    # The graph is anchored on the BASE config's seed: the replica axis
    # sweeps run randomness (sampling, faults, adversary draws) over one
    # fixed problem instance + topology, which is what mean ± std over
    # replicates measures.
    if algo.is_decentralized:
        topo = build_topology(
            config.topology, n, erdos_renyi_p=config.erdos_renyi_p,
            seed=config.resolved_topology_seed(),
            # Resolve from a PER-REPLICA config, not the base: a swept
            # edge_drop_prob axis (base 0.0, positive per replica) is a
            # dense-only feature the base config's auto rule cannot see —
            # all rep_cfgs resolve identically because swept edge values
            # are validated positive above, and each rep_cfg IS the
            # sequential run this batch must reproduce.
            impl=rep_cfgs[0].resolved_topology_impl(),
            sampler=rep_cfgs[0].resolved_topology_sampler(),
        )
        mix_op = make_mixing_op(
            topo, impl=config.mixing_impl, dtype=device_data.X.dtype
        )
        degrees = jnp.asarray(topo.degrees, dtype=device_data.X.dtype)[:, None]
        if algo.comm_payload is not None:
            edge_payload = algo.comm_payload(config, d_model)
            floats_per_iter = topo.floats_per_iteration * edge_payload
        else:
            edge_payload = d_model * algo.gossip_rounds
            floats_per_iter = decentralized_floats_per_iteration(
                topo, d_model, algo.gossip_rounds
            )
        spectral_gap = topo.spectral_gap
    else:
        topo = None
        mix_op = None
        edge_payload = None
        degrees = jnp.zeros((n, 1), dtype=device_data.X.dtype)
        floats_per_iter = centralized_floats_per_iteration(n, d_model)
        spectral_gap = None

    time_varying = (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.participation_rate < 1.0
        or config.gossip_schedule != "synchronous"
        or "edge_drop_prob" in sweep
    )
    byzantine_active = config.attack != "none" or (
        config.aggregation != "gossip" and config.robust_b > 0
    )
    use_timeline = (
        config.burst_len >= 1.0 or config.mttf > 0.0
        or config.participation_rate < 1.0
        # Matrix-free node faults always route through the timeline
        # (parallel/faults.py convention — bitwise the iid draws).
        or (topo is not None and topo.is_matrix_free and time_varying)
    )

    # --- per-replica randomness, derived host-side ---------------------
    # Identical formulas to the sequential path's (jax.random.key(seed) +
    # the fault/adversary stream tags), stacked over the replica axis.
    rp: dict = {"key": jnp.stack([jax.random.key(s) for s in seeds])}
    if algo.is_decentralized and time_varying:
        rp["fault_key"] = jnp.stack([
            jax.random.fold_in(jax.random.key(s), 0x0FA17) for s in seeds
        ])
        rp["node_key"] = jnp.stack([
            jax.random.fold_in(jax.random.key(s), 0x57A66) for s in seeds
        ])
        rp["match_key"] = jnp.stack([
            jax.random.fold_in(jax.random.key(s), 0x3A7C4) for s in seeds
        ])
    stacked_tl = None
    if algo.is_decentralized and use_timeline:
        # One canonical config -> timeline mapping (parallel/faults.py):
        # the host-side consumers (realized B̂, live heartbeats, incident
        # forensics) rebuild bitwise these realizations from it.
        stacked_tl = stack_fault_timelines([
            timeline_for_config(c, topo, horizon) for c in rep_cfgs
        ])
        if stacked_tl.edge_up is not None:
            rp["tl_edge_up"] = jnp.asarray(stacked_tl.edge_up)
        if stacked_tl.node_up is not None:
            rp["tl_node_up"] = jnp.asarray(stacked_tl.node_up)
        if stacked_tl.rejoin is not None:
            rp["tl_rejoin"] = jnp.asarray(stacked_tl.rejoin)
        if stacked_tl.part_up is not None:
            rp["tl_part_up"] = jnp.asarray(stacked_tl.part_up)
    byz_hosts = None
    if byzantine_active and config.attack != "none":
        byz_hosts = np.stack([
            byzantine_mask(n, config.n_byzantine, s) for s in seeds
        ])
        rp["byz"] = jnp.asarray(byz_hosts)
        rp["noise_key"] = jnp.stack([
            jax.random.fold_in(jax.random.key(s), _BYZ_NOISE_TAG)
            for s in seeds
        ])
    if "learning_rate_eta0" in sweep:
        rp["eta0"] = jnp.asarray(
            np.asarray(sweep["learning_rate_eta0"], dtype=np.float64)
        )
    if "clip_tau" in sweep:
        rp["clip_tau"] = jnp.asarray(
            np.asarray(sweep["clip_tau"], dtype=np.float64)
        )
    if "edge_drop_prob" in sweep:
        # float32: the fault threshold's comparison dtype everywhere.
        rp["edge_drop_prob"] = jnp.asarray(
            np.asarray(sweep["edge_drop_prob"], dtype=np.float32)
        )

    # --- data + initial state (unsharded; replica axis fills the chip) --
    # f* rides along as a TRACED scalar (replica-shared), not a closure
    # constant like the sequential path bakes: the executable cache reuses
    # one compiled batched program across requests whose datasets — and
    # therefore optima — differ (docs/SERVING.md). Cast to the run dtype
    # up front, exactly the cast the weak Python float would get at the
    # subtraction, so traced-vs-baked trajectories stay bitwise.
    data_args = {
        "X": jnp.asarray(device_data.X),
        "y": jnp.asarray(device_data.y),
        "n_valid": jnp.asarray(device_data.n_valid),
        "f_opt": jnp.asarray(f_opt, dtype=device_data.X.dtype),
    }
    x0 = jnp.zeros((n, d_model), dtype=device_data.X.dtype)
    st0 = algo.init(
        x0, config,
        neighbor_sum=mix_op.neighbor_sum if mix_op is not None else None,
    )
    if state0 is None:
        state0_R = jax.tree.map(
            lambda a: jnp.repeat(a[None], R, axis=0), st0
        )
    else:
        if set(state0) != set(st0):
            raise ValueError(
                f"state0 leaves {sorted(state0)} do not match the "
                f"algorithm's state {sorted(st0)}"
            )
        state0_R = {
            k: jnp.asarray(v).astype(st0[k].dtype) for k, v in state0.items()
        }
        for k, v in state0_R.items():
            if v.shape != (R,) + st0[k].shape:
                raise ValueError(
                    f"state0[{k!r}] has shape {v.shape}; expected "
                    f"{(R,) + st0[k].shape} ([replicas, ...])"
                )

    full_objective = make_full_objective_fn(problem, reg)
    batch_size = config.local_batch_size
    platform = jax.devices()[0].platform
    sampling_impl = config.resolved_sampling_impl(
        platform, device_data.X.shape[1]
    )
    track_consensus = (
        collect_metrics and algo.is_decentralized and config.record_consensus
    )
    eval_every = config.eval_every
    n_evals = T // eval_every
    scan_unroll = config.resolved_scan_unroll(platform)
    micro, trips_per_eval, flat_unroll = _flat_scan_cadence(
        scan_unroll, eval_every
    )
    n_trips = n_evals * trips_per_eval

    static_degree_sum = (
        float(np.asarray(topo.degrees).sum()) if topo is not None else 0.0
    )

    def make_replica_scan(n_trips_call):
        """Factory over the per-call trip count: the one-shot program runs
        all ``n_trips`` in one call; progress streaming runs segments of
        ``progress_every * trips_per_eval`` trips through the same traced
        body (``t0_dev`` offsets the iteration indices, so one executable
        serves every same-size segment)."""
        return functools.partial(_replica_scan, n_trips_call)

    def _replica_scan(n_trips_call, rp_r, state_init, t0_dev, data):
        """One replica's flat fused scan — the sequential program, traced
        with this replica's randomness/scalars bound from ``rp_r``."""
        faulty = None
        adversary = None
        byz_mix = None
        robust_activity = None
        honest_w = None
        if algo.is_decentralized:
            tl = None
            if stacked_tl is not None:
                tl = FaultTimeline(
                    horizon=horizon,
                    directed=topo.directed,
                    edge_index=stacked_tl.edge_index,
                    edge_up=rp_r.get("tl_edge_up"),
                    node_up=rp_r.get("tl_node_up"),
                    rejoin=rp_r.get("tl_rejoin"),
                    part_up=rp_r.get("tl_part_up"),
                )
            if time_varying:
                faulty = _build_faulty(
                    config, algo, topo, T,
                    drop_prob=rp_r.get("edge_drop_prob"),
                    keys=(
                        rp_r["fault_key"], rp_r["node_key"],
                        rp_r["match_key"],
                    ),
                    timeline=tl, horizon=horizon,
                )
            adversary, byz_mix, robust_activity, _ = _bind_byzantine(
                config, algo, topo, faulty, mix_op,
                clip_tau=rp_r.get("clip_tau"),
                byz=rp_r.get("byz"),
                noise_key=rp_r.get("noise_key"),
                allow_fused=False,
            )
            if adversary is not None:
                honest_w = jnp.asarray(
                    adversary.honest.astype(np.float32)
                )
        pieces = _StepPieces(
            algo=algo, problem=problem, reg=reg, config=config,
            batch_size=batch_size, sampling_impl=sampling_impl,
            key=rp_r["key"],
            eta_fn=_make_eta_fn(config, eta0=rp_r.get("eta0")),
            degrees=degrees, mix_op=mix_op, faulty=faulty,
            byz_mix=byz_mix, adversary=adversary, honest_w=honest_w,
            fused_mix_step=None, full_objective=full_objective,
            f_opt=data["f_opt"], collect_metrics=collect_metrics,
            track_consensus=track_consensus, edge_payload=edge_payload,
            telemetry=config.telemetry, robust_activity=robust_activity,
            static_degree_sum=static_degree_sum,
        )
        step, eval_metrics, floats_for = _make_step_eval(pieces, data)

        def microchunk(state, ts_row):
            for j in range(micro):
                state, _ = step(state, ts_row[j])
            out = eval_metrics(
                state, ts_row[-1], cadence_known=trips_per_eval == 1
            )
            if faulty is not None:
                out["floats"] = floats_for(ts_row)
            return state, out

        ts = (
            t0_dev + jnp.arange(n_trips_call * micro, dtype=jnp.int32)
        ).reshape(n_trips_call, micro)
        return jax.lax.scan(microchunk, state_init, ts, unroll=flat_unroll)

    rp_axes = {k: 0 for k in rp}
    t0_dev = jnp.asarray(t0, dtype=jnp.int32)

    # AOT executable reuse (docs/SERVING.md): the batched program takes
    # seeds/sweeps/timelines/f* as data, so its cache key is the config's
    # STRUCTURAL hash + call-level trace facts — one cached executable
    # serves every seed/sweep variant of this structural config at this R.
    exec_cache = resolve_cache(executable_cache)

    def _compile_trips(n_trips_call, segment):
        """Lower/compile (or fetch from the cache) the batched program
        executing ``n_trips_call`` scan trips per call. Returns
        (compiled, cost, cold_seconds)."""
        batched = jax.vmap(
            make_replica_scan(n_trips_call), in_axes=(rp_axes, 0, None, None)
        )
        cache_key = cached = None
        if exec_cache is not None:
            cache_key = batch_cache_key(
                config, device_data, R=R, t0=t0, rp_keys=rp.keys(),
                sweep_fields=sweep.keys(), collect_metrics=collect_metrics,
                segment=segment,
            )
            cached = exec_cache.get(cache_key)
        if cached is not None:
            return (
                cached.executable,
                cached.cost if config.telemetry else None,
                0.0,
            )
        t_c = time.perf_counter()
        with jax.default_matmul_precision(config.matmul_precision):
            lowered = jax.jit(batched).lower(rp, state0_R, t0_dev, data_args)
            cost = cost_from_lowered(lowered) if config.telemetry else None
            if cost is not None:
                # The analysis covers the WHOLE R-replica vmapped program;
                # the same dict is attached to every per-replica history,
                # so record the replica count rather than letting a
                # consumer read R runs' FLOPs as one run's (divide by
                # program_replicas for an approximate per-replica share —
                # shared data reads make an exact split ill-defined).
                cost = {**cost, "program_replicas": float(R)}
            compiled = lowered.compile()
        cold_seconds = time.perf_counter() - t_c
        if exec_cache is not None:
            exec_cache.put(
                cache_key, compiled, cost=cost, compile_seconds=cold_seconds,
            )
        return compiled, cost, cold_seconds

    n_done_evals = n_evals
    if progress_cb is None and monitors is None:
        compiled, cost, cold_seconds = _compile_trips(n_trips, None)
        compile_seconds = cold_seconds if measure_compile else 0.0
        t_r = time.perf_counter()
        final_states, ys = compiled(rp, state0_R, t0_dev, data_args)
        final_states = jax.block_until_ready(final_states)
        run_seconds = time.perf_counter() - t_r
    else:
        # Progress streaming (ISSUE-10): run the SAME program in segments
        # of ``progress_every`` eval-chunks through the continuation
        # machinery (t0 traced, state carried), one heartbeat per
        # boundary. One executable per segment size; trajectories bitwise
        # the one-shot call (tests/test_observatory.py pins it).
        if progress_every < 1:
            raise ValueError(
                f"progress_every must be >= 1 eval-chunks, got "
                f"{progress_every}"
            )
        emit = _progress_emitter(
            config, _fanout_progress(progress_cb, monitors),
            t0=t0, with_bhat=False,
        )
        halt_check = (
            monitors.should_halt
            if monitors is not None and monitors.halt_on != "never"
            else None
        )
        seg_evals = min(max(int(progress_every), 1), max(n_evals, 1))
        sizes = {min(seg_evals, n_evals)}
        if n_evals % seg_evals:
            sizes.add(n_evals % seg_evals)
        compiled_by_size = {}
        cost = None
        compile_cold = 0.0
        for size in sorted(sizes):
            compiled_by_size[size], size_cost, cold = _compile_trips(
                size * trips_per_eval, ("seg", int(size * trips_per_eval)),
            )
            if cost is None:
                cost = size_cost
            compile_cold += cold
        compile_seconds = compile_cold if measure_compile else 0.0

        t_r = time.perf_counter()
        state_R = state0_R
        ys_segments = []
        gap_means: list[float] = []
        cons_means: list[float] = []
        done = 0
        while done < n_evals:
            this_evals = min(seg_evals, n_evals - done)
            t0_seg = jnp.asarray(
                t0 + done * eval_every, dtype=jnp.int32
            )
            state_R, ys_seg = compiled_by_size[this_evals](
                rp, state_R, t0_seg, data_args
            )
            jax.block_until_ready(state_R)
            ys_segments.append(ys_seg)
            done += this_evals
            extra = {}
            if "gap" in ys_seg:
                # The segment's last trip IS an eval boundary (segments
                # are whole eval-chunks), so the [-1] column is the
                # on-cadence row.
                g = np.asarray(ys_seg["gap"], dtype=np.float64)[:, -1]
                gap_means.append(float(g.mean()))
                extra["gap_per_replica"] = [float(v) for v in g]
            if "cons" in ys_seg:
                c = np.asarray(ys_seg["cons"], dtype=np.float64)[:, -1]
                cons_means.append(float(c.mean()))
            if emit is not None:
                emit(
                    done, gap_means, cons_means,
                    time.perf_counter() - t_r, **extra,
                )
            if halt_check is not None and halt_check():
                # Early-halt policy (ISSUE-13): the whole cohort stops at
                # this segment boundary — one compiled program, one halt.
                break
        final_states = state_R
        ys = jax.tree.map(
            lambda *vs: jnp.concatenate(vs, axis=1), *ys_segments
        ) if len(ys_segments) > 1 else ys_segments[0]
        run_seconds = time.perf_counter() - t_r
        n_done_evals = done
        if monitors is not None and done < n_evals:
            monitors.note_halt(t0 + done * eval_every)

    # --- harvest [R, n_trips, ...] scan outputs to per-eval rows --------
    # ``n_done_evals`` < n_evals only when the early-halt policy stopped
    # the batch: the histories then honestly cover the executed prefix.
    sel = slice(trips_per_eval - 1, None, trips_per_eval)
    gap = (
        np.asarray(ys["gap"], dtype=np.float64)[:, sel]
        if "gap" in ys else None
    )
    cons = (
        np.asarray(ys["cons"], dtype=np.float64)[:, sel]
        if "cons" in ys else None
    )
    floats = (
        np.asarray(ys["floats"], dtype=np.float64)
        .reshape(R, n_done_evals, trips_per_eval).sum(axis=2)
        if "floats" in ys else None
    )
    # Trace-buffer rows select like the gap (eval-boundary trips), with the
    # replica axis leading: [R, n_evals] scalars / [R, n_evals, N] rows.
    trace_R = (
        {k: np.asarray(v)[:, sel] for k, v in ys["trace"].items()}
        if "trace" in ys else None
    )
    objective = (
        gap if gap is not None else np.full((R, n_done_evals), np.nan)
    )

    final_states_np = {
        k: np.asarray(v) for k, v in final_states.items()
    }
    final_models = final_states_np["x"].astype(np.float64)  # [R, N, d]
    executed_T = n_done_evals * eval_every
    aggregate_ips = (
        R * executed_T / run_seconds if run_seconds > 0 else float("nan")
    )
    time_hist = np.linspace(
        run_seconds / max(n_done_evals, 1), run_seconds, n_done_evals
    )
    eval_iterations = np.arange(
        t0 + eval_every, t0 + T + 1, eval_every
    )[:n_done_evals]

    results = []
    for r in range(R):
        total_floats = (
            float(floats[r].sum()) if floats is not None
            else floats_per_iter * (
                executed_T if n_done_evals < n_evals else T
            )
        )
        history = RunHistory(
            objective=objective[r],
            consensus_error=cons[r] if cons is not None else None,
            time=time_hist,
            time_measured=False,
            eval_iterations=eval_iterations,
            total_floats_transmitted=total_floats,
            # The batch time-slices the chip evenly: each replica's share
            # of the aggregate throughput.
            iters_per_second=aggregate_ips / R,
            compile_seconds=compile_seconds,
            spectral_gap=spectral_gap,
            trace=(
                {k: v[r] for k, v in trace_R.items()}
                if trace_R is not None else None
            ),
            cost=cost,
        )
        models_r = final_models[r]
        if byz_hosts is not None:
            final_avg = models_r[~byz_hosts[r]].mean(axis=0)
        else:
            final_avg = models_r.mean(axis=0)
        results.append(BackendRunResult(
            history=history,
            final_models=models_r,
            final_avg_model=final_avg,
        ))

    return BatchRunResult(
        results=results,
        seeds=seeds,
        sweep=sweep or None,
        objective=objective,
        consensus_error=cons,
        aggregate_iters_per_second=aggregate_ips,
        run_seconds=run_seconds,
        compile_seconds=compile_seconds,
        final_states=final_states_np,
    )
