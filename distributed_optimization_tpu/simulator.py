"""Experiment orchestration: data → oracle → run matrix → report/plots.

Capability parity with the reference's ``Simulator`` (reference
``simulator.py:12-201``): generate the dataset once, compute the sklearn
reference optimum, run the experiment matrix (centralized SGD + D-SGD over
ring / toroidal grid / fully-connected, the grid skipped with an N/A record
when N is not a perfect square — reference ``simulator.py:113-125``), record
numerical results after each run, and emit the text report and the 2-panel
log-scale figure.

Differences by design (TPU-first):

- trainers are replaced by pure-step-rule algorithms dispatched through the
  backend layer (``backends.run_algorithm``), so the same matrix runs on the
  JAX/TPU path or the numpy fidelity oracle via ``config.backend``;
- the run matrix is open: any (algorithm, topology) pair the framework
  implements can be added via ``run_one`` / ``run_suite``, not just the
  reference's four rows;
- workers are not stateful objects, so there is no ``_reset_workers`` trap
  (reference ``simulator.py:29-30``) — every run starts from fresh zero
  models by construction;
- plots are saved to a file (headless TPU hosts) instead of ``plt.show()``.
"""

from __future__ import annotations

import dataclasses
import math
import time
from pathlib import Path
from typing import Optional

import numpy as np

from distributed_optimization_tpu.backends.base import (
    BackendRunResult,
    run_algorithm,
    run_algorithm_batch,
)
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.metrics import (
    NumericalResult,
    ReplicateStats,
    summarize_replicates,
    summarize_run,
)
from distributed_optimization_tpu.utils.data import (
    HostDataset,
    generate_synthetic_dataset,
)
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum
from distributed_optimization_tpu.utils.profiling import PhaseTimer

_log = get_logger("simulator")

# The reference's experiment matrix (simulator.py:99-132): algorithm,
# topology (None = centralized), display label.
REFERENCE_MATRIX = (
    ("centralized", None, "Centralized SGD"),
    ("dsgd", "ring", "D-SGD (ring)"),
    ("dsgd", "grid", "D-SGD (grid)"),
    ("dsgd", "fully_connected", "D-SGD (fully connected)"),
)


@dataclasses.dataclass
class ExperimentRecord:
    """One completed (or skipped) run of the matrix.

    Replica-batched runs (``config.replicas > 1``) additionally carry the
    full ``BatchRunResult`` and the seed-variance ``ReplicateStats``;
    ``result``/``summary`` then hold replica 0's trajectory as the
    representative curve (plots need ONE line per row), while the report
    and JSON layers quote the mean ± std columns from ``replicate_stats``.
    """

    label: str
    config: Optional[ExperimentConfig]  # None for skipped rows
    result: Optional[BackendRunResult]
    summary: Optional[NumericalResult]
    skipped_reason: Optional[str] = None
    batch: Optional[object] = None  # jax_backend.BatchRunResult
    replicate_stats: Optional[ReplicateStats] = None
    # Derived run-health block (telemetry.health_summary) — populated when
    # the run recorded flight-recorder trace buffers (config.telemetry);
    # read by format_report's run-health section and the RunTrace manifest.
    health: Optional[dict] = None
    # The run's anomaly MonitorBank (ISSUE-13) when one watched it —
    # carries the fired anomalies/halt facts; ``write_incidents`` drains
    # the forensic bundles.
    monitors: Optional[object] = None


class Simulator:
    """Runs experiments against one shared dataset + reference optimum.

    ``base_config`` fixes the problem, data, and solver hyperparameters;
    per-run calls may override algorithm/topology/backend. Data and f(x*)
    are computed once so every run is measured against the same ground truth
    (reference ``simulator.py:15-18``).
    """

    def __init__(
        self, base_config: ExperimentConfig, dataset: Optional[HostDataset] = None
    ):
        self.config = base_config
        # Phase accounting (ISSUE-5 satellite), now the hierarchical span
        # tracer (ISSUE-10: ``observability/spans.Tracer``; ``PhaseTimer``
        # is an alias): data-gen, oracle, compile, and run wall-clock
        # collected across the simulator's lifetime — surfaced in the text
        # report, the JSON dump, the RunTrace manifests, and exportable as
        # a Chrome trace (``write_chrome_trace``).
        self.phase_timer = PhaseTimer()
        with self.phase_timer.phase("data_gen"):
            self.dataset = (
                dataset if dataset is not None
                else generate_synthetic_dataset(base_config)
            )
        with self.phase_timer.phase("oracle"):
            self.w_opt, self.f_opt = compute_reference_optimum(
                self.dataset, base_config.reg_param,
                huber_delta=base_config.huber_delta,
                n_classes=base_config.n_classes,
            )
        from distributed_optimization_tpu.observability.metrics_registry import (
            observe_phases,
        )

        observe_phases({
            "data_gen": self.phase_timer.phases.get("data_gen", 0.0),
            "oracle": self.phase_timer.phases.get("oracle", 0.0),
        })
        self.records: list[ExperimentRecord] = []

    # ------------------------------------------------------------------ runs
    def run_one(
        self,
        label: Optional[str] = None,
        *,
        verbose: bool = True,
        run_kwargs: Optional[dict] = None,
        **overrides,
    ) -> ExperimentRecord:
        """Run one experiment; ``overrides`` replace base-config fields.

        ``run_kwargs`` pass through to the backend (mesh=..., checkpoint=...).
        """
        cfg = self.config.replace(**overrides) if overrides else self.config
        if label is None:
            label = (
                "Centralized SGD"
                if cfg.algorithm == "centralized"
                else f"{cfg.algorithm} ({cfg.topology})"
            )
        kwargs = dict(run_kwargs or {})
        # Anomaly monitors (ISSUE-13): a MonitorBank is per-run state
        # (latched detectors), so suite/matrix callers pass a FACTORY
        # (config -> bank) and each run gets a fresh one; a bank instance
        # passes through untouched for single runs.
        monitors = kwargs.get("monitors")
        if monitors is not None and not hasattr(monitors, "observe"):
            monitors = kwargs["monitors"] = monitors(cfg)
        replicated = cfg.replicas > 1 or "seeds" in kwargs or "sweep" in kwargs
        if verbose:
            rep = (
                f", replicas={len(kwargs['seeds']) if 'seeds' in kwargs else cfg.replicas}"
                if replicated else ""
            )
            _log.info(
                "running %r (algorithm=%s, topology=%s, backend=%s, T=%s%s)",
                label, cfg.algorithm, cfg.topology, cfg.backend,
                cfg.n_iterations, rep,
            )
        batch = None
        stats = None
        t_run = time.perf_counter()
        # The labeled span groups this run's compile/run children in the
        # Chrome trace (aggregate=False: the children already account the
        # same seconds in the flat phase table).
        with self.phase_timer.span(f"run_one:{label}", aggregate=False):
            if replicated:
                # One vmapped program runs every replica (ISSUE-4): the
                # record keeps replica 0 as the representative trajectory
                # and the mean ± std statistics alongside.
                batch = run_algorithm_batch(
                    cfg, self.dataset, self.f_opt, **kwargs
                )
                result = batch.results[0]
                stats = summarize_replicates(
                    batch.objective,
                    batch.consensus_error,
                    result.history.eval_iterations,
                    cfg.suboptimality_threshold,
                    batch.seeds,
                    batch.aggregate_iters_per_second,
                )
            else:
                result = run_algorithm(cfg, self.dataset, self.f_opt, **kwargs)
            total_seconds = time.perf_counter() - t_run
            # Phase split: compile is measured inside the backend (AOT
            # lowering); the remainder of the wall-clock around the call is
            # the run phase. add_span records both as children of the
            # labeled span AND folds them into the flat phase table.
            compile_seconds = min(result.history.compile_seconds, total_seconds)
            self.phase_timer.add_span(
                "compile", compile_seconds, start=t_run
            )
            self.phase_timer.add_span(
                "run", total_seconds - compile_seconds,
                start=t_run + compile_seconds,
            )
        from distributed_optimization_tpu.observability.metrics_registry import (
            observe_phases,
        )

        observe_phases({
            "compile": compile_seconds,
            "run": total_seconds - compile_seconds,
        })
        summary = summarize_run(
            label,
            result.history,
            cfg.suboptimality_threshold,
            cfg.n_workers,
            spectral_gap=result.history.spectral_gap,
        )
        health = None
        if (
            cfg.telemetry or cfg.execution == "async"
            or cfg.worker_mesh >= 2
            or (monitors is not None and monitors.anomalies)
        ):
            # Async health (staleness histogram, virtual-clock skew,
            # floats per virtual second, the event-fault block under
            # churn/thinning) derives from the presampled event timeline
            # — always available even without the opt-in in-scan trace,
            # so always surfaced (docs/ASYNC.md).
            # Sharded worker-mesh runs likewise: the bytes-over-ICI block
            # derives from the static halo plan (docs/PERF.md §16).
            from distributed_optimization_tpu.telemetry import health_summary

            health = health_summary(
                cfg, result.history, d_features=self.dataset.n_features
            )
        if monitors is not None and monitors.anomalies:
            # The sentinel's verdict rides the health block (the report
            # prints it; the RunTrace manifest records it).
            health["incidents"] = monitors.summary()
            for a in monitors.anomalies:
                _log.warning(
                    "%r: anomaly %s (%s) at iteration %d: %s",
                    label, a.detector, a.severity, a.onset_iteration,
                    a.message,
                )
            if monitors.halted_at is not None:
                _log.warning(
                    "%r: run HALTED at iteration %d of %d "
                    "(halt_on=fatal) — histories cover the executed "
                    "prefix only", label, monitors.halted_at,
                    cfg.n_iterations,
                )
        record = ExperimentRecord(
            label, cfg, result, summary, batch=batch, replicate_stats=stats,
            health=health, monitors=monitors,
        )
        self.records.append(record)
        if verbose:
            if stats is not None:
                _log.info(
                    "%r: final gap %.5f ± %.5f over %d replicas, "
                    "%.1f aggregate iters/sec",
                    label, stats.final_gap_mean, stats.final_gap_std,
                    stats.n_replicas, stats.aggregate_iters_per_second,
                )
            else:
                _log.info(
                    "%r: final gap %.5f, iters-to-threshold %s, "
                    "%.1f iters/sec",
                    label, result.history.objective[-1],
                    summary.iterations_to_threshold,
                    result.history.iters_per_second,
                )
        return record

    def skip(self, label: str, reason: str) -> ExperimentRecord:
        record = ExperimentRecord(label, None, None, None, skipped_reason=reason)
        self.records.append(record)
        return record

    def run_all(
        self, *, verbose: bool = True, run_kwargs: Optional[dict] = None
    ) -> list[ExperimentRecord]:
        """Run the reference's four-row experiment matrix.

        Grid is skipped with an N/A record when N is not a perfect square
        (reference ``simulator.py:113-125``).
        """
        n = self.config.n_workers
        side = math.isqrt(n)
        for algorithm, topology, label in REFERENCE_MATRIX:
            if topology == "grid" and side * side != n:
                self.skip(label, f"N={n} is not a perfect square")
                continue
            overrides = {"algorithm": algorithm}
            if topology is not None:
                overrides["topology"] = topology
            self.run_one(
                label, verbose=verbose, run_kwargs=run_kwargs, **overrides
            )
        return self.records

    def run_suite(
        self,
        specs: list[tuple[str, Optional[str]]],
        *,
        verbose: bool = True,
        run_kwargs: Optional[dict] = None,
    ) -> list[ExperimentRecord]:
        """Run an arbitrary list of (algorithm, topology-or-None) pairs."""
        for algorithm, topology in specs:
            overrides = {"algorithm": algorithm}
            if topology is not None:
                overrides["topology"] = topology
            self.run_one(verbose=verbose, run_kwargs=run_kwargs, **overrides)
        return self.records

    # -------------------------------------------------------------- reporting
    def report_numerical_results(self) -> str:
        """Text report (reference ``simulator.py:139-159``); also returned.

        The report itself is the product (stdout), not a diagnostic —
        it stays a print, unlike the progress logging above.
        """
        from distributed_optimization_tpu.reporting import format_report
        from distributed_optimization_tpu.serving.cache import (
            process_executable_cache,
        )

        # One-line serving summary (docs/SERVING.md): the process-wide
        # executable cache amortizes AOT compiles across run_one calls in
        # this process; surfaced once it has actually saved a compile.
        cache = process_executable_cache()
        serving = (
            cache.stats() if cache is not None and cache.hits > 0 else None
        )
        text = format_report(
            self.records, self.config, self.f_opt,
            phases=dict(self.phase_timer.phases),
            serving=serving,
        )
        print(text)
        return text

    # ------------------------------------------------------------- telemetry
    def run_traces(self) -> list:
        """One ``telemetry.RunTrace`` manifest per completed record —
        config + hash, phase timings, cost analysis, trace buffers, and the
        derived health block (skipped rows emit nothing)."""
        from distributed_optimization_tpu.telemetry import build_run_trace

        traces = []
        for rec in self.records:
            if rec.skipped_reason is not None or rec.result is None:
                continue
            traces.append(build_run_trace(
                rec.label, rec.config, rec.result.history,
                # The Tracer carries both the flat phase dict and the
                # span tree; build_run_trace records both (schema v2).
                phases=self.phase_timer,
                health=rec.health,
            ))
        return traces

    def write_telemetry(self, path) -> None:
        """Serialize the run manifests as JSONL (one manifest per line)."""
        from distributed_optimization_tpu.telemetry import write_jsonl

        write_jsonl(path, self.run_traces())
        _log.info("telemetry manifests saved to %s", path)

    def write_incidents(self, path) -> Path:
        """Serialize every monitored record's anomaly bundles as incident
        JSONL (ISSUE-13; ``observability/monitors.py``) — the file
        ``observatory incidents`` indexes. Returns the path; writes an
        empty file when nothing fired (an empty incident log is a
        statement, not an omission)."""
        from distributed_optimization_tpu.observability.monitors import (
            write_incidents,
        )

        bundles = []
        for rec in self.records:
            bank = rec.monitors
            if bank is None or not bank.anomalies:
                continue
            bundles.extend(bank.incidents(label=rec.label))
        out = write_incidents(path, bundles)
        _log.info(
            "%d incident bundle(s) saved to %s", len(bundles), out
        )
        return out

    def write_chrome_trace(self, path) -> None:
        """Export the simulator's span tree (data_gen/oracle + per-run
        compile/run spans) as Chrome trace-event JSON — open in
        chrome://tracing or https://ui.perfetto.dev (ISSUE-10)."""
        self.phase_timer.write_chrome_trace(path)
        _log.info("chrome trace saved to %s", path)

    def metrics_text(self) -> str:
        """The process metrics registry in Prometheus text format — the
        same exposition the serving daemon's ``/metrics`` endpoint
        scrapes, dumpable from scripts and the CLI (ISSUE-10)."""
        from distributed_optimization_tpu.observability.metrics_registry import (
            metrics_registry,
        )

        return metrics_registry().render()

    def plot_results(self, path: Optional[str] = None, show: bool = False):
        """Two-panel log-scale figure (reference ``simulator.py:161-201``)."""
        from distributed_optimization_tpu.reporting import plot_histories

        return plot_histories(
            self.records,
            self.config,
            path=path,
            show=show,
        )

    def results_dict(self) -> dict:
        """JSON-serializable summary of all runs (new capability)."""
        out = {
            "config": self.config.to_dict(),
            "f_opt": float(self.f_opt),
            "phases": {
                k: float(v) for k, v in self.phase_timer.phases.items()
            },
            "runs": [],
        }
        for rec in self.records:
            row: dict = {"label": rec.label}
            if rec.skipped_reason is not None:
                row["skipped"] = rec.skipped_reason
            else:
                assert rec.summary is not None and rec.result is not None
                secs = rec.summary.seconds_to_threshold
                row.update(
                    iterations_to_threshold=rec.summary.iterations_to_threshold,
                    # None (not NaN) when never reached: strict-JSON friendly.
                    seconds_to_threshold=None if np.isnan(secs) else secs,
                    total_transmission_floats=rec.summary.total_transmission_floats,
                    avg_worker_transmission_floats=(
                        rec.summary.avg_worker_transmission_floats
                    ),
                    spectral_gap=rec.summary.spectral_gap,
                    iters_per_second=rec.summary.iters_per_second,
                    final_objective_gap=float(rec.result.history.objective[-1]),
                    history=rec.result.history.as_dict(),
                )
                if rec.health is not None:
                    row["health"] = rec.health
                if rec.replicate_stats is not None:
                    s = rec.replicate_stats
                    it_mean = s.iterations_to_threshold_mean
                    it_std = s.iterations_to_threshold_std
                    row["replicates"] = {
                        "n": s.n_replicas,
                        "seeds": s.seeds,
                        "final_gap_mean": s.final_gap_mean,
                        "final_gap_std": s.final_gap_std,
                        "consensus_mean": s.consensus_mean,
                        "consensus_std": s.consensus_std,
                        # None (not NaN) when no replica reached ε.
                        "iterations_to_threshold_mean": (
                            None if np.isnan(it_mean) else it_mean
                        ),
                        "iterations_to_threshold_std": (
                            None if np.isnan(it_std) else it_std
                        ),
                        "n_reached": s.n_reached,
                        "per_replica_iterations": s.per_replica_iterations,
                        "aggregate_iters_per_second": (
                            s.aggregate_iters_per_second
                        ),
                        "objective_mean": np.mean(
                            rec.batch.objective, axis=0
                        ).tolist(),
                        "objective_std": np.std(
                            rec.batch.objective, axis=0
                        ).tolist(),
                    }
            out["runs"].append(row)
        return out
