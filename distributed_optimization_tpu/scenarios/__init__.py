"""Scenario engine + chaos harness (ISSUE-12, docs/SCENARIOS.md).

The composition matrix as a first-class tested surface: a queryable
validity table over the repo's ~10 orthogonal axes (``validity``),
declarative scenario specs (``spec``), seeded enumeration/property
sampling (``generator``), a serving-driven runner asserting per-cell
invariants (``engine``/``invariants``), and operational fault injection
against the serving plane itself (``chaos``).

``python -m distributed_optimization_tpu.scenarios`` is the CLI.
"""

from distributed_optimization_tpu.scenarios.engine import (  # noqa: F401
    ScenarioEngine,
    run_scenarios,
)
from distributed_optimization_tpu.scenarios.generator import (  # noqa: F401
    generate,
)
from distributed_optimization_tpu.scenarios.spec import (  # noqa: F401
    ScenarioSpec,
    SpecError,
    load_spec,
    parse_spec,
)
from distributed_optimization_tpu.scenarios import validity  # noqa: F401
