"""Chaos harness: operational fault injection against the serving plane.

The scenario matrix (engine.py) proves the SIMULATED failure axes —
dropped edges, churn, Byzantine payloads — compose correctly; this module
injects OPERATIONAL failures into the serving machinery itself and
asserts graceful degradation (ISSUE-12 part c). Modes:

- ``poisoned_cohort``: a request that passes config validation but is
  rejected by the backend (robust budget > the topology's min degree)
  rides the same scheduling cut as a healthy cohort. The poison must fail
  ALONE with a structured error naming the violation (never a traceback),
  the healthy cohort must complete, and the service must keep serving.
- ``daemon_kill_restart``: a daemon is stopped abruptly between submit
  and result (the queued request dies with it). A new daemon over the
  SAME executable cache must serve the re-submitted request WARM (zero
  compile seconds — the cache recovery the serving docs promise), and the
  retrying client must ride out the restart's connection failures.
- ``store_restart``: a FULL process restart (ISSUE-15): daemon A compiles
  cold and writes through to a persistent executable store; daemon B gets
  a FRESH in-memory cache — nothing but the store directory survives —
  and must serve the same structural class warm (zero compile seconds,
  the entry demonstrably loaded from disk, final gap bitwise equal).
- ``truncated_checkpoint``: the latest checkpoint chunk of an interrupted
  run is gutted mid-save-style; resume must warn, fall back to the last
  intact chunk, and still end BITWISE where the uninterrupted
  (equally-segmented) run ends.
- ``broken_progress_callback``: a progress callback that raises must be
  contained — the run completes and its trajectory is bitwise the
  callback-free program.

Each injection increments the ``dopt_scenario_chaos_injections`` gauge
(per-run reset, ``mode`` label). ``run_chaos_suite`` executes all modes
and returns a JSON-safe record set with boolean gates — the block the
golden corpus commits.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time
import warnings
from typing import Any, Optional

import numpy as np

from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.observability.metrics_registry import (
    metrics_registry,
)

_log = get_logger("scenarios.chaos")

CHAOS_MODES = (
    "poisoned_cohort", "daemon_kill_restart", "store_restart",
    "truncated_checkpoint", "broken_progress_callback",
)


@dataclasses.dataclass
class ChaosRecord:
    mode: str
    passed: bool
    detail: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"mode": self.mode, "passed": self.passed,
                "detail": self.detail}


def _chaos_gauge():
    return metrics_registry().gauge(
        "dopt_scenario_chaos_injections",
        "Operational faults injected by the last chaos-harness run "
        "(by 'mode' label)",
    )


def default_chaos_config(**overrides) -> ExperimentConfig:
    """The harness's small canonical workload (compiles in ~a second on
    the CI container; big enough for multi-chunk checkpointing)."""
    fields: dict[str, Any] = dict(
        n_workers=8, n_samples=400, n_features=10,
        n_informative_features=6, problem_type="quadratic",
        n_iterations=80, eval_every=10, local_batch_size=8,
    )
    fields.update(overrides)
    return ExperimentConfig(**fields)


def _structured_error_ok(message: Optional[str], must_name: str) -> bool:
    """A graceful failure names its cause and is one message, not a
    stack dump."""
    return (
        message is not None
        and must_name in message
        and "Traceback" not in message
    )


# ----------------------------------------------------------------- modes


def chaos_poisoned_cohort(*, service=None) -> ChaosRecord:
    """Poison inside a healthy scheduling cut (see module docstring)."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    own = service is None
    if own:
        service = SimulationService(
            ServingOptions(window_s=0.0), cache=ExecutableCache(),
        )
    base = default_chaos_config(dtype="float64")
    healthy = [
        service.submit(base.replace(learning_rate_eta0=eta))
        for eta in (0.05, 0.08)
    ]
    # Passes config validation; the backend rejects 2·b=6 > ring min
    # degree 2 — the poison of tests/test_serving.py, now riding a cut
    # with real traffic.
    poison = service.submit(base.replace(
        attack="sign_flip", n_byzantine=1, aggregation="trimmed_mean",
        robust_b=3, partition="shuffled",
    ))
    service.drain()
    detail: dict[str, Any] = {}
    preq = service.result(poison, timeout=60.0)
    detail["poison_status"] = preq.status
    detail["poison_error_structured"] = _structured_error_ok(
        preq.error, "robust_b"
    )
    healthy_reqs = [service.result(rid, timeout=60.0) for rid in healthy]
    detail["healthy_statuses"] = [r.status for r in healthy_reqs]
    detail["healthy_cohort_sizes"] = [r.cohort_size for r in healthy_reqs]
    # Still serving after the poison.
    follow_up = service.submit(base)
    service.drain()
    detail["post_poison_status"] = service.result(
        follow_up, timeout=60.0
    ).status
    passed = (
        preq.status == "failed"
        and detail["poison_error_structured"]
        and all(s == "done" for s in detail["healthy_statuses"])
        and all(c == 2 for c in detail["healthy_cohort_sizes"])
        and detail["post_poison_status"] == "done"
    )
    _chaos_gauge().set(1, mode="poisoned_cohort")
    if own:
        service.close()
    return ChaosRecord("poisoned_cohort", passed, detail)


def chaos_daemon_kill_restart(
    *, config: Optional[ExperimentConfig] = None,
) -> ChaosRecord:
    """Kill a daemon between submit and result; a restarted daemon over
    the same executable cache serves the re-submission warm."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    cfg = config or default_chaos_config()
    cache = ExecutableCache()  # survives the daemon, like a process cache
    detail: dict[str, Any] = {}

    # --- daemon A: warm the cache with one served run ------------------
    daemon_a = ServingDaemon(
        "127.0.0.1", 0,
        service=SimulationService(
            ServingOptions(window_s=0.02), cache=cache,
        ),
    )
    daemon_a.start()
    client = RetryingClient(daemon_a.url, max_retries=8, backoff_s=0.05,
                            seed=0)
    code, first = client.run(cfg.to_dict(), timeout=300.0)
    detail["first_run_status"] = code
    detail["first_compile_seconds"] = (
        first.get("compile_seconds") if isinstance(first, dict) else None
    )
    # --- kill between submit and result --------------------------------
    code, sub = client.submit(cfg.replace(seed=push_seed(cfg.seed)).to_dict())
    killed_id = sub.get("id") if isinstance(sub, dict) else None
    detail["killed_request_submitted"] = code == 202
    daemon_a.stop()  # abrupt: the queued request dies with the daemon
    port = daemon_a.address[1]
    detail["daemon_a_port"] = port

    # --- daemon B: same cache, same port (the restart) ------------------
    daemon_b = None
    try:
        for _ in range(20):
            try:
                daemon_b = ServingDaemon(
                    "127.0.0.1", port,
                    service=SimulationService(
                        ServingOptions(window_s=0.02), cache=cache,
                    ),
                )
                break
            except OSError:
                time.sleep(0.1)  # TIME_WAIT on the freed port
        if daemon_b is None:
            return ChaosRecord(
                "daemon_kill_restart", False,
                {**detail, "error": "could not rebind the daemon port"},
            )
        daemon_b.start()
        # The retrying client rides out any remaining restart window.
        code, lost = client.result(killed_id, timeout=1.0)
        detail["killed_request_after_restart"] = {
            "status": code,
            "structured": isinstance(lost, dict) and "error" in lost,
        }
        code, again = client.run(cfg.replace(
            seed=push_seed(cfg.seed)
        ).to_dict(), timeout=300.0)
        detail["resubmit_status"] = code
        resubmit_serving = (
            (again.get("health") or {}).get("serving")
            if isinstance(again, dict) else None
        ) or {}
        detail["resubmit_cache_hit"] = resubmit_serving.get("cache_hit")
        detail["resubmit_compile_seconds"] = (
            again.get("compile_seconds") if isinstance(again, dict) else None
        )
        detail["client_retries"] = client.n_retries
        passed = (
            detail["first_run_status"] == 200
            and detail["killed_request_submitted"]
            # The killed id is an honest 404 on the new daemon, not a hang.
            and detail["killed_request_after_restart"]["status"] == 404
            and detail["killed_request_after_restart"]["structured"]
            # The re-submission is served WARM from the surviving cache.
            and detail["resubmit_status"] == 200
            and detail["resubmit_cache_hit"] is True
            and detail["resubmit_compile_seconds"] == 0.0
        )
    finally:
        if daemon_b is not None:
            daemon_b.stop()
    _chaos_gauge().set(1, mode="daemon_kill_restart")
    return ChaosRecord("daemon_kill_restart", passed, detail)


def push_seed(seed: int) -> int:
    """The kill/restart mode's 'different request, same program' seed."""
    return seed + 101


def chaos_store_restart(
    *, config: Optional[ExperimentConfig] = None,
    store_root: Optional[str] = None,
) -> ChaosRecord:
    """Full process restart: NOTHING in memory survives. Daemon A
    compiles cold through a write-through persistent store; daemon B is
    built over a FRESH ``ExecutableCache`` whose only warm tier is the
    store directory on disk, and must serve the same structural class
    with zero compile seconds and a bitwise-equal final gap."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )
    from distributed_optimization_tpu.serving.store import (
        PersistentExecutableStore,
    )

    # A structural class the other chaos modes do NOT compile, so the
    # disk store is provably this mode's only warm path.
    cfg = config or default_chaos_config(n_iterations=90)
    own_dir = store_root is None
    root = store_root or tempfile.mkdtemp(prefix="dopt-chaos-store-")
    detail: dict[str, Any] = {"store_root": root}
    passed = False
    try:
        # --- incarnation A: cold compile, write-through to disk ---------
        daemon_a = ServingDaemon(
            "127.0.0.1", 0,
            service=SimulationService(
                ServingOptions(window_s=0.0),
                cache=ExecutableCache(store=PersistentExecutableStore(root)),
            ),
        )
        daemon_a.start()
        client = RetryingClient(daemon_a.url, max_retries=8,
                                backoff_s=0.05, seed=0)
        code, first = client.run(cfg.to_dict(), timeout=300.0)
        detail["first_run_status"] = code
        detail["first_compile_seconds"] = (
            first.get("compile_seconds") if isinstance(first, dict) else None
        )
        first_gap = (
            (first.get("health") or {}).get("final_gap")
            if isinstance(first, dict) else None
        )
        daemon_a.stop()  # the whole incarnation dies, cache memory included

        # --- incarnation B: fresh cache, same store directory -----------
        cache_b = ExecutableCache(store=PersistentExecutableStore(root))
        daemon_b = ServingDaemon(
            "127.0.0.1", 0,
            service=SimulationService(
                ServingOptions(window_s=0.0), cache=cache_b,
            ),
        )
        daemon_b.start()
        try:
            client_b = RetryingClient(daemon_b.url, max_retries=8,
                                      backoff_s=0.05, seed=1)
            code, again = client_b.run(cfg.to_dict(), timeout=300.0)
            detail["restart_run_status"] = code
            serving = (
                (again.get("health") or {}).get("serving")
                if isinstance(again, dict) else None
            ) or {}
            detail["restart_cache_hit"] = serving.get("cache_hit")
            detail["restart_compile_seconds"] = (
                again.get("compile_seconds")
                if isinstance(again, dict) else None
            )
            again_gap = (
                (again.get("health") or {}).get("final_gap")
                if isinstance(again, dict) else None
            )
            detail["final_gap_bitwise"] = (
                first_gap is not None and first_gap == again_gap
            )
            store_stats = (cache_b.stats().get("store") or {})
            detail["store_load_hits"] = store_stats.get("load_hits")
            passed = (
                detail["first_run_status"] == 200
                and detail["restart_run_status"] == 200
                # Warm across the restart, and warm FROM DISK: the fresh
                # cache's entry came through the store's load path.
                and detail["restart_cache_hit"] is True
                and detail["restart_compile_seconds"] == 0.0
                and (detail["store_load_hits"] or 0) >= 1
                and detail["final_gap_bitwise"]
            )
        finally:
            daemon_b.stop()
    finally:
        if own_dir:
            shutil.rmtree(root, ignore_errors=True)
    _chaos_gauge().set(1, mode="store_restart")
    return ChaosRecord("store_restart", passed, detail)


def chaos_truncated_checkpoint(
    *, config: Optional[ExperimentConfig] = None,
    workdir: Optional[str] = None,
) -> ChaosRecord:
    """Gut the latest checkpoint chunk; resume must fall back to the last
    intact chunk with a warning and end bitwise with the uninterrupted
    equally-segmented run."""
    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.utils.checkpoint import (
        CheckpointOptions,
        RunCheckpointer,
    )
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    cfg = config or default_chaos_config()
    own_dir = workdir is None
    base = workdir or tempfile.mkdtemp(prefix="dopt-chaos-ck-")
    detail: dict[str, Any] = {}
    try:
        ds = generate_synthetic_dataset(cfg)
        _, f_opt = compute_reference_optimum(
            ds, cfg.reg_param, huber_delta=cfg.huber_delta,
            n_classes=cfg.n_classes,
        )
        every = 2
        ref = jax_backend.run(cfg, ds, f_opt, checkpoint=CheckpointOptions(
            os.path.join(base, "ref"), every_evals=every, resume=False,
        ))
        ckdir = os.path.join(base, "crash")
        jax_backend.run(cfg, ds, f_opt, checkpoint=CheckpointOptions(
            ckdir, every_evals=every, resume=False, max_to_keep=10,
        ))
        ck = RunCheckpointer(CheckpointOptions(ckdir, every_evals=every))
        latest = ck.latest_chunk()
        detail["latest_chunk"] = latest
        # Crash-mid-save: the chunk dir survives, the payload does not.
        step_dir = ck._step_dir(latest)
        for name in os.listdir(step_dir):
            p = os.path.join(step_dir, name)
            shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
        with open(os.path.join(step_dir, "garbage"), "w") as f:
            f.write("crashed mid-save")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            resumed = jax_backend.run(
                cfg, ds, f_opt,
                checkpoint=CheckpointOptions(
                    ckdir, every_evals=every, max_to_keep=10,
                ),
            )
        fallback_warned = any(
            "partial or corrupt" in str(w.message) for w in caught
        )
        detail["fallback_warned"] = fallback_warned
        obj_bitwise = bool(np.array_equal(
            resumed.history.objective, ref.history.objective
        ))
        models_bitwise = bool(np.array_equal(
            resumed.final_models, ref.final_models
        ))
        detail["objective_bitwise"] = obj_bitwise
        detail["final_models_bitwise"] = models_bitwise
        passed = fallback_warned and obj_bitwise and models_bitwise
    finally:
        if own_dir:
            shutil.rmtree(base, ignore_errors=True)
    _chaos_gauge().set(1, mode="truncated_checkpoint")
    return ChaosRecord("truncated_checkpoint", passed, detail)


def chaos_broken_progress_callback(
    *, config: Optional[ExperimentConfig] = None,
) -> ChaosRecord:
    """A raising progress callback must be contained: the run completes
    and is bitwise the callback-free program."""
    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    cfg = config or default_chaos_config()
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(
        ds, cfg.reg_param, huber_delta=cfg.huber_delta,
        n_classes=cfg.n_classes,
    )
    cache = ExecutableCache()
    calls = {"n": 0}

    def exploding_cb(event):
        calls["n"] += 1
        raise RuntimeError("chaos: progress subscriber exploded")

    quiet = jax_backend.run(cfg, ds, f_opt, executable_cache=cache)
    noisy = jax_backend.run(
        cfg, ds, f_opt, executable_cache=cache,
        progress_cb=exploding_cb, progress_every=2,
    )
    detail = {
        "callback_invocations": calls["n"],
        "objective_bitwise": bool(np.array_equal(
            noisy.history.objective, quiet.history.objective
        )),
        "final_models_bitwise": bool(np.array_equal(
            noisy.final_models, quiet.final_models
        )),
    }
    passed = (
        calls["n"] > 0
        and detail["objective_bitwise"]
        and detail["final_models_bitwise"]
    )
    _chaos_gauge().set(1, mode="broken_progress_callback")
    return ChaosRecord("broken_progress_callback", passed, detail)


# ----------------------------------------------------------------- suite


def run_chaos_suite(
    *, config: Optional[ExperimentConfig] = None,
    modes: tuple[str, ...] = CHAOS_MODES,
) -> dict[str, Any]:
    """Run the chaos modes; returns ``{"records": [...], "gates": {...}}``
    (JSON-safe — the golden corpus's ``chaos`` block)."""
    _chaos_gauge().reset()
    runners = {
        "poisoned_cohort": lambda: chaos_poisoned_cohort(),
        "daemon_kill_restart": lambda: chaos_daemon_kill_restart(
            config=config
        ),
        "store_restart": lambda: chaos_store_restart(config=config),
        "truncated_checkpoint": lambda: chaos_truncated_checkpoint(
            config=config
        ),
        "broken_progress_callback": lambda: chaos_broken_progress_callback(
            config=config
        ),
    }
    records = []
    for mode in modes:
        if mode not in runners:
            raise ValueError(
                f"unknown chaos mode {mode!r} (valid: {CHAOS_MODES})"
            )
        _log.info("chaos: injecting %s", mode)
        records.append(runners[mode]())
    return {
        "records": [r.to_dict() for r in records],
        "gates": {
            f"{r.mode}_graceful": bool(r.passed) for r in records
        },
    }
