"""Chaos harness: operational fault injection against the serving plane.

The scenario matrix (engine.py) proves the SIMULATED failure axes —
dropped edges, churn, Byzantine payloads — compose correctly; this module
injects OPERATIONAL failures into the serving machinery itself and
asserts graceful degradation (ISSUE-12 part c). Modes:

- ``poisoned_cohort``: a request that passes config validation but is
  rejected by the backend (robust budget > the topology's min degree)
  rides the same scheduling cut as a healthy cohort. The poison must fail
  ALONE with a structured error naming the violation (never a traceback),
  the healthy cohort must complete, and the service must keep serving.
- ``daemon_kill_restart``: a daemon is stopped abruptly between submit
  and result (the queued request dies with it). A new daemon over the
  SAME executable cache must serve the re-submitted request WARM (zero
  compile seconds — the cache recovery the serving docs promise), and the
  retrying client must ride out the restart's connection failures.
- ``store_restart``: a FULL process restart (ISSUE-15): daemon A compiles
  cold and writes through to a persistent executable store; daemon B gets
  a FRESH in-memory cache — nothing but the store directory survives —
  and must serve the same structural class warm (zero compile seconds,
  the entry demonstrably loaded from disk, final gap bitwise equal).
- ``truncated_checkpoint``: the latest checkpoint chunk of an interrupted
  run is gutted mid-save-style; resume must warn, fall back to the last
  intact chunk, and still end BITWISE where the uninterrupted
  (equally-segmented) run ends.
- ``broken_progress_callback``: a progress callback that raises must be
  contained — the run completes and its trajectory is bitwise the
  callback-free program.

Each injection increments the ``dopt_scenario_chaos_injections`` gauge
(per-run reset, ``mode`` label). ``run_chaos_suite`` executes all modes
and returns a JSON-safe record set with boolean gates — the block the
golden corpus commits.

Fleet chaos (ISSUE-16): a SECOND mode family proves the self-healing
fleet's remediation policies and autoscaler close the detection→action
loop — ``fleet_divergence_remediation`` (a planted over-budget ALIE
attack mid-traffic: incident fires → offender halted with a
policy-attributed error → its class quarantined for the tenant → healthy
traffic untouched), ``fleet_store_remediation`` (a corrupted persistent
store artifact under load: quarantined aside, recompiled cold, fresh
artifact re-saved), ``fleet_worker_storm`` (SIGKILLs beyond the initial
fleet size: every death requeued+respawned with remediation
attribution), and ``fleet_autoscale_cycle`` (burst backlog → scale-up,
idle → scale-down, fleet back at the floor). These run via
``run_fleet_chaos_suite`` — deliberately NOT part of ``CHAOS_MODES`` so
the golden scenario corpus (``examples/bench_scenarios.py``) is
untouched; ``examples/bench_fleet.py`` commits their gates instead.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time
import warnings
from typing import Any, Optional

import numpy as np

from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.observability.metrics_registry import (
    metrics_registry,
)

_log = get_logger("scenarios.chaos")

CHAOS_MODES = (
    "poisoned_cohort", "daemon_kill_restart", "store_restart",
    "truncated_checkpoint", "broken_progress_callback",
)
# The self-healing-fleet family (module docstring): its own tuple and
# suite so the default CHAOS_MODES — and the golden corpus gates built
# on them — are byte-identical to PR 12.
FLEET_CHAOS_MODES = (
    "fleet_divergence_remediation", "fleet_store_remediation",
    "fleet_worker_storm", "fleet_autoscale_cycle",
)


@dataclasses.dataclass
class ChaosRecord:
    mode: str
    passed: bool
    detail: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"mode": self.mode, "passed": self.passed,
                "detail": self.detail}


def _chaos_gauge():
    return metrics_registry().gauge(
        "dopt_scenario_chaos_injections",
        "Operational faults injected by the last chaos-harness run "
        "(by 'mode' label)",
    )


def default_chaos_config(**overrides) -> ExperimentConfig:
    """The harness's small canonical workload (compiles in ~a second on
    the CI container; big enough for multi-chunk checkpointing)."""
    fields: dict[str, Any] = dict(
        n_workers=8, n_samples=400, n_features=10,
        n_informative_features=6, problem_type="quadratic",
        n_iterations=80, eval_every=10, local_batch_size=8,
    )
    fields.update(overrides)
    return ExperimentConfig(**fields)


def _structured_error_ok(message: Optional[str], must_name: str) -> bool:
    """A graceful failure names its cause and is one message, not a
    stack dump."""
    return (
        message is not None
        and must_name in message
        and "Traceback" not in message
    )


# ----------------------------------------------------------------- modes


def chaos_poisoned_cohort(*, service=None) -> ChaosRecord:
    """Poison inside a healthy scheduling cut (see module docstring)."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    own = service is None
    if own:
        service = SimulationService(
            ServingOptions(window_s=0.0), cache=ExecutableCache(),
        )
    base = default_chaos_config(dtype="float64")
    healthy = [
        service.submit(base.replace(learning_rate_eta0=eta))
        for eta in (0.05, 0.08)
    ]
    # Passes config validation; the backend rejects 2·b=6 > ring min
    # degree 2 — the poison of tests/test_serving.py, now riding a cut
    # with real traffic.
    poison = service.submit(base.replace(
        attack="sign_flip", n_byzantine=1, aggregation="trimmed_mean",
        robust_b=3, partition="shuffled",
    ))
    service.drain()
    detail: dict[str, Any] = {}
    preq = service.result(poison, timeout=60.0)
    detail["poison_status"] = preq.status
    detail["poison_error_structured"] = _structured_error_ok(
        preq.error, "robust_b"
    )
    healthy_reqs = [service.result(rid, timeout=60.0) for rid in healthy]
    detail["healthy_statuses"] = [r.status for r in healthy_reqs]
    detail["healthy_cohort_sizes"] = [r.cohort_size for r in healthy_reqs]
    # Still serving after the poison.
    follow_up = service.submit(base)
    service.drain()
    detail["post_poison_status"] = service.result(
        follow_up, timeout=60.0
    ).status
    passed = (
        preq.status == "failed"
        and detail["poison_error_structured"]
        and all(s == "done" for s in detail["healthy_statuses"])
        and all(c == 2 for c in detail["healthy_cohort_sizes"])
        and detail["post_poison_status"] == "done"
    )
    _chaos_gauge().set(1, mode="poisoned_cohort")
    if own:
        service.close()
    return ChaosRecord("poisoned_cohort", passed, detail)


def chaos_daemon_kill_restart(
    *, config: Optional[ExperimentConfig] = None,
) -> ChaosRecord:
    """Kill a daemon between submit and result; a restarted daemon over
    the same executable cache serves the re-submission warm."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    cfg = config or default_chaos_config()
    cache = ExecutableCache()  # survives the daemon, like a process cache
    detail: dict[str, Any] = {}

    # --- daemon A: warm the cache with one served run ------------------
    daemon_a = ServingDaemon(
        "127.0.0.1", 0,
        service=SimulationService(
            ServingOptions(window_s=0.02), cache=cache,
        ),
    )
    daemon_a.start()
    client = RetryingClient(daemon_a.url, max_retries=8, backoff_s=0.05,
                            seed=0)
    code, first = client.run(cfg.to_dict(), timeout=300.0)
    detail["first_run_status"] = code
    detail["first_compile_seconds"] = (
        first.get("compile_seconds") if isinstance(first, dict) else None
    )
    # --- kill between submit and result --------------------------------
    code, sub = client.submit(cfg.replace(seed=push_seed(cfg.seed)).to_dict())
    killed_id = sub.get("id") if isinstance(sub, dict) else None
    detail["killed_request_submitted"] = code == 202
    daemon_a.stop()  # abrupt: the queued request dies with the daemon
    port = daemon_a.address[1]
    detail["daemon_a_port"] = port

    # --- daemon B: same cache, same port (the restart) ------------------
    daemon_b = None
    try:
        for _ in range(20):
            try:
                daemon_b = ServingDaemon(
                    "127.0.0.1", port,
                    service=SimulationService(
                        ServingOptions(window_s=0.02), cache=cache,
                    ),
                )
                break
            except OSError:
                time.sleep(0.1)  # TIME_WAIT on the freed port
        if daemon_b is None:
            return ChaosRecord(
                "daemon_kill_restart", False,
                {**detail, "error": "could not rebind the daemon port"},
            )
        daemon_b.start()
        # The retrying client rides out any remaining restart window.
        code, lost = client.result(killed_id, timeout=1.0)
        detail["killed_request_after_restart"] = {
            "status": code,
            "structured": isinstance(lost, dict) and "error" in lost,
        }
        code, again = client.run(cfg.replace(
            seed=push_seed(cfg.seed)
        ).to_dict(), timeout=300.0)
        detail["resubmit_status"] = code
        resubmit_serving = (
            (again.get("health") or {}).get("serving")
            if isinstance(again, dict) else None
        ) or {}
        detail["resubmit_cache_hit"] = resubmit_serving.get("cache_hit")
        detail["resubmit_compile_seconds"] = (
            again.get("compile_seconds") if isinstance(again, dict) else None
        )
        detail["client_retries"] = client.n_retries
        passed = (
            detail["first_run_status"] == 200
            and detail["killed_request_submitted"]
            # The killed id is an honest 404 on the new daemon, not a hang.
            and detail["killed_request_after_restart"]["status"] == 404
            and detail["killed_request_after_restart"]["structured"]
            # The re-submission is served WARM from the surviving cache.
            and detail["resubmit_status"] == 200
            and detail["resubmit_cache_hit"] is True
            and detail["resubmit_compile_seconds"] == 0.0
        )
    finally:
        if daemon_b is not None:
            daemon_b.stop()
    _chaos_gauge().set(1, mode="daemon_kill_restart")
    return ChaosRecord("daemon_kill_restart", passed, detail)


def push_seed(seed: int) -> int:
    """The kill/restart mode's 'different request, same program' seed."""
    return seed + 101


def chaos_store_restart(
    *, config: Optional[ExperimentConfig] = None,
    store_root: Optional[str] = None,
) -> ChaosRecord:
    """Full process restart: NOTHING in memory survives. Daemon A
    compiles cold through a write-through persistent store; daemon B is
    built over a FRESH ``ExecutableCache`` whose only warm tier is the
    store directory on disk, and must serve the same structural class
    with zero compile seconds and a bitwise-equal final gap."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.client import RetryingClient
    from distributed_optimization_tpu.serving.daemon import ServingDaemon
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )
    from distributed_optimization_tpu.serving.store import (
        PersistentExecutableStore,
    )

    # A structural class the other chaos modes do NOT compile, so the
    # disk store is provably this mode's only warm path.
    cfg = config or default_chaos_config(n_iterations=90)
    own_dir = store_root is None
    root = store_root or tempfile.mkdtemp(prefix="dopt-chaos-store-")
    detail: dict[str, Any] = {"store_root": root}
    passed = False
    try:
        # --- incarnation A: cold compile, write-through to disk ---------
        daemon_a = ServingDaemon(
            "127.0.0.1", 0,
            service=SimulationService(
                ServingOptions(window_s=0.0),
                cache=ExecutableCache(store=PersistentExecutableStore(root)),
            ),
        )
        daemon_a.start()
        client = RetryingClient(daemon_a.url, max_retries=8,
                                backoff_s=0.05, seed=0)
        code, first = client.run(cfg.to_dict(), timeout=300.0)
        detail["first_run_status"] = code
        detail["first_compile_seconds"] = (
            first.get("compile_seconds") if isinstance(first, dict) else None
        )
        first_gap = (
            (first.get("health") or {}).get("final_gap")
            if isinstance(first, dict) else None
        )
        daemon_a.stop()  # the whole incarnation dies, cache memory included

        # --- incarnation B: fresh cache, same store directory -----------
        cache_b = ExecutableCache(store=PersistentExecutableStore(root))
        daemon_b = ServingDaemon(
            "127.0.0.1", 0,
            service=SimulationService(
                ServingOptions(window_s=0.0), cache=cache_b,
            ),
        )
        daemon_b.start()
        try:
            client_b = RetryingClient(daemon_b.url, max_retries=8,
                                      backoff_s=0.05, seed=1)
            code, again = client_b.run(cfg.to_dict(), timeout=300.0)
            detail["restart_run_status"] = code
            serving = (
                (again.get("health") or {}).get("serving")
                if isinstance(again, dict) else None
            ) or {}
            detail["restart_cache_hit"] = serving.get("cache_hit")
            detail["restart_compile_seconds"] = (
                again.get("compile_seconds")
                if isinstance(again, dict) else None
            )
            again_gap = (
                (again.get("health") or {}).get("final_gap")
                if isinstance(again, dict) else None
            )
            detail["final_gap_bitwise"] = (
                first_gap is not None and first_gap == again_gap
            )
            store_stats = (cache_b.stats().get("store") or {})
            detail["store_load_hits"] = store_stats.get("load_hits")
            passed = (
                detail["first_run_status"] == 200
                and detail["restart_run_status"] == 200
                # Warm across the restart, and warm FROM DISK: the fresh
                # cache's entry came through the store's load path.
                and detail["restart_cache_hit"] is True
                and detail["restart_compile_seconds"] == 0.0
                and (detail["store_load_hits"] or 0) >= 1
                and detail["final_gap_bitwise"]
            )
        finally:
            daemon_b.stop()
    finally:
        if own_dir:
            shutil.rmtree(root, ignore_errors=True)
    _chaos_gauge().set(1, mode="store_restart")
    return ChaosRecord("store_restart", passed, detail)


def chaos_truncated_checkpoint(
    *, config: Optional[ExperimentConfig] = None,
    workdir: Optional[str] = None,
) -> ChaosRecord:
    """Gut the latest checkpoint chunk; resume must fall back to the last
    intact chunk with a warning and end bitwise with the uninterrupted
    equally-segmented run."""
    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.utils.checkpoint import (
        CheckpointOptions,
        RunCheckpointer,
    )
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    cfg = config or default_chaos_config()
    own_dir = workdir is None
    base = workdir or tempfile.mkdtemp(prefix="dopt-chaos-ck-")
    detail: dict[str, Any] = {}
    try:
        ds = generate_synthetic_dataset(cfg)
        _, f_opt = compute_reference_optimum(
            ds, cfg.reg_param, huber_delta=cfg.huber_delta,
            n_classes=cfg.n_classes,
        )
        every = 2
        ref = jax_backend.run(cfg, ds, f_opt, checkpoint=CheckpointOptions(
            os.path.join(base, "ref"), every_evals=every, resume=False,
        ))
        ckdir = os.path.join(base, "crash")
        jax_backend.run(cfg, ds, f_opt, checkpoint=CheckpointOptions(
            ckdir, every_evals=every, resume=False, max_to_keep=10,
        ))
        ck = RunCheckpointer(CheckpointOptions(ckdir, every_evals=every))
        latest = ck.latest_chunk()
        detail["latest_chunk"] = latest
        # Crash-mid-save: the chunk dir survives, the payload does not.
        step_dir = ck._step_dir(latest)
        for name in os.listdir(step_dir):
            p = os.path.join(step_dir, name)
            shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
        with open(os.path.join(step_dir, "garbage"), "w") as f:
            f.write("crashed mid-save")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            resumed = jax_backend.run(
                cfg, ds, f_opt,
                checkpoint=CheckpointOptions(
                    ckdir, every_evals=every, max_to_keep=10,
                ),
            )
        fallback_warned = any(
            "partial or corrupt" in str(w.message) for w in caught
        )
        detail["fallback_warned"] = fallback_warned
        obj_bitwise = bool(np.array_equal(
            resumed.history.objective, ref.history.objective
        ))
        models_bitwise = bool(np.array_equal(
            resumed.final_models, ref.final_models
        ))
        detail["objective_bitwise"] = obj_bitwise
        detail["final_models_bitwise"] = models_bitwise
        passed = fallback_warned and obj_bitwise and models_bitwise
    finally:
        if own_dir:
            shutil.rmtree(base, ignore_errors=True)
    _chaos_gauge().set(1, mode="truncated_checkpoint")
    return ChaosRecord("truncated_checkpoint", passed, detail)


def chaos_broken_progress_callback(
    *, config: Optional[ExperimentConfig] = None,
) -> ChaosRecord:
    """A raising progress callback must be contained: the run completes
    and is bitwise the callback-free program."""
    from distributed_optimization_tpu.backends import jax_backend
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    cfg = config or default_chaos_config()
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(
        ds, cfg.reg_param, huber_delta=cfg.huber_delta,
        n_classes=cfg.n_classes,
    )
    cache = ExecutableCache()
    calls = {"n": 0}

    def exploding_cb(event):
        calls["n"] += 1
        raise RuntimeError("chaos: progress subscriber exploded")

    quiet = jax_backend.run(cfg, ds, f_opt, executable_cache=cache)
    noisy = jax_backend.run(
        cfg, ds, f_opt, executable_cache=cache,
        progress_cb=exploding_cb, progress_every=2,
    )
    detail = {
        "callback_invocations": calls["n"],
        "objective_bitwise": bool(np.array_equal(
            noisy.history.objective, quiet.history.objective
        )),
        "final_models_bitwise": bool(np.array_equal(
            noisy.final_models, quiet.final_models
        )),
    }
    passed = (
        calls["n"] > 0
        and detail["objective_bitwise"]
        and detail["final_models_bitwise"]
    )
    _chaos_gauge().set(1, mode="broken_progress_callback")
    return ChaosRecord("broken_progress_callback", passed, detail)


# ----------------------------------------------------------------- suite


def run_chaos_suite(
    *, config: Optional[ExperimentConfig] = None,
    modes: tuple[str, ...] = CHAOS_MODES,
) -> dict[str, Any]:
    """Run the chaos modes; returns ``{"records": [...], "gates": {...}}``
    (JSON-safe — the golden corpus's ``chaos`` block)."""
    _chaos_gauge().reset()
    runners = {
        "poisoned_cohort": lambda: chaos_poisoned_cohort(),
        "daemon_kill_restart": lambda: chaos_daemon_kill_restart(
            config=config
        ),
        "store_restart": lambda: chaos_store_restart(config=config),
        "truncated_checkpoint": lambda: chaos_truncated_checkpoint(
            config=config
        ),
        "broken_progress_callback": lambda: chaos_broken_progress_callback(
            config=config
        ),
    }
    records = []
    for mode in modes:
        if mode not in runners:
            raise ValueError(
                f"unknown chaos mode {mode!r} (valid: {CHAOS_MODES})"
            )
        _log.info("chaos: injecting %s", mode)
        records.append(runners[mode]())
    return {
        "records": [r.to_dict() for r in records],
        "gates": {
            f"{r.mode}_graceful": bool(r.passed) for r in records
        },
    }


# ------------------------------------------------------------ fleet modes


def diverging_chaos_config(**overrides) -> ExperimentConfig:
    """The planted f > b attack on the harness workload: ALIE with 3
    attackers against a b=1 trimmed mean (per-neighborhood budget
    exceeded) at a learning rate the attack-free twin converges under —
    the same breakdown cell the anomaly sentinel's tests plant."""
    fields: dict[str, Any] = dict(
        n_iterations=300, eval_every=20, learning_rate_eta0=0.3,
        attack="alie", n_byzantine=3, attack_scale=1.5,
        aggregation="trimmed_mean", robust_b=1,
    )
    fields.update(overrides)
    return default_chaos_config(**fields)


def chaos_fleet_divergence(
    *, incident_log: Optional[str] = None,
) -> ChaosRecord:
    """Planted over-budget attack mid-traffic: the divergence incident
    fires, the ``divergence_halt_requeue`` policy halts the offender
    with a policy-attributed error, quarantines its (tenant, structural
    class) pair — a repeat submission sheds 429 ``quarantined`` — and
    the healthy traffic sharing the service completes untouched."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.fleet import (
        POLICY_DIVERGENCE,
        FleetOptions,
        RemediationEngine,
    )
    from distributed_optimization_tpu.serving.service import (
        QueueFullError,
        ServingOptions,
        SimulationService,
    )

    service = SimulationService(
        ServingOptions(window_s=0.0, progress_every=1),
        cache=ExecutableCache(),
    )
    engine = RemediationEngine(FleetOptions(
        quarantine_ttl_s=60.0, incident_log=incident_log,
    )).attach(service)
    detail: dict[str, Any] = {}
    try:
        healthy_cfg = default_chaos_config(dtype="float64")
        healthy = [
            service.submit(healthy_cfg.replace(learning_rate_eta0=eta))
            for eta in (0.05, 0.08)
        ]
        attack_cfg = diverging_chaos_config()
        attacker = service.submit(attack_cfg, tenant="attacker")
        service.drain()
        areq = service.result(attacker, timeout=300.0)
        detail["attack_status"] = areq.status
        detail["attack_error_attributed"] = _structured_error_ok(
            areq.error, POLICY_DIVERGENCE
        )
        detail["attack_remediation_policy"] = (
            (areq.remediation or {}).get("policy")
        )
        detail["healthy_statuses"] = [
            service.result(r, timeout=60.0).status for r in healthy
        ]
        # The quarantine is live: the same class from the same tenant
        # sheds with the machine-readable reason...
        try:
            service.submit(
                attack_cfg.replace(seed=attack_cfg.seed + 1),
                tenant="attacker",
            )
            detail["repeat_shed_reason"] = None
        except QueueFullError as e:
            detail["repeat_shed_reason"] = e.reason
        # ... while OTHER tenants and other classes keep serving.
        follow = service.submit(healthy_cfg)
        service.drain()
        detail["post_attack_status"] = service.result(
            follow, timeout=60.0
        ).status
        st = engine.status()
        detail["remediations_total"] = st["remediations"]["total"]
        detail["active_quarantines"] = len(st["quarantines"])
        passed = (
            detail["attack_status"] == "failed"
            and detail["attack_error_attributed"]
            and detail["attack_remediation_policy"] == POLICY_DIVERGENCE
            and all(s == "done" for s in detail["healthy_statuses"])
            and detail["repeat_shed_reason"] == "quarantined"
            and detail["post_attack_status"] == "done"
            and detail["remediations_total"] >= 1
            and detail["active_quarantines"] >= 1
        )
    finally:
        service.close()
    _chaos_gauge().set(1, mode="fleet_divergence_remediation")
    return ChaosRecord("fleet_divergence_remediation", passed, detail)


def chaos_fleet_store_corruption(
    *, store_root: Optional[str] = None,
    incident_log: Optional[str] = None,
) -> ChaosRecord:
    """Corrupted store artifact under load: incarnation A compiles cold
    and writes through to disk; the artifact is gutted; incarnation B
    (fresh cache, fleet attached) hits the corruption on load — the
    ``store_corruption_quarantine`` policy renames it aside, the request
    recompiles cold and completes, and the write-through path re-saves a
    FRESH artifact at the original name."""
    from distributed_optimization_tpu.serving.cache import ExecutableCache
    from distributed_optimization_tpu.serving.fleet import (
        POLICY_STORE,
        QUARANTINE_SUFFIX,
        FleetOptions,
        RemediationEngine,
    )
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )
    from distributed_optimization_tpu.serving.store import (
        ARTIFACT_SUFFIX,
        PersistentExecutableStore,
    )

    # A structural class no other mode compiles (distinct iteration
    # count), so this store's artifact provably comes from here.
    cfg = default_chaos_config(n_iterations=70)
    own_dir = store_root is None
    root = store_root or tempfile.mkdtemp(prefix="dopt-chaos-fleet-store-")
    detail: dict[str, Any] = {"store_root": root}
    passed = False
    try:
        # --- incarnation A: cold compile, write-through ----------------
        svc_a = SimulationService(
            ServingOptions(window_s=0.0),
            cache=ExecutableCache(store=PersistentExecutableStore(root)),
        )
        rid = svc_a.submit(cfg)
        svc_a.drain()
        detail["first_status"] = svc_a.result(rid, timeout=300.0).status
        svc_a.close()
        artifacts = [
            os.path.join(root, n) for n in os.listdir(root)
            if n.endswith(ARTIFACT_SUFFIX)
        ]
        detail["artifacts_written"] = len(artifacts)
        if not artifacts:
            return ChaosRecord(
                "fleet_store_remediation", False,
                {**detail, "error": "no artifact written through"},
            )
        # --- gut the artifact ------------------------------------------
        target = artifacts[0]
        with open(target, "wb") as f:
            f.write(b"chaos: not a pickle")
        # --- incarnation B: fresh cache, fleet attached ----------------
        svc_b = SimulationService(
            ServingOptions(window_s=0.0),
            cache=ExecutableCache(store=PersistentExecutableStore(root)),
        )
        engine = RemediationEngine(FleetOptions(
            incident_log=incident_log,
        )).attach(svc_b)
        try:
            rid = svc_b.submit(cfg)
            svc_b.drain()
            req = svc_b.result(rid, timeout=300.0)
            detail["restart_status"] = req.status
            detail["quarantined_artifact_exists"] = os.path.exists(
                target + QUARANTINE_SUFFIX
            )
            # The cold recompile re-saved a fresh artifact at the
            # ORIGINAL name through the existing write-through path.
            detail["fresh_artifact_resaved"] = os.path.exists(target)
            store_stats = svc_b.cache.stats().get("store") or {}
            detail["store_corrupt_count"] = store_stats.get("corrupt")
            recs = [
                r for r in engine.status()["remediations"]["recent"]
                if r["policy"] == POLICY_STORE
            ]
            detail["store_remediations"] = len(recs)
            detail["store_outcomes"] = sorted(
                {r["outcome"] for r in recs}
            )
            passed = (
                detail["first_status"] == "done"
                and detail["restart_status"] == "done"
                and detail["quarantined_artifact_exists"]
                and detail["fresh_artifact_resaved"]
                and (detail["store_corrupt_count"] or 0) >= 1
                and detail["store_remediations"] >= 1
                and detail["store_outcomes"] == ["remediated"]
            )
        finally:
            svc_b.close()
    finally:
        if own_dir:
            shutil.rmtree(root, ignore_errors=True)
    _chaos_gauge().set(1, mode="fleet_store_remediation")
    return ChaosRecord("fleet_store_remediation", passed, detail)


def chaos_fleet_worker_storm(*, n_kills: int = 2) -> ChaosRecord:
    """SIGKILL storm matching the whole fleet: as many worker kills as
    the pool has workers, injected while cohorts are in flight. Every
    death must be requeued + respawned under the
    ``dead_worker_respawn`` policy (with remediation attribution), and
    every request must still complete."""
    import signal

    from distributed_optimization_tpu.serving.fleet import (
        POLICY_WORKER,
        RemediationEngine,
    )
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    service = SimulationService(
        ServingOptions(window_s=0.0, workers=2),
    )
    engine = RemediationEngine().attach(service)
    detail: dict[str, Any] = {"kills": []}
    try:
        service.start()
        # Distinct structural classes so the work spreads across both
        # workers instead of coalescing into one cohort.
        rids = [
            service.submit(default_chaos_config(n_iterations=40 + 10 * i))
            for i in range(4)
        ]
        # The pool is created lazily by the scheduler's first dispatch.
        t0 = time.time()
        while service._pool is None and time.time() - t0 < 60.0:
            time.sleep(0.05)
        pool = service._pool
        if pool is None:
            return ChaosRecord(
                "fleet_worker_storm", False,
                {**detail, "error": "worker pool never started"},
            )
        killed: set[int] = set()
        deadline = time.time() + 300.0
        while len(killed) < n_kills and time.time() < deadline:
            if all(service.get(r).done.is_set() for r in rids):
                break  # ran out of in-flight work to shoot at
            victim = proc = None
            with pool._lock:
                for task in pool._tasks.values():
                    wid = task.worker_id
                    if wid is not None and wid not in killed:
                        victim, proc = wid, pool._procs.get(wid)
                        break
            if victim is None or proc is None:
                time.sleep(0.05)
                continue
            os.kill(proc.pid, signal.SIGKILL)
            killed.add(victim)
            detail["kills"].append(victim)
            time.sleep(0.3)  # let the health monitor see the death
        detail["n_killed"] = len(killed)
        statuses = [
            service.result(r, timeout=300.0).status for r in rids
        ]
        detail["statuses"] = statuses
        st = engine.status()
        worker_recs = [
            r for r in st["remediations"]["recent"]
            if r["policy"] == POLICY_WORKER
        ]
        detail["worker_remediations"] = len(worker_recs)
        pst = pool.stats()
        detail["pool_alive"] = pst["alive"]
        detail["pool_restarts"] = pst["restarts"]
        passed = (
            detail["n_killed"] >= n_kills
            and all(s == "done" for s in statuses)
            and detail["worker_remediations"] >= n_kills
            and detail["pool_alive"] == 2  # respawned back to strength
            and detail["pool_restarts"] >= n_kills
        )
    finally:
        service.close()
    _chaos_gauge().set(1, mode="fleet_worker_storm")
    return ChaosRecord("fleet_worker_storm", passed, detail)


def chaos_fleet_autoscale(*, burst: int = 6) -> ChaosRecord:
    """Burst backlog → scale-up, idle → scale-down: the queue-driven
    autoscaler grows the worker fleet under a submission burst (within
    its ceiling), drains the backlog, then retires back to the floor
    once the service goes idle — retiring workers finishing their
    in-flight cohorts first (the retire sentinel is only read between
    tasks)."""
    from distributed_optimization_tpu.serving.fleet import (
        AutoscaleOptions,
        QueueAutoscaler,
    )
    from distributed_optimization_tpu.serving.service import (
        ServingOptions,
        SimulationService,
    )

    service = SimulationService(
        # max_workers gives the dispatch executor headroom for the
        # scaled-up fleet; the pool itself starts at ONE worker.
        ServingOptions(window_s=0.0, workers=1, max_workers=4),
    )
    scaler = QueueAutoscaler(service, AutoscaleOptions(
        min_workers=1, max_workers=2, high_depth=1, low_depth=0,
        up_polls=2, down_polls=8, poll_s=0.1,
    ))
    detail: dict[str, Any] = {}
    try:
        service.start()
        scaler.start()
        # Distinct structural classes: no coalescing, a real backlog.
        rids = [
            service.submit(default_chaos_config(n_iterations=30 + 10 * i))
            for i in range(burst)
        ]
        statuses = [
            service.result(r, timeout=300.0).status for r in rids
        ]
        detail["statuses"] = statuses
        detail["scale_ups"] = scaler.n_scale_up
        # Idle now: wait (bounded) for the retire cycle to bottom out.
        deadline = time.time() + 120.0
        while time.time() < deadline:
            if (
                scaler.n_scale_down >= 1
                and service._pool.n_workers == 1
                and service._pool.alive_count() == 1
            ):
                break
            time.sleep(0.2)
        detail["scale_downs"] = scaler.n_scale_down
        pst = service._pool.stats()
        detail["final_target"] = pst["workers"]
        detail["final_alive"] = pst["alive"]
        detail["retired"] = pst["retired"]
        passed = (
            all(s == "done" for s in statuses)
            and detail["scale_ups"] >= 1
            and detail["scale_downs"] >= 1
            and detail["final_target"] == 1
            and detail["final_alive"] == 1
            and detail["retired"] >= 1
        )
    finally:
        service.close()
    _chaos_gauge().set(1, mode="fleet_autoscale_cycle")
    return ChaosRecord("fleet_autoscale_cycle", passed, detail)


def run_fleet_chaos_suite(
    *, modes: tuple[str, ...] = FLEET_CHAOS_MODES,
    incident_log: Optional[str] = None,
) -> dict[str, Any]:
    """Run the fleet chaos modes; same record/gate shape as
    ``run_chaos_suite`` (the block ``docs/perf/fleet.json`` commits).
    ``incident_log`` threads a JSONL path into the remediation modes so
    the bench can assert the forensic stream end-to-end."""
    runners = {
        "fleet_divergence_remediation": lambda: chaos_fleet_divergence(
            incident_log=incident_log
        ),
        "fleet_store_remediation": lambda: chaos_fleet_store_corruption(
            incident_log=incident_log
        ),
        "fleet_worker_storm": lambda: chaos_fleet_worker_storm(),
        "fleet_autoscale_cycle": lambda: chaos_fleet_autoscale(),
    }
    records = []
    for mode in modes:
        if mode not in runners:
            raise ValueError(
                f"unknown fleet chaos mode {mode!r} "
                f"(valid: {FLEET_CHAOS_MODES})"
            )
        _log.info("fleet chaos: injecting %s", mode)
        records.append(runners[mode]())
    return {
        "records": [r.to_dict() for r in records],
        "gates": {
            f"{r.mode}_remediated": bool(r.passed) for r in records
        },
    }
