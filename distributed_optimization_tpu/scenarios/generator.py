"""Cell generation: enumerate or property-sample the composition matrix.

A *cell* is one assignment of every spec axis — merged over the spec's
``base`` into a (partial) ``ExperimentConfig`` field map — classified by
the validity table. Two modes (``spec.mode``):

- ``enumerate``: the full cartesian product of axis settings, rejected
  up front when it exceeds ``max_cells`` (the spec should sample
  instead).
- ``sample``: seeded property sampling — a ``random.Random(spec.seed)``
  stream draws one setting per axis until ``spec.sample`` DISTINCT cells
  exist (or the matrix is exhausted). The draw sequence is a pure
  function of (spec axes order, seed), so a spec names a reproducible
  cell set: same spec file, same cells, every machine.

Valid cells get a constructed ``ExperimentConfig``; any disagreement
between the validity table and construction raises
``ValidityDivergenceError`` loudly — the generator is the belt-and-braces
runtime enforcement of the drift contract tests pin
(``validity.cross_check``).
"""

from __future__ import annotations

import dataclasses
import itertools
import random
from collections import Counter
from typing import Any, Optional

from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.scenarios.spec import ScenarioSpec, SpecError
from distributed_optimization_tpu.scenarios.validity import (
    Verdict,
    explain,
    full_fields,
)


class ValidityDivergenceError(AssertionError):
    """The validity table and ``ExperimentConfig`` construction disagreed
    about a cell — the drift the agreement contract exists to catch."""


@dataclasses.dataclass
class Cell:
    """One classified cell of the matrix."""

    index: int
    settings: dict[str, dict[str, Any]]  # axis -> chosen field group
    fields: dict[str, Any]  # merged base + settings (partial overrides)
    verdict: Verdict
    config: Optional[ExperimentConfig] = None  # constructed when valid

    @property
    def valid(self) -> bool:
        return self.verdict.valid

    def row(self) -> dict[str, Any]:
        """JSON-safe report row (non-default overrides only)."""
        out: dict[str, Any] = {
            "index": self.index,
            "overrides": dict(self.fields),
            "valid": self.verdict.valid,
        }
        if not self.verdict.valid:
            out["rule"] = self.verdict.rule
            out["reason"] = self.verdict.reason
        if self.config is not None:
            out["structural_hash"] = self.config.structural_hash()
        return out


@dataclasses.dataclass
class MatrixSample:
    """The generated cell set plus its accounting."""

    spec: ScenarioSpec
    cells: list[Cell]
    exhausted: bool = False  # sample mode ran out of distinct cells

    @property
    def valid_cells(self) -> list[Cell]:
        return [c for c in self.cells if c.valid]

    def counts(self) -> dict[str, Any]:
        rejected = Counter(
            c.verdict.rule for c in self.cells if not c.valid
        )
        return {
            "cells": len(self.cells),
            "valid": sum(1 for c in self.cells if c.valid),
            "rejected": sum(1 for c in self.cells if not c.valid),
            "rejected_by_rule": dict(sorted(rejected.items())),
        }


def merge_cell_fields(
    spec: ScenarioSpec, choice: dict[str, dict[str, Any]],
) -> dict[str, Any]:
    """Base + one setting per axis, with axis-collision detection: two
    axes that set the same config field make the spec ambiguous (which
    wins would depend on axis order), so that is a spec error, not a
    silent override. Axes legitimately override ``base``."""
    fields = dict(spec.base)
    owner: dict[str, str] = {}
    for axis, setting in choice.items():
        for key, value in setting.items():
            if key in owner:
                raise SpecError(
                    f"axes {owner[key]!r} and {axis!r} both set config "
                    f"field {key!r}; fold them into one axis",
                    field=key,
                )
            owner[key] = axis
            fields[key] = value
    return fields


def _classify(spec: ScenarioSpec, index: int,
              choice: dict[str, dict[str, Any]]) -> Cell:
    fields = merge_cell_fields(spec, choice)
    verdict = explain(full_fields(fields))
    config = None
    error = ExperimentConfig.construction_error(full_fields(fields))
    if verdict.valid != (error is None):
        raise ValidityDivergenceError(
            f"cell {index} of spec {spec.name!r}: validity table says "
            f"{'valid' if verdict.valid else f'invalid ({verdict.rule})'} "
            f"but construction says "
            f"{'valid' if error is None else f'invalid ({error})'} — "
            f"fields {fields}"
        )
    if verdict.valid:
        config = ExperimentConfig(**full_fields(fields))
    return Cell(index=index, settings=dict(choice), fields=fields,
                verdict=verdict, config=config)


def enumerate_cells(spec: ScenarioSpec) -> MatrixSample:
    total = spec.n_cells_total()
    if total > spec.max_cells:
        raise SpecError(
            f"enumerating {spec.name!r} would build {total} cells "
            f"(> max_cells {spec.max_cells}); use mode='sample' or raise "
            "max_cells", field="max_cells",
        )
    names = spec.axis_names
    cells = []
    for index, combo in enumerate(
        itertools.product(*(spec.axes[a] for a in names))
    ):
        cells.append(_classify(spec, index, dict(zip(names, combo))))
    return MatrixSample(spec=spec, cells=cells)


def sample_cells(spec: ScenarioSpec) -> MatrixSample:
    """Seeded distinct-cell sampling (see module docstring). Draws until
    ``spec.sample`` distinct cells exist; the matrix may hold fewer, in
    which case every cell is returned and ``exhausted`` is set."""
    names = spec.axis_names
    total = spec.n_cells_total()
    target = min(spec.sample, total)
    rng = random.Random(spec.seed)
    seen: set[tuple[int, ...]] = set()
    cells: list[Cell] = []
    # Distinctness makes a pure rejection loop slow near exhaustion; cap
    # attempts and fall back to a seeded shuffle of the remainder.
    max_attempts = max(50 * target, 1000)
    attempts = 0
    while len(cells) < target and attempts < max_attempts:
        attempts += 1
        key = tuple(
            rng.randrange(len(spec.axes[a])) for a in names
        )
        if key in seen:
            continue
        seen.add(key)
        choice = {
            a: spec.axes[a][i] for a, i in zip(names, key)
        }
        cells.append(_classify(spec, len(cells), choice))
    if len(cells) < target:
        remainder = [
            key for key in itertools.product(
                *(range(len(spec.axes[a])) for a in names)
            ) if key not in seen
        ]
        rng.shuffle(remainder)
        for key in remainder[: target - len(cells)]:
            choice = {a: spec.axes[a][i] for a, i in zip(names, key)}
            cells.append(_classify(spec, len(cells), choice))
    return MatrixSample(
        spec=spec, cells=cells, exhausted=len(cells) < spec.sample,
    )


def generate(spec: ScenarioSpec) -> MatrixSample:
    if spec.mode == "enumerate":
        return enumerate_cells(spec)
    return sample_cells(spec)
