"""``python -m distributed_optimization_tpu.scenarios`` — the scenario CLI.

Subcommands:

- ``explain field=value ...``  query the validity table for one cell:
  prints "valid" or the rejecting rule + exact reason (exit 0 either
  way; exit 2 on unknown fields, with the nearest valid field named).
- ``sample SPEC [--json]``     generate the spec's seeded cell set and
  print the validity accounting WITHOUT running anything.
- ``run SPEC [--out OUT]``     run the engine: serve every valid cell,
  assert per-cell invariants, write the JSON report. Exit 1 when any
  invariant fails or a cell errors; 0 on a clean matrix.
- ``chaos [--out OUT]``        run the operational chaos suite against a
  fresh serving plane; exit 1 on any non-graceful degradation.

Error contract: malformed specs and bad field names print ONE structured
``scenarios: error: ...`` line (offending field + nearest-valid-field
suggestion) on stderr and exit 2 — never a traceback.
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_optimization_tpu.scenarios.spec import SpecError
from distributed_optimization_tpu.scenarios.validity import (
    UnknownFieldError,
)


def _coerce(value: str):
    """CLI field=value parsing: JSON literal when it parses, else str."""
    try:
        return json.loads(value)
    except json.JSONDecodeError:
        return value


def _cmd_explain(args) -> int:
    from distributed_optimization_tpu.scenarios import validity

    overrides = {}
    for pair in args.fields:
        if "=" not in pair:
            print(
                f"scenarios: error: expected field=value, got {pair!r}",
                file=sys.stderr,
            )
            return 2
        key, _, value = pair.partition("=")
        overrides[key] = _coerce(value)
    verdict = validity.explain(validity.full_fields(overrides))
    if args.json:
        print(json.dumps({
            "valid": verdict.valid, "rule": verdict.rule,
            "axes": list(verdict.axes), "reason": verdict.reason,
        }, indent=1))
    elif verdict.valid:
        print("valid")
    else:
        print(f"invalid [{verdict.rule}] ({'×'.join(verdict.axes)})")
        print(f"  {verdict.reason}")
    return 0


def _cmd_sample(args) -> int:
    from distributed_optimization_tpu.scenarios.generator import generate
    from distributed_optimization_tpu.scenarios.spec import load_spec

    sample = generate(load_spec(args.spec))
    counts = sample.counts()
    if args.json:
        print(json.dumps({
            "spec": sample.spec.name, "seed": sample.spec.seed,
            "counts": counts,
            "cells": [c.row() for c in sample.cells],
        }, indent=1))
        return 0
    print(
        f"spec {sample.spec.name!r} (seed {sample.spec.seed}, "
        f"{sample.spec.mode}): {counts['cells']} cells — "
        f"{counts['valid']} valid, {counts['rejected']} rejected"
    )
    for rule, n in counts["rejected_by_rule"].items():
        print(f"  {n:5d}  {rule}")
    return 0


def _cmd_run(args) -> int:
    from distributed_optimization_tpu.scenarios.engine import run_scenarios
    from distributed_optimization_tpu.scenarios.spec import load_spec

    report = run_scenarios(load_spec(args.spec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"[scenarios] report -> {args.out}", file=sys.stderr)
    gates = report["gates"]
    inv = report["invariants"]
    print(
        f"[scenarios] {report['counts']['valid']} valid cells, "
        f"{inv['checks']} invariant checks, {inv['failures']} failures "
        f"({report['wall_seconds']:.1f}s)"
    )
    for name, ok in sorted(gates.items()):
        print(f"  {'PASS' if ok else 'FAIL'}  {name}")
    return 0 if all(gates.values()) else 1


def _cmd_chaos(args) -> int:
    from distributed_optimization_tpu.scenarios.chaos import run_chaos_suite

    suite = run_chaos_suite()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(suite, f, indent=1, sort_keys=True)
        print(f"[scenarios] chaos report -> {args.out}", file=sys.stderr)
    for record in suite["records"]:
        print(
            f"  {'PASS' if record['passed'] else 'FAIL'}  {record['mode']}"
        )
    return 0 if all(suite["gates"].values()) else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="distributed_optimization_tpu.scenarios",
        description=(
            "Scenario engine + chaos harness over the composition matrix "
            "(docs/SCENARIOS.md)."
        ),
    )
    sub = p.add_subparsers(dest="command", required=True)

    pe = sub.add_parser(
        "explain",
        help="classify one cell: valid, or the rejecting rule + reason",
    )
    pe.add_argument("fields", nargs="*",
                    help="config overrides as field=value (JSON literals)")
    pe.add_argument("--json", action="store_true")
    pe.set_defaults(fn=_cmd_explain)

    ps = sub.add_parser(
        "sample", help="generate a spec's cell set without running it",
    )
    ps.add_argument("spec", help="scenario spec file (JSON; YAML when "
                                 "available)")
    ps.add_argument("--json", action="store_true")
    ps.set_defaults(fn=_cmd_sample)

    pr = sub.add_parser(
        "run", help="run a spec through the serving layer + invariants",
    )
    pr.add_argument("spec")
    pr.add_argument("--out", default=None, help="write the JSON report here")
    pr.set_defaults(fn=_cmd_run)

    pc = sub.add_parser(
        "chaos", help="run the operational chaos suite",
    )
    pc.add_argument("--out", default=None)
    pc.set_defaults(fn=_cmd_chaos)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except (SpecError, UnknownFieldError) as e:
        hint = getattr(e, "suggestion", None)
        extra = "" if hint is None else f" (did you mean {hint!r}?)"
        # The suggestion is already part of str(e) for these types; the
        # extra clause only fires for bare UnknownFieldError paths.
        msg = str(e)
        print(
            f"scenarios: error: {msg}"
            + (extra if hint and hint not in msg else ""),
            file=sys.stderr,
        )
        return 2


if __name__ == "__main__":
    sys.exit(main())
