"""Per-cell invariants: what a valid cell of the matrix must DO.

Each invariant is a named, self-describing check over one executed cell
(and, where the property is relational, its derived twin runs). The
catalog executes through the engine — twins are served through the same
serving layer as the cells, so the invariant suite doubles as mixed
traffic — and every result is a JSON-safe record the golden corpus
commits (docs/perf/scenarios.json, guarded by the perf-diff checker).

The catalog (auto-selected per cell by ``applies``; a spec may restrict
with its ``invariants`` list):

- ``finite_gap``        every cell: the objective history is finite.
- ``gt_tracking``       gradient tracking: mean(y) == mean(g_prev) at the
                        final state (the DIGing invariant — Nedić,
                        Olshevsky, Shi '17), tolerance by dtype.
- ``robust_envelope``   attacked robust cells: honest final gap within an
                        envelope factor of the attack-free twin
                        (Karimireddy-style containment).
- ``bhat_degradation``  fault cells: the realized windowed-connectivity
                        B̂ exists (the union graph stays connected), grows
                        with burst length at matched marginal (Koloskova
                        '20's B-connectivity), and the gap-vs-fault-free
                        ratio sits inside a no-free-lunch envelope.
- ``reduction_burst``   iid edge-fault cells: burst_len=1 twin is BITWISE
                        the burst_len=0 (memoryless) program.
- ``reduction_churn``   straggler cells: the mttf=1/q, mttr=1/(1-q) churn
                        twin is BITWISE the iid straggler program.
- ``reduction_zero_budget`` robust-rule cells without attack: robust_b=0
                        twin is BITWISE plain gossip.
- ``reduction_explicit_defaults`` cells that spell out degenerate knobs
                        (τ=1, q=1.0, burst 0): the stripped twin names
                        the SAME experiment — equal config and structural
                        hash, hence one serving cohort. Definitional for
                        a frozen config; its content is guarding the
                        off-point table against default drift. The
                        empirical τ/q/burst bitwise claims live in the
                        reduction_* run comparisons above.
- ``checkpoint_resume`` sync jax cells: interrupt + resume is BITWISE the
                        uninterrupted (equally-segmented) run.
- ``replica_cohort``    replicas>1 cells: the R seed-expanded requests
                        coalesce into one cohort of size R and every
                        replica finishes finite.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Callable, Optional

import numpy as np

from distributed_optimization_tpu.config import ExperimentConfig


@dataclasses.dataclass
class InvariantResult:
    name: str
    passed: bool
    detail: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "passed": self.passed,
                "detail": self.detail}


@dataclasses.dataclass(frozen=True)
class Invariant:
    name: str
    applies: Callable[[ExperimentConfig], bool]
    check: Callable[["CellContext"], InvariantResult]
    doc: str = ""


class CellContext:
    """What one invariant check may touch: the executed cell and the
    engine's run services (serving-routed twins, direct backend runs for
    state/checkpoint access, datasets, envelopes)."""

    def __init__(self, cell, config, results, requests, engine, envelopes):
        self.cell = cell
        self.config: ExperimentConfig = config
        self.results = results  # list[BackendRunResult], one per replica
        self.requests = requests  # serving Request records (same order)
        self.engine = engine
        self.envelopes = dict(envelopes)

    @property
    def result(self):
        return self.results[0]

    def envelope(self, name: str, default: float) -> float:
        return float(self.envelopes.get(name, default))

    def run_served(self, config: ExperimentConfig):
        return self.engine.run_served(config)

    def run_direct(self, config: ExperimentConfig, **kwargs):
        return self.engine.run_direct(config, **kwargs)


def _gap(result) -> float:
    return float(result.history.objective[-1])


def _bitwise(a, b) -> dict[str, Any]:
    """Exact-equality comparison of two runs' trajectories."""
    obj_equal = bool(np.array_equal(
        a.history.objective, b.history.objective
    ))
    models_equal = bool(np.array_equal(a.final_models, b.final_models))
    out = {
        "objective_bitwise": obj_equal,
        "final_models_bitwise": models_equal,
    }
    if not (obj_equal and models_equal):
        out["max_abs_objective_dev"] = float(np.max(np.abs(
            np.asarray(a.history.objective)
            - np.asarray(b.history.objective)
        ))) if len(a.history.objective) == len(b.history.objective) else None
    return out


def _fault_free_fields(fields: dict) -> dict:
    out = dict(fields)
    for key in ("edge_drop_prob", "straggler_prob", "burst_len", "mttf",
                "mttr", "rejoin", "participation_rate"):
        out.pop(key, None)
    return out


def _has_fault_process(cfg: ExperimentConfig) -> bool:
    return (
        cfg.edge_drop_prob > 0.0 or cfg.straggler_prob > 0.0
        or cfg.mttf > 0.0 or cfg.participation_rate < 1.0
    )


def _robust_rule_on(cfg: ExperimentConfig) -> bool:
    return cfg.aggregation != "gossip" and cfg.robust_b > 0


# --------------------------------------------------------------- checks


def _check_finite(ctx: CellContext) -> InvariantResult:
    details = []
    ok = True
    for result in ctx.results:
        obj = np.asarray(result.history.objective)
        finite = bool(np.all(np.isfinite(obj)))
        ok = ok and finite and obj.size > 0
        details.append({
            "final_gap": float(obj[-1]) if obj.size else None,
            "all_finite": finite,
        })
    return InvariantResult("finite_gap", ok, {"replicas": details})


def _check_gt_tracking(ctx: CellContext) -> InvariantResult:
    cfg = ctx.config
    res = ctx.run_direct(cfg, return_state=True)
    state = res.final_state or {}
    if "y" not in state or "g_prev" not in state:
        return InvariantResult(
            "gt_tracking", False,
            {"error": "final state carries no y/g_prev leaves"},
        )
    resid = float(np.max(np.abs(
        np.asarray(state["y"]).mean(axis=0)
        - np.asarray(state["g_prev"]).mean(axis=0)
    )))
    tol = ctx.envelope(
        "gt_tracking_tol", 1e-8 if cfg.dtype == "float64" else 5e-3
    )
    return InvariantResult(
        "gt_tracking", resid <= tol, {"residual": resid, "tol": tol},
    )


def _check_robust_envelope(ctx: CellContext) -> InvariantResult:
    cfg = ctx.config
    twin = cfg.replace(attack="none", n_byzantine=0, attack_scale=1.0)
    clean = ctx.run_served(twin)
    envelope = ctx.envelope("robust_envelope", 5.0)
    gap, gap_clean = _gap(ctx.result), _gap(clean)
    ratio = gap / max(gap_clean, 1e-12)
    passed = math.isfinite(gap) and ratio <= envelope
    return InvariantResult(
        "robust_envelope", passed,
        {"gap_attacked": gap, "gap_attack_free": gap_clean,
         "ratio": ratio, "envelope": envelope},
    )


def _check_bhat_degradation(ctx: CellContext) -> InvariantResult:
    from distributed_optimization_tpu import telemetry

    cfg = ctx.config
    detail: dict[str, Any] = {}
    ok = True
    bhat = telemetry.realized_bhat(cfg)
    detail["bhat"] = None if bhat is None else bhat.get("bhat")
    # (a) connectivity survives: a finite B̂ exists over the horizon.
    if bhat is None or bhat.get("bhat") is None:
        ok = False
        detail["bhat_exists"] = False
    else:
        detail["bhat_exists"] = True
        # (b) burstiness monotonicity at matched marginal (deterministic:
        # same seed, same marginal, longer bursts).
        if cfg.burst_len > 1.0:
            iid = telemetry.realized_bhat(cfg.replace(burst_len=1.0))
            detail["bhat_iid"] = None if iid is None else iid.get("bhat")
            if iid is not None and iid.get("bhat") is not None:
                ok = ok and bhat["bhat"] >= iid["bhat"]
                detail["bhat_monotone_in_burst"] = (
                    bhat["bhat"] >= iid["bhat"]
                )
    # (c) convergence no-free-lunch vs the fault-free twin.
    clean_cfg = ExperimentConfig(**_fault_free_fields(cfg.to_dict()))
    clean = ctx.run_served(clean_cfg)
    gap, gap_clean = _gap(ctx.result), _gap(clean)
    ratio = gap / max(gap_clean, 1e-12)
    lo = ctx.envelope("no_free_lunch_floor", 0.5)
    hi = ctx.envelope("degradation_cap", 200.0)
    in_envelope = math.isfinite(ratio) and lo <= ratio <= hi
    ok = ok and in_envelope
    detail.update({
        "gap_faulty": gap, "gap_fault_free": gap_clean,
        "degradation_ratio": ratio, "envelope": [lo, hi],
        "in_envelope": in_envelope,
    })
    return InvariantResult("bhat_degradation", ok, detail)


# The bitwise reductions compare DIRECT sequential runs on both sides:
# the established bitwise contracts (burst_len=1 == iid, churn at
# mttf=1/q == stragglers, robust_b=0 == gossip) are stated on
# ``jax_backend.run``'s sequential program, and serving-routed twins
# would land in different cohort SHAPES (R=2 vs R=1 vmap programs),
# where XLA's per-shape fusion only guarantees the repo's ≤1e-12 f64
# cross-shape convention — not bit equality (measured ~9e-13 when the
# engine first tried it served).


def _check_reduction_burst(ctx: CellContext) -> InvariantResult:
    a = ctx.run_direct(ctx.config)
    b = ctx.run_direct(ctx.config.replace(burst_len=1.0))
    detail = _bitwise(a, b)
    return InvariantResult(
        "reduction_burst",
        detail["objective_bitwise"] and detail["final_models_bitwise"],
        detail,
    )


def _check_reduction_churn(ctx: CellContext) -> InvariantResult:
    q = ctx.config.straggler_prob
    twin_cfg = ctx.config.replace(
        straggler_prob=0.0, mttf=1.0 / q, mttr=1.0 / (1.0 - q),
    )
    a = ctx.run_direct(ctx.config)
    b = ctx.run_direct(twin_cfg)
    detail = _bitwise(a, b)
    detail["mttf"] = twin_cfg.mttf
    detail["mttr"] = twin_cfg.mttr
    return InvariantResult(
        "reduction_churn",
        detail["objective_bitwise"] and detail["final_models_bitwise"],
        detail,
    )


def _check_reduction_zero_budget(ctx: CellContext) -> InvariantResult:
    base = ctx.config.replace(
        robust_b=0, clip_tau=0.0, robust_impl="auto",
    )
    robust_off = ctx.run_direct(base)
    gossip = ctx.run_direct(base.replace(aggregation="gossip"))
    detail = _bitwise(robust_off, gossip)
    detail["aggregation"] = ctx.config.aggregation
    return InvariantResult(
        "reduction_zero_budget",
        detail["objective_bitwise"] and detail["final_models_bitwise"],
        detail,
    )


# The degenerate knobs whose explicit spelling must not change the
# program: value == the knob's "off" point.
_EXPLICIT_DEFAULTS = {
    "local_steps": 1, "participation_rate": 1.0, "burst_len": 0.0,
    "replicas": 1, "worker_mesh": 0,
}


def _explicit_default_keys(fields: dict) -> list[str]:
    return [
        k for k, off in _EXPLICIT_DEFAULTS.items()
        if k in fields and fields[k] == off
    ]


def _check_reduction_explicit_defaults(ctx: CellContext) -> InvariantResult:
    """Spelling out a degenerate knob (τ=1, q=1.0, burst 0, replicas 1,
    mesh 0) must name the SAME experiment as omitting it: the stripped
    twin builds an equal config with an equal structural hash, so the
    serving layer coalesces the two spellings into one cohort/executable.

    Scope, honestly: for a frozen config dataclass this is definitional
    — so the check's real content is guarding the off-point table above
    against drift (a future default change, or a validation rule that
    starts rejecting an explicitly-spelled off value, breaks it loudly).
    No twin RUN is compared: the memoized served result would be the
    cell's own object, and the empirical bitwise reductions live in
    reduction_burst/churn/zero_budget instead.
    """
    keys = _explicit_default_keys(ctx.cell.fields)
    stripped = {
        k: v for k, v in ctx.cell.fields.items() if k not in keys
    }
    try:
        twin_cfg = ExperimentConfig(**_full(stripped))
    except (TypeError, ValueError) as e:
        return InvariantResult(
            "reduction_explicit_defaults", False,
            {"stripped_fields": keys, "twin_rejected": str(e)},
        )
    detail = {
        "stripped_fields": keys,
        "config_equal": twin_cfg == ctx.config,
        "structural_hash_equal": (
            twin_cfg.structural_hash() == ctx.config.structural_hash()
        ),
    }
    return InvariantResult(
        "reduction_explicit_defaults",
        detail["config_equal"] and detail["structural_hash_equal"],
        detail,
    )


def _full(overrides: dict) -> dict:
    from distributed_optimization_tpu.scenarios.validity import full_fields

    return full_fields(overrides)


def _check_checkpoint_resume(ctx: CellContext) -> InvariantResult:
    from distributed_optimization_tpu.utils.checkpoint import (
        CheckpointOptions,
    )

    cfg = ctx.config
    n_evals = cfg.n_iterations // cfg.eval_every
    every = max(1, n_evals // 4)
    half_evals = max(every, (n_evals // 2 // every) * every)
    workdir = ctx.engine.workdir(
        f"ckpt-{ctx.cell.index}-{cfg.structural_hash()}"
    )
    ref = ctx.run_direct(cfg, checkpoint=CheckpointOptions(
        os.path.join(workdir, "ref"), every_evals=every, resume=False,
    ))
    resume_dir = os.path.join(workdir, "resume")
    if cfg.execution == "async":
        # The event schedule is horizon-global, so a shorter-horizon run
        # is a DIFFERENT event sequence — n_iterations is not resumable
        # on the event clock (the RunCheckpointer sidecar pins it).
        # Interrupt instead by dropping every chunk past the midpoint of
        # a full run; resume replays the suffix from the surviving
        # mid-schedule chunk (the PR 3 truncated-chunk fallback path).
        import shutil

        from distributed_optimization_tpu.utils.checkpoint import (
            RunCheckpointer,
        )

        opts = CheckpointOptions(
            resume_dir, every_evals=every, resume=False,
        )
        ctx.run_direct(cfg, checkpoint=opts)
        ck = RunCheckpointer(opts)
        chunks = ck.completed_chunks()
        # Retention (max_to_keep) already dropped the earliest saves;
        # keep only the earliest SURVIVING chunk so the resume genuinely
        # replays a mid-schedule suffix.
        for chunk in chunks[1:]:
            shutil.rmtree(ck._step_dir(chunk), ignore_errors=True)
        half_evals = chunks[0] if chunks else half_evals
    else:
        # The "interrupted" run: half the horizon, then resume to the
        # full horizon from its last saved chunk.
        half_cfg = cfg.replace(n_iterations=half_evals * cfg.eval_every)
        ctx.run_direct(half_cfg, checkpoint=CheckpointOptions(
            resume_dir, every_evals=every, resume=False,
        ))
    resumed = ctx.run_direct(cfg, checkpoint=CheckpointOptions(
        resume_dir, every_evals=every, resume=True,
    ))
    detail = _bitwise(ref, resumed)
    detail["every_evals"] = every
    detail["interrupted_at_iteration"] = half_evals * cfg.eval_every
    return InvariantResult(
        "checkpoint_resume",
        detail["objective_bitwise"] and detail["final_models_bitwise"],
        detail,
    )


def _check_replica_cohort(ctx: CellContext) -> InvariantResult:
    R = ctx.config.replicas
    sizes = [req.cohort_size for req in ctx.requests]
    coalesced = [bool(req.coalesced) for req in ctx.requests]
    gaps = [_gap(r) for r in ctx.results]
    # The R expanded requests must land in ONE coalesced cohort — of at
    # least R (other same-class traffic in the wave legitimately rides
    # the same cohort, so == R would be wrong by design).
    ok = (
        len(ctx.results) == R
        and all(s == sizes[0] and s >= R for s in sizes)
        and all(coalesced)
        and all(math.isfinite(g) for g in gaps)
    )
    return InvariantResult(
        "replica_cohort", ok,
        {"replicas": R, "cohort_sizes": sizes, "coalesced": coalesced,
         "gaps": gaps},
    )


# --------------------------------------------------------------- catalog


def _sync_jax(cfg: ExperimentConfig) -> bool:
    return cfg.backend == "jax" and cfg.execution == "sync"


CATALOG: dict[str, Invariant] = {
    inv.name: inv for inv in (
        Invariant(
            "finite_gap", lambda cfg: True, _check_finite,
            doc="objective history is finite end to end",
        ),
        Invariant(
            "gt_tracking",
            # The DIGing identity mean(y) == mean(g_prev) is preserved by
            # average-preserving mixing ONLY: it survives faults/churn
            # (frozen rejoin) because realized-MH stays doubly stochastic,
            # but Byzantine payloads corrupt the exchanged y rows and
            # screening rules (trimmed mean/median/clipping) are not
            # average-preserving — measured residuals under attack are
            # O(payload), so the invariant's own applicability boundary
            # is plain gossip (the engine smoke that found this is why
            # the catalog encodes it).
            # Applies on BOTH clocks (ISSUE-17): the async event update's
            # per-event telescoping (y_i' picks up g_new − g_prev_i, the
            # pair averages preserve both means) keeps the identity exact
            # at ANY staleness, under event-realized crash/participation
            # faults included — a no-op event changes nothing and a
            # degraded self-exchange averages a row with itself.
            lambda cfg: (
                cfg.algorithm == "gradient_tracking"
                and cfg.backend == "jax"
                and cfg.attack == "none" and cfg.aggregation == "gossip"
                and cfg.rejoin == "frozen"
                and cfg.worker_mesh == 0 and cfg.replicas == 1
                and cfg.tp_degree == 1
            ),
            _check_gt_tracking,
            doc="mean(y) tracks mean(g_prev) at the final state",
        ),
        Invariant(
            "robust_envelope",
            lambda cfg: (
                cfg.attack != "none" and _robust_rule_on(cfg)
                and cfg.replicas == 1
            ),
            _check_robust_envelope,
            doc="honest gap within an envelope of the attack-free twin",
        ),
        Invariant(
            "bhat_degradation",
            lambda cfg: (
                _has_fault_process(cfg) and _sync_jax(cfg)
                and cfg.gossip_schedule == "synchronous"
                and cfg.worker_mesh == 0 and cfg.replicas == 1
                and cfg.resolved_topology_impl() == "dense"
            ),
            _check_bhat_degradation,
            doc="realized B-hat exists, grows with burstiness, and the "
                "fault degradation stays inside the envelope",
        ),
        Invariant(
            "reduction_burst",
            lambda cfg: (
                cfg.edge_drop_prob > 0.0 and cfg.burst_len == 0.0
                and _sync_jax(cfg)
                and cfg.gossip_schedule == "synchronous"
                and cfg.worker_mesh == 0 and cfg.replicas == 1
            ),
            _check_reduction_burst,
            doc="burst_len=1 is bitwise the memoryless iid sampler",
        ),
        Invariant(
            "reduction_churn",
            # Holds on the event clock too (ISSUE-17): the event
            # realization reads the same (seed, horizon)-pure chains at
            # (local_step, worker), and iid stragglers collapse to churn
            # at mttf=1/q bitwise at the CHAIN level, so the realized
            # fire/partner arrays — and hence the scanned program — are
            # identical.
            lambda cfg: (
                cfg.straggler_prob > 0.0 and cfg.mttf == 0.0
                and cfg.backend == "jax"
                and cfg.gossip_schedule == "synchronous"
                and cfg.worker_mesh == 0 and cfg.replicas == 1
            ),
            _check_reduction_churn,
            doc="mttf=1/q, mttr=1/(1-q) churn is bitwise iid stragglers",
        ),
        Invariant(
            "reduction_zero_budget",
            lambda cfg: (
                cfg.aggregation != "gossip" and cfg.attack == "none"
                and _sync_jax(cfg) and cfg.worker_mesh == 0
                and cfg.replicas == 1
            ),
            _check_reduction_zero_budget,
            doc="robust_b=0 degrades bitwise to plain gossip",
        ),
        Invariant(
            "reduction_explicit_defaults",
            lambda cfg: cfg.replicas == 1,
            _check_reduction_explicit_defaults,
            doc="spelling out τ=1/q=1-style off points names the same "
                "experiment (equal config + structural hash — the "
                "coalescing identity; guards the off-point table against "
                "default drift)",
        ),
        Invariant(
            "checkpoint_resume",
            # Async runs checkpoint on the same RunCheckpointer chunk
            # grammar (ISSUE-17): an eval row is a chunk, the event
            # cursor is chunk·eval_every·N, and restore replays the
            # suffix bitwise (prefix-stable schedules + counter-based
            # batch draws).
            lambda cfg: (
                cfg.backend == "jax" and cfg.replicas == 1
                and cfg.worker_mesh == 0 and cfg.tp_degree == 1
                and not cfg.telemetry
                and cfg.n_iterations // cfg.eval_every >= 4
            ),
            _check_checkpoint_resume,
            doc="interrupt + resume is bitwise the uninterrupted "
                "equally-segmented run",
        ),
        Invariant(
            "replica_cohort",
            lambda cfg: cfg.replicas > 1,
            _check_replica_cohort,
            doc="seed-expanded replica requests coalesce into one cohort",
        ),
    )
}


def applicable_invariants(
    cfg: ExperimentConfig, cell_fields: Optional[dict] = None,
    restrict: Optional[tuple[str, ...]] = None,
) -> list[Invariant]:
    """The invariants this cell must satisfy. ``restrict`` (a spec's
    ``invariants`` list) intersects the auto-selection — it never forces
    an inapplicable check onto a cell."""
    out = []
    for inv in CATALOG.values():
        if restrict is not None and inv.name not in restrict:
            continue
        if not inv.applies(cfg):
            continue
        if (
            inv.name == "reduction_explicit_defaults"
            and not _explicit_default_keys(cell_fields or {})
        ):
            continue
        out.append(inv)
    return out
