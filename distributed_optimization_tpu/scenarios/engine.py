"""The scenario engine: sampled matrix cells → served runs → invariants.

``ScenarioEngine`` ties the pieces together (docs/SCENARIOS.md):

1. ``generator.generate`` draws the spec's seeded cell set and classifies
   every cell against the validity table (construction agreement enforced
   per cell — a divergence aborts the run loudly).
2. Every valid cell becomes serving traffic: cells are submitted to a
   ``SimulationService`` in one wave and drained together, so
   structurally identical cells coalesce into ``run_batch`` cohorts and
   repeated programs ride the executable cache exactly as production
   requests would — the engine IS a traffic generator. Cells with
   ``replicas == R > 1`` are expanded into R seed-variant requests with
   the dataset and the random-topology seed pinned, which is the serving
   layer's own replica axis (the coalescer must reassemble the cohort —
   asserted by the ``replica_cohort`` invariant).
3. Each completed cell runs its applicable invariant catalog
   (``scenarios.invariants``); twin runs route through the same service
   (memoized — a twin shared by two cells runs once).

Per-run metrics (ISSUE-12 satellite): the engine resets and sets the
``dopt_scenario_*`` gauge families in the process metrics registry —
cells sampled/valid/rejected (plus a per-rule breakdown), invariant
checks/failures — the same reset-per-run discipline as the worker-mesh
per-device gauges.
"""

from __future__ import annotations

import shutil
import tempfile
import time
from pathlib import Path
from typing import Any, Optional

from distributed_optimization_tpu.config import (
    RANDOM_TOPOLOGIES,
    ExperimentConfig,
)
from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.observability.metrics_registry import (
    metrics_registry,
)
from distributed_optimization_tpu.scenarios.generator import (
    Cell,
    MatrixSample,
    generate,
)
from distributed_optimization_tpu.scenarios.invariants import (
    CellContext,
    applicable_invariants,
)
from distributed_optimization_tpu.scenarios.spec import ScenarioSpec

_log = get_logger("scenarios")


class EngineRunError(RuntimeError):
    """A served run the engine depended on failed; carries the service's
    structured error message."""


def expand_cell_requests(cfg: ExperimentConfig) -> list[ExperimentConfig]:
    """A cell config's serving requests: itself, or the R-replica seed
    expansion with dataset + random-graph pinned so the coalescer can
    reassemble the cohort. Shared by the engine's run loop and the
    sustained-load traffic sampler below — one definition of how a
    sampled cell becomes submit-ready traffic."""
    if cfg.replicas == 1:
        return [cfg]
    pins: dict[str, Any] = {
        "replicas": 1, "data_seed": cfg.resolved_data_seed(),
    }
    if cfg.topology in RANDOM_TOPOLOGIES:
        pins["topology_seed"] = cfg.resolved_topology_seed()
    return [
        cfg.replace(seed=seed, **pins) for seed in cfg.replica_seeds()
    ]


def sample_traffic(
    spec: ScenarioSpec, *, limit: Optional[int] = None,
) -> list[ExperimentConfig]:
    """Serving traffic from a scenario spec (ISSUE-15): every valid
    sampled cell expanded into its submit-ready requests, in sample
    order — the mixed-cohort structural-class stream the sustained-load
    bench (``examples/bench_serving_load.py``) replays at rate. The
    spec's seed makes the stream reproducible; ``limit`` truncates it."""
    sample = generate(spec)
    out: list[ExperimentConfig] = []
    for cell in sample.valid_cells:
        assert cell.config is not None
        out.extend(expand_cell_requests(cell.config))
        if limit is not None and len(out) >= limit:
            return out[:limit]
    return out


def triage_cell(incidents, run_error=None) -> str:
    """Mechanical cell triage (ISSUE-13): sweeps separate 'converged'
    (no anomaly fired), 'validly_degraded' (warn-severity incidents only
    — the cell degraded the way its fault/attack composition is allowed
    to), and 'pathological' (a fatal incident, or the run itself failed)
    without a human reading per-cell curves."""
    if run_error is not None:
        return "pathological"
    if any(i.get("severity") == "fatal" for i in incidents):
        return "pathological"
    if incidents:
        return "validly_degraded"
    return "converged"


def _reset_scenario_gauges(reg) -> dict:
    gauges = {
        "sampled": reg.gauge(
            "dopt_scenario_cells_sampled",
            "Cells drawn from the composition matrix in the last "
            "scenario-engine run",
        ),
        "triage": reg.gauge(
            "dopt_scenario_cells_triage",
            "Completed cells of the last scenario-engine run by triage "
            "class (converged / validly_degraded / pathological)",
        ),
        "valid": reg.gauge(
            "dopt_scenario_cells_valid",
            "Valid cells in the last scenario-engine run",
        ),
        "rejected": reg.gauge(
            "dopt_scenario_cells_rejected",
            "Cells the validity table rejected in the last "
            "scenario-engine run (by rule via the 'rule' label)",
        ),
        "checks": reg.gauge(
            "dopt_scenario_invariant_checks",
            "Invariant checks executed in the last scenario-engine run",
        ),
        "failures": reg.gauge(
            "dopt_scenario_invariant_failures",
            "Invariant checks that failed in the last scenario-engine run",
        ),
    }
    for g in gauges.values():
        g.reset()
    return gauges


class ScenarioEngine:
    """One spec, one engine run (see the module docstring)."""

    def __init__(
        self,
        spec: ScenarioSpec,
        *,
        service=None,
        max_cohort: int = 32,
        workdir: Optional[str] = None,
    ):
        self.spec = spec
        if service is None:
            from distributed_optimization_tpu.serving.cache import (
                ExecutableCache,
            )
            from distributed_optimization_tpu.serving.service import (
                ServingOptions,
                SimulationService,
            )

            expected_cells = (
                spec.sample if spec.mode == "sample"
                else min(spec.n_cells_total(), spec.max_cells)
            )
            service = SimulationService(
                ServingOptions(
                    window_s=0.0, max_cohort=max_cohort,
                    # Every cell and twin must stay pollable for the whole
                    # engine run; size the history to the spec.
                    max_done=max(4096, 8 * expected_cells),
                ),
                # Size the LRU to the matrix: cells + invariant twins +
                # direct-run programs all live here, and the warm-replay
                # gate requires wave-1 executables to SURVIVE to the end
                # of the run (the 64-entry default evicts them on big
                # specs).
                cache=ExecutableCache(
                    max_entries=max(64, 6 * expected_cells),
                ),
            )
        self.service = service
        self._own_workdir = workdir is None
        self._workdir = Path(
            workdir if workdir is not None
            else tempfile.mkdtemp(prefix="dopt-scenarios-")
        )
        # Served-run memo: identical configs (a twin equal to another
        # cell, the explicit-defaults twin of an already-run cell) run
        # once. ExperimentConfig is frozen/hashable. Direct runs keep
        # their own memo (different program shapes — see run_direct).
        self._served: dict[ExperimentConfig, Any] = {}
        self._direct: dict[ExperimentConfig, Any] = {}

    # ------------------------------------------------------------ plumbing
    def workdir(self, name: str) -> str:
        path = self._workdir / name
        path.mkdir(parents=True, exist_ok=True)
        return str(path)

    def close(self) -> None:
        if self._own_workdir:
            shutil.rmtree(self._workdir, ignore_errors=True)

    def __enter__(self) -> "ScenarioEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def run_served(self, config: ExperimentConfig):
        """One config through the serving layer (memoized); returns its
        ``BackendRunResult``. Raises ``EngineRunError`` on a failed run."""
        hit = self._served.get(config)
        if hit is not None:
            return hit
        rid = self.service.submit(config)
        self.service.drain()
        req = self.service.result(rid, timeout=60.0)
        if req.status != "done":
            raise EngineRunError(
                f"served twin failed ({req.error}) for config "
                f"{config.structural_hash()}"
            )
        self._served[config] = req.result
        return req.result

    def run_direct(self, config: ExperimentConfig, **kwargs):
        """A direct backend run (the bitwise-reduction twins, final-state
        and checkpoint invariants — comparisons/capabilities the served
        cohort path does not provide). Shares the service's dataset memo
        and executable cache; kwargs-free calls are memoized like served
        twins."""
        from distributed_optimization_tpu.backends.base import run_algorithm

        if not kwargs and config in self._direct:
            return self._direct[config]
        ds, f_opt = self.service.dataset_for(config)
        call_kwargs = dict(kwargs)
        if config.backend == "jax" and config.tp_degree == 1:
            call_kwargs.setdefault(
                "executable_cache",
                self.service.cache if self.service.cache is not None
                else False,
            )
        result = run_algorithm(config, ds, f_opt, **call_kwargs)
        if not kwargs:
            self._direct[config] = result
        return result

    # ------------------------------------------------------------- running
    def _expand(self, cell: Cell) -> list[ExperimentConfig]:
        """A cell's serving requests (see ``expand_cell_requests``)."""
        assert cell.config is not None
        return expand_cell_requests(cell.config)

    def run(self) -> dict[str, Any]:
        t0 = time.perf_counter()
        sample = generate(self.spec)
        reg = metrics_registry()
        gauges = _reset_scenario_gauges(reg)
        counts = sample.counts()
        gauges["sampled"].set(counts["cells"])
        gauges["valid"].set(counts["valid"])
        for rule, n in counts["rejected_by_rule"].items():
            gauges["rejected"].set(n, rule=rule)
        if not counts["rejected_by_rule"]:
            gauges["rejected"].set(0)

        # ---- one submission wave: let the coalescer see every cell ----
        submissions: dict[int, list[str]] = {}
        for cell in sample.valid_cells:
            submissions[cell.index] = [
                self.service.submit(cfg) for cfg in self._expand(cell)
            ]
        self.service.drain()

        rows: list[dict[str, Any]] = []
        n_checks = n_failures = n_run_errors = 0
        by_invariant: dict[str, dict[str, int]] = {}
        triage_counts = {
            "converged": 0, "validly_degraded": 0, "pathological": 0,
        }
        for cell in sample.cells:
            row = cell.row()
            if not cell.valid:
                rows.append(row)
                continue
            requests = [
                self.service.result(rid, timeout=60.0)
                for rid in submissions[cell.index]
            ]
            failed = [r for r in requests if r.status != "done"]
            if failed:
                n_run_errors += 1
                row["run_error"] = failed[0].error
                row["triage"] = triage_cell([], run_error=failed[0].error)
                triage_counts[row["triage"]] += 1
                rows.append(row)
                continue
            # Anomaly-sentinel incidents per cell (ISSUE-13): the serving
            # layer's per-request monitor banks watched every replica of
            # this cell; triage separates converged / validly degraded /
            # pathological cells mechanically.
            incidents = [i for r in requests for i in r.incidents]
            if incidents:
                row["incidents"] = incidents
            row["triage"] = triage_cell(incidents)
            triage_counts[row["triage"]] += 1
            results = [r.result for r in requests]
            self._served.setdefault(requests[0].config, results[0])
            row["serving"] = requests[0].serving_block()
            ctx = CellContext(
                cell=cell, config=cell.config, results=results,
                requests=requests, engine=self,
                envelopes=self.spec.envelopes,
            )
            row["invariants"] = []
            for inv in applicable_invariants(
                cell.config, cell.fields, restrict=self.spec.invariants
            ):
                try:
                    res = inv.check(ctx)
                except EngineRunError as e:
                    res_dict = {"name": inv.name, "passed": False,
                                "detail": {"twin_error": str(e)}}
                else:
                    res_dict = res.to_dict()
                n_checks += 1
                slot = by_invariant.setdefault(
                    inv.name, {"checks": 0, "failures": 0}
                )
                slot["checks"] += 1
                if not res_dict["passed"]:
                    n_failures += 1
                    slot["failures"] += 1
                    _log.warning(
                        "cell %d (%s): invariant %s FAILED: %s",
                        cell.index, cell.config.structural_hash(),
                        inv.name, res_dict["detail"],
                    )
                row["invariants"].append(res_dict)
            rows.append(row)
        gauges["checks"].set(n_checks)
        gauges["failures"].set(n_failures)
        for cls, count in triage_counts.items():
            gauges["triage"].set(count, **{"class": cls})

        replay = self._warm_replay(sample, submissions)

        stats = self.service.stats()
        serving = {
            "cohorts": stats["cohorts"],
            "requests_done": stats["requests_done"],
            "requests_failed": stats["requests_failed"],
            "cache": {
                k: stats["cache"].get(k)
                for k in ("hits", "misses", "compile_seconds_saved")
            },
        }
        serving["any_coalesced_cohort"] = any(
            (r.get("serving") or {}).get("coalesced") for r in rows
        )
        report = {
            "spec": {
                "name": self.spec.name, "seed": self.spec.seed,
                "mode": self.spec.mode, "axes": list(self.spec.axis_names),
                "description": self.spec.description,
            },
            "counts": counts,
            "invariants": {
                "checks": n_checks, "failures": n_failures,
                "by_name": by_invariant,
            },
            "triage": triage_counts,
            "serving": serving,
            "warm_replay": replay,
            "gates": {
                "validity_agreement": True,  # generator aborts otherwise
                "all_cells_completed": n_run_errors == 0,
                "all_invariants_passed": n_failures == 0,
                "warm_replay_ok": (
                    not replay["attempted"]
                    or (replay["bitwise"] and replay["cache_hit"])
                ),
            },
            "cells": rows,
            "wall_seconds": time.perf_counter() - t0,
        }
        return report

    def _warm_replay(self, sample: MatrixSample, submissions) -> dict:
        """Re-submit one structural class's wave-1 requests verbatim and
        require the repeat to be served WARM (zero compile — the
        executable cache) and BITWISE equal to the first wave.

        This is the serving-identity reduction the matrix rides on: a
        repeated identical wave must cut an identical cohort, reuse its
        compiled program, and reproduce its trajectories exactly. The
        replayed group is the first jax-backed class in submission order
        (numpy/cpp runs have no compiled program to be warm about)."""
        from distributed_optimization_tpu.serving.coalescer import (
            structural_group_key,
        )

        groups: dict[tuple, list] = {}
        order: list[tuple] = []
        for cell in sample.valid_cells:
            for rid in submissions[cell.index]:
                req = self.service.get(rid)
                if req.status != "done":
                    continue
                key = structural_group_key(req.config)
                if key not in groups:
                    groups[key] = []
                    order.append(key)
                groups[key].append(req)
        chosen = None
        for key in order:
            reqs = groups[key]
            if (
                reqs[0].config.backend == "jax"
                and reqs[0].config.tp_degree == 1
                # plan_cohorts chunks groups at max_cohort; replaying a
                # chunked group would cut different cohorts than wave 1.
                and len(reqs) <= self.service.options.max_cohort
            ):
                chosen = reqs
                break
        if chosen is None:
            return {"attempted": False}
        replay_ids = [self.service.submit(r.config) for r in chosen]
        self.service.drain()
        import numpy as np

        bitwise = True
        warm = True
        for first, rid in zip(chosen, replay_ids):
            again = self.service.result(rid, timeout=60.0)
            if again.status != "done":
                bitwise = warm = False
                break
            bitwise = bitwise and bool(np.array_equal(
                again.result.history.objective,
                first.result.history.objective,
            ))
            warm = warm and (
                again.result.history.compile_seconds == 0.0
            )
        return {
            "attempted": True,
            "structural_hash": chosen[0].config.structural_hash(),
            "size": len(chosen),
            "bitwise": bool(bitwise),
            "cache_hit": bool(warm),
        }


def run_scenarios(spec: ScenarioSpec, **kwargs) -> dict[str, Any]:
    """Convenience wrapper: build an engine, run, clean up."""
    with ScenarioEngine(spec, **kwargs) as engine:
        return engine.run()
