"""Scenario specs: declarative descriptions of a composition-matrix sweep.

A spec is a JSON (or YAML, when the interpreter has a yaml module) object
that names a region of the composition matrix::

    {
      "name": "robustness-sweep",
      "seed": 7,
      "mode": "sample",            // or "enumerate"
      "sample": 200,               // cells to draw in sample mode
      "base": {"n_workers": 16, "n_iterations": 200, "eval_every": 50},
      "axes": {
        "algorithm": ["dsgd", "gradient_tracking"],
        "faults": [{}, {"edge_drop_prob": 0.2, "burst_len": 4.0}],
        "byzantine": [{}, {"attack": "sign_flip", "n_byzantine": 1,
                            "aggregation": "trimmed_mean", "robust_b": 1}]
      }
    }

An axis whose name is an ``ExperimentConfig`` field takes scalar values;
any other axis name is a composite label whose values are field dicts
(one knob group per axis — the 10-axis decomposition in
``validity.AXES``). The cartesian product of axis settings over ``base``
is the cell matrix; ``scenarios.generator`` enumerates or
property-samples it and ``scenarios.validity`` classifies every cell.

Error contract (ISSUE-12 satellite): every malformed spec — unreadable
file, bad JSON/YAML, unknown top-level key, unknown axis field, wrong
value type, conflicting axes — raises ``SpecError`` with the offending
field named and (for typos) the nearest valid field suggested. The CLI
maps these to structured stderr lines; a user never sees a traceback for
a bad spec.
"""

from __future__ import annotations

import dataclasses
import difflib
import json
from pathlib import Path
from typing import Any, Mapping, Optional

from distributed_optimization_tpu.scenarios.validity import (
    CONFIG_FIELDS,
    UnknownFieldError,
)

MODES = ("sample", "enumerate")

SPEC_FIELDS = (
    "name", "description", "seed", "mode", "sample", "max_cells", "base",
    "axes", "invariants", "envelopes",
)

# Invariant names a spec may restrict to (mirrors invariants.CATALOG —
# kept as a plain tuple so spec parsing stays import-light).
KNOWN_INVARIANTS = (
    "finite_gap", "gt_tracking", "robust_envelope", "bhat_degradation",
    "reduction_burst", "reduction_churn", "reduction_zero_budget",
    "reduction_explicit_defaults", "checkpoint_resume", "replica_cohort",
)


class SpecError(ValueError):
    """A malformed scenario spec: the message names the offending field
    (and the nearest valid one for typos); ``field``/``suggestion`` carry
    the same facts structurally."""

    def __init__(
        self, message: str, *, field: Optional[str] = None,
        suggestion: Optional[str] = None,
    ):
        self.field = field
        self.suggestion = suggestion
        super().__init__(message)


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One parsed, validated scenario spec (see module docstring)."""

    name: str
    axes: dict[str, tuple[dict[str, Any], ...]]
    base: dict[str, Any] = dataclasses.field(default_factory=dict)
    seed: int = 0
    mode: str = "sample"
    sample: int = 100
    max_cells: int = 20_000
    invariants: Optional[tuple[str, ...]] = None  # None = auto per cell
    envelopes: dict[str, float] = dataclasses.field(default_factory=dict)
    description: str = ""

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.axes)

    def n_cells_total(self) -> int:
        total = 1
        for values in self.axes.values():
            total *= len(values)
        return total

    def to_dict(self) -> dict[str, Any]:
        out = dataclasses.asdict(self)
        out["axes"] = {k: list(v) for k, v in self.axes.items()}
        return out


def _suggest(name: str, candidates) -> Optional[str]:
    matches = difflib.get_close_matches(name, list(candidates), n=1)
    return matches[0] if matches else None


def _reject_unknown(name: str, candidates, *, context: str) -> SpecError:
    suggestion = _suggest(name, candidates)
    hint = f"; did you mean {suggestion!r}?" if suggestion else ""
    return SpecError(
        f"unknown {context} {name!r}{hint}", field=name,
        suggestion=suggestion,
    )


def _check_fields_dict(d: Mapping, *, context: str) -> dict[str, Any]:
    """Validate a {config_field: value} mapping; unknown fields get the
    nearest-valid-field suggestion (the UnknownFieldError contract)."""
    if not isinstance(d, Mapping):
        raise SpecError(
            f"{context} must be an object of ExperimentConfig fields, got "
            f"{type(d).__name__}", field=context,
        )
    for key, value in d.items():
        if key not in CONFIG_FIELDS:
            try:
                raise UnknownFieldError(str(key), context=f"{context} field")
            except UnknownFieldError as e:
                raise SpecError(str(e), field=str(key),
                                suggestion=e.suggestion) from None
        if isinstance(value, (dict, list)):
            raise SpecError(
                f"{context} field {key!r} must be a scalar, got "
                f"{type(value).__name__}", field=str(key),
            )
    return dict(d)


def _parse_axis(name: str, values: Any) -> tuple[dict[str, Any], ...]:
    """One axis: a list of settings. A config-field axis takes scalars
    (or single-field dicts); a composite axis takes field dicts."""
    if not isinstance(values, list) or not values:
        raise SpecError(
            f"axis {name!r} must be a non-empty list of settings, got "
            f"{type(values).__name__}", field=name,
        )
    is_field = name in CONFIG_FIELDS
    settings: list[dict[str, Any]] = []
    for i, value in enumerate(values):
        if isinstance(value, Mapping):
            settings.append(
                _check_fields_dict(value, context=f"axis {name!r}[{i}]")
            )
        elif is_field:
            if isinstance(value, list):
                raise SpecError(
                    f"axis {name!r}[{i}] must be a scalar "
                    f"{name} value, got a list", field=name,
                )
            settings.append({name: value})
        elif any(isinstance(v, Mapping) for v in values):
            # The axis is clearly composite (other settings are field
            # dicts) — blame the odd scalar value, not the axis name.
            raise SpecError(
                f"axis {name!r}[{i}] must be a field object "
                f"({{config_field: value}}) like the axis's other "
                f"settings, got {value!r}", field=name,
            )
        else:
            # Every setting is a scalar but the axis names no config
            # field: either a field-name typo (suggest the nearest) or a
            # composite axis whose settings forgot their dict form.
            err = _reject_unknown(name, CONFIG_FIELDS, context="axis")
            raise SpecError(
                f"{err} — scalar settings are only valid when the axis "
                "names the config field it sweeps; composite axes take "
                "field objects ({config_field: value})",
                field=name, suggestion=err.suggestion,
            )
    return tuple(settings)


def parse_spec(obj: Any, *, origin: str = "<spec>") -> ScenarioSpec:
    """Validate a decoded spec object into a ``ScenarioSpec`` (raises
    ``SpecError`` — see the module docstring's error contract)."""
    if not isinstance(obj, Mapping):
        raise SpecError(
            f"{origin}: spec must be a JSON object, got "
            f"{type(obj).__name__}"
        )
    for key in obj:
        if key not in SPEC_FIELDS:
            raise _reject_unknown(str(key), SPEC_FIELDS,
                                  context="spec field")
    name = obj.get("name")
    if not isinstance(name, str) or not name:
        raise SpecError(
            "spec needs a non-empty string 'name'", field="name"
        )
    mode = obj.get("mode", "sample")
    if mode not in MODES:
        raise SpecError(
            f"mode must be one of {MODES}, got {mode!r}", field="mode",
            suggestion=_suggest(str(mode), MODES),
        )
    seed = obj.get("seed", 0)
    if not isinstance(seed, int) or isinstance(seed, bool):
        raise SpecError(
            f"seed must be an integer, got {seed!r}", field="seed"
        )
    sample = obj.get("sample", 100)
    if not isinstance(sample, int) or isinstance(sample, bool) or sample < 1:
        raise SpecError(
            f"sample must be a positive integer, got {sample!r}",
            field="sample",
        )
    max_cells = obj.get("max_cells", 20_000)
    if (not isinstance(max_cells, int) or isinstance(max_cells, bool)
            or max_cells < 1):
        raise SpecError(
            f"max_cells must be a positive integer, got {max_cells!r}",
            field="max_cells",
        )
    base = _check_fields_dict(obj.get("base", {}), context="base")
    axes_obj = obj.get("axes")
    if not isinstance(axes_obj, Mapping) or not axes_obj:
        raise SpecError(
            "spec needs a non-empty 'axes' object "
            "({axis_name: [settings, ...]})", field="axes",
        )
    axes = {
        str(axis): _parse_axis(str(axis), values)
        for axis, values in axes_obj.items()
    }
    invariants = obj.get("invariants")
    if invariants is not None:
        if not isinstance(invariants, list):
            raise SpecError(
                "invariants must be a list of invariant names",
                field="invariants",
            )
        for inv in invariants:
            if inv not in KNOWN_INVARIANTS:
                raise _reject_unknown(str(inv), KNOWN_INVARIANTS,
                                      context="invariant")
        invariants = tuple(invariants)
    envelopes = obj.get("envelopes", {})
    if not isinstance(envelopes, Mapping):
        raise SpecError("envelopes must be an object of numeric bounds",
                        field="envelopes")
    for key, value in envelopes.items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise SpecError(
                f"envelope {key!r} must be a number, got {value!r}",
                field=str(key),
            )
    description = obj.get("description", "")
    if not isinstance(description, str):
        raise SpecError("description must be a string",
                        field="description")
    return ScenarioSpec(
        name=name, axes=axes, base=base, seed=seed, mode=mode,
        sample=sample, max_cells=max_cells, invariants=invariants,
        envelopes={str(k): float(v) for k, v in envelopes.items()},
        description=description,
    )


def load_spec(path) -> ScenarioSpec:
    """Read + parse a spec file. JSON always; ``.yaml``/``.yml`` when a
    yaml module is importable (the container may not ship one — the
    rejection says so instead of ImportError-ing)."""
    p = Path(path)
    try:
        text = p.read_text()
    except OSError as e:
        raise SpecError(f"cannot read spec {p}: {e}") from e
    if p.suffix.lower() in (".yaml", ".yml"):
        try:
            import yaml  # type: ignore[import-not-found]
        except ImportError:
            raise SpecError(
                f"{p.name}: YAML specs need a yaml module, which this "
                "environment does not ship — use the JSON spec format "
                "(docs/SCENARIOS.md)"
            ) from None
        try:
            obj = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise SpecError(f"{p.name}: malformed YAML: {e}") from e
    else:
        try:
            obj = json.loads(text)
        except json.JSONDecodeError as e:
            raise SpecError(f"{p.name}: malformed JSON: {e}") from e
    return parse_spec(obj, origin=p.name)
