"""The queryable validity table of the composition matrix.

The repo's ~10 orthogonal axes (algorithm × topology/impl × faults ×
Byzantine × compression × local steps × participation × execution ×
replicas × worker_mesh) compose under pairwise rules that historically
lived ONLY inside ``ExperimentConfig.__post_init__`` — correct, but
opaque: the only way to ask "is this cell valid, and if not, why?" was to
construct a config and parse the exception. This module is the same rule
set as DATA: every composition rule is a named ``Rule`` with the axes it
couples, a predicate, and the rejection reason, so the scenario engine
can

- pre-filter sampled cells without paying construction on invalid ones,
- count rejections BY RULE (which compositions dominate the invalid
  region), and
- answer ``explain(fields)`` with a structured verdict instead of a
  stringly exception.

Drift discipline (docs/SCENARIOS.md): the table deliberately DUPLICATES
``__post_init__`` — a table that called the constructor would be
unqueryable, and a constructor that read the table would put jax-free
config behind an import of this package. The contract that keeps the two
honest is ``ExperimentConfig.construction_error``: tests (and the golden
corpus bench) sample hundreds of seeded cells across every axis and
assert verdict-for-verdict agreement, so a rule added to one side without
the other fails loudly instead of silently mis-classifying cells.
"""

from __future__ import annotations

import dataclasses
import difflib
import math
from typing import Any, Callable, Mapping, Optional

from distributed_optimization_tpu.config import (
    AGGREGATIONS,
    ALGORITHMS,
    ATTACKS,
    BACKENDS,
    COMPRESSED_ALGORITHMS,
    COMPRESSIONS,
    DIRECTED_TOPOLOGIES,
    EXECUTIONS,
    LATENCY_MODELS,
    LOCAL_STEP_ALGORITHMS,
    NEIGHBOR_TOPOLOGIES,
    PROBLEM_TYPES,
    REJOINS,
    TOPOLOGIES,
    ExperimentConfig,
)

CONFIG_FIELDS: tuple[str, ...] = tuple(
    f.name for f in dataclasses.fields(ExperimentConfig)
)
DEFAULT_FIELDS: dict[str, Any] = {
    f.name: f.default for f in dataclasses.fields(ExperimentConfig)
}

# The ten orthogonal axes of the composition matrix (ISSUE-12), named for
# reporting: each validity rule tags the axes it couples so rejection
# counters and docs group by composition, not by field soup.
AXES: tuple[str, ...] = (
    "algorithm", "topology", "faults", "byzantine", "compression",
    "local_steps", "participation", "execution", "replicas", "worker_mesh",
)


class UnknownFieldError(ValueError):
    """A field name outside the ExperimentConfig schema, with the nearest
    valid field attached — the structured form of a typo."""

    def __init__(self, field: str, *, context: str = "field"):
        self.field = field
        matches = difflib.get_close_matches(field, CONFIG_FIELDS, n=1)
        self.suggestion = matches[0] if matches else None
        hint = (
            f"; did you mean {self.suggestion!r}?" if self.suggestion
            else "; valid fields are the ExperimentConfig schema"
        )
        super().__init__(f"unknown {context} {field!r}{hint}")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One composition rule: ``when(fields)`` is True where the rule
    REJECTS the cell, ``reason(fields)`` the exact rejection message."""

    name: str
    axes: tuple[str, ...]
    when: Callable[[dict], bool]
    reason: Callable[[dict], str]
    doc: str = ""


@dataclasses.dataclass(frozen=True)
class Verdict:
    """``explain``'s answer: valid, or the first rejecting rule."""

    valid: bool
    rule: Optional[str] = None
    axes: tuple[str, ...] = ()
    reason: str = "valid"

    def __bool__(self) -> bool:
        return self.valid


VALID = Verdict(valid=True)


def _robust_rule_on(f: dict) -> bool:
    return f["aggregation"] != "gossip" and f["robust_b"] > 0


def _is_perfect_square(n: int) -> bool:
    s = int(math.isqrt(int(n)))
    return s * s == n


def _r(name, axes, when, reason, doc=""):
    return Rule(name=name, axes=tuple(axes), when=when, reason=reason,
                doc=doc)


def _domain(field: str, axis: str, values) -> Rule:
    vals = tuple(values)
    return _r(
        f"domain:{field}", (axis,),
        lambda f, _field=field, _vals=vals: f[_field] not in _vals,
        lambda f, _field=field, _vals=vals: (
            f"unknown {_field} {f[_field]!r} (valid: {list(_vals)})"
        ),
        doc=f"{field} must be one of {list(vals)}",
    )


# Ordered like ``ExperimentConfig.__post_init__`` so the first rejecting
# rule names the same violation construction would raise first.
RULES: tuple[Rule, ...] = (
    # ---------------------------------------------------------- domains
    _domain("problem_type", "algorithm", PROBLEM_TYPES),
    _domain("algorithm", "algorithm", ALGORITHMS),
    _domain("topology", "topology", TOPOLOGIES),
    _domain("backend", "execution", BACKENDS),
    _domain("mixing_impl", "topology",
            ("auto", "dense", "stencil", "shard_map", "pallas", "sparse",
             "gather")),
    _domain("sampling_impl", "execution", ("auto", "gather", "dense")),
    _domain("lr_schedule", "algorithm", ("auto", "sqrt_decay", "constant")),
    _domain("compression", "compression", COMPRESSIONS),
    # ------------------------------------------------------ compression
    _r("compression×algorithm", ("compression", "algorithm"),
       lambda f: f["compression"] != "none"
       and f["algorithm"] not in COMPRESSED_ALGORITHMS,
       lambda f: (
           f"compression={f['compression']!r} only takes effect with the "
           f"error-feedback gossip algorithms {COMPRESSED_ALGORITHMS}"
       ),
       doc="error-feedback compression needs a gossip recursion that "
           "carries the shared estimate"),
    _r("compression:k", ("compression",),
       lambda f: f["compression"] != "none" and f["compression_k"] <= 0,
       lambda f: "compression_k must be positive with compression on"),
    _r("compression×faults", ("compression", "faults"),
       lambda f: f["compression"] != "none" and (
           f["edge_drop_prob"] > 0.0 or f["straggler_prob"] > 0.0
           or f["mttf"] > 0.0 or f["gossip_schedule"] != "synchronous"),
       lambda f: (
           "compressed gossip does not compose with time-varying graphs: "
           "a dropped exchange leaves the neighbor's error-feedback "
           "estimate stale"
       )),
    _r("compression×byzantine", ("compression", "byzantine"),
       lambda f: f["compression"] != "none" and (
           f["attack"] != "none" or f["aggregation"] != "gossip"),
       lambda f: (
           "compressed gossip does not compose with Byzantine injection / "
           "robust aggregation: screening operates on models, "
           "error-feedback exchanges compressed differences"
       )),
    # ----------------------------------------------------- scalar sanity
    _r("domain:huber_delta", ("algorithm",),
       lambda f: f["huber_delta"] <= 0.0,
       lambda f: f"huber_delta must be positive, got {f['huber_delta']}"),
    _r("domain:n_classes", ("algorithm",),
       lambda f: f["n_classes"] < 2,
       lambda f: f"n_classes must be >= 2, got {f['n_classes']}"),
    _r("domain:choco_gamma", ("compression", "algorithm"),
       lambda f: (f["algorithm"] == "choco" or f["compression"] != "none")
       and not 0.0 < f["choco_gamma"] <= 1.0,
       lambda f: f"choco_gamma must be in (0, 1], got {f['choco_gamma']}"),
    _domain("partition", "algorithm", ("sorted", "shuffled")),
    _domain("attack", "byzantine", ATTACKS),
    _domain("aggregation", "byzantine", AGGREGATIONS),
    # -------------------------------------------------------- byzantine
    _r("domain:n_byzantine", ("byzantine",),
       lambda f: f["n_byzantine"] < 0,
       lambda f: f"n_byzantine must be >= 0, got {f['n_byzantine']}"),
    _r("byzantine:attack↔count", ("byzantine",),
       lambda f: (f["attack"] == "none") != (f["n_byzantine"] == 0),
       lambda f: (
           f"attack={f['attack']!r} and n_byzantine={f['n_byzantine']} "
           "must be set together"
       ),
       doc="an attack needs attackers, and Byzantine workers need a "
           "payload to send"),
    _r("byzantine:honest_majority_floor", ("byzantine",),
       lambda f: f["attack"] != "none"
       and f["n_byzantine"] >= f["n_workers"],
       lambda f: (
           f"n_byzantine ({f['n_byzantine']}) must leave at least one "
           f"honest worker out of {f['n_workers']}"
       )),
    _r("byzantine:scale_positive", ("byzantine",),
       lambda f: f["attack"] != "none" and f["attack_scale"] <= 0.0,
       lambda f: f"attack_scale must be positive, got {f['attack_scale']}"),
    _r("byzantine:scale_without_attack", ("byzantine",),
       lambda f: f["attack"] == "none" and f["attack_scale"] != 1.0,
       lambda f: (
           f"attack_scale={f['attack_scale']} only takes effect with an "
           "attack"
       )),
    _r("domain:robust_b", ("byzantine",),
       lambda f: f["robust_b"] < 0,
       lambda f: f"robust_b must be >= 0, got {f['robust_b']}"),
    _r("byzantine:budget_without_rule", ("byzantine",),
       lambda f: f["robust_b"] > 0 and f["aggregation"] == "gossip",
       lambda f: (
           f"robust_b={f['robust_b']} only takes effect with a robust "
           "aggregation rule"
       )),
    _domain("robust_impl", "byzantine", ("auto", "dense", "gather", "fused")),
    _r("byzantine:impl_without_rule", ("byzantine",),
       lambda f: f["robust_impl"] != "auto" and not _robust_rule_on(f),
       lambda f: (
           f"robust_impl={f['robust_impl']!r} selects the execution form "
           "of a robust aggregation rule; without one it would be "
           "silently ignored"
       )),
    _r("domain:clip_tau", ("byzantine",),
       lambda f: f["clip_tau"] < 0.0,
       lambda f: f"clip_tau must be >= 0, got {f['clip_tau']}"),
    _r("byzantine:clip_tau_without_clipping", ("byzantine",),
       lambda f: f["clip_tau"] > 0.0
       and f["aggregation"] != "clipped_gossip",
       lambda f: (
           "clip_tau only applies to aggregation='clipped_gossip'"
       )),
    _r("byzantine×schedule", ("byzantine", "topology"),
       lambda f: f["aggregation"] != "gossip"
       and f["gossip_schedule"] != "synchronous",
       lambda f: (
           f"aggregation={f['aggregation']!r} screens multiple received "
           "messages per round; matching schedules deliver at most one"
       )),
    # ------------------------------------------------------------ faults
    _r("domain:edge_drop_prob", ("faults",),
       lambda f: not 0.0 <= f["edge_drop_prob"] < 1.0,
       lambda f: (
           f"edge_drop_prob must be in [0, 1), got {f['edge_drop_prob']}"
       )),
    _r("domain:straggler_prob", ("faults",),
       lambda f: not 0.0 <= f["straggler_prob"] < 1.0,
       lambda f: (
           f"straggler_prob must be in [0, 1), got {f['straggler_prob']}"
       )),
    _r("domain:burst_len", ("faults",),
       lambda f: f["burst_len"] != 0.0 and f["burst_len"] < 1.0,
       lambda f: (
           f"burst_len must be 0 (iid edge drops) or >= 1, got "
           f"{f['burst_len']}"
       )),
    _r("faults:burst_without_drops", ("faults",),
       lambda f: f["burst_len"] != 0.0 and f["edge_drop_prob"] == 0.0,
       lambda f: (
           f"burst_len={f['burst_len']} shapes the edge-failure process "
           "and needs edge_drop_prob > 0"
       )),
    _r("faults:mttf↔mttr", ("faults",),
       lambda f: (f["mttf"] > 0.0) != (f["mttr"] > 0.0),
       lambda f: (
           f"mttf ({f['mttf']}) and mttr ({f['mttr']}) must be set "
           "together"
       )),
    _r("domain:mttf_mttr_sign", ("faults",),
       lambda f: f["mttf"] < 0.0 or f["mttr"] < 0.0,
       lambda f: (
           f"mttf/mttr must be >= 0, got ({f['mttf']}, {f['mttr']})"
       )),
    _r("faults:churn_holding_times", ("faults",),
       lambda f: f["mttf"] > 0.0 and (f["mttf"] < 1.0 or f["mttr"] < 1.0),
       lambda f: (
           "mttf/mttr are mean holding times in rounds and must be >= 1"
       )),
    _r("faults:churn×stragglers", ("faults",),
       lambda f: f["mttf"] >= 1.0 and f["mttr"] >= 1.0
       and f["straggler_prob"] > 0.0,
       lambda f: (
           "crash-recovery churn (mttf/mttr) replaces iid stragglers; "
           "set straggler_prob=0"
       )),
    _r("faults:churn×schedule", ("faults", "topology"),
       lambda f: f["mttf"] >= 1.0 and f["mttr"] >= 1.0
       and f["straggler_prob"] == 0.0
       and f["gossip_schedule"] != "synchronous",
       lambda f: (
           "crash-recovery churn requires gossip_schedule='synchronous'"
       )),
    _domain("rejoin", "faults", REJOINS),
    _r("faults:restart×byzantine", ("faults", "byzantine"),
       lambda f: f["rejoin"] == "neighbor_restart"
       and (f["attack"] != "none" or _robust_rule_on(f)),
       lambda f: (
           "rejoin='neighbor_restart' does not compose with Byzantine "
           "injection / robust aggregation: the warm restart averages "
           "raw neighbor rows, bypassing attacks and screening"
       )),
    _r("faults:rejoin_without_churn", ("faults",),
       lambda f: f["rejoin"] != "frozen" and f["mttf"] == 0.0,
       lambda f: (
           f"rejoin={f['rejoin']!r} only takes effect with crash-recovery "
           "churn (mttf/mttr)"
       )),
    # ------------------------------------------------------- local steps
    _r("domain:local_steps", ("local_steps",),
       lambda f: f["local_steps"] < 1,
       lambda f: f"local_steps must be >= 1, got {f['local_steps']}"),
    _r("local_steps×algorithm", ("local_steps", "algorithm"),
       lambda f: f["local_steps"] > 1
       and f["algorithm"] not in LOCAL_STEP_ALGORITHMS,
       lambda f: (
           f"local_steps={f['local_steps']} is unsupported for "
           f"{f['algorithm']!r}: only {LOCAL_STEP_ALGORITHMS} survive τ "
           "local descents between exchanges"
       )),
    _r("local_steps×compression", ("local_steps", "compression"),
       lambda f: f["local_steps"] > 1
       and f["algorithm"] in LOCAL_STEP_ALGORITHMS
       and f["compression"] != "none",
       lambda f: (
           "local_steps > 1 does not compose with compressed gossip"
       )),
    _r("local_steps×cpp", ("local_steps", "execution"),
       lambda f: f["local_steps"] > 1 and f["backend"] == "cpp",
       lambda f: "local_steps > 1 is unsupported on the cpp backend"),
    _r("local_steps×tp", ("local_steps",),
       lambda f: f["local_steps"] > 1 and f["tp_degree"] > 1,
       lambda f: (
           "local_steps > 1 does not compose with tp_degree > 1"
       )),
    # ----------------------------------------------------- participation
    _r("domain:participation_rate", ("participation",),
       lambda f: not 0.0 < f["participation_rate"] <= 1.0,
       lambda f: (
           f"participation_rate must be in (0, 1], got "
           f"{f['participation_rate']}"
       )),
    _r("participation×centralized", ("participation", "algorithm"),
       lambda f: f["participation_rate"] < 1.0
       and f["algorithm"] == "centralized",
       lambda f: (
           "participation_rate models client sampling of peer exchanges; "
           "the centralized pattern has no peer edges"
       )),
    _r("participation×schedule", ("participation", "topology"),
       lambda f: f["participation_rate"] < 1.0
       and f["algorithm"] != "centralized"
       and f["gossip_schedule"] != "synchronous",
       lambda f: (
           "participation_rate < 1 requires gossip_schedule='synchronous'"
       )),
    _r("participation×compression", ("participation", "compression"),
       lambda f: f["participation_rate"] < 1.0
       and f["compression"] != "none",
       lambda f: (
           "participation_rate < 1 does not compose with compressed "
           "gossip"
       )),
    _r("participation×cpp", ("participation", "execution"),
       lambda f: f["participation_rate"] < 1.0 and f["backend"] == "cpp",
       lambda f: (
           "participation_rate < 1 is unsupported on the cpp backend"
       )),
    _r("participation×tp", ("participation",),
       lambda f: f["participation_rate"] < 1.0 and f["tp_degree"] > 1,
       lambda f: (
           "participation_rate < 1 does not compose with tp_degree > 1"
       )),
    # ----------------------------------------------------- topology impl
    _domain("topology_impl", "topology", ("auto", "dense", "neighbor")),
    _r("neighbor×fully_connected", ("topology",),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] == "fully_connected",
       lambda f: (
           "topology_impl='neighbor' with 'fully_connected' would "
           "allocate the quadratic [N, N-1] table the matrix-free path "
           "exists to avoid"
       )),
    _r("neighbor×topology", ("topology",),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] != "fully_connected"
       and f["topology"] not in NEIGHBOR_TOPOLOGIES,
       lambda f: (
           f"topology_impl='neighbor' supports {NEIGHBOR_TOPOLOGIES}; "
           f"{f['topology']!r} has no matrix-free constructor"
       )),
    _r("neighbor×backend", ("topology", "execution"),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] in NEIGHBOR_TOPOLOGIES and f["backend"] != "jax",
       lambda f: (
           "topology_impl='neighbor' is a jax-backend capability"
       )),
    _r("neighbor×mixing_impl", ("topology",),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] in NEIGHBOR_TOPOLOGIES and f["backend"] == "jax"
       and f["mixing_impl"] not in ("auto", "gather", "stencil"),
       lambda f: (
           "topology_impl='neighbor' never materializes the [N, N] "
           f"matrices mixing_impl={f['mixing_impl']!r} consumes"
       )),
    _r("neighbor×robust_impl", ("topology", "byzantine"),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] in NEIGHBOR_TOPOLOGIES and f["backend"] == "jax"
       and (f["attack"] != "none" or _robust_rule_on(f))
       and f["robust_impl"] not in ("auto", "gather"),
       lambda f: (
           "topology_impl='neighbor' runs robust aggregation in gather "
           f"form; robust_impl={f['robust_impl']!r} materializes "
           "dense/VMEM objects the matrix-free path never builds"
       )),
    _r("neighbor×schedule", ("topology",),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] in NEIGHBOR_TOPOLOGIES and f["backend"] == "jax"
       and f["gossip_schedule"] != "synchronous",
       lambda f: (
           "topology_impl='neighbor' requires "
           "gossip_schedule='synchronous'"
       )),
    _r("neighbor×tp", ("topology",),
       lambda f: f["topology_impl"] == "neighbor"
       and f["topology"] in NEIGHBOR_TOPOLOGIES and f["backend"] == "jax"
       and f["tp_degree"] > 1,
       lambda f: (
           "topology_impl='neighbor' does not compose with tp_degree > 1"
       )),
    # ------------------------------------------------------- worker mesh
    _r("domain:worker_mesh", ("worker_mesh",),
       lambda f: f["worker_mesh"] < 0 or f["worker_mesh"] == 1,
       lambda f: (
           f"worker_mesh must be 0 (unsharded) or >= 2 devices, got "
           f"{f['worker_mesh']}"
       )),
    _r("mesh×backend", ("worker_mesh", "execution"),
       lambda f: f["worker_mesh"] >= 2 and f["backend"] != "jax",
       lambda f: (
           "worker_mesh shards the worker axis over a jax device mesh"
       )),
    _r("mesh×centralized", ("worker_mesh", "algorithm"),
       lambda f: f["worker_mesh"] >= 2 and f["backend"] == "jax"
       and f["algorithm"] == "centralized",
       lambda f: (
           "worker_mesh shards the gossip neighbor tables; the "
           "centralized pattern has no peer graph to shard"
       )),
    _r("mesh:divisibility", ("worker_mesh",),
       lambda f: f["worker_mesh"] >= 2 and f["backend"] == "jax"
       and f["algorithm"] != "centralized"
       and f["n_workers"] % f["worker_mesh"] != 0,
       lambda f: (
           f"worker_mesh={f['worker_mesh']} must divide n_workers "
           f"({f['n_workers']})"
       )),
    _r("mesh×topology", ("worker_mesh", "topology"),
       lambda f: f["worker_mesh"] >= 2 and f["backend"] == "jax"
       and f["algorithm"] != "centralized"
       and f["n_workers"] % f["worker_mesh"] == 0
       and f["topology"] not in NEIGHBOR_TOPOLOGIES,
       lambda f: (
           f"worker_mesh runs the neighbor-table halo-exchange path; "
           f"topology {f['topology']!r} has no matrix-free constructor"
       )),
    _r("mesh×dense_impl", ("worker_mesh", "topology"),
       lambda f: _mesh_base_ok(f) and f["topology_impl"] == "dense",
       lambda f: (
           "worker_mesh shards the [N, k_max] neighbor tables; "
           "topology_impl='dense' materializes the [N, N] matrices"
       )),
    _r("mesh×mixing_impl", ("worker_mesh", "topology"),
       lambda f: _mesh_base_ok(f)
       and f["mixing_impl"] not in ("auto", "gather"),
       lambda f: (
           f"worker_mesh lowers gather mixing to a ppermute halo "
           f"exchange; mixing_impl={f['mixing_impl']!r} has no sharded "
           "form"
       )),
    _r("mesh×async", ("worker_mesh", "execution"),
       lambda f: _mesh_base_ok(f) and f["execution"] == "async",
       lambda f: (
           "worker_mesh does not compose with execution='async'"
       )),
    _r("mesh×schedule", ("worker_mesh", "topology"),
       lambda f: _mesh_base_ok(f)
       and f["gossip_schedule"] != "synchronous",
       lambda f: (
           "worker_mesh requires gossip_schedule='synchronous'"
       )),
    _r("mesh×edge_faults", ("worker_mesh", "faults"),
       lambda f: _mesh_base_ok(f) and f["edge_drop_prob"] > 0.0,
       lambda f: (
           "worker_mesh does not yet compose with per-edge fault "
           "processes (edge_drop_prob/burst_len)"
       )),
    _r("mesh×alie", ("worker_mesh", "byzantine"),
       lambda f: _mesh_base_ok(f) and f["attack"] == "alie",
       lambda f: (
           "worker_mesh does not compose with attack='alie' (the "
           "colluders' global moment reduction breaks sharded bitwise "
           "parity)"
       )),
    _r("mesh×neighbor_restart", ("worker_mesh", "faults"),
       lambda f: _mesh_base_ok(f) and f["rejoin"] == "neighbor_restart",
       lambda f: (
           "worker_mesh does not yet compose with "
           "rejoin='neighbor_restart'"
       )),
    _r("mesh×robust_impl", ("worker_mesh", "byzantine"),
       lambda f: _mesh_base_ok(f)
       and f["robust_impl"] not in ("auto", "gather"),
       lambda f: (
           f"worker_mesh screens in halo-gather form; robust_impl="
           f"{f['robust_impl']!r} materializes dense/VMEM objects"
       )),
    _r("mesh×robust_telemetry", ("worker_mesh", "byzantine"),
       lambda f: _mesh_base_ok(f) and f["telemetry"]
       and _robust_rule_on(f),
       lambda f: (
           "worker_mesh does not yet compose with the telemetry "
           "robust-activity probe"
       )),
    # mesh×compression and mesh×replicas deleted (ISSUE-18): compressed
    # gossip runs the halo-compressed exchange (only boundary rows of the
    # error-feedback increment cross the wire — collectives.
    # make_halo_compressed_mixing_op), and a worker_mesh run with
    # replicas=R dispatches R sequential mesh runs through run_batch's
    # sequential-mesh path. The mesh+replicas+compression triple stays
    # rejected via the surviving replicas×compression/replicas×choco
    # rules below.
    _r("mesh×tp", ("worker_mesh",),
       lambda f: _mesh_base_ok(f) and f["tp_degree"] > 1,
       lambda f: (
           "worker_mesh and tp_degree > 1 are mutually exclusive"
       )),
    # --------------------------------------------------------- execution
    _domain("execution", "execution", EXECUTIONS),
    _domain("latency_model", "execution", LATENCY_MODELS),
    _r("sync:latency_knobs", ("execution",),
       lambda f: f["execution"] == "sync" and (
           f["latency_model"] != "constant" or f["latency_mean"] != 1.0
           or f["latency_tail"] != 0.0),
       lambda f: (
           "latency_model/latency_mean/latency_tail shape the "
           "asynchronous event schedule; execution='sync' would silently "
           "ignore them"
       )),
    _r("async:latency_mean", ("execution",),
       lambda f: f["execution"] == "async" and f["latency_mean"] <= 0.0,
       lambda f: f"latency_mean must be positive, got {f['latency_mean']}"),
    _r("async:lognormal_tail", ("execution",),
       lambda f: f["execution"] == "async"
       and f["latency_model"] == "lognormal" and f["latency_tail"] <= 0.0,
       lambda f: "latency_model='lognormal' needs latency_tail > 0"),
    _r("async:pareto_tail", ("execution",),
       lambda f: f["execution"] == "async"
       and f["latency_model"] == "pareto" and f["latency_tail"] <= 1.0,
       lambda f: "latency_model='pareto' needs latency_tail > 1"),
    _r("async:tail_without_shape", ("execution",),
       lambda f: f["execution"] == "async"
       and f["latency_model"] in ("constant", "exponential")
       and f["latency_tail"] != 0.0,
       lambda f: (
           f"latency_tail only shapes the lognormal/pareto tails; "
           f"latency_model={f['latency_model']!r} would silently ignore it"
       )),
    _r("async×cpp", ("execution",),
       lambda f: f["execution"] == "async" and f["backend"] == "cpp",
       lambda f: "execution='async' is unsupported on the cpp backend"),
    _r("async×algorithm", ("execution", "algorithm"),
       lambda f: f["execution"] == "async" and f["backend"] != "cpp"
       and f["algorithm"] not in ("dsgd", "gradient_tracking"),
       lambda f: (
           f"execution='async' is unsupported for {f['algorithm']!r}: an "
           "event applies ONE worker's update at its realized staleness — "
           "only dsgd and gradient tracking's per-event tracker "
           "telescoping have an event form; use algorithm='dsgd' or "
           "'gradient_tracking'"
       )),
    _r("async×directed", ("execution", "topology"),
       lambda f: f["execution"] == "async"
       and f["topology"] in DIRECTED_TOPOLOGIES,
       lambda f: (
           "execution='async' realizes mutual pairwise exchanges; "
           f"directed topology {f['topology']!r} has one-way links"
       )),
    # ISSUE-17 deleted the async×schedule and async×faults rejections:
    # gossip_schedule now has an event-axis meaning ('synchronous'/
    # 'one_peer' name the sampled mutual matchings, 'round_robin' the
    # deterministic phase rotation) and the round-indexed fault knobs
    # (edge_drop/straggler/mttf/participation) are realized on the event
    # axis by parallel.events.realize_event_faults.  The surviving
    # churn×schedule / participation×schedule rules below still apply.
    _r("async×byzantine", ("execution", "byzantine"),
       lambda f: f["execution"] == "async"
       and (f["attack"] != "none" or _robust_rule_on(f)),
       lambda f: (
           "execution='async' does not compose with Byzantine injection "
           "/ robust aggregation: an event delivers exactly one pairwise "
           "exchange"
       )),
    _r("async×compression", ("execution", "compression"),
       lambda f: f["execution"] == "async" and f["compression"] != "none",
       lambda f: (
           "execution='async' does not compose with compressed gossip"
       )),
    # ISSUE-17 deleted async×local_steps: τ local descents fuse into one
    # event (the firing worker chains τ stale-read minibatch steps before
    # its pairwise exchange), so the round-based lever composes.
    _r("async×tp_replicas", ("execution", "replicas"),
       lambda f: f["execution"] == "async"
       and (f["tp_degree"] > 1 or f["replicas"] > 1),
       lambda f: (
           "execution='async' is a sequential scan over a totally "
           "ordered event schedule — run tp_degree=1, replicas=1"
       )),
    _r("async×neighbor", ("execution", "topology"),
       lambda f: f["execution"] == "async"
       and f["topology_impl"] == "neighbor",
       lambda f: (
           "execution='async' scans events over the dense topology "
           "representation"
       )),
    # ISSUE-17 deleted async×telemetry: trace rows now ride the event
    # scan's per-eval outputs (grad/param norms, per-worker event-fire
    # fractions, live-edge rates), so telemetry=True composes.
    # ---------------------------------------------------------- schedule
    _domain("gossip_schedule", "topology",
            ("synchronous", "one_peer", "round_robin")),
    _r("round_robin×faults", ("topology", "faults"),
       lambda f: f["gossip_schedule"] == "round_robin"
       and (f["edge_drop_prob"] > 0.0 or f["straggler_prob"] > 0.0),
       lambda f: (
           "round_robin is a deterministic schedule; combine failure "
           "injection with 'synchronous' or 'one_peer'"
       )),
    _domain("dtype", "execution", ("float32", "float64", "bfloat16")),
    _domain("matmul_precision", "execution", ("default", "high", "highest")),
    # ------------------------------------------------------ shape sanity
    _r("domain:n_workers", ("topology",),
       lambda f: f["n_workers"] <= 0,
       lambda f: "n_workers must be positive"),
    _r("domain:informative_features", ("algorithm",),
       lambda f: f["n_informative_features"] > f["n_features"],
       lambda f: (
           f"n_informative_features ({f['n_informative_features']}) "
           f"cannot exceed n_features ({f['n_features']})"
       )),
    _r("domain:eval_every", ("execution",),
       lambda f: f["eval_every"] <= 0,
       lambda f: "eval_every must be positive"),
    _r("domain:scan_unroll", ("execution",),
       lambda f: f["scan_unroll"] < 0,
       lambda f: "scan_unroll must be >= 0 (0 = auto)"),
    _r("cadence:divisibility", ("execution",),
       lambda f: f["eval_every"] > 0
       and f["n_iterations"] % f["eval_every"] != 0,
       lambda f: (
           f"eval_every ({f['eval_every']}) must divide n_iterations "
           f"({f['n_iterations']})"
       )),
    _r("grid:square_worker_count", ("topology",),
       lambda f: f["topology"] == "grid"
       and not _is_perfect_square(f["n_workers"]),
       lambda f: (
           f"grid topology requires a perfect-square worker count, got "
           f"{f['n_workers']}"
       )),
    _r("directed×schedule", ("topology",),
       lambda f: f["topology"] in DIRECTED_TOPOLOGIES
       and f["gossip_schedule"] != "synchronous",
       lambda f: (
           f"gossip_schedule={f['gossip_schedule']!r} realizes mutual "
           "matchings, an undirected construction"
       )),
    _r("directed×algorithm", ("topology", "algorithm"),
       lambda f: f["topology"] in DIRECTED_TOPOLOGIES
       and f["algorithm"] != "push_sum",
       lambda f: (
           f"topology {f['topology']!r} is directed: its column-"
           f"stochastic mixing needs algorithm='push_sum', not "
           f"{f['algorithm']!r}"
       )),
    _r("domain:topology_seed", ("topology",),
       lambda f: f["topology_seed"] < -1,
       lambda f: (
           f"topology_seed must be -1 (follow seed) or >= 0, got "
           f"{f['topology_seed']}"
       )),
    _r("domain:data_seed", ("execution",),
       lambda f: f["data_seed"] < -1,
       lambda f: (
           f"data_seed must be -1 (follow seed) or >= 0, got "
           f"{f['data_seed']}"
       )),
    # ---------------------------------------------------------- replicas
    _r("domain:replicas", ("replicas",),
       lambda f: f["replicas"] < 1,
       lambda f: f"replicas must be >= 1, got {f['replicas']}"),
    _r("replicas×backend", ("replicas", "execution"),
       lambda f: f["replicas"] > 1 and f["backend"] != "jax",
       lambda f: (
           f"replicas={f['replicas']} batches seed replicates through "
           "one vmapped XLA program, which only the jax backend compiles"
       )),
    _r("replicas×mixing_impl", ("replicas", "topology"),
       lambda f: f["replicas"] > 1 and f["backend"] == "jax"
       and f["mixing_impl"] in ("shard_map", "pallas"),
       lambda f: (
           f"replicas={f['replicas']} is incompatible with mixing_impl="
           f"{f['mixing_impl']!r}: mesh-pinned / unbatched-VMEM forms "
           "cannot ride the replica vmap axis"
       )),
    _r("replicas×choco", ("replicas", "algorithm"),
       lambda f: f["replicas"] > 1 and f["backend"] == "jax"
       and f["algorithm"] == "choco",
       lambda f: (
           "replicas > 1 is unsupported for 'choco': its compressor "
           "stream derives from config.seed internally"
       )),
    _r("replicas×compression", ("replicas", "compression"),
       lambda f: f["replicas"] > 1 and f["backend"] == "jax"
       and f["compression"] != "none",
       lambda f: (
           "replicas > 1 is unsupported with compressed gossip: the "
           "compressor stream derives from config.seed internally"
       )),
    _r("replicas×fused", ("replicas", "byzantine"),
       lambda f: f["replicas"] > 1 and f["backend"] == "jax"
       and f["robust_impl"] == "fused",
       lambda f: (
           "replicas > 1 is incompatible with robust_impl='fused'"
       )),
    # --------------------------------------------------- tensor parallel
    _r("domain:tp_degree", ("worker_mesh",),
       lambda f: f["tp_degree"] < 1,
       lambda f: f"tp_degree must be >= 1, got {f['tp_degree']}"),
    _r("tp×backend", ("worker_mesh", "execution"),
       lambda f: f["tp_degree"] > 1 and f["backend"] != "jax",
       lambda f: "tp_degree > 1 shards the model over a jax device mesh"),
    _r("tp×problem", ("worker_mesh", "algorithm"),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax"
       and f["problem_type"] != "softmax",
       lambda f: (
           f"tp_degree={f['tp_degree']} shards the softmax classifier; "
           f"problem_type={f['problem_type']!r} has no model axis"
       )),
    _r("tp×algorithm", ("worker_mesh", "algorithm", "topology"),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax"
       and f["problem_type"] == "softmax"
       and (f["algorithm"] != "dsgd" or f["topology"] != "ring"),
       lambda f: (
           "the tensor-parallel path implements D-SGD ring gossip only"
       )),
    _r("tp:class_divisibility", ("worker_mesh",),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax"
       and f["problem_type"] == "softmax" and f["algorithm"] == "dsgd"
       and f["topology"] == "ring"
       and f["n_classes"] % f["tp_degree"] != 0,
       lambda f: (
           f"tp_degree={f['tp_degree']} must divide n_classes "
           f"({f['n_classes']})"
       )),
    _r("tp×faults_byzantine", ("worker_mesh", "faults", "byzantine"),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax" and (
           f["edge_drop_prob"] > 0.0 or f["straggler_prob"] > 0.0
           or f["mttf"] > 0.0 or f["gossip_schedule"] != "synchronous"
           or f["attack"] != "none" or f["aggregation"] != "gossip"),
       lambda f: (
           "tp_degree > 1 does not compose with fault injection, "
           "matching schedules, or Byzantine machinery"
       )),
    _r("tp×compression", ("worker_mesh", "compression"),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax"
       and f["compression"] != "none",
       lambda f: (
           "tp_degree > 1 does not compose with compressed gossip"
       )),
    _r("tp×replicas", ("worker_mesh", "replicas"),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax"
       and f["replicas"] > 1,
       lambda f: (
           "tp_degree > 1 and replicas > 1 are mutually exclusive"
       )),
    _r("tp×mixing_impl", ("worker_mesh", "topology"),
       lambda f: f["tp_degree"] > 1 and f["backend"] == "jax"
       and f["mixing_impl"] not in ("auto", "stencil"),
       lambda f: (
           f"tp_degree > 1 realizes ring gossip as its own stencil; "
           f"mixing_impl={f['mixing_impl']!r} would be silently ignored"
       )),
)


def _mesh_base_ok(f: dict) -> bool:
    """worker_mesh >= 2 with the prerequisite layers already satisfied —
    the guard every later mesh×feature rule shares, so each rule fires on
    ITS violation rather than re-reporting an earlier one."""
    return (
        f["worker_mesh"] >= 2 and f["backend"] == "jax"
        and f["algorithm"] != "centralized"
        and f["n_workers"] % f["worker_mesh"] == 0
        and f["topology"] in NEIGHBOR_TOPOLOGIES
    )


def full_fields(overrides: Mapping[str, Any]) -> dict[str, Any]:
    """A complete field map: dataclass defaults + ``overrides``.

    Unknown override names raise ``UnknownFieldError`` (with the nearest
    valid field) — the structured form the spec layer surfaces.
    """
    for name in overrides:
        if name not in DEFAULT_FIELDS:
            raise UnknownFieldError(str(name))
    fields = dict(DEFAULT_FIELDS)
    fields.update(overrides)
    return fields


def explain(cell, *, all_rules: bool = False):
    """Classify one cell of the composition matrix.

    ``cell``: an ``ExperimentConfig``, or a (possibly partial) field
    mapping completed with the config defaults. Returns a ``Verdict`` —
    valid, or the first rejecting rule with its exact reason; with
    ``all_rules=True`` returns the list of EVERY rejecting verdict (a
    cell can violate several composition rules at once)."""
    if isinstance(cell, ExperimentConfig):
        fields = cell.to_dict()
    else:
        fields = full_fields(cell)
    hits = []
    for rule in RULES:
        if rule.when(fields):
            v = Verdict(
                valid=False, rule=rule.name, axes=rule.axes,
                reason=rule.reason(fields),
            )
            if not all_rules:
                return v
            hits.append(v)
    if all_rules:
        return hits
    return VALID


def cross_check(overrides: Mapping[str, Any]) -> Optional[str]:
    """The divergence between this table and ``ExperimentConfig``
    construction for one cell, or None when they agree.

    The drift guard's primitive: tests and the golden-corpus bench run it
    over hundreds of seeded cells and require zero divergences."""
    fields = full_fields(overrides)
    verdict = explain(fields)
    error = ExperimentConfig.construction_error(fields)
    if verdict.valid and error is not None:
        return (
            f"validity table says VALID but construction rejects: {error}"
        )
    if not verdict.valid and error is None:
        return (
            f"validity table rejects ({verdict.rule}: {verdict.reason}) "
            "but construction accepts"
        )
    return None


def rules_by_axis() -> dict[str, list[str]]:
    """Rule names grouped by the axes they couple (docs/SCENARIOS.md's
    catalog view)."""
    out: dict[str, list[str]] = {axis: [] for axis in AXES}
    for rule in RULES:
        for axis in rule.axes:
            out.setdefault(axis, []).append(rule.name)
    return out
