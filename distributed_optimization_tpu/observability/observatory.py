"""The run registry + perf-regression checker (ISSUE-10 tentpole layer 4).

The repo now emits schema-versioned run evidence everywhere — RunTrace
JSONL from the CLI/Simulator/daemon, ``*.manifest.json`` provenance
sidecars from every bench — but nothing could READ that corpus: finding
"the runs of this config on this machine" meant grepping JSON by hand,
and a regenerated bench artifact was only ever compared to its committed
ancestor by eyeball. This module is the query side:

- ``index``/``list``: walk a directory for RunTrace manifests (``.jsonl``
  lines and bare ``.json`` objects) and bench sidecars, normalize each
  into a flat record (kind, label, config/structural hash, platform,
  provenance, final gap, iters/sec), filter by any of them, and emit a
  table or JSON. The structural hash is recomputed from the embedded
  config via ``ExperimentConfig.structural_hash`` — the SERVING cohort
  identity, so "which runs would have coalesced" is a one-flag query.
- ``compare A B``: field-level diff of two manifests — config fields
  that differ, provenance drift (different commit? dirty tree? other
  chip?), and the headline numbers side by side with ratios.
- ``perf-diff``: the regression checker. Re-checks a directory of
  freshly regenerated bench JSON against the committed ``docs/perf/*``
  within PER-ARTIFACT tolerances (``PERF_TOLERANCES``): structural keys
  must match exactly (the drift-guard contract), flagged booleans must
  not regress, and the named numeric series must agree within each
  entry's relative tolerance. Wall-clock-dependent numbers are NOT
  checked by default — on a co-tenant machine they vary 2-3× between
  sessions (docs/ROUND5_NOTES.md); the specs name the quantities that
  are supposed to be stable (ratios, convergence envelopes, gate
  booleans). Exit code 1 on any regression — ``make perf-diff`` wires it
  into CI, turning the bench corpus into a guarded time series.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import sys
from pathlib import Path
from typing import Any, Iterator, Optional

# ---------------------------------------------------------------- indexing


@dataclasses.dataclass
class RunRecord:
    """One indexed manifest (RunTrace or bench sidecar), flattened."""

    path: str
    line: Optional[int]  # JSONL line number (None for whole-file manifests)
    kind: str
    schema_version: int
    label: str
    backend: Optional[str]
    platform: Optional[str]
    config_hash: Optional[str]
    structural_hash: Optional[str]
    algorithm: Optional[str]
    n_workers: Optional[int]
    final_gap: Optional[float]
    iters_per_second: Optional[float]
    git_sha: Optional[str]
    device_kind: Optional[str]

    def row(self) -> str:
        gap = (
            f"{self.final_gap:.3e}" if self.final_gap is not None else "—"
        )
        ips = (
            f"{self.iters_per_second:.1f}"
            if self.iters_per_second is not None else "—"
        )
        sha = (self.git_sha or "—")[:8]
        return (
            f"{self.label[:32]:<34}{self.kind:<16}"
            f"{(self.structural_hash or '—')[:12]:<14}"
            f"{(self.algorithm or '—'):<18}{gap:>11}{ips:>9}  "
            f"{(self.platform or '—'):<5} {sha}"
        )


_HEADER = (
    f"{'label':<34}{'kind':<16}{'struct_hash':<14}{'algorithm':<18}"
    f"{'final_gap':>11}{'iters/s':>9}  {'plat':<5} git"
)


def _structural_hash_of(config_dict) -> Optional[str]:
    if not isinstance(config_dict, dict):
        return None
    try:
        from distributed_optimization_tpu.config import ExperimentConfig

        return ExperimentConfig.from_dict(config_dict).structural_hash()
    except Exception:
        # Configs from older schema versions may no longer validate;
        # an indexer must degrade to "unknown", not crash the listing.
        return None


def _record_from_manifest(
    blob: dict, path: Path, line: Optional[int]
) -> Optional[RunRecord]:
    kind = blob.get("kind")
    if kind not in ("run_trace", "bench_manifest"):
        return None
    cfg = blob.get("config") or {}
    health = blob.get("health") or {}
    prov = blob.get("provenance") or {}
    return RunRecord(
        path=str(path),
        line=line,
        kind=kind,
        schema_version=int(blob.get("schema_version", 0)),
        label=str(blob.get("label") or blob.get("artifact") or path.stem),
        backend=blob.get("backend"),
        platform=blob.get("platform"),
        config_hash=blob.get("config_hash"),
        structural_hash=_structural_hash_of(cfg),
        algorithm=cfg.get("algorithm") if isinstance(cfg, dict) else None,
        n_workers=cfg.get("n_workers") if isinstance(cfg, dict) else None,
        final_gap=_as_float(health.get("final_gap")),
        iters_per_second=_as_float(blob.get("iters_per_second")),
        git_sha=prov.get("git_sha"),
        device_kind=prov.get("device_kind"),
    )


def _as_float(v) -> Optional[float]:
    try:
        return float(v) if v is not None and not isinstance(v, str) else None
    except (TypeError, ValueError):
        return None


def iter_manifests(root) -> Iterator[tuple[dict, Path, Optional[int]]]:
    """Yield (manifest dict, path, jsonl-line-or-None) for every readable
    RunTrace/bench manifest under ``root`` (a file or a directory).
    Unreadable or foreign JSON is skipped — an index walks what it can."""
    from distributed_optimization_tpu.telemetry import _decode_nonfinite

    root = Path(root)
    paths = (
        [root] if root.is_file()
        else sorted(
            p for pattern in ("*.json", "*.jsonl") for p in root.rglob(pattern)
        )
    )
    for path in paths:
        try:
            text = path.read_text()
        except OSError:
            continue
        if path.suffix == ".jsonl":
            for i, line in enumerate(text.splitlines()):
                if not line.strip():
                    continue
                try:
                    yield _decode_nonfinite(json.loads(line)), path, i
                except json.JSONDecodeError:
                    continue
        else:
            try:
                yield _decode_nonfinite(json.loads(text)), path, None
            except json.JSONDecodeError:
                continue


def build_index(root, **filters) -> list[RunRecord]:
    """Index every manifest under ``root`` into ``RunRecord`` rows.

    ``filters``: config_hash=, structural_hash=, backend=, platform=,
    kind=, label= (substring, case-insensitive) — all ANDed.
    """
    records = []
    for blob, path, line in iter_manifests(root):
        if not isinstance(blob, dict):
            continue
        rec = _record_from_manifest(blob, path, line)
        if rec is None:
            continue
        if _matches(rec, filters):
            records.append(rec)
    return records


# --------------------------------------------------------------- incidents


@dataclasses.dataclass
class IncidentRecord:
    """One indexed anomaly-sentinel incident bundle (ISSUE-13;
    ``observability/monitors.py::build_incident``), flattened for the
    ``incidents`` subcommand and the ``list --with-incidents`` join."""

    path: str
    line: Optional[int]
    label: str
    detector: str
    severity: str
    onset_iteration: Optional[int]
    message: str
    config_hash: Optional[str]
    structural_hash: Optional[str]
    algorithm: Optional[str]
    # Fleet remediation attribution (ISSUE-16; ``serving/fleet.py``):
    # what the policy engine DID about this incident. None when the
    # bundle predates the fleet or nothing acted on it.
    remediation_policy: Optional[str] = None
    remediation_outcome: Optional[str] = None
    # Event-clock forensics (ISSUE-17; async fault context): the onset
    # round's first event index and the onset window's in-flight gradient
    # losses. None for synchronous or fault-free bundles.
    onset_event: Optional[int] = None
    n_inflight_lost: Optional[int] = None

    def row(self) -> str:
        onset = (
            str(self.onset_iteration)
            if self.onset_iteration is not None else "—"
        )
        ev = str(self.onset_event) if self.onset_event is not None else "—"
        lost = (
            str(self.n_inflight_lost)
            if self.n_inflight_lost is not None else "—"
        )
        return (
            f"{self.label[:28]:<30}{self.detector:<22}{self.severity:<8}"
            f"{onset:>8}{ev:>9}{lost:>6}  {(self.config_hash or '—')[:12]:<14}"
            f"{(self.algorithm or '—'):<18}"
            f"{(self.remediation_outcome or '—'):<12}{self.message[:48]}"
        )


_INCIDENT_HEADER = (
    f"{'label':<30}{'detector':<22}{'sev':<8}{'onset':>8}{'event':>9}"
    f"{'lost':>6}  {'config_hash':<14}{'algorithm':<18}"
    f"{'remediation':<12}message"
)


def build_incident_index(root, **filters) -> list[IncidentRecord]:
    """Index every ``kind='incident'`` JSONL record under ``root``
    (the bundles ``monitors.write_incidents`` leaves next to RunTrace
    manifests). ``filters``: detector=, severity=, config_hash=,
    structural_hash=, label= (substring) — all ANDed, the
    ``build_index`` convention."""
    records = []
    for blob, path, line in iter_manifests(root):
        if not isinstance(blob, dict) or blob.get("kind") != "incident":
            continue
        cfg = blob.get("config") or {}
        rem = blob.get("remediation")
        rem = rem if isinstance(rem, dict) else {}
        actx = (blob.get("context") or {}).get("async")
        actx = actx if isinstance(actx, dict) else {}
        rec = IncidentRecord(
            path=str(path),
            line=line,
            label=str(blob.get("label") or path.stem),
            detector=str(blob.get("detector") or "—"),
            severity=str(blob.get("severity") or "—"),
            onset_iteration=blob.get("onset_iteration"),
            message=str(blob.get("message") or ""),
            config_hash=blob.get("config_hash"),
            structural_hash=blob.get("structural_hash"),
            algorithm=cfg.get("algorithm") if isinstance(cfg, dict) else None,
            remediation_policy=rem.get("policy"),
            remediation_outcome=rem.get("outcome"),
            onset_event=actx.get("onset_event"),
            n_inflight_lost=actx.get("n_inflight_lost_window"),
        )
        if _matches(rec, filters):
            records.append(rec)
    return records


def incident_counts(root) -> dict[str, int]:
    """config_hash → incident count under ``root`` — the join key the
    ``list --with-incidents`` column uses (an incident bundle records
    the full config, so its content hash matches its run's manifest)."""
    counts: dict[str, int] = {}
    for rec in build_incident_index(root):
        if rec.config_hash:
            counts[rec.config_hash] = counts.get(rec.config_hash, 0) + 1
    return counts


def index_with_incident_counts(
    root, **filters
) -> tuple[list[RunRecord], dict[str, int]]:
    """``(build_index(root, **filters), incident_counts(root))`` in ONE
    directory walk — ``list --with-incidents`` reads both from the same
    corpus, and a scenario-engine-sized runs/ directory should not pay
    the JSON decode twice."""
    records: list[RunRecord] = []
    counts: dict[str, int] = {}
    for blob, path, line in iter_manifests(root):
        if not isinstance(blob, dict):
            continue
        if blob.get("kind") == "incident":
            ch = blob.get("config_hash")
            if ch:
                counts[ch] = counts.get(ch, 0) + 1
            continue
        rec = _record_from_manifest(blob, path, line)
        if rec is not None and _matches(rec, filters):
            records.append(rec)
    return records, counts


def _matches(rec: RunRecord, filters: dict) -> bool:
    for key, want in filters.items():
        if want is None:
            continue
        have = getattr(rec, key, None)
        if key == "label":
            if have is None or want.lower() not in have.lower():
                return False
        elif have != want:
            return False
    return True


# ---------------------------------------------------------------- compare


def load_manifest(spec: str) -> dict:
    """Load one manifest: ``path.json``, or ``path.jsonl[:line]`` (line 0
    when omitted)."""
    path, line = spec, 0
    if ":" in spec and not Path(spec).exists():
        path, _, line_s = spec.rpartition(":")
        try:
            line = int(line_s)
        except ValueError:
            path, line = spec, 0
    from distributed_optimization_tpu.telemetry import _decode_nonfinite

    p = Path(path)
    text = p.read_text()
    if p.suffix == ".jsonl":
        lines = [ln for ln in text.splitlines() if ln.strip()]
        return _decode_nonfinite(json.loads(lines[line]))
    return _decode_nonfinite(json.loads(text))


def compare_manifests(a: dict, b: dict) -> dict:
    """Field-level diff of two manifests (the ``compare`` subcommand)."""
    cfg_a, cfg_b = a.get("config") or {}, b.get("config") or {}
    config_diff = {
        k: [cfg_a.get(k), cfg_b.get(k)]
        for k in sorted(set(cfg_a) | set(cfg_b))
        if cfg_a.get(k) != cfg_b.get(k)
    }
    prov_a, prov_b = a.get("provenance") or {}, b.get("provenance") or {}
    prov_diff = {
        k: [prov_a.get(k), prov_b.get(k)]
        for k in sorted(set(prov_a) | set(prov_b))
        if prov_a.get(k) != prov_b.get(k)
    }
    ha, hb = a.get("health") or {}, b.get("health") or {}

    def ratio(x, y):
        x, y = _as_float(x), _as_float(y)
        if x is None or y is None or x == 0:
            return None
        return y / x

    headline = {}
    for key, va, vb in (
        ("final_gap", ha.get("final_gap"), hb.get("final_gap")),
        ("iters_per_second", a.get("iters_per_second"),
         b.get("iters_per_second")),
        ("compile_seconds", a.get("compile_seconds"),
         b.get("compile_seconds")),
    ):
        headline[key] = {"a": va, "b": vb, "b_over_a": ratio(va, vb)}

    def inc_block(h):
        inc = (h or {}).get("incidents") or {}
        return {
            "count": int(inc.get("count", 0)),
            "fatal": int(inc.get("fatal", 0)),
            "detectors": sorted({
                an.get("detector") for an in inc.get("anomalies", [])
                if an.get("detector")
            }),
        }

    def rem_outcomes(blob, h):
        # Remediation outcomes visible on this side (ISSUE-16): a
        # top-level block (comparing incident-bundle JSONL lines
        # directly — the fleet-on vs fleet-off workflow), plus any
        # carried by the health block's anomaly digests.
        outs = []
        rem = blob.get("remediation")
        if isinstance(rem, dict) and rem.get("outcome"):
            outs.append(str(rem["outcome"]))
        inc = (h or {}).get("incidents") or {}
        for an in inc.get("anomalies", []):
            r = an.get("remediation") if isinstance(an, dict) else None
            if isinstance(r, dict) and r.get("outcome"):
                outs.append(str(r["outcome"]))
        return sorted(outs)

    inc_a, inc_b = inc_block(ha), inc_block(hb)
    rem_a, rem_b = rem_outcomes(a, ha), rem_outcomes(b, hb)

    def async_ctx(blob):
        # Event-clock fault context (ISSUE-17): present when comparing
        # incident-bundle JSONL lines for async faulty runs.
        ctx = blob.get("context")
        actx = ctx.get("async") if isinstance(ctx, dict) else None
        if not isinstance(actx, dict):
            return None
        return {
            k: actx.get(k)
            for k in ("onset_event", "n_inflight_lost_window",
                      "window_availability", "crashed_workers_at_onset")
            if k in actx
        }

    actx_a, actx_b = async_ctx(a), async_ctx(b)
    async_delta = None
    if actx_a is not None or actx_b is not None:
        av_a = (actx_a or {}).get("window_availability")
        av_b = (actx_b or {}).get("window_availability")
        async_delta = {
            "a": actx_a,
            "b": actx_b,
            "availability_delta": (
                av_b - av_a if av_a is not None and av_b is not None
                else None
            ),
        }
    return {
        "a": {"label": a.get("label") or a.get("artifact"),
              "config_hash": a.get("config_hash")},
        "b": {"label": b.get("label") or b.get("artifact"),
              "config_hash": b.get("config_hash")},
        "same_config_hash": (
            a.get("config_hash") == b.get("config_hash")
            and a.get("config_hash") is not None
        ),
        "structural_match": (
            _structural_hash_of(cfg_a) == _structural_hash_of(cfg_b)
            and _structural_hash_of(cfg_a) is not None
        ),
        "config_diff": config_diff,
        "provenance_diff": prov_diff,
        "headline": headline,
        # Anomaly-sentinel delta (ISSUE-13): which run carried incidents,
        # how many, which detectors — the first thing to look at when two
        # runs of one config disagree.
        "incidents": {
            "a": inc_a,
            "b": inc_b,
            "delta": inc_b["count"] - inc_a["count"],
            "detectors_only_in_b": sorted(
                set(inc_b["detectors"]) - set(inc_a["detectors"])
            ),
            "detectors_only_in_a": sorted(
                set(inc_a["detectors"]) - set(inc_b["detectors"])
            ),
            # Fleet remediation-outcome delta (ISSUE-16): did the policy
            # engine act, and did the two sides resolve differently?
            "remediation": {
                "a": rem_a,
                "b": rem_b,
                "delta_remediated": (
                    rem_b.count("remediated") - rem_a.count("remediated")
                ),
            },
        },
        # Event-clock fault-context delta (ISSUE-17): None unless at
        # least one side is an incident bundle carrying an async block.
        "async_context": async_delta,
    }


# ------------------------------------------------------------- perf-diff


@dataclasses.dataclass(frozen=True)
class Check:
    """One tolerance rule: dotted-path pattern (fnmatch, list indices are
    path components) → how fresh may differ from committed.

    ``rtol``: numeric leaves must satisfy |fresh − committed| ≤
    rtol·max(|committed|, atol_floor). ``equal``: exact equality (gate
    booleans, flags, counts); with ``bool_only`` the pattern's non-boolean
    matches are skipped — the idiom for ``gates.*`` blocks that mix
    asserted booleans with measured floats. ``direction``: 'min' fails a
    fresh value only BELOW the envelope (throughput-style floors where
    faster is fine), 'max' the mirror (overhead/deviation ceilings).
    """

    pattern: str
    rtol: float = 0.25
    equal: bool = False
    bool_only: bool = False
    direction: Optional[str] = None  # None | 'min' | 'max'
    atol_floor: float = 1e-9


# Per-artifact checks. Deliberately NOT exhaustive: bench JSON is full of
# session-dependent wall-clock numbers that vary 2-3× between runs on this
# shared machine (docs/ROUND5_NOTES.md) — checking those would make the
# guard cry wolf. What IS checked: the gate booleans every bench asserts
# (a regen that flips one has regressed — including platform-conditional
# flags like ``floor_applied``, which correctly fail when "fresh" came
# from different hardware: such a regen is not comparable evidence),
# deterministic convergence facts (final gaps, B̂ tables, floats-to-ε)
# inside generous envelopes, f64 parity ceilings, and the committed floor
# constants themselves. Artifacts without an entry get the top-level
# key-structure check only (the drift-guard parity).
PERF_TOLERANCES: dict[str, tuple[Check, ...]] = {
    "observatory.json": (
        Check("gates.*", equal=True, bool_only=True),
        Check("heartbeat.overhead_frac", rtol=1.0, direction="max",
              atol_floor=0.03),
        Check("scrape.p95_ms", rtol=3.0, direction="max", atol_floor=5.0),
    ),
    "telemetry.json": (
        Check("gates.*", equal=True, bool_only=True),
        Check("cells.*.overhead_ok", equal=True),
        Check("cells.*.off_on_bitwise_objective", equal=True),
    ),
    "serving.json": (
        Check("gates.applied", equal=True),
        Check("parity.max_abs_deviation_f64", rtol=1.0, atol_floor=1e-12,
              direction="max"),
        Check("latency.speedup_submit_to_start", rtol=0.9, direction="min"),
        Check("throughput.speedup", rtol=0.6, direction="min"),
        Check("throughput.coalescing_loses", equal=True),
    ),
    "serving_load.json": (
        # The sustained-load plane (ISSUE-15): the boolean gates —
        # restart replay 100% warm + bitwise over the persistent store,
        # shed observed at the tenant cap, the honest saturation/
        # fairness loses flags — must reproduce exactly; the wall-clock
        # cells (warm p99, saturation req/s, victim fairness ratio) get
        # generous envelopes because this shared CPU container's load
        # varies 2-3x between sessions.
        Check("gates.*", equal=True, bool_only=True),
        Check("gates.parity_max_abs_deviation_f64",
              rtol=1.0, atol_floor=1e-12, direction="max"),
        Check("latency.warm_p99_s", rtol=2.0, direction="max",
              atol_floor=1.0),
        Check("saturation.requests_per_s", rtol=0.7, direction="min"),
        Check("saturation.saturation_loses", equal=True),
        Check("fairness.victim_p99_ratio", rtol=2.0, direction="max",
              atol_floor=2.0),
        Check("fairness.fairness_loses", equal=True),
        Check("restart.warm_ratio", equal=True),
        Check("restart.bitwise", equal=True),
    ),
    "async.json": (
        Check("gates.*", equal=True, bool_only=True),
        Check("gates.jax_vs_numpy_per_event_parity_max_dev_f64",
              rtol=1.0, atol_floor=1e-12, direction="max"),
    ),
    "async_faults.json": (
        # Faults on the event clock (ISSUE-17): the crash-free bitwise
        # gate, the no-free-lunch and matched-availability flags must
        # reproduce exactly; the tracker residual is an f64 exactness
        # ceiling; the under-faults barrier speedup and the
        # churn-vs-thinning envelope get generous envelopes (latency
        # draws are seeded, but ε-crossing indices quantize at the eval
        # cadence).
        Check("gates.*", equal=True, bool_only=True),
        Check("gates.tracking_residual_max", rtol=1.0,
              atol_floor=1e-12, direction="max"),
        Check("gates.tracking_residual_staleness_zero", rtol=1.0,
              atol_floor=1e-12, direction="max"),
        Check("gates.wall_clock_speedup_under_faults", rtol=0.4,
              direction="min"),
        Check("runs.crash_free_gate.bitwise_*", equal=True),
        Check("runs.matched_availability.faulty_vs_faulty_envelope",
              rtol=0.7, direction="max", atol_floor=1.0),
    ),
    "federated.json": (
        Check("gates.max_n_completed_matrix_free", equal=True),
        Check("gates.best_floats_to_eps_reduction", rtol=0.5,
              direction="min"),
    ),
    "fused_robust.json": (
        Check("gates.*", equal=True, bool_only=True),
        Check("gates.compiled_floor", equal=True),
        Check("gates.bytes_ceiling", equal=True),
        Check("gates.gap_envelope", equal=True),
    ),
    "churn.json": (
        Check("gates.burst1_bitwise_iid", equal=True),
        Check("gates.bhat_by_burst.*", equal=True),
        Check("gates.monotone_gap_degradation.*", rtol=0.5),
    ),
    "sweep.json": (
        Check("floors.accelerator_speedup_at_r32", equal=True),
        Check("floors.cpu_steady_speedup_at_r32", equal=True),
    ),
    "scenarios.json": (
        # The golden corpus (ISSUE-12): every gate boolean — validity
        # agreement, per-cell invariants, warm replay, chaos
        # degradation — plus the exact cell counts must reproduce.
        Check("gates.*", equal=True, bool_only=True),
        Check("gates.agreement_cells", equal=True),
        # Composition closure (ISSUE-17): the fixed sample's valid-cell
        # count/fraction are committed numbers — a regen that shrinks
        # them has re-grown a rejection rule.
        Check("gates.agreement_valid_cells", equal=True),
        Check("gates.agreement_valid_fraction", equal=True),
        Check("gates.matrix_n_valid_cells", equal=True),
        Check("matrix.counts.valid", equal=True),
        Check("matrix.invariants.failures", equal=True),
    ),
    "worker_mesh.json": (
        Check("gates.*", equal=True, bool_only=True),
        Check("gates.parity_max_objective_rel_deviation_f64",
              rtol=1.0, atol_floor=1e-12, direction="max"),
        Check("gates.n100k_ici_bytes_per_device_per_round", equal=True),
    ),
    "mesh_scale.json": (
        # overlap_loses is measured, not asserted (CPU may tie either
        # way between sessions) — every other gate boolean is pinned.
        Check("gates.n1m_*", equal=True),
        Check("gates.per_device_flat_at_matched_rows", equal=True),
        Check("gates.ring_ici_bytes_per_device_flat_in_n", equal=True),
        Check("gates.er_1m_sparse_plan_built", equal=True),
        Check("gates.topk_wire_bytes_halved", equal=True),
        Check("gates.topk_gap_within_envelope", equal=True),
        Check("gates.compressed_models_match_unsharded", equal=True),
        # deterministic pricing off the static plan: exact
        Check("gates.topk_wire_bytes_ratio", equal=True),
    ),
    "monitors.json": (
        # The anomaly sentinel (ISSUE-13): every gate boolean — monitor
        # overhead within the ≤5% ceiling on the sequential AND async
        # paths, monitors-on bitwise == off, the planted f>b divergence
        # firing with onset inside the 2-eval-window envelope, the
        # early halt actually saving work, and the incident bundle
        # naming the attacker context — must reproduce exactly; the
        # measured overhead fractions get a generous ceiling envelope.
        Check("gates.*", equal=True, bool_only=True),
        Check("overhead.overhead_frac", rtol=1.0, direction="max",
              atol_floor=0.05),
        Check("async.overhead_frac", rtol=1.0, direction="max",
              atol_floor=0.05),
        Check("divergence.onset_error_eval_windows", rtol=0.0,
              direction="max", atol_floor=2.0),
    ),
    "fleet.json": (
        # The self-healing fleet soak (ISSUE-16): the boolean gates —
        # every injected incident remediated (divergence halt +
        # quarantine, dead-worker respawn, store-corruption quarantine
        # + cold recompile), zero stuck requests, a full scale-up/
        # scale-down cycle — must reproduce exactly; the warm-p99 SLO
        # cell gets a generous ceiling envelope (shared CPU container,
        # 2-3x session-to-session wall-clock variance).
        Check("gates.*", equal=True, bool_only=True),
        Check("latency.warm_p99_s", rtol=2.0, direction="max",
              atol_floor=2.0),
        Check("stuck_requests", equal=True),
    ),
}


def _iter_leaves(obj, prefix=()) -> Iterator[tuple[tuple, Any]]:
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _iter_leaves(v, prefix + (str(k),))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            yield from _iter_leaves(v, prefix + (str(i),))
    else:
        yield prefix, obj


def _check_leaf(check: Check, path: str, committed, fresh) -> Optional[str]:
    """None when within tolerance, else the failure message."""
    if check.equal:
        if fresh != committed:
            return f"{path}: {committed!r} -> {fresh!r} (must match exactly)"
        return None
    c, f = _as_float(committed), _as_float(fresh)
    if c is None or f is None:
        if fresh != committed and (c is None) != (f is None):
            return f"{path}: {committed!r} -> {fresh!r} (type changed)"
        return None
    scale = max(abs(c), check.atol_floor)
    if check.direction == "min":
        if f < c - check.rtol * scale:
            return (
                f"{path}: {c:.6g} -> {f:.6g} (below floor envelope "
                f"rtol={check.rtol})"
            )
        return None
    if check.direction == "max":
        if f > c + check.rtol * scale:
            return (
                f"{path}: {c:.6g} -> {f:.6g} (above ceiling envelope "
                f"rtol={check.rtol})"
            )
        return None
    if abs(f - c) > check.rtol * scale:
        return f"{path}: {c:.6g} -> {f:.6g} (rtol={check.rtol})"
    return None


def perf_diff(
    fresh_dir, committed_dir, *, artifacts: Optional[list] = None,
) -> dict:
    """Compare fresh bench JSON against the committed artifacts.

    Returns {"artifacts": {name: {"status", "failures", "checked"}},
    "ok": bool}. Every committed non-manifest artifact present in
    ``fresh_dir`` is compared: top-level key sets must match exactly
    (the drift-guard contract), then the artifact's ``PERF_TOLERANCES``
    checks run over matching leaves. A fresh artifact missing a checked
    leaf fails (a silently vanished gate is a regression, not a pass).
    """
    fresh_dir, committed_dir = Path(fresh_dir), Path(committed_dir)
    out: dict[str, Any] = {"artifacts": {}, "ok": True}
    names = sorted(
        p.name for p in committed_dir.glob("*.json")
        if not p.name.endswith(".manifest.json")
    )
    if artifacts:
        names = [n for n in names if n in set(artifacts)]
    for name in names:
        fresh_path = fresh_dir / name
        entry: dict[str, Any] = {"failures": [], "checked": 0}
        out["artifacts"][name] = entry
        if not fresh_path.exists():
            entry["status"] = "missing"
            continue
        committed = json.loads((committed_dir / name).read_text())
        fresh = json.loads(fresh_path.read_text())
        if set(committed) != set(fresh):
            entry["failures"].append(
                f"top-level keys drifted: extra={set(fresh) - set(committed)}"
                f", missing={set(committed) - set(fresh)}"
            )
        checks = PERF_TOLERANCES.get(name, ())
        committed_leaves = dict(_iter_leaves(committed))
        fresh_leaves = dict(_iter_leaves(fresh))
        for check in checks:
            matched = False
            for path_t, cval in committed_leaves.items():
                dotted = ".".join(path_t)
                if not fnmatch.fnmatch(dotted, check.pattern):
                    continue
                matched = True
                if check.bool_only and not isinstance(cval, bool):
                    continue
                entry["checked"] += 1
                if path_t not in fresh_leaves:
                    entry["failures"].append(
                        f"{dotted}: present in committed, missing in fresh"
                    )
                    continue
                msg = _check_leaf(check, dotted, cval, fresh_leaves[path_t])
                if msg is not None:
                    entry["failures"].append(msg)
            if not matched:
                entry["failures"].append(
                    f"tolerance pattern {check.pattern!r} matched nothing "
                    "in the committed artifact (stale spec)"
                )
        entry["status"] = "ok" if not entry["failures"] else "regressed"
        if entry["failures"]:
            out["ok"] = False
    return out


# -------------------------------------------------------------------- CLI


def _cmd_list(args) -> int:
    filters = dict(
        config_hash=args.config_hash,
        structural_hash=args.structural_hash,
        backend=args.backend,
        platform=args.platform,
        kind=args.kind,
        label=args.label,
    )
    if args.with_incidents:
        records, counts = index_with_incident_counts(args.root, **filters)
    else:
        records, counts = build_index(args.root, **filters), None

    def n_inc(rec):
        return counts.get(rec.config_hash, 0) if counts is not None else None

    if args.json:
        rows = []
        for rec in records:
            d = dataclasses.asdict(rec)
            if counts is not None:
                d["incidents"] = n_inc(rec)
            rows.append(d)
        print(json.dumps(rows, indent=1))
        return 0
    header = _HEADER + ("  incidents" if counts is not None else "")
    print(header)
    print("-" * len(header))
    for rec in records:
        line = rec.row()
        if counts is not None:
            line += f"  {n_inc(rec):>9}"
        print(line)
    print(f"{len(records)} manifest(s) under {args.root}")
    return 0


def _cmd_incidents(args) -> int:
    records = build_incident_index(
        args.root,
        detector=args.detector,
        severity=args.severity,
        config_hash=args.config_hash,
        structural_hash=args.structural_hash,
        label=args.label,
    )
    # Remediation-outcome filters (ISSUE-16): --remediated keeps bundles
    # the fleet's policy engine resolved; --unremediated keeps the rest —
    # failed/skipped outcomes AND bundles nothing acted on (those are
    # the ones an operator still owes a response).
    if getattr(args, "remediated", False):
        records = [
            r for r in records if r.remediation_outcome == "remediated"
        ]
    if getattr(args, "unremediated", False):
        records = [
            r for r in records if r.remediation_outcome != "remediated"
        ]
    if args.json:
        print(json.dumps(
            [dataclasses.asdict(r) for r in records], indent=1,
        ))
        return 0
    print(_INCIDENT_HEADER)
    print("-" * len(_INCIDENT_HEADER))
    for rec in records:
        print(rec.row())
    print(f"{len(records)} incident(s) under {args.root}")
    return 0


def _cmd_compare(args) -> int:
    diff = compare_manifests(load_manifest(args.a), load_manifest(args.b))
    if args.json:
        print(json.dumps(diff, indent=1, default=str))
        return 0
    print(f"A: {diff['a']['label']}  ({diff['a']['config_hash']})")
    print(f"B: {diff['b']['label']}  ({diff['b']['config_hash']})")
    print(
        f"config: {'IDENTICAL' if diff['same_config_hash'] else 'differs'}"
        f"; structural (serving-cohort) match: {diff['structural_match']}"
    )
    for k, pair in diff["config_diff"].items():
        print(f"  config.{k}: {pair[0]!r} -> {pair[1]!r}")
    for k, pair in diff["provenance_diff"].items():
        print(f"  provenance.{k}: {pair[0]!r} -> {pair[1]!r}")
    for k, row in diff["headline"].items():
        r = row["b_over_a"]
        print(
            f"  {k}: {row['a']} vs {row['b']}"
            + (f"  (B/A = {r:.3f})" if r is not None else "")
        )
    inc = diff["incidents"]
    if inc["a"]["count"] or inc["b"]["count"]:
        print(
            f"  incidents: {inc['a']['count']} vs {inc['b']['count']} "
            f"(delta {inc['delta']:+d})"
        )
        if inc["detectors_only_in_b"]:
            print(
                "    fired only in B: "
                + ", ".join(inc["detectors_only_in_b"])
            )
        if inc["detectors_only_in_a"]:
            print(
                "    fired only in A: "
                + ", ".join(inc["detectors_only_in_a"])
            )
    rem = inc["remediation"]
    if rem["a"] or rem["b"]:
        print(
            f"  remediation: {rem['a'] or ['none']} vs "
            f"{rem['b'] or ['none']} "
            f"(remediated delta {rem['delta_remediated']:+d})"
        )
    actx = diff.get("async_context")
    if actx:
        sa, sb = actx["a"] or {}, actx["b"] or {}
        print(
            "  async fault context: "
            f"availability {sa.get('window_availability')} vs "
            f"{sb.get('window_availability')}"
            + (
                f" (delta {actx['availability_delta']:+.3f})"
                if actx["availability_delta"] is not None else ""
            )
        )
        print(
            f"    in-flight losses: {sa.get('n_inflight_lost_window')} vs "
            f"{sb.get('n_inflight_lost_window')}; onset event "
            f"{sa.get('onset_event')} vs {sb.get('onset_event')}"
        )
    return 0


def _cmd_perf_diff(args) -> int:
    result = perf_diff(
        args.fresh, args.committed, artifacts=args.artifact or None,
    )
    n_ok = n_checked = 0
    for name, entry in result["artifacts"].items():
        n_checked += entry["checked"]
        status = entry["status"]
        if status == "ok":
            n_ok += 1
            print(f"[perf-diff] OK        {name} ({entry['checked']} checks)")
        elif status == "missing":
            print(f"[perf-diff] MISSING   {name} (no fresh artifact)")
        else:
            print(f"[perf-diff] REGRESSED {name}")
            for msg in entry["failures"]:
                print(f"    {msg}")
    total = len(result["artifacts"])
    print(
        f"[perf-diff] {n_ok}/{total} artifacts ok, {n_checked} leaf checks, "
        f"fresh={args.fresh} committed={args.committed}"
    )
    return 0 if result["ok"] else 1


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="distributed_optimization_tpu.observatory",
        description=(
            "Run registry + perf-regression checker over RunTrace "
            "manifests and bench sidecars (docs/OBSERVABILITY.md)."
        ),
    )
    sub = p.add_subparsers(dest="command", required=True)

    pl = sub.add_parser(
        "list", help="index manifests under a directory and print a table",
    )
    pl.add_argument("root", help="directory (or single file) to index")
    pl.add_argument("--config-hash", default=None)
    pl.add_argument("--structural-hash", default=None,
                    help="filter by the serving-cohort structural hash "
                         "(recomputed from each manifest's config)")
    pl.add_argument("--backend", default=None)
    pl.add_argument("--platform", default=None)
    pl.add_argument("--kind", default=None,
                    choices=("run_trace", "bench_manifest"))
    pl.add_argument("--label", default=None,
                    help="case-insensitive substring on label/artifact")
    pl.add_argument("--with-incidents", action="store_true",
                    help="join anomaly-sentinel incident bundles under "
                         "the same root onto the listing (per-manifest "
                         "incident count column, keyed by config hash)")
    pl.add_argument("--json", action="store_true")
    pl.set_defaults(fn=_cmd_list)

    pi = sub.add_parser(
        "incidents",
        help="list anomaly-sentinel incident bundles (the JSONL the "
             "monitors write next to RunTrace manifests)",
    )
    pi.add_argument("root", help="directory (or single file) to index")
    pi.add_argument("--detector", default=None)
    pi.add_argument("--severity", default=None,
                    choices=("info", "warn", "fatal"))
    pi.add_argument("--config-hash", default=None)
    pi.add_argument("--structural-hash", default=None)
    rem_group = pi.add_mutually_exclusive_group()
    rem_group.add_argument(
        "--remediated", action="store_true",
        help="only incidents the fleet's policy engine resolved "
             "(remediation outcome 'remediated')")
    rem_group.add_argument(
        "--unremediated", action="store_true",
        help="only incidents still owed a response (no remediation "
             "block, or a failed/skipped outcome)")
    pi.add_argument("--label", default=None,
                    help="case-insensitive substring on the run label")
    pi.add_argument("--json", action="store_true")
    pi.set_defaults(fn=_cmd_incidents)

    pc = sub.add_parser(
        "compare", help="field-level diff of two manifests",
    )
    pc.add_argument("a", help="manifest path (.json, or .jsonl[:line])")
    pc.add_argument("b")
    pc.add_argument("--json", action="store_true")
    pc.set_defaults(fn=_cmd_compare)

    pd = sub.add_parser(
        "perf-diff",
        help="check regenerated bench JSON against committed docs/perf "
             "within per-artifact tolerances (exit 1 on regression)",
    )
    pd.add_argument("--fresh", default="docs/perf",
                    help="directory of freshly regenerated artifacts "
                         "(default: docs/perf — a self-check)")
    pd.add_argument("--committed", default="docs/perf")
    pd.add_argument("--artifact", action="append",
                    help="restrict to this artifact name (repeatable)")
    pd.set_defaults(fn=_cmd_perf_diff)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
