"""The live observatory (ISSUE-10): in-flight observability for a system
whose runs are otherwise single opaque XLA programs.

PR 5's flight recorder is post-hoc — ``RunTrace`` manifests appear only
after a run completes — and the serving daemon plus the async execution
path are operationally blind while work is in flight. This package is the
live layer on top of it:

- ``progress``   — per-chunk heartbeats from the executing backends (host
  callbacks at chunk boundaries; bitwise-free when off), plus the bounded
  pub/sub stream the daemon's ``/v1/progress/<id>`` channel reads.
- ``metrics_registry`` — a small process-wide counter/gauge/histogram
  registry the existing counters (executable cache, coalescer, async
  staleness, phase timers) feed into, exported in Prometheus text format
  at the daemon's ``/metrics`` and dumpable via the ``Simulator``.
- ``spans``      — hierarchical span tracing (request → cohort → compile →
  run → chunk) replacing the flat ``PhaseTimer``, with Chrome trace-event
  JSON export (chrome://tracing / Perfetto).
- ``observatory`` — the run registry + perf-regression CLI: index
  RunTrace/manifest sidecars into a queryable store, compare runs, and
  re-check regenerated bench JSON against the committed ``docs/perf/*``
  within per-artifact tolerances (``make perf-diff``).

Everything here is observability: no module in this package may change an
optimization trajectory (tests assert progress/metrics on ⇒ bitwise the
off trajectories).
"""

from distributed_optimization_tpu.observability.metrics_registry import (  # noqa: F401
    MetricsRegistry,
    metrics_registry,
)
from distributed_optimization_tpu.observability.progress import (  # noqa: F401
    ProgressEvent,
    ProgressStream,
    format_progress_line,
)
from distributed_optimization_tpu.observability.spans import (  # noqa: F401
    Tracer,
)
