"""In-flight progress streaming: heartbeats from the executing backends.

A fused run is one opaque XLA call: between submit and result there is
nothing to look at, which is exactly wrong for a serving daemon under
load and for the long async/federated runs this repo now executes. This
module defines the heartbeat contract the backends emit at CHUNK
boundaries (``jax_backend.run(..., progress_cb=...)``: segmented fused
scan, chunked loop, batched segments, async eval-chunk loop) and the
bounded pub/sub stream the daemon's ``/v1/progress/<request_id>`` channel
reads.

Discipline (the ``config.telemetry`` convention, asserted in tests):
progress OFF changes nothing — same code path, same compiled program,
bitwise-identical trajectories. Progress ON executes the SAME flat scan
in segments through the already-tested continuation machinery, so
trajectories stay bitwise-identical too; the only cost is one host sync
per heartbeat (measured ≤3% steady-state in
``docs/perf/observatory.json``).

The heartbeat payload is the live form of the post-hoc health block:
iteration/event index and wall seconds always; current gap/consensus when
metrics are collected; the realized windowed-connectivity B̂ over the
executed prefix when a synchronous fault process is active (Koloskova et
al. '20 — the quantity time-varying-gossip convergence depends on); and
realized staleness quantiles for async runs (Assran et al. '19's
straggler accounting, live).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Iterator, Optional

import numpy as np

# Default cap on buffered heartbeats per stream: late subscribers replay
# at most this many events. A run emits one per progress_every evals, so
# 4096 covers every realistic cadence; beyond it the oldest drop (the
# stream is a live channel, not an archive — the RunTrace manifest is).
DEFAULT_STREAM_CAPACITY = 4096


@dataclasses.dataclass
class ProgressEvent:
    """One heartbeat. ``kind``: 'chunk' (round-based paths), 'async'
    (event path), or 'lifecycle' (serving queued/running/done markers)."""

    kind: str
    iteration: int                    # global iteration/round index reached
    n_iterations: int                 # the run's horizon
    wall_seconds: float               # since the run (not the queue) started
    gap: Optional[float] = None      # current suboptimality (metrics on)
    consensus: Optional[float] = None
    # Live realized windowed-connectivity over the executed prefix
    # (synchronous fault processes only; None when n/a or over budget).
    bhat: Optional[int] = None
    # Async extras: executed event index and realized staleness quantiles
    # over the executed window.
    event_index: Optional[int] = None
    n_events: Optional[int] = None
    staleness_p50: Optional[float] = None
    staleness_p90: Optional[float] = None
    staleness_max: Optional[float] = None
    # Replica-batched extras: per-replica gaps at this boundary (small R).
    gap_per_replica: Optional[list] = None
    # Lifecycle / free-form annotations (status strings, cohort facts).
    status: Optional[str] = None
    extra: Optional[dict] = None

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            if isinstance(v, (np.floating, np.integer)):
                v = v.item()
            out[f.name] = v
        return out


def format_progress_line(ev: ProgressEvent, label: str = "") -> str:
    """One human-readable heartbeat line (the CLI ``--progress`` output)."""
    head = f"[progress{':' + label if label else ''}]"
    pct = 100.0 * ev.iteration / max(ev.n_iterations, 1)
    parts = [
        f"{head} iter {ev.iteration}/{ev.n_iterations} ({pct:.0f}%)",
        f"t={ev.wall_seconds:.2f}s",
    ]
    if ev.gap is not None and np.isfinite(ev.gap):
        parts.append(f"gap={ev.gap:.3e}")
    if ev.consensus is not None and np.isfinite(ev.consensus):
        parts.append(f"cons={ev.consensus:.3e}")
    if ev.bhat is not None:
        parts.append(f"B̂={ev.bhat}")
    if ev.event_index is not None:
        parts.append(f"events={ev.event_index}/{ev.n_events}")
    if ev.staleness_p90 is not None:
        parts.append(
            f"staleness p50/p90={ev.staleness_p50:.0f}/"
            f"{ev.staleness_p90:.0f}"
        )
    if ev.status is not None:
        parts.append(ev.status)
    return " ".join(parts)


class ProgressStream:
    """Bounded, thread-safe heartbeat channel (one per served request).

    Producers (``SimulationService._execute``'s backend callback) call
    ``publish``; consumers (the daemon's ``/v1/progress`` handler) call
    ``follow`` and receive every event exactly once in order, blocking
    for new ones until the stream is closed. Events carry a monotone
    ``seq`` so a reconnecting client can resume with ``after_seq``.
    """

    def __init__(self, capacity: int = DEFAULT_STREAM_CAPACITY):
        self._cond = threading.Condition()
        self._events: list[tuple[int, dict]] = []  # (seq, payload)
        self._capacity = max(int(capacity), 1)
        self._next_seq = 0
        self._closed = False

    def publish(self, event) -> int:
        payload = event.to_dict() if hasattr(event, "to_dict") else dict(event)
        with self._cond:
            if self._closed:
                return self._next_seq  # late heartbeat after close: drop
            seq = self._next_seq
            self._next_seq += 1
            payload = {"seq": seq, **payload}
            self._events.append((seq, payload))
            if len(self._events) > self._capacity:
                del self._events[0]
            self._cond.notify_all()
            return seq

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def events(self, after_seq: int = -1) -> list[dict]:
        """Buffered events with seq > after_seq (non-blocking snapshot)."""
        with self._cond:
            return [p for s, p in self._events if s > after_seq]

    def follow(
        self, after_seq: int = -1, timeout: Optional[float] = None,
        poll_s: float = 0.2,
    ) -> Iterator[dict]:
        """Yield events in order, blocking for new ones; stops when the
        stream is closed and drained, or when ``timeout`` seconds elapse
        without the stream closing (bounded wait for the HTTP handler)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        last = after_seq
        while True:
            with self._cond:
                fresh = [p for s, p in self._events if s > last]
                if not fresh:
                    if self._closed:
                        return
                    if deadline is not None and time.monotonic() >= deadline:
                        return
                    self._cond.wait(timeout=poll_s)
                    continue
            for payload in fresh:
                last = payload["seq"]
                yield payload


# -------------------------------------------------- live B̂ over the prefix


def make_live_bhat(config, max_cells: int = 200_000):
    """``fn(t) -> Optional[int]``: realized windowed-connectivity B̂ over
    the first ``t`` rounds of this config's fault timeline — the live form
    of ``telemetry.realized_bhat`` — or None when the notion does not
    apply (no synchronous fault process / matching schedule / centralized)
    or the per-heartbeat rebuild would exceed ``max_cells`` timeline
    cells (honesty over silent cost: heartbeats must stay cheap).

    The timeline is built ONCE host-side (bitwise the realization the
    backend consumes — the ``parallel/faults.py`` purity contract) and
    each call measures B̂ on a prefix view.
    """
    from distributed_optimization_tpu.algorithms import get_algorithm

    if not get_algorithm(config.algorithm).is_decentralized:
        return None
    if getattr(config, "execution", "sync") == "async":
        return None
    if config.gossip_schedule != "synchronous":
        return None
    from distributed_optimization_tpu.parallel import build_topology
    from distributed_optimization_tpu.parallel.faults import (
        _edge_list,
        config_faults_active,
        timeline_for_config,
        windowed_connectivity,
    )

    if not config_faults_active(config):
        return None
    topo = build_topology(
        config.topology, config.n_workers,
        erdos_renyi_p=config.erdos_renyi_p,
        seed=config.resolved_topology_seed(),
        impl=config.resolved_topology_impl(),
        sampler=config.resolved_topology_sampler(),
    )
    n_edges = max(len(_edge_list(topo)), 1)
    if config.n_iterations * n_edges > max_cells:
        return None
    tl = timeline_for_config(config, topo, config.n_iterations)

    def prefix(arr, t):
        return None if arr is None else arr[:t]

    def live_bhat(t: int) -> Optional[int]:
        t = int(min(max(t, 1), tl.horizon))
        tl_t = dataclasses.replace(
            tl,
            horizon=t,
            edge_up=prefix(tl.edge_up, t),
            node_up=prefix(tl.node_up, t),
            rejoin=prefix(tl.rejoin, t),
            part_up=prefix(tl.part_up, t),
        )
        return windowed_connectivity(tl_t, topo)

    return live_bhat


def progress_heartbeat_counter():
    """The registry counter every emitted heartbeat increments."""
    from distributed_optimization_tpu.observability.metrics_registry import (
        metrics_registry,
    )

    return metrics_registry().counter(
        "dopt_progress_heartbeats_total",
        "Progress heartbeats emitted by executing backends",
    )
