"""Hierarchical span tracing with Chrome trace-event export.

The flat ``PhaseTimer`` (PR 5) answers "how many seconds went to compile
vs run" but not "WHICH request's cohort paid that compile, and where
inside it the time went". This module replaces it with spans: named,
nested, timestamped intervals (request → cohort → compile → run → chunk)
that still aggregate to the same ``{name: seconds}`` phase dict every
existing consumer reads (reports, ``--json``, manifests), plus a Chrome
trace-event JSON export viewable in chrome://tracing or Perfetto.

``Tracer`` is a drop-in superset of ``PhaseTimer``:

- ``with tracer.phase("compile"):`` / ``with tracer.span("run", id=7):``
  time a live interval; nesting is tracked per thread (the serving
  daemon's handler threads each get their own span stack), so children
  recorded inside a parent's ``with`` body parent correctly.
- ``tracer.add_span(name, seconds)`` records a post-hoc interval whose
  duration was measured elsewhere (the backend's AOT compile seconds) —
  it lands as a child of the thread's current open span.
- ``tracer.phases`` is a real, writable dict aggregating seconds by span
  name — existing code that reads or adjusts it keeps working unchanged
  (the Simulator's compile/run split assigns into it directly).
- ``to_chrome_trace()`` / ``write_chrome_trace(path)`` export complete
  ("ph": "X") events with microsecond timestamps; ``chrome_events()``
  returns the raw event list for embedding in manifests.

``utils/profiling.PhaseTimer`` is now an alias of ``Tracer``, so every
bench script's existing ``PhaseTimer()`` transparently records spans and
its manifest sidecar gains the span tree for free.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Iterator, Optional

import contextlib


class Tracer:
    """Span recorder + phase aggregator (see module docstring).

    Thread-safe: span completion appends under a lock; the per-thread
    open-span stack lives in a ``threading.local``.
    """

    def __init__(self, phases: Optional[dict] = None):
        # Aggregate seconds by span name — the PhaseTimer-compatible
        # surface. A plain dict on purpose: callers assign into it.
        self.phases: dict[str, float] = dict(phases or {})
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._next_id = 0
        # Epoch anchor so timestamps from perf_counter are absolute-ish
        # and comparable across tracers in one process.
        self._t0_wall = time.time() - time.perf_counter()

    # ------------------------------------------------------------- spans
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _record(self, name, start, duration, parent_id, args, aggregate):
        with self._lock:
            self._next_id += 1
            ev = {
                "id": self._next_id,
                "name": name,
                "start": start,  # perf_counter seconds
                "duration": duration,
                "parent": parent_id,
                "thread": threading.current_thread().name,
            }
            if args:
                ev["args"] = dict(args)
            self._events.append(ev)
            if aggregate:
                self.phases[name] = self.phases.get(name, 0.0) + duration
            return ev

    @contextlib.contextmanager
    def span(self, name: str, aggregate: bool = True, **args) -> Iterator[None]:
        """Time a live interval; nests under the thread's open span.
        ``aggregate=False`` records the span without folding its duration
        into ``phases`` — for grouping spans (a request, a labeled run)
        whose children already account the same seconds."""
        stack = self._stack()
        parent_id = stack[-1] if stack else None
        with self._lock:
            self._next_id += 1
            span_id = self._next_id
        stack.append(span_id)
        start = time.perf_counter()
        try:
            yield
        finally:
            duration = time.perf_counter() - start
            stack.pop()
            with self._lock:
                ev = {
                    "id": span_id,
                    "name": name,
                    "start": start,
                    "duration": duration,
                    "parent": parent_id,
                    "thread": threading.current_thread().name,
                }
                if args:
                    ev["args"] = dict(args)
                self._events.append(ev)
                if aggregate:
                    self.phases[name] = self.phases.get(name, 0.0) + duration

    # PhaseTimer compatibility: same name, same semantics, now a span.
    phase = span

    def add_span(
        self,
        name: str,
        seconds: float,
        *,
        start: Optional[float] = None,
        aggregate: bool = True,
        **args,
    ) -> None:
        """Record an interval measured elsewhere (e.g. the backend's AOT
        compile seconds) as a child of the thread's current open span.
        ``start`` defaults to "it just ended" (now − seconds)."""
        stack = self._stack()
        parent_id = stack[-1] if stack else None
        if start is None:
            start = time.perf_counter() - seconds
        self._record(name, start, float(seconds), parent_id, args, aggregate)

    # ------------------------------------------------------------ reading
    def spans(self) -> list[dict]:
        with self._lock:
            return [dict(ev) for ev in self._events]

    def report(self) -> str:
        """The PhaseTimer text table (share-of-total per phase name)."""
        total = sum(self.phases.values())
        lines = [f"{'phase':<24}{'seconds':>10}{'share':>8}"]
        for name, secs in sorted(self.phases.items(), key=lambda kv: -kv[1]):
            share = secs / total if total > 0 else 0.0
            lines.append(f"{name:<24}{secs:>10.3f}{share:>7.1%}")
        lines.append(f"{'total':<24}{total:>10.3f}")
        return "\n".join(lines)

    # ------------------------------------------------------ chrome export
    def chrome_events(self) -> list[dict]:
        """Complete ("ph": "X") trace events, µs timestamps, one tid per
        recording thread — the list ``write_bench_manifest`` embeds."""
        tids: dict[str, int] = {}
        out = []
        with self._lock:
            events = [dict(ev) for ev in self._events]
        for ev in sorted(events, key=lambda e: e["start"]):
            tid = tids.setdefault(ev["thread"], len(tids))
            entry = {
                "name": ev["name"],
                "ph": "X",
                "ts": (self._t0_wall + ev["start"]) * 1e6,
                "dur": ev["duration"] * 1e6,
                "pid": os.getpid(),
                "tid": tid,
            }
            args = dict(ev.get("args") or {})
            if ev.get("parent") is not None:
                args["parent_span"] = ev["parent"]
            args["span"] = ev["id"]
            entry["args"] = args
            out.append(entry)
        return out

    def to_chrome_trace(self) -> dict:
        """The chrome://tracing / Perfetto JSON object (thread-name
        metadata rows + the complete events)."""
        events = self.chrome_events()
        with self._lock:
            raw = [dict(ev) for ev in self._events]
        tids: dict[str, int] = {}
        for ev in sorted(raw, key=lambda e: e["start"]):
            tids.setdefault(ev["thread"], len(tids))
        meta = [
            {
                "name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid, "args": {"name": thread},
            }
            for thread, tid in sorted(tids.items(), key=lambda kv: kv[1])
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path) -> Path:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(self.to_chrome_trace()) + "\n")
        return p
