"""Process-wide metrics registry with Prometheus text exposition.

The serving subsystem already counts everything that matters — executable-
cache hits/misses/evictions and compile-seconds-saved
(``serving/cache.py``), coalescer cohort sizes and queue depth
(``serving/service.py``), async events and staleness (``parallel/
events.py`` via the telemetry health block), phase timers — but each
counter lives in its own object with its own ad-hoc ``stats()`` dict.
This module is the one place they all land so one scrape sees the whole
process: a small counter/gauge/histogram registry rendered in the
Prometheus text exposition format (v0.0.4) at the daemon's ``/metrics``
endpoint and dumpable via ``Simulator.metrics_text()``.

Design constraints (and why, not how):

- **Consistent snapshots.** A scrape mid-run must never observe a torn
  histogram (bucket counts that do not sum to ``_count``, or a ``_sum``
  from a different moment than the buckets). Every mutation AND every
  read of a metric family goes through the registry's one lock;
  ``render()``/``snapshot()`` copy all values under it, so the exposition
  is a point-in-time cut of the whole registry (tests hammer observes
  from threads while scraping and assert the invariant).
- **Get-or-create.** ``counter(name)`` returns the existing family when
  one is registered — instrumented modules (cache, service) can be
  constructed many times per process (tests, scoped caches) without
  duplicate-registration errors; their increments accumulate into the
  same family.
- **Callback gauges.** Values that are someone else's source of truth
  (queue depth, cache entry count) register a read callback instead of
  pushing on every change — the registry polls them at scrape time, so
  they can never go stale or drift from the owner.
- **Stdlib only** (the serving daemon's constraint), and jax-free at
  import time like ``config.py``/``telemetry.py``.

Metric names follow the Prometheus conventions: ``dopt_`` prefix,
``_total`` suffix on counters, base-unit names (seconds, bytes).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

# Default histogram buckets: latency-ish spread (seconds) that also works
# for small counts (cohort sizes, staleness). Families can override.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_VALID_KINDS = ("counter", "gauge", "histogram")


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _format_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    # Prometheus wants plain floats; integers print without the trailing
    # '.0' noise so counter series stay readable.
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Family:
    """One metric family (name + help + kind) with per-label-set values.

    All mutation happens under the owning registry's lock — the family
    itself has none; it is never shared across registries.
    """

    def __init__(self, registry, name, help_text, kind, buckets=None):
        self._registry = registry
        self.name = name
        self.help = help_text
        self.kind = kind
        self.buckets = tuple(buckets) if buckets else DEFAULT_BUCKETS
        # label-key -> float (counter/gauge) or
        # label-key -> [bucket_counts list, sum, count] (histogram)
        self._values: dict = {}
        self._callback: Optional[Callable[[], float]] = None

    # ------------------------------------------------------------ mutation
    def inc(self, amount: float = 1.0, **labels) -> None:
        if self.kind != "counter":
            raise TypeError(f"{self.name} is a {self.kind}, not a counter")
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def set(self, value: float, **labels) -> None:
        if self.kind != "gauge":
            raise TypeError(f"{self.name} is a {self.kind}, not a gauge")
        key = _label_key(labels)
        with self._registry._lock:
            self._values[key] = float(value)

    def reset(self) -> None:
        """Drop every labeled series in this family (gauges only).

        For families whose label universe is run-scoped — e.g. the
        worker-mesh per-device series — a new run must replace the old
        set wholesale, or a smaller mesh leaves stale device labels
        exporting a topology that is no longer running.
        """
        if self.kind != "gauge":
            raise TypeError(f"{self.name} is a {self.kind}, not a gauge")
        with self._registry._lock:
            self._values.clear()

    def replace(self, series) -> None:
        """Atomically replace EVERY labeled series in this family (gauges
        only) with ``series`` — an iterable of ``(labels_dict, value)``
        pairs.

        ``reset()`` + per-series ``set()`` is two-plus lock acquisitions:
        a scrape landing between them observes a torn (empty or partial)
        family. For run-scoped label universes that are republished
        wholesale every poll — the fleet autoscaler's per-worker liveness
        series is the motivating case — this swaps the whole set under
        ONE lock acquisition, so a scale-down can never leave a stale
        worker label exporting a topology that is no longer running, and
        no scrape ever sees the family half-published.
        """
        if self.kind != "gauge":
            raise TypeError(f"{self.name} is a {self.kind}, not a gauge")
        new_values = {
            _label_key(labels): float(value) for labels, value in series
        }
        with self._registry._lock:
            self._values = new_values

    def observe(self, value: float, **labels) -> None:
        if self.kind != "histogram":
            raise TypeError(f"{self.name} is a {self.kind}, not a histogram")
        key = _label_key(labels)
        v = float(value)
        with self._registry._lock:
            cell = self._values.get(key)
            if cell is None:
                cell = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._values[key] = cell
            counts, _, _ = cell
            for i, le in enumerate(self.buckets):
                if v <= le:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1  # +Inf bucket
            cell[1] += v
            cell[2] += 1

    def observe_many(self, values: Sequence[float], **labels) -> None:
        """Bulk-observe under ONE lock acquisition (e.g. a finished run's
        whole staleness series) — cheaper and atomically visible.

        Bucketization is vectorized when numpy is importable: the async
        progress path bulk-observes each chunk's whole staleness slice
        per heartbeat, and the per-value Python loop was a measurable
        slice of the ISSUE-10 async heartbeat overhead (the registry
        itself stays stdlib-only — numpy is an optional fast path)."""
        if self.kind != "histogram":
            raise TypeError(f"{self.name} is a {self.kind}, not a histogram")
        key = _label_key(labels)
        try:
            import numpy as np

            vals = np.asarray(values, dtype=float)
            # searchsorted(..., 'left') returns the first bucket whose
            # upper edge is >= v — exactly the scalar path's `v <= le`
            # rule; values past the last edge land in +Inf.
            idx = np.searchsorted(
                np.asarray(self.buckets, dtype=float), vals, side="left"
            )
            binned = np.bincount(idx, minlength=len(self.buckets) + 1)
            total, n = float(vals.sum()), int(vals.size)
            with self._registry._lock:
                cell = self._values.get(key)
                if cell is None:
                    cell = [[0] * (len(self.buckets) + 1), 0.0, 0]
                    self._values[key] = cell
                counts = cell[0]
                for i, c in enumerate(binned):
                    counts[i] += int(c)
                cell[1] += total
                cell[2] += n
            return
        except ImportError:  # stdlib fallback: the original scalar loop
            pass
        with self._registry._lock:
            cell = self._values.get(key)
            if cell is None:
                cell = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._values[key] = cell
            counts = cell[0]
            for value in values:
                v = float(value)
                for i, le in enumerate(self.buckets):
                    if v <= le:
                        counts[i] += 1
                        break
                else:
                    counts[-1] += 1
                cell[1] += v
                cell[2] += 1

    # ------------------------------------------------------------- reading
    def value(self, **labels) -> float:
        """Current scalar value (counter/gauge) — tests and status blocks."""
        key = _label_key(labels)
        with self._registry._lock:
            if self.kind == "histogram":
                cell = self._values.get(key)
                return float(cell[2]) if cell else 0.0
            if self._callback is not None:
                return float(self._callback())
            return float(self._values.get(key, 0.0))


class MetricsRegistry:
    """A set of metric families sharing one lock (see module docstring)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}

    # ----------------------------------------------------------- families
    def _family(self, name, help_text, kind, buckets=None) -> _Family:
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}, "
                        f"cannot re-register as {kind}"
                    )
                return fam
            fam = _Family(self, name, help_text, kind, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "") -> _Family:
        return self._family(name, help_text, "counter")

    def gauge(self, name: str, help_text: str = "") -> _Family:
        return self._family(name, help_text, "gauge")

    def histogram(
        self, name: str, help_text: str = "", buckets=None
    ) -> _Family:
        return self._family(name, help_text, "histogram", buckets)

    def gauge_fn(
        self, name: str, help_text: str, fn: Callable[[], float]
    ) -> _Family:
        """A gauge whose value is read from ``fn`` at scrape time.

        Re-registering REPLACES the callback: the newest owner (e.g. the
        most recently constructed service) is the live source of truth.
        """
        fam = self._family(name, help_text, "gauge")
        with self._lock:
            fam._callback = fn
        return fam

    # ------------------------------------------------------------ reading
    def snapshot(self) -> dict:
        """Point-in-time copy of every family (JSON-safe), taken under the
        registry lock — the no-torn-histogram guarantee."""
        with self._lock:
            out = {}
            for name, fam in sorted(self._families.items()):
                if fam.kind == "histogram":
                    out[name] = {
                        "kind": fam.kind,
                        "buckets": list(fam.buckets),
                        "series": {
                            _format_labels(k) or "": {
                                "bucket_counts": list(cell[0]),
                                "sum": cell[1],
                                "count": cell[2],
                            }
                            for k, cell in fam._values.items()
                        },
                    }
                else:
                    values = dict(fam._values)
                    if fam._callback is not None:
                        try:
                            values[()] = float(fam._callback())
                        except Exception:
                            values.setdefault((), 0.0)
                    out[name] = {
                        "kind": fam.kind,
                        "series": {
                            _format_labels(k) or "": v
                            for k, v in values.items()
                        },
                    }
            return out

    def render(self) -> str:
        """Prometheus text exposition (v0.0.4) of the whole registry —
        one consistent cut (see ``snapshot``)."""
        lines: list[str] = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                if fam.help:
                    lines.append(f"# HELP {name} {fam.help}")
                lines.append(f"# TYPE {name} {fam.kind}")
                if fam.kind == "histogram":
                    for key, cell in sorted(fam._values.items()):
                        counts, total, count = cell
                        cum = 0
                        for i, le in enumerate(fam.buckets):
                            cum += counts[i]
                            lk = _format_labels(key + (("le", _fmt(le)),))
                            lines.append(f"{name}_bucket{lk} {cum}")
                        cum += counts[-1]
                        lk = _format_labels(key + (("le", "+Inf"),))
                        lines.append(f"{name}_bucket{lk} {cum}")
                        ls = _format_labels(key)
                        lines.append(f"{name}_sum{ls} {_fmt(total)}")
                        lines.append(f"{name}_count{ls} {count}")
                    if not fam._values:
                        # An empty histogram still exposes its full zero
                        # shape: bare _sum/_count without _bucket lines
                        # is invalid exposition ("histogram has no
                        # buckets") and strict scrapers reject the whole
                        # payload — exactly in the cold-daemon state.
                        for le in fam.buckets:
                            lk = _format_labels((("le", _fmt(le)),))
                            lines.append(f"{name}_bucket{lk} 0")
                        lk = _format_labels((("le", "+Inf"),))
                        lines.append(f"{name}_bucket{lk} 0")
                        lines.append(f"{name}_sum 0")
                        lines.append(f"{name}_count 0")
                    continue
                values = dict(fam._values)
                if fam._callback is not None:
                    try:
                        values[()] = float(fam._callback())
                    except Exception:
                        values.setdefault((), 0.0)
                if not values:
                    values[()] = 0.0
                for key, v in sorted(values.items()):
                    lines.append(f"{name}{_format_labels(key)} {_fmt(v)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every family (tests only — production counters are
        monotone for the whole process lifetime)."""
        with self._lock:
            self._families.clear()


# ------------------------------------------------------ process-wide default

_process_registry = MetricsRegistry()


def metrics_registry() -> MetricsRegistry:
    """The process-wide default registry: what the daemon's ``/metrics``
    scrapes and ``Simulator.metrics_text()`` dumps. Instrumented modules
    (``serving/cache.py``, ``serving/service.py``, the progress layer)
    feed it by default; tests may construct scoped ``MetricsRegistry``
    instances instead."""
    return _process_registry


def observe_phases(
    phases: dict, registry: Optional[MetricsRegistry] = None
) -> None:
    """Fold a {phase: seconds} accounting delta into the registry's
    ``dopt_phase_seconds_total`` counter family — the bridge from the
    span/phase layer to the scrape surface."""
    reg = registry if registry is not None else metrics_registry()
    fam = reg.counter(
        "dopt_phase_seconds_total",
        "Wall-clock seconds spent per named phase (data_gen, oracle, "
        "compile, run, ...)",
    )
    for name, secs in phases.items():
        if secs > 0:
            fam.inc(float(secs), phase=str(name))


def now() -> float:
    return time.time()
