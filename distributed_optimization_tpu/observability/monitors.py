"""Anomaly sentinel: online run-health monitors + incident forensics.

The repo can stream per-chunk heartbeats (``observability/progress.py``)
and record in-scan trace buffers (``telemetry.TRACE_FIELDS``), but until
ISSUE-13 nothing *watched* those signals: a diverging cell — an
over-budget ALIE attack (Baruch et al. '19), a partitioned realized-B̂
window violating Koloskova et al. '20's B-connectivity assumption, an
async staleness blowup past the bounded-staleness regime (Lian et al.
'17) — burned its full horizon and was only discovered in the final
report. This module closes the loop:

- **Detectors** are small stateful observers fed the SAME
  ``ProgressEvent`` heartbeats the progress streams carry (and, for the
  trace-derived signals, the flight-recorder buffers after the run).
  Each fires AT MOST ONCE per run (a latch — the incident records the
  onset; re-firing every subsequent heartbeat would be noise) and emits
  a structured ``Anomaly`` carrying the detector name, severity, onset
  iteration, and the evidence window it fired on.
- **MonitorBank** owns a run's detector set, collects anomalies,
  increments the ``dopt_anomaly_*`` families in the process metrics
  registry, and answers the early-halt policy question
  (``halt_on={'fatal','never'}``) the backends consult at chunk
  boundaries. Observation NEVER perturbs the run: monitors ride the
  segmented-scan progress machinery, whose off==on bitwise contract is
  already pinned (tests/test_observatory.py), and a monitor that raises
  is contained like any progress callback.
- **Incident forensics**: ``build_incident`` assembles a
  schema-versioned bundle per anomaly — config + structural hash, the
  evidence window, and the fault/attack context around the onset (which
  nodes were down, which Byzantine workers were active and whether the
  attack exceeded the robust budget, the realized B̂ over the onset
  window — all rebuilt host-side from the (seed, horizon)-pure timeline,
  the ``realized_bhat`` convention). Bundles serialize as JSONL next to
  RunTrace manifests (``observatory incidents`` lists them;
  ``observatory list --with-incidents`` joins them onto the run index).

Detection thresholds are heuristics, not theorems — they are constructor
knobs with conservative defaults, and every anomaly carries its evidence
window so a consumer can re-judge the call. The one hard rule: halting
is opt-in (``halt_on='fatal'``), stops only at a chunk boundary the
progress machinery already syncs at, and the executed prefix stays
bitwise the full run's prefix (the continuation contract).
"""

from __future__ import annotations

import dataclasses
import json
import math
from collections import deque
from pathlib import Path
from typing import Any, Iterable, Optional

import numpy as np

# Incident-bundle schema version (independent of the RunTrace schema:
# incidents are their own artifact kind). Bump on field changes;
# ``read_incidents`` rejects versions it does not know.
INCIDENT_SCHEMA_VERSION = 1

INCIDENT_KEYS = (
    "schema_version", "kind", "label", "detector", "severity",
    "onset_iteration", "message", "config", "config_hash",
    "structural_hash", "evidence", "context", "provenance",
)

# Severity scale, least to most severe. ``halt_on='fatal'`` halts only on
# the top tier; 'warn' anomalies are recorded and surfaced but never stop
# a run.
SEVERITIES = ("info", "warn", "fatal")

HALT_POLICIES = ("never", "fatal")


def severity_rank(severity: str) -> int:
    """Total order over severities (tests pin fatal > warn > info)."""
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        raise ValueError(
            f"unknown severity {severity!r}; expected one of {SEVERITIES}"
        )


@dataclasses.dataclass
class Anomaly:
    """One detector firing: what, how bad, when, and on what evidence."""

    detector: str
    severity: str
    onset_iteration: int
    message: str
    # The observation window the detector fired on: small JSON-safe
    # arrays keyed by signal name, each paired with its iterations.
    evidence: dict
    # False for advisory firings that must NOT latch their detector:
    # connectivity_loss's B̂-ceiling warn keeps watching for the fatal
    # disconnection it exists to catch (a latched warn would mask it).
    latches: bool = True

    def to_dict(self) -> dict:
        return {
            "detector": self.detector,
            "severity": self.severity,
            "onset_iteration": int(self.onset_iteration),
            "message": self.message,
            "evidence": self.evidence,
        }


def _event_gap(ev) -> Optional[float]:
    """The gap a detector should judge: the worst replica's when the
    heartbeat carries per-replica gaps (a cohort heartbeat's mean would
    hide one diverging replica behind R-1 healthy ones)."""
    gaps = [float(ev.gap)] if ev.gap is not None else []
    per_replica = getattr(ev, "gap_per_replica", None)
    if per_replica:
        gaps.extend(float(g) for g in per_replica)
    if not gaps:
        return None
    finite = [g for g in gaps if math.isfinite(g)]
    return max(finite) if len(finite) == len(gaps) else float("nan")


class Detector:
    """Base class: a named, severity-tagged, fire-once observer.

    ``observe(ev)`` consumes one ``ProgressEvent`` heartbeat;
    ``scan_trace(trace, eval_iterations)`` consumes the flight recorder's
    post-run buffers (both optional per subclass). Both return the
    ``Anomaly`` on the firing call and None otherwise; after firing the
    detector latches and ignores further input.
    """

    name = "detector"
    severity = "warn"

    def __init__(self):
        self.fired: Optional[Anomaly] = None

    # -- subclass hooks ------------------------------------------------
    def _observe(self, ev) -> Optional[Anomaly]:
        return None

    def _scan_trace(self, trace, eval_iterations) -> Optional[Anomaly]:
        return None

    # -- public API ----------------------------------------------------
    def observe(self, ev) -> Optional[Anomaly]:
        if self.fired is not None:
            return None
        anomaly = self._observe(ev)
        if anomaly is not None and anomaly.latches:
            self.fired = anomaly
        return anomaly

    def scan_trace(self, trace, eval_iterations) -> Optional[Anomaly]:
        if self.fired is not None or trace is None:
            return None
        anomaly = self._scan_trace(trace, eval_iterations)
        if anomaly is not None and anomaly.latches:
            self.fired = anomaly
        return anomaly

    def _anomaly(self, onset: int, message: str, evidence: dict) -> Anomaly:
        return Anomaly(
            detector=self.name, severity=self.severity,
            onset_iteration=int(onset), message=message, evidence=evidence,
        )


class DivergenceDetector(Detector):
    """Suboptimality gap rising over ``window`` consecutive heartbeats,
    or breaching ``rel_ceiling`` × the best gap seen (or an absolute
    ``ceiling``). Both arms additionally require the gap to be WORSE
    than the first heartbeat's — a converged run's floating-point noise
    around a ~0 gap can satisfy any relative ratio, but only a genuinely
    degrading run climbs back above where it started. The onset is the
    FIRST heartbeat of the rising streak / the breaching heartbeat — the
    moment degradation began, not the moment the evidence became
    conclusive."""

    name = "divergence"
    severity = "fatal"

    def __init__(self, window: int = 3, rel_ceiling: float = 1e3,
                 ceiling: float = float("inf")):
        super().__init__()
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.rel_ceiling = float(rel_ceiling)
        self.ceiling = float(ceiling)
        self._obs: deque = deque(maxlen=self.window + 1)
        self._best: Optional[float] = None
        self._first: Optional[float] = None

    def _evidence(self) -> dict:
        return {
            "iterations": [int(t) for t, _ in self._obs],
            "gap": [float(g) for _, g in self._obs],
            "best_gap": self._best,
            "first_gap": self._first,
        }

    def _observe(self, ev):
        gap = _event_gap(ev)
        if gap is None or not math.isfinite(gap):
            return None  # the non-finite sentinel owns that case
        if self._first is None:
            self._first = gap
        self._obs.append((ev.iteration, gap))
        if self._best is None or gap < self._best:
            self._best = gap
        degrading = gap > self._first
        if gap > self.ceiling or (
            degrading and self._best > 0
            and gap > self.rel_ceiling * self._best
        ):
            return self._anomaly(
                ev.iteration,
                f"gap {gap:.3e} breached the divergence ceiling (abs "
                f"{self.ceiling:.3g} / {self.rel_ceiling:.3g}x best "
                f"{self._best:.3e})",
                self._evidence(),
            )
        if degrading and len(self._obs) == self.window + 1:
            pairs = list(self._obs)
            rising = all(
                pairs[i + 1][1] > pairs[i][1] for i in range(self.window)
            )
            if rising:
                return self._anomaly(
                    pairs[1][0],
                    f"gap rose over {self.window} consecutive heartbeats "
                    f"({pairs[0][1]:.3e} -> {pairs[-1][1]:.3e})",
                    self._evidence(),
                )
        return None


class ConsensusStallDetector(Detector):
    """Consensus error failing to decrease for ``window`` consecutive
    heartbeats while still above ``floor`` — the gossip averaging has
    stopped making progress but the network is not yet in consensus
    (disconnection, screening pathologies, a too-weak mixing rate).
    A converged run's flat consensus sits below the floor and never
    fires."""

    name = "consensus_stall"
    severity = "warn"

    def __init__(self, window: int = 4, floor: float = 1e-6):
        super().__init__()
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.floor = float(floor)
        self._obs: deque = deque(maxlen=self.window + 1)

    def _observe(self, ev):
        cons = ev.consensus
        if cons is None or not math.isfinite(float(cons)):
            return None
        self._obs.append((ev.iteration, float(cons)))
        if len(self._obs) < self.window + 1:
            return None
        pairs = list(self._obs)
        stalled = all(
            pairs[i + 1][1] >= pairs[i][1] and pairs[i + 1][1] > self.floor
            for i in range(self.window)
        )
        if stalled:
            return self._anomaly(
                pairs[1][0],
                f"consensus error stalled above {self.floor:.1e} for "
                f"{self.window} heartbeats ({pairs[0][1]:.3e} -> "
                f"{pairs[-1][1]:.3e})",
                {
                    "iterations": [int(t) for t, _ in pairs],
                    "consensus": [c for _, c in pairs],
                    "floor": self.floor,
                },
            )
        return None


class NonFiniteDetector(Detector):
    """NaN/Inf sentinels: a non-finite gap/consensus in a heartbeat, or a
    positive non-finite state-leaf count in the flight-recorder trace.
    Always fatal — nothing downstream of a NaN is meaningful."""

    name = "non_finite"
    severity = "fatal"

    def _observe(self, ev):
        bad = {}
        gap = _event_gap(ev)
        if gap is not None and not math.isfinite(gap):
            bad["gap"] = float(gap)
        if ev.consensus is not None and not math.isfinite(
            float(ev.consensus)
        ):
            bad["consensus"] = float(ev.consensus)
        if not bad:
            return None
        return self._anomaly(
            ev.iteration,
            f"non-finite metric(s) at iteration {ev.iteration}: "
            f"{sorted(bad)}",
            {"iteration": int(ev.iteration), **bad},
        )

    def _scan_trace(self, trace, eval_iterations):
        counts = np.asarray(trace.get("nonfinite", []), dtype=np.float64)
        if counts.size == 0:
            return None
        bad = np.flatnonzero(counts > 0)
        if bad.size == 0:
            return None
        onset_row = int(bad[0])
        iters = np.asarray(eval_iterations)
        onset = int(iters[onset_row]) if iters.size > onset_row else onset_row
        return self._anomaly(
            onset,
            f"{counts[onset_row]:.0f} non-finite state entries at "
            f"iteration {onset} (trace sentinel)",
            {
                "iterations": iters[bad][:8].astype(int).tolist(),
                "nonfinite_counts": counts[bad][:8].tolist(),
            },
        )


class ConnectivityLossDetector(Detector):
    """Realized windowed-connectivity B̂ violations: the live-B̂ heartbeat
    reporting a DISCONNECTED prefix union (no finite B exists — the
    Koloskova '20 B-connectivity assumption is void, fatal), or B̂
    exceeding ``bhat_ceiling`` (connectivity still exists but is weaker
    than the run budgeted for, warn)."""

    name = "connectivity_loss"
    severity = "fatal"  # disconnection; a ceiling breach downgrades to warn

    def __init__(self, bhat_ceiling: Optional[float] = None):
        super().__init__()
        self.bhat_ceiling = (
            float(bhat_ceiling) if bhat_ceiling is not None else None
        )
        self._seen: list = []  # (iteration, bhat) history, bounded below
        self._warned = False   # the ceiling warn fires once, non-latching

    def _observe(self, ev):
        disconnected = bool((ev.extra or {}).get("bhat_disconnected"))
        if ev.bhat is None and not disconnected:
            return None  # live B̂ not applicable on this path
        if len(self._seen) >= 64:
            del self._seen[0]
        self._seen.append(
            (int(ev.iteration), None if disconnected else int(ev.bhat))
        )
        evidence = {
            "iterations": [t for t, _ in self._seen],
            "bhat": [b for _, b in self._seen],
        }
        if disconnected:
            return self._anomaly(
                ev.iteration,
                f"realized graph union over [0, {ev.iteration}) is "
                "disconnected: no finite B-connectivity window exists",
                evidence,
            )
        if (
            self.bhat_ceiling is not None and ev.bhat > self.bhat_ceiling
            and not self._warned
        ):
            self._warned = True
            anomaly = self._anomaly(
                ev.iteration,
                f"realized B-hat {ev.bhat} exceeded the ceiling "
                f"{self.bhat_ceiling:.0f}",
                {**evidence, "ceiling": self.bhat_ceiling},
            )
            anomaly.severity = "warn"
            # Non-latching: a ceiling breach must not blind the detector
            # to a later genuine disconnection (the fatal case the
            # halt policy exists for).
            anomaly.latches = False
            return anomaly
        return None


class StalenessBlowupDetector(Detector):
    """Asynchronous staleness escaping the bounded regime: the realized
    p90 staleness over the executed window exceeding ``ceiling`` writes.
    AD-PSGD's convergence story assumes bounded staleness (Lian et al.
    '17); a blowup means the schedule's tail is starving rows."""

    name = "staleness_blowup"
    severity = "warn"

    def __init__(self, ceiling: float = 64.0):
        super().__init__()
        self.ceiling = float(ceiling)

    def _observe(self, ev):
        p90 = ev.staleness_p90
        if p90 is None or not math.isfinite(float(p90)):
            return None
        if float(p90) <= self.ceiling:
            return None
        return self._anomaly(
            ev.iteration,
            f"async staleness p90 {float(p90):.0f} exceeded the ceiling "
            f"{self.ceiling:.0f} writes (p50 {float(ev.staleness_p50):.0f}"
            f", max {float(ev.staleness_max):.0f})",
            {
                "iteration": int(ev.iteration),
                "staleness_p50": float(ev.staleness_p50),
                "staleness_p90": float(p90),
                "staleness_max": float(ev.staleness_max),
                "ceiling": self.ceiling,
            },
        )


class ScreeningSaturationDetector(Detector):
    """Robust screening trimming ~everything: the flight recorder's
    ``clip_frac`` activity (fraction of received closed-neighborhood
    messages screened out) at or above ``threshold`` for ``window``
    consecutive eval rows. A healthy trimmed-mean run screens a fixed
    2b/(deg+1) slice; near-total screening means the rule is rejecting
    honest traffic wholesale (an over-budget attack, or a radius/budget
    misconfiguration) and the 'aggregate' is mostly self-loops."""

    name = "screening_saturation"
    severity = "warn"

    def __init__(self, threshold: float = 0.95, window: int = 2):
        super().__init__()
        if not 0.0 < threshold <= 1.0:
            raise ValueError(f"threshold must be in (0, 1], got {threshold}")
        self.threshold = float(threshold)
        self.window = max(int(window), 1)

    def _scan_trace(self, trace, eval_iterations):
        frac = np.asarray(trace.get("clip_frac", []), dtype=np.float64)
        if frac.size < self.window:
            return None
        saturated = frac >= self.threshold
        run = 0
        for row, sat in enumerate(saturated):
            run = run + 1 if sat else 0
            if run == self.window:
                onset_row = row - self.window + 1
                iters = np.asarray(eval_iterations)
                onset = (
                    int(iters[onset_row]) if iters.size > onset_row
                    else onset_row
                )
                lo = max(onset_row - 1, 0)
                hi = min(row + 2, frac.size)
                return self._anomaly(
                    onset,
                    f"robust screening trimmed >= {self.threshold:.0%} of "
                    f"received messages for {self.window} consecutive "
                    f"eval windows from iteration {onset}",
                    {
                        "iterations": iters[lo:hi].astype(int).tolist(),
                        "clip_frac": frac[lo:hi].tolist(),
                        "threshold": self.threshold,
                    },
                )
        return None


def default_detectors(config, **overrides) -> list:
    """The detector set a config's run should watch — every signal the
    config can actually emit (an async run gets the staleness watcher, a
    robust-aggregation run the saturation watcher, ...), so a bank never
    carries detectors that can only stay silent. ``overrides`` replace a
    detector's constructor kwargs by detector name, e.g.
    ``divergence={'window': 2}``."""

    def kw(name):
        return dict(overrides.get(name, {}))

    dets: list = [
        DivergenceDetector(**kw("divergence")),
        NonFiniteDetector(**kw("non_finite")),
        ConsensusStallDetector(**kw("consensus_stall")),
    ]
    faults_active = (
        config.edge_drop_prob > 0.0
        or config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.participation_rate < 1.0
    )
    if faults_active and config.gossip_schedule == "synchronous":
        dets.append(ConnectivityLossDetector(**kw("connectivity_loss")))
    if getattr(config, "execution", "sync") == "async":
        dets.append(StalenessBlowupDetector(**kw("staleness_blowup")))
    if config.aggregation != "gossip" and config.robust_b > 0:
        dets.append(
            ScreeningSaturationDetector(**kw("screening_saturation"))
        )
    return dets


def _anomaly_metrics():
    from distributed_optimization_tpu.observability.metrics_registry import (
        metrics_registry,
    )

    reg = metrics_registry()
    return (
        reg.counter(
            "dopt_anomaly_firings_total",
            "Anomaly-detector firings by detector and severity",
        ),
        reg.counter(
            "dopt_anomaly_halts_total",
            "Runs halted early by the halt_on=fatal policy",
        ),
        reg.gauge(
            "dopt_anomaly_last_onset_iteration",
            "Onset iteration of the most recent firing per detector",
        ),
    )


class MonitorBank:
    """One run's detector set + the early-halt policy (module docstring).

    Feed it heartbeats via ``observe`` (the backends compose it into the
    progress callback chain) and, for trace-derived detectors, the
    flight-recorder buffers via ``scan_trace`` after the run. The
    backends consult ``should_halt()`` at chunk boundaries and call
    ``note_halt(iteration)`` when they actually stop.
    """

    def __init__(self, config, detectors: Optional[list] = None,
                 halt_on: str = "never", label: str = ""):
        if halt_on not in HALT_POLICIES:
            raise ValueError(
                f"halt_on must be one of {HALT_POLICIES}, got {halt_on!r}"
            )
        self.config = config
        self.detectors = (
            list(detectors) if detectors is not None
            else default_detectors(config)
        )
        self.halt_on = halt_on
        self.label = label
        self.anomalies: list[Anomaly] = []
        self.halted_at: Optional[int] = None
        self._firings, self._halts, self._last_onset = _anomaly_metrics()

    # ------------------------------------------------------------ feeding
    def observe(self, ev) -> list[Anomaly]:
        """Feed one heartbeat to every detector; returns the NEWLY fired
        anomalies (empty on a healthy beat). Never raises: a broken
        detector is contained like a broken progress callback."""
        fired: list[Anomaly] = []
        for det in self.detectors:
            try:
                anomaly = det.observe(ev)
            except Exception:
                from distributed_optimization_tpu.log import get_logger

                get_logger("monitors").exception(
                    "detector %s failed on a heartbeat; continuing", det.name
                )
                continue
            if anomaly is not None:
                fired.append(anomaly)
        self._record(fired)
        return fired

    def scan_trace(self, trace, eval_iterations) -> list[Anomaly]:
        """Feed the post-run flight-recorder buffers (telemetry runs
        only) to the trace-capable detectors."""
        fired: list[Anomaly] = []
        for det in self.detectors:
            try:
                anomaly = det.scan_trace(trace, eval_iterations)
            except Exception:
                from distributed_optimization_tpu.log import get_logger

                get_logger("monitors").exception(
                    "detector %s failed on the trace scan; continuing",
                    det.name,
                )
                continue
            if anomaly is not None:
                fired.append(anomaly)
        self._record(fired)
        return fired

    def _record(self, fired: Iterable[Anomaly]) -> None:
        for anomaly in fired:
            self.anomalies.append(anomaly)
            self._firings.inc(
                detector=anomaly.detector, severity=anomaly.severity,
            )
            self._last_onset.set(
                float(anomaly.onset_iteration), detector=anomaly.detector,
            )

    # ------------------------------------------------------------ policy
    def has_fatal(self) -> bool:
        return any(a.severity == "fatal" for a in self.anomalies)

    def should_halt(self) -> bool:
        """The backends' chunk-boundary question: stop now?"""
        return self.halt_on == "fatal" and self.has_fatal()

    def note_halt(self, iteration: int) -> None:
        """Called by the backend when it actually stops the run."""
        if self.halted_at is None:
            self.halted_at = int(iteration)
            self._halts.inc()

    # ----------------------------------------------------------- surfaces
    def summary(self) -> dict:
        """JSON-safe digest for health blocks / status polls, anomalies
        most-severe first."""
        ordered = sorted(
            self.anomalies,
            key=lambda a: (-severity_rank(a.severity), a.onset_iteration),
        )
        return {
            "count": len(self.anomalies),
            "fatal": sum(
                1 for a in self.anomalies if a.severity == "fatal"
            ),
            "halted_at": self.halted_at,
            "halt_on": self.halt_on,
            "anomalies": [a.to_dict() for a in ordered],
        }

    def incidents(self, label: Optional[str] = None) -> list[dict]:
        """One forensic bundle per recorded anomaly (``build_incident``)."""
        return [
            build_incident(
                self.config, a,
                label=label if label is not None else self.label,
            )
            for a in self.anomalies
        ]


# ------------------------------------------------------ incident forensics


def fault_context(config, onset: int, *, window: Optional[int] = None,
                  max_cells: int = 200_000) -> dict:
    """The operational facts around an anomaly's onset, rebuilt host-side
    from the config's (seed, horizon)-pure processes — bitwise what the
    backend executed (the ``parallel/faults.py`` purity contract):

    - attack block: the Byzantine set (seed-deterministic node indices),
      payload, and whether the attack exceeds the robust budget
      (``n_byzantine > robust_b`` is exactly the f > b breakdown regime);
    - fault block: which nodes were down at the onset round, the mean
      realized edge-up fraction over the onset window, and the realized
      B̂ of that window (None when even its union is disconnected);
    - async block: the onset-window staleness facts for event schedules.

    ``window`` is the half-width in iterations (default: 4 eval windows).
    Cost-capped like ``realized_bhat``: past ``max_cells`` timeline cells
    the fault block records ``{"skipped": ...}`` instead of stalling the
    incident path on a giant rebuild.
    """
    from distributed_optimization_tpu.algorithms import get_algorithm

    onset = int(onset)
    if window is None:
        window = 4 * config.eval_every
    lo = max(onset - window, 0)
    hi = min(onset + window, config.n_iterations)
    context: dict[str, Any] = {"window": [int(lo), int(hi)]}

    if config.attack != "none":
        from distributed_optimization_tpu.parallel.adversary import (
            byzantine_mask,
        )

        mask = byzantine_mask(
            config.n_workers, config.n_byzantine, config.seed
        )
        block = {
            "attack": config.attack,
            "attack_scale": float(config.attack_scale),
            "n_byzantine": int(config.n_byzantine),
            "byzantine_nodes": np.flatnonzero(mask).astype(int).tolist(),
            "aggregation": config.aggregation,
            "robust_b": int(config.robust_b),
        }
        if config.aggregation != "gossip":
            # The f > b regime: more attackers than the per-neighborhood
            # budget the screening rule defends — the sharp breakdown
            # docs/perf/byzantine.json measures.
            block["over_budget"] = config.n_byzantine > config.robust_b
        context["attack"] = block

    from distributed_optimization_tpu.parallel.faults import (
        config_faults_active,
    )

    if (
        config_faults_active(config)
        and config.gossip_schedule == "synchronous"
        and getattr(config, "execution", "sync") != "async"
        and get_algorithm(config.algorithm).is_decentralized
    ):
        from distributed_optimization_tpu.parallel import build_topology
        from distributed_optimization_tpu.parallel.faults import (
            _edge_list,
            timeline_for_config,
            windowed_connectivity,
        )

        topo = build_topology(
            config.topology, config.n_workers,
            erdos_renyi_p=config.erdos_renyi_p,
            seed=config.resolved_topology_seed(),
            impl=config.resolved_topology_impl(),
            sampler=config.resolved_topology_sampler(),
        )
        n_edges = max(len(_edge_list(topo)), 1)
        if hi * n_edges > max_cells:
            context["faults"] = {
                "skipped": (
                    f"timeline rebuild to t={hi} over {n_edges} edges "
                    f"exceeds the {max_cells}-cell incident budget"
                ),
            }
        else:
            tl = timeline_for_config(config, topo, max(hi, 1))

            def view(arr):
                return None if arr is None else arr[lo:hi]

            tl_win = dataclasses.replace(
                tl, horizon=max(hi - lo, 1),
                edge_up=view(tl.edge_up), node_up=view(tl.node_up),
                rejoin=view(tl.rejoin), part_up=view(tl.part_up),
            )
            block = {
                "window_bhat": windowed_connectivity(tl_win, topo),
            }
            onset_row = min(onset, max(hi - 1, 0))
            up = np.ones(config.n_workers, dtype=np.float32)
            if tl.node_up is not None:
                up = up * tl.node_up[onset_row]
            if tl.part_up is not None:
                up = up * tl.part_up[onset_row]
            down = np.flatnonzero(up < 0.5)
            block["nodes_down_at_onset"] = down.astype(int).tolist()[:64]
            block["n_nodes_down_at_onset"] = int(down.size)
            if tl.edge_up is not None:
                block["edge_up_frac_window"] = float(
                    np.asarray(tl.edge_up[lo:hi], dtype=np.float64).mean()
                )
            context["faults"] = block

    if getattr(config, "execution", "sync") == "async":
        from distributed_optimization_tpu.backends.async_scan import (
            timeline_for,
        )

        _, tl = timeline_for(config)
        n = config.n_workers
        ev_lo, ev_hi = lo * n, max(hi * n, lo * n + 1)
        stale = np.asarray(
            tl.staleness[ev_lo:ev_hi], dtype=np.float64
        )
        if stale.size:
            block = {
                "latency_model": config.latency_model,
                "latency_tail": float(config.latency_tail),
                # Event-axis coordinates of the onset (ISSUE-17): one
                # round is N events, so the onset round's first event
                # index anchors the incident on the clock the backend
                # actually scanned.
                "onset_event": int(onset * n),
                "event_window": [int(ev_lo), int(min(ev_hi, len(tl.worker)))],
                "window_staleness_p50": float(np.percentile(stale, 50)),
                "window_staleness_p90": float(np.percentile(stale, 90)),
                "window_staleness_max": float(stale.max()),
            }
            if config_faults_active(config):
                # Event-realized fault forensics: which firings in the
                # onset window were in-flight losses (the stale gradient
                # evaporated with the crash) and which workers were down
                # at the onset round — host-rebuilt, bitwise the
                # realization the backend executed.
                from distributed_optimization_tpu.parallel import (
                    build_topology,
                )
                from distributed_optimization_tpu.parallel.events import (
                    realize_event_faults,
                )
                from distributed_optimization_tpu.parallel.faults import (
                    timeline_for_config,
                )

                topo = build_topology(
                    config.topology, config.n_workers,
                    erdos_renyi_p=config.erdos_renyi_p,
                    seed=config.resolved_topology_seed(),
                )
                ft = timeline_for_config(config, topo, tl.n_rounds)
                real = realize_event_faults(tl, ft)
                win_fire = real.fire[ev_lo:ev_hi]
                kk = tl.local_step.astype(np.int64)[ev_lo:ev_hi]
                win_worker = tl.worker[ev_lo:ev_hi].astype(np.int64)
                # Crash no-ops only (the EventFaultRealization
                # ``n_inflight_lost`` split): thinned events never had a
                # gradient in flight.
                win_up = (
                    ft.node_up[kk, win_worker]
                    if ft.node_up is not None
                    else np.ones(len(win_worker), dtype=bool)
                )
                lost = win_worker[~win_up]
                onset_row = min(onset, tl.n_rounds - 1)
                up = np.ones(n, dtype=bool)
                if ft.node_up is not None:
                    up &= ft.node_up[onset_row]
                if ft.part_up is not None:
                    up &= ft.part_up[onset_row]
                crashed = np.flatnonzero(~up)
                block["n_inflight_lost_window"] = int((~win_up).sum())
                block["inflight_lost_workers"] = sorted(
                    set(lost.tolist())
                )[:64]
                block["crashed_workers_at_onset"] = (
                    crashed.astype(int).tolist()[:64]
                )
                block["window_availability"] = (
                    float(win_fire.mean()) if win_fire.size else 1.0
                )
            context["async"] = block
    return context


def build_incident(config, anomaly: Anomaly, *, label: str = "",
                   remediation: Optional[dict] = None) -> dict:
    """One schema-versioned forensic bundle for a fired anomaly (module
    docstring): the anomaly facts, the producing config (+ content and
    serving-cohort structural hashes), the evidence window, the
    fault/attack context around the onset, and the environment
    provenance. Serialized as JSONL next to RunTrace manifests via
    ``write_incidents``.

    ``remediation``: optional structured block recording what the fleet's
    policy engine (``serving/fleet.py``) DID about this incident —
    ``{"policy", "outcome", "actions", ...}`` — so the forensic record
    carries detection AND response in one bundle. Readers that predate
    the fleet ignore the extra key (``read_incidents`` validates only
    kind + schema_version)."""
    from distributed_optimization_tpu.telemetry import (
        config_hash,
        provenance,
    )

    cd = config.to_dict()
    out = {
        "schema_version": INCIDENT_SCHEMA_VERSION,
        "kind": "incident",
        "label": label,
        "detector": anomaly.detector,
        "severity": anomaly.severity,
        "onset_iteration": int(anomaly.onset_iteration),
        "message": anomaly.message,
        "config": cd,
        "config_hash": config_hash(cd),
        "structural_hash": config.structural_hash(),
        "evidence": anomaly.evidence,
        "context": fault_context(config, anomaly.onset_iteration),
        "provenance": provenance(),
    }
    if remediation is not None:
        out["remediation"] = dict(remediation)
    return out


def incidents_path_for(manifest_path) -> Path:
    """The incident JSONL that rides next to a RunTrace manifest file:
    ``runs.jsonl`` → ``runs.incidents.jsonl``."""
    p = Path(manifest_path)
    stem = p.name[:-len(p.suffix)] if p.suffix else p.name
    return p.with_name(f"{stem}.incidents.jsonl")


def write_incidents(path, incidents: list[dict], *, append: bool = False,
                    ) -> Path:
    """Serialize incident bundles as strict-JSON JSONL (the telemetry
    non-finite sentinel convention: divergence evidence IS non-finite)."""
    from distributed_optimization_tpu.telemetry import _encode_nonfinite

    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    mode = "a" if append else "w"
    with open(p, mode) as f:
        for inc in incidents:
            f.write(
                json.dumps(
                    _encode_nonfinite(inc), sort_keys=True, allow_nan=False,
                )
                + "\n"
            )
    return p


def read_incidents(path) -> list[dict]:
    """Parse an incident JSONL file, validating the schema version."""
    from distributed_optimization_tpu.telemetry import _decode_nonfinite

    out = []
    for line in Path(path).read_text().splitlines():
        if not line.strip():
            continue
        blob = _decode_nonfinite(json.loads(line))
        if blob.get("kind") != "incident":
            raise ValueError(
                f"not an incident record: kind={blob.get('kind')!r}"
            )
        if blob.get("schema_version") != INCIDENT_SCHEMA_VERSION:
            raise ValueError(
                f"unsupported incident schema_version "
                f"{blob.get('schema_version')} (this build reads "
                f"v{INCIDENT_SCHEMA_VERSION})"
            )
        out.append(blob)
    return out
