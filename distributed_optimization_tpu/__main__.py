"""``python -m distributed_optimization_tpu`` entry point."""

from distributed_optimization_tpu.cli import main

raise SystemExit(main())
