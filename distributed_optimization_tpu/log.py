"""Package logging (ISSUE-5 satellite).

Library code must not ``print``: diagnostics go through the package logger
hierarchy ``distributed_optimization_tpu.*`` so applications can route or
silence them. The CLI maps ``--verbose``/``--quiet`` onto log levels via
``configure``; direct library users (tests, notebooks) get a stderr handler
at INFO on first use — the same visible behaviour the old ``print(...,
file=sys.stderr)`` calls had, now overridable.
"""

from __future__ import annotations

import logging
import sys

PACKAGE = "distributed_optimization_tpu"


class _TagFormatter(logging.Formatter):
    """``[simulator] message`` — the short tag the old prints used (the last
    dotted component of the logger name)."""

    def format(self, record: logging.LogRecord) -> str:
        tag = record.name.rsplit(".", 1)[-1]
        return f"[{tag}] {record.getMessage()}"


class _StderrHandler(logging.StreamHandler):
    """StreamHandler resolving ``sys.stderr`` at EMIT time, not creation —
    so stream redirection (pytest capsys, contextlib.redirect_stderr) sees
    the records, exactly as the old ``print(..., file=sys.stderr)`` did."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


def _ensure_handler() -> logging.Logger:
    root = logging.getLogger(PACKAGE)
    if not root.handlers:
        handler = _StderrHandler()
        handler.setFormatter(_TagFormatter())
        root.addHandler(handler)
        root.propagate = False
        if root.level == logging.NOTSET:
            root.setLevel(logging.INFO)
    return root


def get_logger(name: str | None = None) -> logging.Logger:
    """Logger under the package hierarchy: ``get_logger('simulator')`` →
    ``distributed_optimization_tpu.simulator`` (tagged ``[simulator]``)."""
    _ensure_handler()
    return logging.getLogger(PACKAGE if not name else f"{PACKAGE}.{name}")


def configure(verbosity: int = 0) -> None:
    """Map a CLI verbosity to the package log level.

    ``verbosity`` < 0 (``--quiet``) → WARNING, 0 → INFO,
    > 0 (``--verbose``) → DEBUG.
    """
    level = (
        logging.WARNING if verbosity < 0
        else logging.DEBUG if verbosity > 0
        else logging.INFO
    )
    _ensure_handler().setLevel(level)
