"""Text report and figure generation.

Capability parity with the reference's reporting layer (reference
``simulator.py:139-201``): a numerical-results table (iterations to the
suboptimality threshold, total and per-worker floats transmitted) and a
2-panel log-scale matplotlib figure (suboptimality gap, consensus error)
with the same defensive guards — skip non-finite histories, tolerate runs
that recorded no consensus error. New columns the reference prints elsewhere
or not at all: spectral gap (reference prints it at trainer construction,
``trainer.py:133-135``) and measured iterations/second (the TPU-side
observability metric).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _fmt_sci(v: float) -> str:
    return f"{v:.3e}"


def format_report(records, config, f_opt: float, phases=None,
                  serving=None) -> str:
    """Render the numerical-results table for a list of ExperimentRecords.

    ``phases``: optional {name: seconds} wall-clock phase accounting
    (Simulator's PhaseTimer) appended as its own section. Records carrying
    flight-recorder state (``config.telemetry``) additionally get a
    run-health section: worst-worker gradient norm, non-finite counts, and
    realized-vs-nominal connectivity (docs/OBSERVABILITY.md).

    ``serving``: optional executable-cache / coalescing counters (the
    Simulator passes the process cache's stats once it has recorded a hit;
    the serving layer passes ``SimulationService.stats()``) rendered as a
    one-line serving summary (docs/SERVING.md).
    """
    lines = [
        "=" * 78,
        f"Numerical results — problem={config.problem_type}, N={config.n_workers}, "
        f"T={config.n_iterations}, b={config.local_batch_size}, "
        f"eta0={config.learning_rate_eta0}, lambda={config.l2_regularization_lambda}",
        f"backend={config.backend}; f(x*) = {f_opt:.6f}; "
        f"suboptimality threshold = {config.suboptimality_threshold}",
        "=" * 78,
    ]
    header = (
        f"{'run':<28}{'iters→ε':>9}{'sec→ε':>8}{'floats total':>14}"
        f"{'floats/worker':>15}{'1−ρ':>8}{'iters/s':>10}"
    )
    lines += [header, "-" * len(header)]
    any_interpolated = False
    for rec in records:
        if rec.skipped_reason is not None:
            lines.append(f"{rec.label:<28}{'N/A — ' + rec.skipped_reason}")
            continue
        stats = getattr(rec, "replicate_stats", None)
        if stats is not None:
            # Replica-batched row (ISSUE-4): every quoted number is a
            # mean ± std over the seed replicates, not one trajectory's.
            if stats.n_reached:
                iters = (
                    f"{stats.iterations_to_threshold_mean:.0f}"
                    f"±{stats.iterations_to_threshold_std:.0f}"
                )
                if stats.n_reached < stats.n_replicas:
                    iters += f" ({stats.n_reached}/{stats.n_replicas})"
            else:
                iters = "never"
            s = rec.summary
            gap = (
                f"{s.spectral_gap:.4f}" if s.spectral_gap is not None else "—"
            )
            lines.append(
                # The mean±std iters→ε spans the iters→ε + sec→ε columns
                # (per-eval wall-clock is batch-wide, so sec→ε has no
                # per-replica meaning).
                f"{rec.label + f' [R={stats.n_replicas}]':<28}{iters:>17}"
                f"{_fmt_sci(s.total_transmission_floats):>14}"
                f"{_fmt_sci(s.avg_worker_transmission_floats):>15}{gap:>8}"
                f"{stats.aggregate_iters_per_second:>10.1f}"
            )
            cons = (
                f", consensus {stats.consensus_mean:.3e} ± "
                f"{stats.consensus_std:.3e}"
                if stats.consensus_mean is not None else ""
            )
            # 'a..b' only for a genuinely consecutive seed vector; an
            # explicit --seeds list is printed verbatim (11..42 would
            # misreport which seeds ran).
            consecutive = stats.seeds == list(
                range(stats.seeds[0], stats.seeds[0] + len(stats.seeds))
            )
            seed_str = (
                f"{stats.seeds[0]}..{stats.seeds[-1]}"
                if consecutive and len(stats.seeds) > 1
                else ",".join(str(s) for s in stats.seeds)
            )
            lines.append(
                f"{'':<28}final gap {stats.final_gap_mean:.5f} ± "
                f"{stats.final_gap_std:.5f} over seeds "
                f"{seed_str}{cons} "
                "(iters/s = aggregate across replicas)"
            )
            continue
        s = rec.summary
        iters = str(s.iterations_to_threshold) if s.iterations_to_threshold > 0 else "never"
        if np.isfinite(s.seconds_to_threshold):
            # "~" = interpolated from the total run wall-clock, not a measured
            # per-eval timestamp (fully fused scan path).
            mark = "" if s.time_measured else "~"
            any_interpolated |= not s.time_measured
            secs = f"{mark}{s.seconds_to_threshold:.2f}"
        else:
            secs = "—"
        gap = f"{s.spectral_gap:.4f}" if s.spectral_gap is not None else "—"
        lines.append(
            f"{rec.label:<28}{iters:>9}{secs:>8}"
            f"{_fmt_sci(s.total_transmission_floats):>14}"
            f"{_fmt_sci(s.avg_worker_transmission_floats):>15}{gap:>8}"
            f"{s.iters_per_second:>10.1f}"
        )
    lines.append("=" * 78)
    if any_interpolated:
        lines.append(
            "~ sec→ε interpolated from total run wall-clock "
            "(use --measure-time for per-eval timestamps)"
        )
    health_lines = _health_section(records)
    if health_lines:
        lines.append("run health (telemetry):")
        lines += health_lines
    serving_line = _serving_line(serving)
    if serving_line:
        lines.append(serving_line)
    if phases:
        total = sum(phases.values())
        lines.append("phases:")
        for name, secs in sorted(phases.items(), key=lambda kv: -kv[1]):
            share = secs / total if total > 0 else 0.0
            lines.append(f"  {name:<12}{secs:>10.3f}s{share:>8.1%}")
    return "\n".join(lines)


def _serving_line(serving) -> Optional[str]:
    """One-line executable-cache / coalescing summary (docs/SERVING.md).

    Accepts either a bare ``ExecutableCache.stats()`` dict or a full
    ``SimulationService.stats()`` dict (cache nested under "cache" with
    cohort/queue counters alongside); returns None when there is nothing
    to report.
    """
    if not serving:
        return None
    cache = serving.get("cache", serving)
    if not cache or (cache.get("hits", 0) + cache.get("misses", 0)) == 0:
        return None
    parts = [
        f"cache {cache['hits']} hit{'s' if cache['hits'] != 1 else ''} / "
        f"{cache['misses']} miss{'es' if cache['misses'] != 1 else ''}",
        f"{cache.get('compile_seconds_saved', 0.0):.1f}s compile saved",
    ]
    cohorts = serving.get("cohorts")
    if cohorts and cohorts.get("count"):
        parts.append(
            f"{cohorts['count']} cohort{'s' if cohorts['count'] != 1 else ''}"
            f" (mean R={cohorts['mean_size']:.1f})"
        )
    qw = serving.get("queue_wait_s")
    if qw and qw.get("mean") is not None:
        parts.append(f"mean queue wait {qw['mean'] * 1e3:.0f}ms")
    return "serving: " + ", ".join(parts)


def _health_section(records) -> list[str]:
    """Run-health lines for records that recorded trace buffers."""
    lines: list[str] = []
    for rec in records:
        h = getattr(rec, "health", None)
        if h is None:
            continue
        parts = []
        if "worst_worker_grad_norm" in h:
            parts.append(
                f"worst grad-norm {h['worst_worker_grad_norm']:.3e} "
                f"(worker {h['worst_worker']})"
            )
        if "nonfinite_total" in h:
            parts.append(f"non-finite {int(h['nonfinite_total'])}")
        if h.get("realized_edge_frac") is not None:
            parts.append(
                f"realized edges {h['realized_edge_frac']:.1%} of nominal"
            )
        wc = h.get("windowed_connectivity")
        if wc is not None:
            bhat = wc.get("bhat")
            parts.append(
                f"B̂ {bhat if bhat is not None else '∞ (disconnected union)'}"
            )
        part = h.get("participation")
        if part is not None:
            # Client sampling (docs/PERF.md §14): realized participation
            # against the configured rate — a realized fraction far off
            # target is the first sign the sampling mask isn't composing.
            parts.append(
                f"participation {part['realized_frac_mean']:.1%} "
                f"(target {part['rate']:.0%})"
            )
        if h.get("clip_frac_mean"):
            parts.append(f"screened msgs {h['clip_frac_mean']:.1%}")
        a = h.get("async")
        if a is not None:
            # Event-driven execution (docs/ASYNC.md): realized staleness,
            # the virtual-clock spread a barrier would have flattened, and
            # the straggler tax the barrier would have charged (sync twin
            # priced on the same latency draws).
            tax = (
                a["sync_virtual_duration"] / a["virtual_duration"]
                if a.get("virtual_duration") else float("nan")
            )
            parts.append(
                f"async[{a['latency_model']}] staleness "
                f"{a['staleness']['mean']:.2f} mean/"
                f"{a['staleness']['max']} max, clock skew "
                f"{a['virtual_clock']['rel_spread']:.1%}, sync tax "
                f"{tax:.2f}x, {a['floats_per_virtual_second']:.4g} "
                "floats/vs"
            )
        comms = h.get("comms")
        if comms is not None:
            # Bytes moved per ITERATION (realized mean; both gossip
            # rounds for two-mix algorithms) — the number a compression
            # operator exists to shrink; tagged with the operator so a
            # 'top_k' win reads directly off the report.
            tag = (
                f" ({comms['compression']})"
                if comms.get("compression", "none") != "none" else ""
            )
            parts.append(
                f"floats/iter {comms['floats_per_iteration_mean']:.4g}{tag}"
            )
            if comms.get("local_steps"):
                # τ gradient steps per exchanged round: the federated
                # comms-reduction lever, quoted per gradient step.
                parts.append(
                    f"floats/grad-step "
                    f"{comms['floats_per_gradient_step']:.4g} "
                    f"(τ={comms['local_steps']})"
                )
            ici = comms.get("ici")
            if ici is not None:
                # Sharded worker mesh (docs/PERF.md §16): REAL collective
                # traffic next to the analytic floats — the static halo
                # plan's per-device ppermute bytes per gossip round.
                parts.append(
                    f"ICI {ici['bytes_per_device_per_round_max']:,} "
                    f"B/dev/round over P={ici['worker_mesh']} mesh "
                    f"(halo {ici['halo_rows_max']} rows)"
                )
        inc = h.get("incidents")
        if inc is not None and inc.get("count"):
            # Anomaly sentinel (ISSUE-13): the run fired detectors — the
            # report names the worst one and whether the halt policy cut
            # the run short; the full forensics live in the incident
            # bundles / manifest health block.
            worst = inc["anomalies"][0]
            line = (
                f"INCIDENTS {inc['count']} ({inc['fatal']} fatal): "
                f"{worst['detector']} [{worst['severity']}] at iter "
                f"{worst['onset_iteration']}"
            )
            if inc.get("halted_at") is not None:
                line += f"; HALTED at iter {inc['halted_at']}"
            parts.append(line)
        if parts:
            lines.append(f"  {rec.label:<26}" + ", ".join(parts))
    return lines


def _finite_curve(iters: np.ndarray, values: Optional[np.ndarray]):
    """Return (iters, values) restricted to finite, positive entries, or None.

    Mirrors the reference's pre-plot guards (``simulator.py:178-188``): a
    curve with no finite data is skipped rather than crashing the figure.
    """
    if values is None or len(values) == 0 or len(values) != len(iters):
        return None
    mask = np.isfinite(values)
    if not mask.any():
        return None
    return iters[mask], values[mask]


def plot_histories(records, config, path: Optional[str] = None, show: bool = False):
    """2-panel log-scale figure: suboptimality gap + consensus error.

    Saves to ``path`` when given (headless-friendly); returns the Figure.
    """
    import matplotlib

    if not show:
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, (ax_gap, ax_cons) = plt.subplots(1, 2, figsize=(13, 5))

    for rec in records:
        if rec.skipped_reason is not None or rec.result is None:
            continue
        hist = rec.result.history
        curve = _finite_curve(hist.eval_iterations, hist.objective)
        if curve is not None:
            ax_gap.plot(curve[0], np.maximum(curve[1], 1e-16), label=rec.label)
        curve = _finite_curve(hist.eval_iterations, hist.consensus_error)
        if curve is not None:
            ax_cons.plot(curve[0], np.maximum(curve[1], 1e-16), label=rec.label)

    ax_gap.axhline(
        config.suboptimality_threshold, color="gray", ls="--", lw=0.8,
        label=f"ε = {config.suboptimality_threshold}",
    )
    ax_gap.set_yscale("log")
    ax_gap.set_xlabel("iteration")
    ax_gap.set_ylabel("f(x̄) − f(x*)")
    ax_gap.set_title(f"Suboptimality gap ({config.problem_type})")
    ax_gap.legend(fontsize=8)
    ax_gap.grid(True, which="both", alpha=0.3)

    ax_cons.set_yscale("log")
    ax_cons.set_xlabel("iteration")
    ax_cons.set_ylabel("(1/N) Σ ‖x_i − x̄‖²")
    ax_cons.set_title("Consensus error")
    if ax_cons.lines:
        ax_cons.legend(fontsize=8)
    ax_cons.grid(True, which="both", alpha=0.3)

    fig.tight_layout()
    if path is not None:
        fig.savefig(path, dpi=130)
    if show:
        plt.show()
    return fig
