"""``python -m distributed_optimization_tpu.serve`` — the serving daemon.

Boots the stdlib HTTP front end over ``serving.SimulationService``:
config JSON in, RunTrace manifest JSONL out, with AOT executable caching
and request coalescing (docs/SERVING.md has the protocol and a curl
example). All flags live on ``serving.daemon.main``.
"""

from distributed_optimization_tpu.serving.daemon import main

if __name__ == "__main__":
    raise SystemExit(main())
