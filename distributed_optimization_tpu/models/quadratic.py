"""L2-regularized least squares ("quadratic" — strongly convex).

Capability parity with reference ``obj_problems.py:39-69`` (the strongly
convex test problem of the study, PDF §II-B).
"""

from distributed_optimization_tpu.models.base import Problem, register_problem
from distributed_optimization_tpu.ops import losses

QUADRATIC = register_problem(
    Problem(
        name="quadratic",
        objective=losses.quadratic_objective,
        gradient=losses.quadratic_gradient,
        objective_weighted=losses.quadratic_objective_weighted,
        gradient_weighted=losses.quadratic_gradient_weighted,
    )
)
