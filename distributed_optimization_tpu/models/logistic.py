"""L2-regularized binary logistic regression (labels in {-1, +1}).

Capability parity with reference ``obj_problems.py:3-36`` (the convex test
problem of the study, PDF §II-B).
"""

from distributed_optimization_tpu.models.base import Problem, register_problem
from distributed_optimization_tpu.ops import losses

LOGISTIC = register_problem(
    Problem(
        name="logistic",
        objective=losses.logistic_objective,
        gradient=losses.logistic_gradient,
        objective_weighted=losses.logistic_objective_weighted,
        gradient_weighted=losses.logistic_gradient_weighted,
    )
)
