"""L2-regularized Huber regression (convex, robust).

Not in the reference (``obj_problems.py`` has logistic + least squares);
this is the framework's third objective family — robust regression with the
per-sample gradient capped at δ‖x‖ (δ fixed at the synthetic data's noise
scale; see ``ops/losses.py``). Uses the same regression data pipeline as
the quadratic problem and a scipy L-BFGS reference optimum
(``utils/oracle.py`` — sklearn's HuberRegressor jointly estimates a scale
parameter and does not minimize this objective).
"""

import functools

from distributed_optimization_tpu.models.base import Problem, register_problem
from distributed_optimization_tpu.ops import losses


@functools.lru_cache(maxsize=None)
def make_huber_problem(delta: float) -> Problem:
    """Huber Problem with the transition point bound to ``delta``.

    Cached per δ so a given δ always yields the SAME callable objects —
    the backends pass these as jit static arguments, and a fresh partial
    per call would defeat XLA's compilation cache.
    """
    return Problem(
        name="huber",
        objective=functools.partial(losses.huber_objective, delta=delta),
        gradient=functools.partial(losses.huber_gradient, delta=delta),
        objective_weighted=functools.partial(
            losses.huber_objective_weighted, delta=delta
        ),
        gradient_weighted=functools.partial(
            losses.huber_gradient_weighted, delta=delta
        ),
    )


HUBER = register_problem(make_huber_problem(losses.HUBER_DELTA))
