"""L2-regularized Huber regression (convex, robust).

Not in the reference (``obj_problems.py`` has logistic + least squares);
this is the framework's third objective family — robust regression with the
per-sample gradient capped at δ‖x‖ (δ fixed at the synthetic data's noise
scale; see ``ops/losses.py``). Uses the same regression data pipeline as
the quadratic problem and a scipy L-BFGS reference optimum
(``utils/oracle.py`` — sklearn's HuberRegressor jointly estimates a scale
parameter and does not minimize this objective).
"""

from distributed_optimization_tpu.models.base import Problem, register_problem
from distributed_optimization_tpu.ops import losses

HUBER = register_problem(
    Problem(
        name="huber",
        objective=losses.huber_objective,
        gradient=losses.huber_gradient,
        objective_weighted=losses.huber_objective_weighted,
        gradient_weighted=losses.huber_gradient_weighted,
    )
)
