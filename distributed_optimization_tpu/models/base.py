"""Problem abstraction: one convex objective family, as pure functions.

The reference dispatches on a ``problem_type`` string in four separate places
(reference ``worker.py:35-44``, ``trainer.py:21-28``, ``trainer.py:142-149``,
``simulator.py:36``). Here the dispatch happens once: a :class:`Problem`
bundles the jittable objective/gradient kernels and is threaded through the
backends as a static argument, so XLA specializes the compiled step per
problem.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax


@dataclasses.dataclass(frozen=True)
class Problem:
    """A convex objective family f(w) = data_term(w; X, y) + (reg/2)‖w‖².

    All callables are pure and jittable:

    - ``objective(w, X, y, reg)`` — full/mini-batch mean objective
      (reference parity: obj_problems.py:3-11, 39-44).
    - ``gradient(w, X, y, reg)`` — mean gradient over the given rows
      (reference parity: obj_problems.py:13-20, 46-53).
    - ``objective_weighted(w, X, y, weights, reg)`` / ``gradient_weighted`` —
      per-sample-weight forms used on the TPU path (static shapes; weights
      encode masking / effective batch size).
    - ``param_dim(n_features)`` — the flattened parameter dimension for a
      d-feature dataset. Identity for the scalar-output GLMs; d·K for the
      softmax family, whose [d, K] weight matrix travels through the
      mixing/algorithm layers as a flat vector (gossip is elementwise over
      the parameter axis, so flattening is exact).
    """

    name: str
    objective: Callable[..., jax.Array]
    gradient: Callable[..., jax.Array]
    objective_weighted: Callable[..., jax.Array]
    gradient_weighted: Callable[..., jax.Array]
    param_dim: Callable[[int], int] = lambda d: d


_REGISTRY: dict[str, Problem] = {}


def register_problem(problem: Problem) -> Problem:
    _REGISTRY[problem.name] = problem
    return problem


def get_problem(
    name: str,
    *,
    huber_delta: float | None = None,
    n_classes: int | None = None,
) -> Problem:
    """Look up a problem family by name ('logistic', 'quadratic', ...).

    ``huber_delta`` binds the Huber transition point (ignored for other
    families); ``None`` means the registered default
    (config.DEFAULT_HUBER_DELTA). ``n_classes`` binds the softmax family's
    class count (ignored elsewhere; ``None`` means the registered default).
    Per-parameter Problems are cached so jit static arguments stay
    identical across calls.
    """
    # Import here so registration happens on first use without import cycles.
    from distributed_optimization_tpu.models import (  # noqa: F401
        huber,
        logistic,
        quadratic,
        softmax,
    )

    if name not in _REGISTRY:
        raise ValueError(f"Unknown problem type: {name!r}; known: {sorted(_REGISTRY)}")
    if name == "huber" and huber_delta is not None:
        return huber.make_huber_problem(float(huber_delta))
    if name == "softmax" and n_classes is not None:
        return softmax.make_softmax_problem(int(n_classes))
    return _REGISTRY[name]
