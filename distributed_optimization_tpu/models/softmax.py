"""L2-regularized multinomial (softmax) logistic regression — the
compute-bound objective family.

Not in the reference (``obj_problems.py``'s GLMs are all scalar-output) —
this is the framework's MXU tier: the [d, K] weight matrix makes the
per-worker gradient a pair of real matmuls (X @ W forward, X^T @ (P − Y)
backward, 2·b·d·K FLOPs each) instead of the scalar GLMs' matvecs, so wide
(d, K) configurations load the systolic array instead of the memory bus
(measured: docs/perf/compute_bound.json, docs/PERF.md §compute-bound).

Parameters travel flattened ([d·K]) through mixing/algorithms — gossip is
elementwise over the parameter axis, so flattening is exact; ``param_dim``
tells the backends how long the flat vector is. The kernels themselves
infer K from static shapes (``ops/losses.py`` softmax section), so the
bound class count only sizes the parameter vector.
"""

import functools

from distributed_optimization_tpu.models.base import Problem, register_problem
from distributed_optimization_tpu.ops import losses

DEFAULT_N_CLASSES = 10


@functools.lru_cache(maxsize=None)
def make_softmax_problem(n_classes: int) -> Problem:
    """Softmax Problem with the class count bound to ``n_classes``.

    Cached per K so a given class count always yields the SAME callable
    objects — the backends pass these as jit static arguments, and a fresh
    instance per call would defeat XLA's compilation cache.
    """
    if n_classes < 2:
        raise ValueError(f"softmax needs n_classes >= 2, got {n_classes}")
    return Problem(
        name="softmax",
        objective=losses.softmax_objective,
        gradient=losses.softmax_gradient,
        objective_weighted=losses.softmax_objective_weighted,
        gradient_weighted=losses.softmax_gradient_weighted,
        param_dim=lambda d: d * n_classes,
    )


SOFTMAX = register_problem(make_softmax_problem(DEFAULT_N_CLASSES))
