"""Problem/model definitions: objective families the framework can optimize."""

from distributed_optimization_tpu.models.base import Problem, get_problem  # noqa: F401
