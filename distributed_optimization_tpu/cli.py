"""Command-line interface.

The reference has no CLI — its entry point is hard-coded module constants
(reference ``main.py:6-41``). This is the typed-config + real-flags layer
SURVEY.md §5.6 calls for, including the ``--backend`` selection named in
BASELINE.json's north star.

Examples:

    # the reference study, end to end, on the TPU backend:
    python -m distributed_optimization_tpu --problem-type logistic --suite \
        --plot logistic.png --json logistic.json

    # one decentralized run:
    python -m distributed_optimization_tpu --algorithm gradient_tracking \
        --topology grid --n-workers 64 --n-iterations 2000

    # the numpy fidelity oracle (reference semantics):
    python -m distributed_optimization_tpu --backend numpy --suite
"""

from __future__ import annotations

import argparse
import json
from typing import Optional, Sequence

from distributed_optimization_tpu.log import configure as configure_logging
from distributed_optimization_tpu.log import get_logger
from distributed_optimization_tpu.config import (
    AGGREGATIONS,
    ALGORITHMS,
    ATTACKS,
    BACKENDS,
    COMPRESSIONS,
    EXECUTIONS,
    LATENCY_MODELS,
    MATRIX_FREE_AUTO_N,
    PROBLEM_TYPES,
    REJOINS,
    TOPOLOGIES,
    ExperimentConfig,
)

_DEFAULTS = ExperimentConfig()
_log = get_logger("cli")

# The five target configurations named in BASELINE.json, as CLI presets.
# Flags given alongside --preset still override individual fields.
PRESETS: dict[str, dict] = {
    # 1. Quadratic consensus, 4 workers, fully-connected — DGD
    "quadratic-fc-4": dict(problem_type="quadratic", algorithm="dsgd",
                           topology="fully_connected", n_workers=4),
    # 2. Logistic regression, synthetic data, 8-worker ring — DGD
    "logistic-ring-8": dict(problem_type="logistic", algorithm="dsgd",
                            topology="ring", n_workers=8),
    # 3. Decentralized ADMM, logistic, 16-worker Erdős–Rényi graph
    "admm-er-16": dict(problem_type="logistic", algorithm="admm",
                       topology="erdos_renyi", n_workers=16),
    # 4. Gradient tracking / EXTRA, quadratic, 64-worker 2D torus
    "gt-torus-64": dict(problem_type="quadratic", algorithm="gradient_tracking",
                        topology="grid", n_workers=64,
                        learning_rate_eta0=0.01),
    # 5. Decentralized logistic on real image features (stretch). The only
    # offline real image dataset in this environment is sklearn's bundled
    # 8x8 digits (1,797 samples), which supports ~28 samples/worker at
    # N=64; the BASELINE "256 workers" scale is demonstrated on the
    # synthetic config (12,500 samples — bench.py's headline), because 256
    # workers over 1,797 real samples would be 7 samples/worker — runnable
    # but statistically degenerate. docs/perf/presets.json measures both.
    "digits-64": dict(problem_type="logistic", algorithm="dsgd",
                      topology="ring", n_workers=64, dataset="digits"),
    # 6. Push-sum SGP, logistic, 16-worker strongly connected DIRECTED
    # Erdős–Rényi graph (round 4; beyond BASELINE.json) — the asymmetric-
    # link setting where MH gossip is undefined and column-stochastic
    # mixing + weight debiasing is required (Nedić-Olshevsky '16, Assran
    # et al. '19). Measured in docs/perf/presets.json like the others.
    "push-sum-der-16": dict(problem_type="logistic", algorithm="push_sum",
                            topology="directed_erdos_renyi", n_workers=16),
    # 7. Multiclass softmax on the real digits images (round 5; beyond
    # BASELINE.json) — the ten digit classes ARE the labels, so this is
    # the natural multiclass form of the stretch config: a [65, 10]
    # weight matrix per worker gossiped as a flat 650-vector.
    "digits-softmax-64": dict(problem_type="softmax", n_classes=10,
                              algorithm="dsgd", topology="ring",
                              n_workers=64, dataset="digits",
                              learning_rate_eta0=0.1),
    # 8. The compute-bound tier at CLI scale (round 5): wide softmax whose
    # gradients are real MXU matmuls — a small sibling of
    # examples/bench_compute_bound.py's measured cells
    # (docs/perf/compute_bound.json: 33-36% median MFU at d in
    # {4096, 8192}, K=512, bf16).
    "softmax-mxu-8": dict(problem_type="softmax", n_classes=128,
                          algorithm="dsgd", topology="ring", n_workers=8,
                          n_features=1024, n_informative_features=64,
                          n_samples=2048, local_batch_size=256,
                          learning_rate_eta0=0.1, n_iterations=2000),
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_optimization_tpu",
        description=(
            "TPU-native decentralized optimization: centralized SGD, D-SGD, "
            "gradient tracking, EXTRA and decentralized ADMM over graph "
            "topologies, on a JAX/XLA collective backend or a numpy "
            "reference-semantics oracle."
        ),
    )
    run = p.add_argument_group("run selection")
    run.add_argument("--preset", choices=sorted(PRESETS), default=None,
                     help="apply one of the BASELINE.json target configs; "
                          "other flags still override individual fields")
    run.add_argument("--suite", action="store_true",
                     help="run the reference experiment matrix (centralized + "
                          "D-SGD over ring/grid/fully-connected) instead of a "
                          "single run")
    run.add_argument("--algorithm", choices=ALGORITHMS,
                     default=_DEFAULTS.algorithm)
    run.add_argument("--topology", choices=TOPOLOGIES, default=_DEFAULTS.topology)
    run.add_argument("--backend", choices=BACKENDS, default=_DEFAULTS.backend)
    run.add_argument("--platform", choices=("tpu", "cpu", "auto"), default="auto",
                     help="force the JAX platform (cpu is useful for quick "
                          "checks and virtual multi-device runs)")
    run.add_argument("--multihost", action="store_true",
                     help="call jax.distributed.initialize() so the worker "
                          "mesh spans all hosts of a multi-host TPU slice "
                          "(run the same command on every host; coordinator "
                          "discovery via the standard TPU env vars)")

    prob = p.add_argument_group("problem / data (reference main.py parity)")
    prob.add_argument("--problem-type", choices=PROBLEM_TYPES,
                      default=_DEFAULTS.problem_type)
    prob.add_argument("--n-workers", type=int, default=_DEFAULTS.n_workers)
    prob.add_argument("--n-samples", type=int, default=_DEFAULTS.n_samples)
    prob.add_argument("--n-features", type=int, default=_DEFAULTS.n_features)
    prob.add_argument("--n-informative-features", type=int,
                      default=_DEFAULTS.n_informative_features)
    prob.add_argument("--classification-sep", type=float,
                      default=_DEFAULTS.classification_sep)
    prob.add_argument("--n-classes", type=int, default=_DEFAULTS.n_classes,
                      help="class count K for --problem-type softmax (the "
                           "compute-bound [d,K]-matrix-parameter family)")
    prob.add_argument("--dataset", choices=("synthetic", "digits"),
                      default="synthetic",
                      help="'digits' = real image features (the MNIST-features "
                           "stretch config) instead of synthetic data")

    opt = p.add_argument_group("optimization")
    opt.add_argument("--n-iterations", type=int, default=_DEFAULTS.n_iterations)
    opt.add_argument("--local-batch-size", type=int,
                     default=_DEFAULTS.local_batch_size)
    opt.add_argument("--learning-rate-eta0", type=float,
                     default=_DEFAULTS.learning_rate_eta0)
    opt.add_argument("--l2-lambda", type=float,
                     default=_DEFAULTS.l2_regularization_lambda)
    opt.add_argument("--lr-schedule", choices=("auto", "sqrt_decay", "constant"),
                     default=_DEFAULTS.lr_schedule)
    opt.add_argument("--admm-c", type=float, default=_DEFAULTS.admm_c)
    opt.add_argument("--admm-rho", type=float, default=_DEFAULTS.admm_rho)
    opt.add_argument("--huber-delta", type=float, default=_DEFAULTS.huber_delta,
                     help="Huber transition point δ (problem huber only; "
                          "default = the synthetic data's noise scale)")
    opt.add_argument("--erdos-renyi-p", type=float,
                     default=_DEFAULTS.erdos_renyi_p)
    opt.add_argument("--compression", choices=COMPRESSIONS,
                     default=_DEFAULTS.compression,
                     help="error-feedback gossip compression operator "
                          "(choco, dsgd, gradient_tracking)")
    opt.add_argument("--compression-k", type=int,
                     default=_DEFAULTS.compression_k,
                     help="coordinates kept per transmitted vector "
                          "(top_k/random_k) or quantization bits (qsgd)")
    opt.add_argument("--choco-gamma", type=float, default=_DEFAULTS.choco_gamma,
                     help="error-feedback consensus step size gamma "
                          "(CHOCO and compressed dsgd/gradient_tracking)")
    opt.add_argument("--local-steps", type=int, default=_DEFAULTS.local_steps,
                     help="federated local updates: τ gradient steps per "
                          "gossip round, fused in the same compiled scan "
                          "(dsgd: plain local SGD; gradient_tracking: "
                          "tracker-corrected). Per-round comms is "
                          "unchanged, so τ>1 cuts floats per unit of "
                          "progress up to τ× (docs/PERF.md §14). 1 = the "
                          "classic one-step round, bitwise")
    opt.add_argument("--participation-rate", type=float,
                     default=_DEFAULTS.participation_rate,
                     help="per-round client sampling: each worker "
                          "independently participates with this "
                          "probability (presampled [horizon, N] masks on "
                          "the fault timeline; sampled-out workers freeze "
                          "and exchange nothing; composes with churn and "
                          "the Byzantine layer). 1.0 = everyone, bitwise "
                          "the no-sampling program")
    opt.add_argument("--edge-drop-prob", type=float,
                     default=_DEFAULTS.edge_drop_prob,
                     help="failure injection: per-iteration probability that "
                          "each topology edge drops (gossip reweights on the "
                          "surviving graph)")
    opt.add_argument("--gossip-schedule",
                     choices=("synchronous", "one_peer", "round_robin"),
                     default=_DEFAULTS.gossip_schedule,
                     help="'one_peer' = randomized pairwise gossip (one "
                          "random mutual neighbor/iter); 'round_robin' = "
                          "deterministic matchings covering the edge set "
                          "every P iterations")
    opt.add_argument("--straggler-prob", type=float,
                     default=_DEFAULTS.straggler_prob,
                     help="straggler injection: per-iteration probability "
                          "that a node sits the round out (no exchange, no "
                          "local step)")
    opt.add_argument("--burst-len", type=float, default=_DEFAULTS.burst_len,
                     help="bursty link failures (Gilbert-Elliott): mean "
                          "burst-length multiplier at the SAME marginal "
                          "--edge-drop-prob (mean burst = "
                          "burst_len/(1-p) rounds). 0 = memoryless iid "
                          "drops; 1 reduces bitwise to them; > 1 "
                          "correlates failures in time (docs/CHURN.md)")
    opt.add_argument("--mttf", type=float, default=_DEFAULTS.mttf,
                     help="crash-recovery churn: mean up-time (rounds) "
                          "before a node crashes; >= 1, set together with "
                          "--mttr (replaces --straggler-prob; stationary "
                          "downtime = mttr/(mttf+mttr))")
    opt.add_argument("--mttr", type=float, default=_DEFAULTS.mttr,
                     help="crash-recovery churn: mean outage length "
                          "(rounds) before a crashed node rejoins; >= 1, "
                          "set together with --mttf")
    opt.add_argument("--rejoin", choices=REJOINS, default=_DEFAULTS.rejoin,
                     help="what a node resumes with after an outage: "
                          "'frozen' = stale pre-crash state (staleness "
                          "stress test); 'neighbor_restart' = warm restart "
                          "of the model row from the realized-neighborhood "
                          "average on the rejoin round")
    opt.add_argument("--attack", choices=ATTACKS, default=_DEFAULTS.attack,
                     help="Byzantine injection: n-byzantine workers replace "
                          "their outgoing models with this payload each "
                          "gossip round (docs/BYZANTINE.md)")
    opt.add_argument("--n-byzantine", type=int,
                     default=_DEFAULTS.n_byzantine,
                     help="size of the static seed-deterministic Byzantine "
                          "worker set")
    opt.add_argument("--attack-scale", type=float,
                     default=_DEFAULTS.attack_scale,
                     help="payload magnitude: sign-flip multiplier, "
                          "large-noise sigma, or ALIE's z (honest std "
                          "devs of shift)")
    opt.add_argument("--aggregation", choices=AGGREGATIONS,
                     default=_DEFAULTS.aggregation,
                     help="robust neighbor aggregation rule honest workers "
                          "use in place of plain W@x gossip")
    opt.add_argument("--robust-b", type=int, default=_DEFAULTS.robust_b,
                     help="per-neighborhood attack budget for the robust "
                          "rule (values trimmed per tail / messages "
                          "clipped); 0 degrades to plain gossip; needs "
                          "2*b <= min node degree")
    opt.add_argument("--clip-tau", type=float, default=_DEFAULTS.clip_tau,
                     help="fixed clipping radius for clipped_gossip "
                          "(0 = adaptive per-node radius)")
    opt.add_argument("--robust-impl",
                     choices=("auto", "dense", "gather", "fused"),
                     default=_DEFAULTS.robust_impl,
                     help="execution form of the robust rule (jax "
                          "backend): 'dense' sorts the [N,N,d] closed-"
                          "neighborhood tensor (O(N^2 d log N)); 'gather' "
                          "screens over a static [N,k_max] padded "
                          "neighbor table (O(N k_max d log k_max), "
                          "~N/k_max less work on degree-bounded graphs); "
                          "'fused' runs the gather math as one pallas "
                          "kernel (gather+screen+mix+SGD for dsgd), the "
                          "[N,k_max,d] stack never hitting HBM; 'auto' = "
                          "measured rule: gather unless fully connected, "
                          "promoted to fused when eligible (static "
                          "topology, supported rule, telemetry off — "
                          "docs/perf/fused_robust.json)")
    opt.add_argument("--partition", choices=("sorted", "shuffled"),
                     default=_DEFAULTS.partition,
                     help="worker data split: 'sorted' = the study's "
                          "non-IID sort-by-target slices; 'shuffled' = "
                          "IID control (bounded heterogeneity)")
    opt.add_argument("--seed", type=int, default=_DEFAULTS.seed)
    opt.add_argument("--topology-seed", type=int,
                     default=_DEFAULTS.topology_seed,
                     help="pin the random-topology (Erdős–Rényi) edge "
                          "draws independently of --seed (-1 = follow "
                          "--seed); replicated runs pin it automatically "
                          "so every replica shares one graph instance")
    opt.add_argument("--data-seed", type=int, default=_DEFAULTS.data_seed,
                     help="pin the DATASET's random draws independently "
                          "of --seed (-1 = follow --seed); with it "
                          "pinned, runs that differ only in --seed share "
                          "one problem instance — the serving layer "
                          "coalesces such requests into one batched "
                          "program (docs/SERVING.md)")
    opt.add_argument("--replicas", type=int, default=_DEFAULTS.replicas,
                     help="run this many seed replicates (seed, seed+1, "
                          "...) as ONE vmapped jax program and report "
                          "mean ± std over the replica axis (jax backend "
                          "only; docs/PERF.md 'Replica-batched sweeps')")
    opt.add_argument("--seeds", metavar="S1,S2,...", default=None,
                     help="explicit comma-separated replica seed list "
                          "(overrides --replicas/--seed's arithmetic "
                          "progression); implies replica-batched "
                          "execution")
    opt.add_argument("--suboptimality-threshold", type=float,
                     default=_DEFAULTS.suboptimality_threshold)

    execg = p.add_argument_group("execution")
    execg.add_argument("--execution", choices=EXECUTIONS,
                       default=_DEFAULTS.execution,
                       help="'async' scans a precomputed EVENT schedule "
                            "(AD-PSGD-style bounded-staleness gossip: one "
                            "worker's stale-read local step + a pairwise "
                            "exchange per event; stragglers are latency, "
                            "not drops — docs/ASYNC.md). n_iterations "
                            "then counts per-worker gradient steps (N "
                            "events per round); dsgd only")
    execg.add_argument("--latency-model", choices=LATENCY_MODELS,
                       default=_DEFAULTS.latency_model,
                       help="per-worker compute-time distribution of the "
                            "async event schedule (all matched to mean "
                            "--latency-mean; async only)")
    execg.add_argument("--latency-mean", type=float,
                       default=_DEFAULTS.latency_mean,
                       help="mean compute time per gradient step in "
                            "virtual seconds (async only)")
    execg.add_argument("--latency-tail", type=float,
                       default=_DEFAULTS.latency_tail,
                       help="heavy-tail straggler knob: lognormal log-std "
                            "(> 0) or pareto shape alpha (> 1); 0 for "
                            "constant/exponential (async only)")
    execg.add_argument("--tp", type=int, default=_DEFAULTS.tp_degree,
                       metavar="TP_DEGREE",
                       help="tensor parallelism: shard the softmax [d, K] "
                            "classifier over TP_DEGREE devices of a 2-D "
                            "(workers, model) mesh (jax backend; supported "
                            "combination: softmax + dsgd + ring + full "
                            "local batches — anything else is rejected "
                            "with the reason). 1 = pure data parallelism")
    execg.add_argument("--worker-mesh", type=int,
                       default=_DEFAULTS.worker_mesh, metavar="P",
                       help="shard the WORKER axis over P devices "
                            "(docs/PERF.md §16): state rows [N/P, d] and "
                            "neighbor tables [N/P, k_max] live per-shard, "
                            "gossip becomes a ppermute halo exchange at "
                            "shard edges, and trajectories stay bitwise "
                            "the unsharded gather path's. P must divide "
                            "n-workers; jax backend + neighbor-table "
                            "topologies (ring/grid/chain/erdos_renyi). On "
                            "CPU hosts simulate P devices via XLA_FLAGS="
                            "'--xla_force_host_platform_device_count=P'. "
                            "0 = unsharded")
    execg.add_argument("--topology-sampler",
                       choices=("auto", "dense", "sparse"),
                       default=_DEFAULTS.topology_sampler,
                       help="Erdős–Rényi graph sampler (docs/PERF.md §17): "
                            "'dense' replays the [N, N] uniform stream "
                            "bit-for-bit (O(N²) draws), 'sparse' draws "
                            "O(N·k_max) — the million-worker path, a "
                            "DIFFERENT realization of the same G(n, p) "
                            "law (structural identity). 'auto' = dense "
                            "below N=65,536 on the matrix-free ER path, "
                            "sparse above")
    execg.add_argument("--halo-overlap", choices=("off", "double_buffer"),
                       default=_DEFAULTS.halo_overlap,
                       help="worker-mesh halo-exchange overlap (docs/"
                            "PERF.md §17): 'double_buffer' issues the "
                            "boundary ppermutes first and computes the "
                            "in-block partial sum while they are in "
                            "flight (plain gossip mesh path only; "
                            "reordered summation — not bitwise vs 'off'). "
                            "'off' = PR 11's exchange, bitwise-pinned")
    execg.add_argument("--eval-every", type=int, default=_DEFAULTS.eval_every,
                       help="full-data objective eval cadence (1 = reference "
                            "parity)")
    execg.add_argument("--mixing-impl",
                       choices=("auto", "dense", "stencil", "shard_map",
                                "pallas", "sparse", "gather"),
                       default=_DEFAULTS.mixing_impl,
                       help="'gather' = the k_max-bounded neighbor-table "
                            "mixing operator, O(N*k_max*d) per round with "
                            "no [N,N] matrix — the matrix-free/federated-"
                            "scale route (auto picks it on matrix-free "
                            "topologies and above the measured dense "
                            "crossover; docs/PERF.md §14)")
    execg.add_argument("--topology-impl",
                       choices=("auto", "dense", "neighbor"),
                       default=_DEFAULTS.topology_impl,
                       help="topology representation: 'neighbor' builds "
                            "the matrix-free padded [N, k_max] neighbor "
                            "table (ring/grid/chain/erdos_renyi; the only "
                            "form that fits N >= 10k), 'dense' the "
                            "[N, N] matrices; 'auto' = neighbor on the "
                            "jax backend above "
                            f"{MATRIX_FREE_AUTO_N} workers when no "
                            "dense-only feature is requested")
    execg.add_argument("--sampling-impl",
                       choices=("auto", "gather", "dense"),
                       default=_DEFAULTS.sampling_impl,
                       help="mini-batch realization on the jax backend: "
                            "gathered [N,b,d] batches vs dense per-row "
                            "weights over the full shard (auto = measured "
                            "rule: dense for shards <= 64 rows on "
                            "accelerators). dense builds an [L,L] ranking "
                            "matrix per worker per iteration — O(N*L^2) — "
                            "so forcing it on large shards is quadratic "
                            "(the backend warns beyond the measured "
                            "crossover)")
    execg.add_argument("--scan-unroll", type=int, default=_DEFAULTS.scan_unroll,
                       help="XLA unroll factor for the training scan "
                            "(0 = auto: 8 on accelerators, 1 on CPU)")
    execg.add_argument("--compile-cache", metavar="DIR", default=None,
                       help="enable jax's persistent compilation cache in "
                            "DIR (repeat runs skip the 5-30s XLA compile)")
    execg.add_argument("--dtype", choices=("float32", "float64", "bfloat16"),
                       default=_DEFAULTS.dtype)
    execg.add_argument("--matmul-precision",
                       choices=("default", "high", "highest"),
                       default=_DEFAULTS.matmul_precision)

    ckpt = p.add_argument_group("checkpoint / resume (jax backend)")
    ckpt.add_argument("--checkpoint-dir", metavar="DIR", default=None,
                      help="save orbax checkpoints under DIR during the run")
    ckpt.add_argument("--checkpoint-every", type=int, default=10, metavar="K",
                      help="checkpoint cadence in eval-chunks "
                           "(K × eval_every iterations)")
    ckpt.add_argument("--no-resume", action="store_true",
                      help="start fresh even if DIR holds a checkpoint")

    diag = p.add_argument_group("profiling / diagnostics")
    diag.add_argument("--measure-time", action=argparse.BooleanOptionalAction,
                      default=None,
                      help="record real per-eval wall-clock timestamps "
                           "(host-driven chunk loop; one sync per eval) "
                           "instead of interpolating the fused scan's total "
                           "(jax backend). Default: off — the fused flat "
                           "scan is the fastest path at every eval cadence "
                           "(docs/PERF.md root-cause section); opt in when "
                           "measured per-eval wall-clock matters more than "
                           "throughput")
    diag.add_argument("--profile-dir", metavar="DIR", default=None,
                      help="collect a jax.profiler (XProf/TensorBoard) trace "
                           "of the run into DIR")
    diag.add_argument("--check-nans", action="store_true",
                      help="enable jax_debug_nans: raise at the first "
                           "NaN-producing op instead of finishing with NaNs")
    diag.add_argument("--preflight", action="store_true",
                      help="run the named preflight identities before the "
                           "main experiment — collective wiring (ppermute "
                           "round-trip, psum identity) and jit determinism "
                           "— failing loudly with the broken identity "
                           "named (utils/diagnostics.PREFLIGHT_CHECKS)")
    diag.add_argument("--telemetry", metavar="OUT", default=None,
                      help="enable the flight recorder (in-scan trace "
                           "buffers + cost analysis; docs/OBSERVABILITY.md) "
                           "and write one schema-versioned RunTrace "
                           "manifest per run to OUT as JSONL")
    diag.add_argument("--progress", action="store_true",
                      help="stream live per-chunk heartbeats to stderr "
                           "(iteration, wall seconds, current gap/"
                           "consensus, live B-hat under faults, staleness "
                           "quantiles on async runs). The fused scan then "
                           "executes as segments split at eval "
                           "boundaries — trajectories stay bitwise "
                           "identical (docs/OBSERVABILITY.md); jax "
                           "backend, tp=1")
    diag.add_argument("--progress-every", type=int, default=1, metavar="K",
                      help="heartbeat cadence in eval-chunks (K x "
                           "eval_every iterations per heartbeat; "
                           "default 1)")
    diag.add_argument("--monitors", action="store_true",
                      help="watch the run with the anomaly sentinel "
                           "(docs/OBSERVABILITY.md 'Monitors & "
                           "incidents'): online detectors for "
                           "divergence, consensus stall, non-finite "
                           "state, realized-B-hat connectivity loss, "
                           "async staleness blowup, and robust-"
                           "screening saturation consume the run's "
                           "heartbeats; firings are reported and can "
                           "be written as incident bundles. Rides the "
                           "segmented progress machinery (jax backend, "
                           "tp=1); trajectories stay bitwise when "
                           "nothing fires")
    diag.add_argument("--halt-on", choices=("never", "fatal"),
                      default="never",
                      help="early-halt policy (implies --monitors): "
                           "'fatal' stops the run at the next chunk "
                           "boundary after a fatal anomaly "
                           "(divergence, non-finite state, realized "
                           "disconnection) and reports the executed "
                           "prefix as a partial result; 'never' "
                           "(default) only records")
    diag.add_argument("--incidents-out", metavar="PATH", default=None,
                      help="write anomaly incident bundles (config + "
                           "structural hash, evidence window, fault/"
                           "attack context around the onset) as JSONL "
                           "to PATH (implies --monitors; default with "
                           "--telemetry OUT: OUT's sibling "
                           "'<OUT>.incidents.jsonl' when something "
                           "fired). Browse with 'observatory "
                           "incidents'")
    diag.add_argument("--trace-out", metavar="PATH", default=None,
                      help="write the span tracer's Chrome trace-event "
                           "JSON (data_gen/oracle + per-run compile/run "
                           "spans) to PATH — open in chrome://tracing or "
                           "ui.perfetto.dev")
    diag.add_argument("--metrics-out", metavar="PATH", default=None,
                      help="dump the process metrics registry (Prometheus "
                           "text format — the daemon's /metrics "
                           "exposition) to PATH at exit")

    out = p.add_argument_group("output")
    out.add_argument("--plot", metavar="PATH", default=None,
                     help="save the 2-panel log-scale figure to PATH")
    out.add_argument("--json", metavar="PATH", default=None,
                     help="dump all run histories + summaries as JSON")
    out.add_argument("-q", "--quiet", action="store_true",
                     help="log warnings only (package log level WARNING)")
    out.add_argument("-v", "--verbose", action="store_true",
                     help="debug-level package logging")
    return p


def config_from_args(args: argparse.Namespace) -> ExperimentConfig:
    return ExperimentConfig(
        n_workers=args.n_workers,
        local_batch_size=args.local_batch_size,
        n_iterations=args.n_iterations,
        learning_rate_eta0=args.learning_rate_eta0,
        l2_regularization_lambda=args.l2_lambda,
        strong_convexity_mu=args.l2_lambda,
        problem_type=args.problem_type,
        n_samples=args.n_samples,
        n_features=args.n_features,
        n_informative_features=args.n_informative_features,
        classification_sep=args.classification_sep,
        suboptimality_threshold=args.suboptimality_threshold,
        backend=args.backend,
        algorithm=args.algorithm,
        topology=args.topology,
        lr_schedule=args.lr_schedule,
        admm_c=args.admm_c,
        admm_rho=args.admm_rho,
        huber_delta=args.huber_delta,
        n_classes=args.n_classes,
        compression=args.compression,
        compression_k=args.compression_k,
        choco_gamma=args.choco_gamma,
        local_steps=args.local_steps,
        participation_rate=args.participation_rate,
        execution=args.execution,
        latency_model=args.latency_model,
        latency_mean=args.latency_mean,
        latency_tail=args.latency_tail,
        topology_impl=args.topology_impl,
        seed=args.seed,
        topology_seed=args.topology_seed,
        data_seed=args.data_seed,
        replicas=args.replicas,
        tp_degree=args.tp,
        worker_mesh=args.worker_mesh,
        topology_sampler=args.topology_sampler,
        halo_overlap=args.halo_overlap,
        eval_every=args.eval_every,
        erdos_renyi_p=args.erdos_renyi_p,
        edge_drop_prob=args.edge_drop_prob,
        straggler_prob=args.straggler_prob,
        burst_len=args.burst_len,
        mttf=args.mttf,
        mttr=args.mttr,
        rejoin=args.rejoin,
        attack=args.attack,
        n_byzantine=args.n_byzantine,
        attack_scale=args.attack_scale,
        aggregation=args.aggregation,
        robust_b=args.robust_b,
        clip_tau=args.clip_tau,
        robust_impl=args.robust_impl,
        partition=args.partition,
        gossip_schedule=args.gossip_schedule,
        mixing_impl=args.mixing_impl,
        sampling_impl=args.sampling_impl,
        scan_unroll=args.scan_unroll,
        dtype=args.dtype,
        matmul_precision=args.matmul_precision,
        telemetry=getattr(args, "telemetry", None) is not None,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    # --verbose/-q map to package log levels (log.py; ISSUE-5 satellite):
    # WARNING under -q, DEBUG under -v, INFO otherwise.
    configure_logging(1 if args.verbose else (-1 if args.quiet else 0))

    if args.preset is not None:
        # Preset values apply only to flags the user did not pass. Detection
        # must not compare against defaults (an explicit flag set to its
        # default value still wins): re-parse with all defaults suppressed so
        # only command-line-provided dests appear.
        aux = build_parser()
        for action in aux._actions:
            action.default = argparse.SUPPRESS
        explicit = set(vars(aux.parse_args(argv)))
        for field, value in PRESETS[args.preset].items():
            if field not in explicit:
                setattr(args, field, value)

    if args.platform != "auto":
        # Must run before any jax operation; overrides the TPU plugin's pin
        # (and for 'tpu' fails fast if no TPU platform can initialize,
        # instead of silently benchmarking on a CPU fallback).
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.compile_cache:
        import jax

        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    if args.multihost:
        # Multi-host slice: every host runs this same process; jax wires the
        # global device mesh over ICI within a slice (and DCN across slices),
        # and the worker-axis sharding + collectives need no other changes.
        import jax

        try:
            jax.distributed.initialize()
        except ValueError as e:
            raise SystemExit(
                f"--multihost: jax.distributed.initialize() failed ({e}). "
                "On Cloud TPU slices the coordinator is auto-discovered; "
                "elsewhere set JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / "
                "JAX_PROCESS_ID, or omit --multihost on a single host."
            ) from e
        _log.info(
            "multihost: process %d of %d, %d global devices",
            jax.process_index(), jax.process_count(), len(jax.devices()),
        )

    # Grid in the suite is skipped gracefully for non-square N, but a single
    # run with an invalid combination should fail fast in config validation.
    if args.suite and args.topology == "grid":
        args.topology = _DEFAULTS.topology

    seeds_list = None
    if args.seeds:
        try:
            seeds_list = [int(x) for x in args.seeds.split(",") if x.strip()]
        except ValueError:
            raise SystemExit(
                f"--seeds must be a comma-separated integer list, got "
                f"{args.seeds!r}"
            )
        if not seeds_list:
            raise SystemExit("--seeds needs at least one seed")
        # The explicit list defines the replica axis; seed[0] anchors
        # everything else that derives from the base seed (the dataset,
        # and the topology unless --topology-seed pins it).
        args.replicas = len(seeds_list)
        args.seed = seeds_list[0]

    config = config_from_args(args)

    from distributed_optimization_tpu.simulator import Simulator

    dataset = None
    if args.dataset == "digits":
        from distributed_optimization_tpu.utils.data import generate_digits_dataset

        dataset = generate_digits_dataset(config)

    run_kwargs = {}
    replicated = config.replicas > 1 or seeds_list is not None
    if replicated:
        if seeds_list is not None:
            run_kwargs["seeds"] = seeds_list
        if args.checkpoint_dir:
            raise SystemExit(
                "--checkpoint-dir does not compose with --replicas/--seeds: "
                "continue a batch programmatically via run_batch(state0=, "
                "t0=) instead"
            )
        if args.measure_time:
            raise SystemExit(
                "--measure-time does not compose with --replicas/--seeds: "
                "the batched program is one fused vmapped scan with no "
                "per-eval host sync"
            )
    if args.checkpoint_dir:
        if args.backend != "jax":
            raise SystemExit("--checkpoint-dir requires --backend jax")
        if args.telemetry:
            raise SystemExit(
                "--telemetry does not compose with --checkpoint-dir: trace "
                "buffers are not checkpointed, so a resumed run would emit "
                "a truncated manifest"
            )
        from distributed_optimization_tpu.utils.checkpoint import CheckpointOptions

        run_kwargs["checkpoint"] = CheckpointOptions(
            directory=args.checkpoint_dir,
            every_evals=args.checkpoint_every,
            resume=not args.no_resume,
        )
    if args.progress:
        if args.backend != "jax" or args.tp > 1:
            # Heartbeats ride the jax scan's segmented execution; the
            # numpy/cpp/TP paths have no chunked form to hook — warn and
            # run without, rather than failing a script that toggles
            # backends.
            _log.warning(
                "--progress streams from the jax backend's chunked "
                "execution (tp=1); backend=%s tp=%d runs without "
                "heartbeats", args.backend, args.tp,
            )
        else:
            import sys

            from distributed_optimization_tpu.observability.progress import (
                format_progress_line,
            )

            def _print_progress(ev):
                print(format_progress_line(ev), file=sys.stderr, flush=True)

            run_kwargs["progress_cb"] = _print_progress
            run_kwargs["progress_every"] = args.progress_every
    want_monitors = (
        args.monitors or args.halt_on != "never"
        or args.incidents_out is not None
    )
    if want_monitors:
        if args.backend != "jax" or args.tp > 1:
            # Like --progress: monitors consume the jax backend's
            # segmented heartbeats — warn and run unwatched rather than
            # failing a script that toggles backends.
            _log.warning(
                "--monitors/--halt-on ride the jax backend's chunked "
                "execution (tp=1); backend=%s tp=%d runs unwatched",
                args.backend, args.tp,
            )
        else:
            from distributed_optimization_tpu.observability.monitors import (
                MonitorBank,
            )

            # A factory, not a bank: detectors latch per run, so every
            # run of a suite/matrix gets a fresh bank (the Simulator
            # resolves callables per run).
            run_kwargs["monitors"] = (
                lambda cfg: MonitorBank(cfg, halt_on=args.halt_on)
            )
    if args.measure_time is not None:
        if args.backend == "jax":
            run_kwargs["measure_timestamps"] = args.measure_time
        elif not args.measure_time:
            # Warn, don't reject: scripts that toggle the flag across
            # backends shouldn't hard-fail on the always-measured ones
            # (where --measure-time is likewise an accepted no-op).
            _log.warning(
                "--no-measure-time only applies to the jax backend's fused "
                "scan; the numpy and cpp backends always record measured "
                "per-eval timestamps — ignoring"
            )

    if args.preflight:
        from distributed_optimization_tpu.utils.diagnostics import (
            PreflightError,
            run_preflight,
        )

        try:
            passed = run_preflight()
        except PreflightError as e:
            # Loud, named failure BEFORE any compile/run time is spent:
            # the broken identity is the diagnosis.
            raise SystemExit(
                f"[cli] preflight FAILED at {e.check!r}: {e.cause}"
            ) from e
        _log.info("preflight passed: %s", ", ".join(passed))

    from distributed_optimization_tpu.utils.diagnostics import nan_debugging
    from distributed_optimization_tpu.utils.profiling import trace

    sim = Simulator(config, dataset=dataset)
    if not args.quiet:
        # Generation-time per-worker distribution report (parity: reference
        # utils.py:43-48) — makes the sorted-partition non-IID skew visible.
        from distributed_optimization_tpu.utils.data import partition_summary

        _log.info("%s", partition_summary(sim.dataset))
    with trace(args.profile_dir), nan_debugging(args.check_nans):
        if args.suite:
            if "checkpoint" in run_kwargs:
                raise SystemExit(
                    "--checkpoint-dir applies to single runs, not --suite"
                )
            sim.run_all(verbose=not args.quiet, run_kwargs=run_kwargs)
        else:
            sim.run_one(verbose=not args.quiet, run_kwargs=run_kwargs)

    sim.report_numerical_results()
    if args.plot:
        sim.plot_results(path=args.plot)
        _log.info("figure saved to %s", args.plot)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(sim.results_dict(), f, indent=1)
        _log.info("results saved to %s", args.json)
    if args.telemetry:
        sim.write_telemetry(args.telemetry)
    if want_monitors and args.backend == "jax" and args.tp <= 1:
        fired = any(
            rec.monitors is not None and rec.monitors.anomalies
            for rec in sim.records
        )
        incidents_out = args.incidents_out
        if incidents_out is None and args.telemetry and fired:
            # Incident bundles ride next to the RunTrace manifests by
            # default (the observatory convention: one directory, one
            # story).
            from distributed_optimization_tpu.observability.monitors import (
                incidents_path_for,
            )

            incidents_out = str(incidents_path_for(args.telemetry))
        if incidents_out is not None:
            sim.write_incidents(incidents_out)
        elif fired:
            _log.warning(
                "anomalies fired but no --incidents-out/--telemetry "
                "path was given; forensic bundles were not persisted"
            )
    if args.trace_out:
        sim.write_chrome_trace(args.trace_out)
    if args.metrics_out:
        from pathlib import Path

        Path(args.metrics_out).write_text(sim.metrics_text())
        _log.info("metrics dumped to %s", args.metrics_out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
