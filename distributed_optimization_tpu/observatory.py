"""``python -m distributed_optimization_tpu.observatory`` — the run
registry + perf-regression CLI.

Indexes RunTrace manifests and bench sidecars into a queryable listing
(``list``), diffs two runs (``compare``), and re-checks regenerated bench
JSON against the committed ``docs/perf/*`` within per-artifact tolerances
(``perf-diff``; ``make perf-diff``). All subcommands live on
``observability.observatory.main`` (docs/OBSERVABILITY.md).
"""

from distributed_optimization_tpu.observability.observatory import main

if __name__ == "__main__":
    raise SystemExit(main())
