"""Flight recorder: trace buffers, cost accounting, versioned run manifests.

The backends compile whole runs into fused scans where everything between
eval points is invisible; this module is the structured-observability layer
on top of them (ISSUE-5 tentpole):

- **trace buffers** (``TRACE_FIELDS``): opt-in per-eval-row health series —
  per-worker gradient/parameter norms, non-finite sentinel counts, realized
  fault-layer liveness (node-up masks, live-edge counts), and robust-
  aggregation activity — recorded INSIDE the compiled scan through the
  scan's stacked outputs (never the carry, so telemetry off or on leaves
  the optimization dataflow untouched; tests assert bitwise trajectory
  parity). Both backends emit the same schema: jax fills the rows from the
  scan ``ys``, the numpy oracle from its per-iteration loop.
- **cost & phase accounting**: XLA ``Lowered.cost_analysis()`` FLOPs/bytes
  per compiled program (``cost_from_lowered``) and wall-clock phase timings
  (``utils.profiling.PhaseTimer``, wired by the Simulator) collected into
  one structure instead of scattered locals.
- **versioned run manifests** (``RunTrace``): one schema-versioned artifact
  per run — config + hash, backend/platform, phase timings, cost analysis,
  trace buffers, and a derived run-health summary including the realized
  windowed-connectivity B̂ over the run (the quantity time-varying-gossip
  convergence actually depends on — Koloskova et al. '20; see
  ``parallel/faults.py::windowed_connectivity``). Serialized as JSON/JSONL
  by the Simulator (``write_telemetry``), the CLI (``--telemetry OUT``),
  and the bench scripts (``write_bench_manifest`` sidecars).

This module is jax-free at import time (like ``config.py``); anything that
needs the topology/fault machinery imports it lazily.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from pathlib import Path
from typing import Any, Optional

import numpy as np

# Manifest / trace schema version. Bump when a field is added, removed, or
# changes meaning; ``RunTrace.from_dict`` rejects versions it does not know.
# v2 (ISSUE-10): every manifest carries a ``provenance`` block (git SHA +
# dirty flag, jax version, device kind — before it, only the platform
# string was captured) and an optional ``spans`` list (Chrome trace
# events from the span tracer).
SCHEMA_VERSION = 2

# The trace-buffer schema: field name -> row shape kind. 'per_worker'
# fields are [n_evals, N] float32, 'scalar' fields are [n_evals] float32
# (one row per eval point; the replica-batched path adds a leading [R]).
# Both backends emit EXACTLY these keys when telemetry is on — the
# jax-vs-numpy schema-parity test pins it.
TRACE_FIELDS: dict[str, str] = {
    # L2 norm of each worker's minibatch gradient at the eval boundary,
    # evaluated at the post-step state with the SAME batch realization the
    # eval iteration's step consumed (counter-based keys on jax; the cached
    # last-drawn indices on the numpy oracle).
    "grad_norm": "per_worker",
    # L2 norm of each worker's model row.
    "param_norm": "per_worker",
    # Fault-layer node availability at the eval iteration (1.0 = up);
    # all-ones when no node-fault process is active.
    "nodes_up": "per_worker",
    # Count of non-finite entries across ALL algorithm state leaves — the
    # NaN/Inf sentinel that otherwise stays invisible until the final fetch.
    "nonfinite": "scalar",
    # Realized directed-degree sum Σ_i deg_i(t) at the eval iteration (the
    # fault layer's live-edge accounting; the static topology's degree sum
    # when fault-free, 0.0 for centralized runs).
    "live_edges": "scalar",
    # Robust-aggregation activity: fraction of received closed-neighborhood
    # messages screened out (trimmed / clipped) this round; 0.0 when no
    # robust rule is active. See ops/robust_aggregation.py activity twins.
    "clip_frac": "scalar",
}

_RUN_TRACE_KEYS = (
    "schema_version", "kind", "label", "backend", "platform", "config",
    "config_hash", "phases", "compile_seconds", "iters_per_second",
    "eval_iterations", "cost", "trace", "health", "provenance", "spans",
)

# Top-level keys of a bench manifest sidecar (``write_bench_manifest``);
# the drift-guard schema test validates committed ``*.manifest.json``
# artifacts against exactly this set.
BENCH_MANIFEST_KEYS = (
    "schema_version", "kind", "artifact", "backend", "platform", "config",
    "config_hash", "phases", "provenance", "spans",
)


def _encode_nonfinite(obj):
    """NaN/±Inf → the sentinel strings "NaN"/"Infinity"/"-Infinity".

    A flight recorder exists precisely for divergent runs, whose
    grad-norm/gap rows ARE non-finite — and bare NaN/Infinity tokens are
    invalid JSON (jq / JSON.parse reject them). Sentinel strings keep the
    manifests strict-JSON and round-trip exactly through
    ``_decode_nonfinite``.
    """
    if isinstance(obj, float):
        if math.isnan(obj):
            return "NaN"
        if math.isinf(obj):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: _encode_nonfinite(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_encode_nonfinite(v) for v in obj]
    return obj


_NONFINITE = {"NaN": float("nan"), "Infinity": float("inf"),
              "-Infinity": float("-inf")}


def _decode_nonfinite(obj):
    if isinstance(obj, str) and obj in _NONFINITE:
        return _NONFINITE[obj]
    if isinstance(obj, dict):
        return {k: _decode_nonfinite(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode_nonfinite(v) for v in obj]
    return obj


def config_hash(config_dict: dict) -> str:
    """Stable content hash of a config dict (sorted-key JSON, sha256)."""
    blob = json.dumps(config_dict, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def cost_from_lowered(lowered) -> Optional[dict]:
    """Extract the XLA cost analysis of a ``jax.stages.Lowered`` program.

    Returns a small float dict (flops, bytes accessed, ...) or None when
    the platform/version provides no analysis — never raises: cost numbers
    are telemetry, not control flow.
    """
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    keep = ("flops", "bytes accessed", "transcendentals", "optimal_seconds")
    out = {k.replace(" ", "_"): float(ca[k]) for k in keep if k in ca}
    return out or None


def _platform() -> str:
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return "unknown"


def _git_state() -> tuple:
    """(sha, dirty) of the checkout this package runs from, or (None,
    None) outside a git worktree — provenance is telemetry, never
    control flow worth raising for."""
    import subprocess

    root = str(Path(__file__).resolve().parent.parent)
    try:
        sha = subprocess.run(
            ["git", "-C", root, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        if sha.returncode != 0:
            return None, None
        status = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=10,
        )
        dirty = bool(status.stdout.strip()) if status.returncode == 0 else None
        return sha.stdout.strip(), dirty
    except Exception:
        return None, None


_PROVENANCE_CACHE: Optional[dict] = None


def provenance(refresh: bool = False) -> dict:
    """The run-environment facts every schema-v2 manifest records
    (ISSUE-10 satellite): git SHA + dirty flag of the producing checkout,
    ``jax.__version__``, and the device kind — before v2 only the
    platform string was captured, which cannot distinguish two TPU
    generations or tie a number to a commit. Cached per process (the
    git subprocess is not free); ``refresh=True`` re-reads."""
    global _PROVENANCE_CACHE
    if _PROVENANCE_CACHE is not None and not refresh:
        return dict(_PROVENANCE_CACHE)
    sha, dirty = _git_state()
    jax_version = None
    device_kind = None
    try:
        import jax

        jax_version = jax.__version__
        device_kind = jax.devices()[0].device_kind
    except Exception:
        pass
    _PROVENANCE_CACHE = {
        "git_sha": sha,
        "git_dirty": dirty,
        "jax_version": jax_version,
        "device_kind": device_kind,
    }
    return dict(_PROVENANCE_CACHE)


@dataclasses.dataclass
class RunTrace:
    """One run's flight-recorder manifest (see the module docstring).

    ``trace`` holds the per-eval-row buffers as plain lists keyed by
    ``TRACE_FIELDS`` (None when telemetry was off or the backend emits
    none); ``health`` the derived summary from ``health_summary``.
    """

    label: str
    backend: str
    platform: str
    config: dict
    config_hash: str
    phases: dict
    compile_seconds: float
    iters_per_second: float
    eval_iterations: list
    cost: Optional[dict] = None
    trace: Optional[dict] = None
    health: Optional[dict] = None
    # Schema v2: the producing environment (git sha/dirty, jax version,
    # device kind — see ``provenance()``) and the span tracer's Chrome
    # trace events (None when the producer recorded no spans).
    provenance: Optional[dict] = None
    spans: Optional[list] = None
    schema_version: int = SCHEMA_VERSION
    kind: str = "run_trace"

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in _RUN_TRACE_KEYS}

    def to_json(self) -> str:
        # allow_nan=False + sentinel-string encoding: strict JSON even for
        # the divergent runs whose trace rows are non-finite.
        return json.dumps(
            _encode_nonfinite(self.to_dict()), sort_keys=True,
            allow_nan=False,
        )

    @classmethod
    def from_dict(cls, d: dict) -> "RunTrace":
        unknown = set(d) - set(_RUN_TRACE_KEYS)
        if unknown:
            raise ValueError(
                f"RunTrace carries unknown keys {sorted(unknown)}; "
                f"schema v{SCHEMA_VERSION} defines {_RUN_TRACE_KEYS}"
            )
        missing = set(_RUN_TRACE_KEYS) - set(d)
        if missing:
            raise ValueError(f"RunTrace is missing keys {sorted(missing)}")
        if d["schema_version"] != SCHEMA_VERSION:
            raise ValueError(
                f"unsupported RunTrace schema_version {d['schema_version']} "
                f"(this build reads v{SCHEMA_VERSION})"
            )
        if d["kind"] != "run_trace":
            raise ValueError(f"not a run_trace manifest: kind={d['kind']!r}")
        return cls(**d)

    @classmethod
    def from_json(cls, blob: str) -> "RunTrace":
        return cls.from_dict(_decode_nonfinite(json.loads(blob)))


def build_run_trace(
    label: str,
    config,
    history,
    *,
    phases: Optional[dict] = None,
    health: Optional[dict] = None,
    platform: Optional[str] = None,
    spans: Optional[list] = None,
) -> RunTrace:
    """Assemble a ``RunTrace`` from an ``ExperimentConfig`` + ``RunHistory``.

    ``phases`` may be a plain dict or a span ``Tracer`` (its aggregated
    ``.phases`` dict is recorded, and — unless ``spans`` is passed
    explicitly — its Chrome trace events land in the ``spans`` field).
    """
    cd = config.to_dict()
    trace = None
    if history.trace is not None:
        trace = {
            k: np.asarray(v, dtype=np.float64).tolist()
            for k, v in history.trace.items()
        }
    if spans is None and hasattr(phases, "chrome_events"):
        spans = phases.chrome_events()
    phase_dict = dict(getattr(phases, "phases", phases) or {})
    return RunTrace(
        label=label,
        backend=config.backend,
        platform=platform if platform is not None else _platform(),
        config=cd,
        config_hash=config_hash(cd),
        phases=phase_dict,
        compile_seconds=float(history.compile_seconds),
        iters_per_second=float(history.iters_per_second),
        eval_iterations=np.asarray(history.eval_iterations).tolist(),
        cost=history.cost,
        trace=trace,
        health=health,
        provenance=provenance(),
        spans=spans,
    )


def write_jsonl(path, traces: list[RunTrace]) -> None:
    """One manifest per line (JSONL) — the CLI/Simulator emission format."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "w") as f:
        for tr in traces:
            f.write(tr.to_json() + "\n")


def read_jsonl(path) -> list[RunTrace]:
    return [
        RunTrace.from_json(line)
        for line in Path(path).read_text().splitlines()
        if line.strip()
    ]


# --------------------------------------------------------------- run health


def _config_topology(config):
    """The run's communication graph, built once per health derivation
    (None for centralized configs). ``health_summary`` threads one build
    through both consumers — at the matrix-free scales the ER constructor
    walks an O(N²) draw stream, so rebuilding per helper is real seconds
    of redundant host work per request."""
    from distributed_optimization_tpu.algorithms import get_algorithm
    from distributed_optimization_tpu.parallel import build_topology

    if not get_algorithm(config.algorithm).is_decentralized:
        return None
    return build_topology(
        config.topology, config.n_workers,
        erdos_renyi_p=config.erdos_renyi_p,
        seed=config.resolved_topology_seed(),
        impl=config.resolved_topology_impl(),
        sampler=config.resolved_topology_sampler(),
    )


def realized_bhat(
    config, max_cells: int = 2_000_000, *, topo=None
) -> Optional[dict]:
    """Realized windowed-connectivity B̂ of this config's fault process.

    Rebuilds the run's fault timeline host-side — bitwise the realization
    the backends consume (memoryless modes are the burst_len=1 /
    iid-equivalent points of the persistent chains, see
    ``parallel/faults.py``) — and measures the smallest B such that every
    length-B window's union graph is connected. Returns ``{"bhat",
    "horizon"}`` (bhat None when even the full-horizon union is
    disconnected), or None when the notion does not apply (centralized,
    matching schedules, no peer graph). The horizon is truncated so the
    [horizon, E] unroll stays under ``max_cells`` — recorded honestly in
    the result.
    """
    from distributed_optimization_tpu.algorithms import get_algorithm
    from distributed_optimization_tpu.parallel.faults import (
        _edge_list,
        _union_connected,
        config_faults_active,
        timeline_for_config,
        windowed_connectivity,
    )

    if not get_algorithm(config.algorithm).is_decentralized:
        return None
    if config.gossip_schedule != "synchronous":
        # Matching schedules realize per-round matchings, not edge-drop
        # processes — the timeline rebuild below would not be the realized
        # graph sequence.
        return None
    if topo is None:
        topo = _config_topology(config)
    edges = _edge_list(topo)
    n_edges = max(len(edges), 1)
    if not config_faults_active(config):
        connected = _union_connected(
            np.ones(len(edges), dtype=bool), edges, config.n_workers
        )
        return {"bhat": 1 if connected else None,
                "horizon": config.n_iterations}
    horizon = min(config.n_iterations, max(1, max_cells // n_edges))
    tl = timeline_for_config(config, topo, horizon)
    return {"bhat": windowed_connectivity(tl, topo),
            "horizon": horizon}


def health_summary(
    config, history, *, serving: Optional[dict] = None,
    d_features: Optional[int] = None,
) -> dict:
    """Derive the run-health block from a finished run's history.

    Always includes the final gap, the realized/nominal connectivity
    diagnostics, and the comms block (bytes moved per round — the
    production currency compressed gossip trades on); trace-derived
    statistics (worst-worker grad norm, non-finite totals, liveness)
    appear when the run recorded trace buffers.

    ``serving``: the per-request serving facts (executable-cache hit,
    compile seconds saved, cohort size/coalescing, queue wait — see
    ``serving.service.Request.serving_block``) recorded verbatim under
    ``"serving"`` when the run was served rather than invoked directly;
    ``format_report`` summarizes them in its one-line serving section.
    """
    h: dict[str, Any] = {}
    if serving is not None:
        h["serving"] = dict(serving)
    obj = np.asarray(history.objective, dtype=np.float64)
    finite = obj[np.isfinite(obj)]
    h["final_gap"] = float(obj[-1]) if obj.size else None
    h["n_nonfinite_evals"] = int(obj.size - finite.size)
    topo = _config_topology(config)  # one build serves every block below
    tr = history.trace
    if tr:
        gn = np.asarray(tr["grad_norm"], dtype=np.float64)
        per_worker_peak = gn.max(axis=tuple(range(gn.ndim - 1)))
        h["worst_worker_grad_norm"] = float(per_worker_peak.max())
        h["worst_worker"] = int(per_worker_peak.argmax())
        h["final_max_param_norm"] = float(
            np.asarray(tr["param_norm"])[..., -1, :].max()
        )
        h["nonfinite_total"] = float(np.sum(tr["nonfinite"]))
        nodes = np.asarray(tr["nodes_up"], dtype=np.float64)
        h["min_nodes_up_frac"] = float(nodes.mean(axis=-1).min())
        if config.participation_rate < 1.0:
            # Realized participation per eval round (the satellite: the
            # recorded series IS the nodes_up trace — availability under
            # client sampling is churn-up AND sampled-in); the summary
            # quotes its mean against the configured target rate.
            h["participation"] = {
                "rate": float(config.participation_rate),
                "realized_frac_mean": float(nodes.mean()),
            }
        h["clip_frac_mean"] = float(np.mean(tr["clip_frac"]))
        live = np.asarray(tr["live_edges"], dtype=np.float64)
        nominal = (
            float(np.asarray(topo.degrees).sum()) if topo is not None
            else None
        )
        h["realized_edge_frac"] = (
            float(live.mean() / nominal) if nominal else None
        )
    h["comms"] = comms_summary(
        config, history, topo=topo, d_features=d_features
    )
    h["windowed_connectivity"] = realized_bhat(config, topo=topo)
    # Async block scoped to the rounds THIS history executed (a
    # continuation slice's eval axis carries its global round window, so
    # its health never mixes slice floats with full-schedule durations).
    rounds = None
    ev = np.asarray(getattr(history, "eval_iterations", []))
    if ev.size:
        rounds = (int(ev[0]) - config.eval_every, int(ev[-1]))
    a = async_summary(config, rounds=rounds)
    if a is not None:
        # Floats per VIRTUAL second from the run's OWN realized
        # accounting (the comms_summary convention) over the executed
        # window's simulated duration — events have no shared round, so
        # per-round accounting has the wrong denominator (docs/ASYNC.md).
        total = getattr(history, "total_floats_transmitted", None)
        a["floats_per_virtual_second"] = (
            float(total) / a["virtual_duration"]
            if total is not None and a["virtual_duration"] > 0 else 0.0
        )
        h["async"] = a
    return h


def async_summary(config, *, rounds=None) -> Optional[dict]:
    """Event-schedule health block for asynchronous runs (docs/ASYNC.md).

    Reads the run's event timeline host-side — bitwise the schedule the
    backends executed (``parallel/events.py`` is (seed, horizon)-pure, the
    ``realized_bhat`` convention) — and derives what the execution mode is
    ABOUT: the realized staleness histogram, the per-worker virtual-clock
    skew a barrier would have flattened, and the schedule facts behind
    the floats-per-VIRTUAL-second figure ``health_summary`` completes
    from the run's own realized comms accounting (events have no shared
    round, so per-round accounting is the wrong denominator).
    ``sync_virtual_duration`` prices the bulk-synchronous twin on the
    same latency draws — the ratio is the realized straggler tax.
    ``rounds``: an optional (start, stop) global ROUND window — a
    continuation slice describes only the events it executed. None for
    synchronous configs.
    """
    if getattr(config, "execution", "sync") != "async":
        return None
    from distributed_optimization_tpu.backends.async_scan import timeline_for
    from distributed_optimization_tpu.parallel.events import (
        clock_skew,
        realize_event_faults,
        staleness_histogram,
        sync_round_times,
    )
    from distributed_optimization_tpu.parallel.faults import (
        config_faults_active,
        timeline_for_config,
    )

    # Shares the backend's own cached build (timeline_for's LRU): the
    # O(E) host unroll runs once per config, not once per consumer.
    _, tl = timeline_for(config)
    n = tl.n_workers
    start_r, stop_r = (0, tl.n_rounds) if rounds is None else rounds
    ev_window = (start_r * n, stop_r * n)
    sl = slice(*ev_window)
    # Virtual duration of the executed window: event times are global, so
    # a slice's duration is the time between its boundary events.
    t_start = float(tl.t_virtual[ev_window[0] - 1]) if ev_window[0] else 0.0
    t_stop = (
        float(tl.t_virtual[ev_window[1] - 1])
        if ev_window[1] > ev_window[0] else t_start
    )
    svt = sync_round_times(tl)
    s_start = float(svt[start_r - 1]) if start_r else 0.0
    faults: Optional[dict] = None
    if config_faults_active(config):
        # Event-realized fault diagnostics (ISSUE-17): the SAME
        # (seed, horizon)-pure realization the backends executed —
        # availability is the fired-event fraction, in-flight losses are
        # crashed firing workers (their stale gradient evaporates),
        # thinned events are participation draws, degraded exchanges are
        # live firings whose partner (or edge) was down and fell back to
        # the self-loop.
        from distributed_optimization_tpu.parallel import build_topology
        topo = build_topology(
            config.topology, config.n_workers,
            erdos_renyi_p=config.erdos_renyi_p,
            seed=config.resolved_topology_seed(),
        )
        ft = timeline_for_config(config, topo, tl.n_rounds)
        real = realize_event_faults(tl, ft)
        fire_w = real.fire[sl]
        kk = tl.local_step.astype(np.int64)
        ww = tl.worker.astype(np.int64)
        ones = np.ones(len(ww), dtype=bool)
        worker_up = ft.node_up[kk, ww] if ft.node_up is not None else ones
        worker_in = ft.part_up[kk, ww] if ft.part_up is not None else ones
        faults = {
            "availability": (
                float(fire_w.mean()) if fire_w.size else 1.0
            ),
            # Crash no-ops (the in-flight gradient evaporated) vs
            # participation skips — the EventFaultRealization split,
            # windowed to the executed slice.
            "n_inflight_lost": int((~worker_up[sl]).sum()),
            "n_thinned": int((worker_up & ~worker_in)[sl].sum()),
            "n_degraded_exchanges": int(real.n_degraded),
            "n_rejoin_events": int(real.rejoin[sl].sum()),
            "matched_fired": int(real.matched_fired[sl].sum()),
        }
    return {
        "latency_model": config.latency_model,
        "latency_mean": float(config.latency_mean),
        "latency_tail": float(config.latency_tail),
        "events": int(ev_window[1] - ev_window[0]),
        # One pairwise exchange (2·d floats) per matched event; the
        # absolute floats-per-virtual-second figure is completed by
        # health_summary from the run's realized accounting — the
        # trained dimension is the DATASET's (bias column included), not
        # a config-derived guess.
        "matched_events": int(tl.matched()[sl].sum()),
        "staleness": staleness_histogram(tl, events=ev_window),
        "virtual_clock": clock_skew(tl, rounds=(start_r, stop_r)),
        "virtual_duration": t_stop - t_start,
        "sync_virtual_duration": (
            float(svt[stop_r - 1]) - s_start if stop_r > start_r else 0.0
        ),
        "faults": faults,
    }


def comms_summary(
    config, history, *, topo=None, d_features: Optional[int] = None
) -> Optional[dict]:
    """Bytes-moved accounting block (ISSUE-6 satellite).

    Derived from the run's OWN float accounting so it is exact on every
    path: the backends record ``total_floats_transmitted`` as per-edge
    payload (``Compressor.floats_per_edge`` × the algorithm's gossip
    rounds) × realized live edges — summed over the fault timeline when
    one is active — so dividing by the horizon gives the realized mean
    floats moved per ITERATION, and dividing further by the mean
    realized live-edge count recovers the per-edge per-iteration
    payload: the compressor's floats_per_edge times the algorithm's
    gossip rounds (2× for gradient tracking, which compresses both its
    x and y exchanges). This is what makes a compression win visible in
    the report/manifest without opening bench JSON. None for
    centralized runs (no peer edges to account).
    """
    from distributed_optimization_tpu.algorithms import get_algorithm

    algo = get_algorithm(config.algorithm)
    if not algo.is_decentralized:
        return None
    total = getattr(history, "total_floats_transmitted", None)
    if total is None:
        return None
    per_iter = float(total) / max(config.n_iterations, 1)
    out: dict[str, Any] = {
        "compression": config.compression,
        # Per ITERATION, not per gossip round: gradient tracking's two
        # exchanges per iteration are both included (its per-round
        # payload is the same as dsgd's; the per-iteration figure is 2×).
        "floats_per_iteration_mean": per_iter,
    }
    if config.local_steps > 1:
        # τ local descents per round at unchanged per-round comms — the
        # federated communication-reduction lever (docs/PERF.md §14):
        # floats per GRADIENT STEP is the per-round figure over τ.
        out["local_steps"] = int(config.local_steps)
        out["floats_per_gradient_step"] = per_iter / config.local_steps
    tr = history.trace
    if tr and "live_edges" in tr:
        live = np.asarray(tr["live_edges"], dtype=np.float64)
        if live.size and live.mean() > 0:
            out["floats_per_edge_per_iteration"] = float(
                per_iter / live.mean()
            )
    ici = ici_summary(config, topo=topo, d_features=d_features)
    if ici is not None:
        # Sharded worker mesh (docs/PERF.md §16): real collective bytes
        # alongside the analytic floats — the halo plan is static, so the
        # per-device ppermute traffic is exact, and simulated floats and
        # ICI bytes finally sit in one report (the PAPER.md north star).
        out["ici"] = ici
    return out


def ici_summary(
    config, *, topo=None, d_features: Optional[int] = None
) -> Optional[dict]:
    """Bytes-over-ICI block for sharded worker-mesh runs (ISSUE-11).

    Rebuilds the static halo-exchange plan host-side — the identical plan
    the backend's shard_map mixing executes — and prices the per-device
    ppermute traffic exactly: each device ships the rotation-padded WIRE
    rows per gossip round (every rotation pads to its max per-device
    count so the collective is shape-uniform; on regular rings wire ==
    useful, on irregular graphs the pad rows ride the wire too), each
    row carrying the per-config payload width. Plain gossip moves the
    d_model model row in the state dtype; node-process faults
    (stragglers/churn/participation) add the 1-float availability
    exchange (always f32 on the wire) plus the realized-degree column
    riding the model buffer in the body's accumulation dtype
    (``faults.make_halo_faulty_mixing``); robust screening adds the
    availability exchange, and clipped gossip additionally the degree
    column (``collectives.make_halo_robust_aggregator_t``). An active
    adversary executes BOTH branches of the screened mix's ``jnp.where``
    (the benign base mix AND the honest view —
    ``parallel/adversary.py``), so attack configs price two exchange
    forms per round. None when
    the run is unsharded (``worker_mesh`` off) or centralized. The same
    numbers feed the PR-10 metrics registry as ``dopt_worker_mesh_*``
    per-device gauges when the backend actually runs.

    ``topo``: the already-built topology when the caller has one
    (``health_summary`` builds it once for every block) — rebuilding a
    matrix-free Erdős–Rényi graph replays the dense sampler's O(N²)
    stream, so the one-build convention matters here.
    """
    if getattr(config, "worker_mesh", 0) < 2:
        return None
    from distributed_optimization_tpu.algorithms import get_algorithm
    from distributed_optimization_tpu.models import get_problem
    from distributed_optimization_tpu.parallel.topology import (
        build_halo_plan,
        neighbor_tables_for,
    )

    algo = get_algorithm(config.algorithm)
    if not algo.is_decentralized:
        return None
    if topo is None:
        topo = _config_topology(config)
    nbr_idx, nbr_mask = neighbor_tables_for(topo)
    plan = build_halo_plan(
        nbr_idx, nbr_mask, config.worker_mesh,
        sampler=topo.sampler, overlap=config.halo_overlap,
    )
    problem = get_problem(
        config.problem_type, huber_delta=config.huber_delta,
        n_classes=config.n_classes,
    )
    # The trained dimension — the payload width every gossip round
    # actually moves per row — plus the fault/robust side-channel floats
    # enumerated in the docstring. ``d_features`` is the DATASET's
    # realized column count (bias included) when the caller has one
    # (Simulator/backend do; the digits dataset ignores ``n_features``);
    # the config-derived ``n_features + 1`` is the synthetic-path value.
    if d_features is None:
        d_features = config.n_features + 1
    d_model = problem.param_dim(d_features)
    robust = config.aggregation != "gossip" and config.robust_b > 0
    attack = config.attack != "none"
    node_faults = (
        config.straggler_prob > 0.0
        or config.mttf > 0.0
        or config.participation_rate < 1.0
    )
    if robust:
        avail = 1
        deg_col = 1 if config.aggregation == "clipped_gossip" else 0
    elif node_faults:
        avail, deg_col = 1, 1  # availability bit + realized-degree column
    else:
        avail = deg_col = 0
    if config.compression != "none":
        from distributed_optimization_tpu.ops.compression import (
            make_compressor,
        )

        floats_per_row = make_compressor(
            config.compression, d_model, config.compression_k
        ).floats_per_edge * algo.gossip_rounds
    else:
        floats_per_row = (d_model + deg_col + avail) * algo.gossip_rounds
    itemsize = int(np.dtype(config.dtype).itemsize)
    # Per-row bytes of each exchange FORM the compiled round can run.
    # The availability bit ships as its OWN f32 halo exchange (fault
    # masks are explicit float32 on every path — 4 B/row at any model
    # dtype); the fault/robust model buffers ship in the bodies'
    # ACCUMULATION dtype (promote(f32, model) — 4 B floats even under
    # bfloat16 state); only the plain no-fault mixing op exchanges in
    # the state dtype itself.
    acc_size = max(itemsize, 4)
    if node_faults:
        base_row = 4 + (d_model + 1) * acc_size  # avail + model+degree
    elif config.compression != "none":
        # Compressed halo exchange (ISSUE-18): the wire rows carry the
        # compressor's payload instead of the dense d_model row — the
        # analytic accounting convention every comms number in this repo
        # uses (top_k/random_k: k values + k indices; qsgd: packed bits +
        # the norm). Compression composes only with the plain benign mesh
        # (config rejects it with faults/robust/attack), so this branch
        # never interacts with the side-channel pricing above.
        from distributed_optimization_tpu.ops.compression import (
            make_compressor,
        )

        base_row = make_compressor(
            config.compression, d_model, config.compression_k
        ).floats_per_edge * itemsize
    else:
        base_row = d_model * itemsize            # plain halo mix
    robust_row = 4 + (d_model + deg_col) * acc_size
    # An active adversary executes BOTH branches of the screened mix's
    # jnp.where (parallel/adversary.py::make_byzantine_mixing): the
    # benign base mix for Byzantine rows AND the honest view — the
    # robust aggregate when a rule defends, the base mix of the
    # corrupted stack otherwise. A pure defense (robust rule, no
    # attack) binds the aggregate alone.
    if attack and robust:
        round_row_bytes = base_row + robust_row
    elif attack:
        round_row_bytes = 2 * base_row
    elif robust:
        round_row_bytes = robust_row
    else:
        round_row_bytes = base_row
    row_bytes = algo.gossip_rounds * round_row_bytes
    # Wire rows, not useful rows: every rotation pads to its max
    # per-device count so the ppermute stays shape-uniform — each device
    # ships s_max rows per rotation whether or not all of them are
    # referenced by the destination (HaloStep.send_idx pad rows).
    wire_rows = int(sum(st.send_idx.shape[1] for st in plan.steps))
    sent = plan.sent_rows.astype(np.int64)
    n_dev = int(config.worker_mesh)
    return {
        "worker_mesh": n_dev,
        "shard_rows": int(plan.shard_rows),
        "halo_rows_max": int(plan.h_max),
        "halo_rows_per_device": [
            int(len(h)) for h in plan.halo_idx
        ],
        "exchange_rotations": len(plan.steps),
        "wire_rows_per_device": wire_rows,
        "useful_rows_per_device": [int(r) for r in sent],
        "bytes_per_device_per_round": [wire_rows * row_bytes] * n_dev,
        "bytes_per_device_per_round_max": wire_rows * row_bytes,
        "bytes_total_per_round": n_dev * wire_rows * row_bytes,
        "payload_floats_per_row": (
            float(floats_per_row) if config.compression != "none"
            else int(floats_per_row)
        ),
        "compression": config.compression,
        "itemsize": itemsize,
    }


def _nominal_degree_sum(config) -> Optional[float]:
    topo = _config_topology(config)
    return float(np.asarray(topo.degrees).sum()) if topo is not None else None


# ----------------------------------------------------------- bench sidecars


def write_bench_manifest(
    artifact_path, *, config=None, phases=None, artifact_name=None,
) -> Path:
    """Write the ``<artifact>.manifest.json`` sidecar for a bench artifact.

    Every ``examples/bench_*.py`` calls this after writing its JSON so regen
    runs leave a schema-versioned provenance record (platform, config hash,
    phase timings) next to each number. ``config`` is the bench's base
    ``ExperimentConfig`` (or a plain dict, or None for benches without one
    canonical config); ``phases`` a ``PhaseTimer`` or plain dict.
    """
    p = Path(artifact_path)
    out = p.with_suffix(".manifest.json")
    cd = None
    if config is not None:
        cd = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    phase_dict = dict(getattr(phases, "phases", phases) or {})
    # Span tracing (schema v2): bench scripts pass their PhaseTimer —
    # now a span Tracer — so the manifest carries the perfetto-viewable
    # span tree alongside the flat phase totals, with no bench changes.
    spans = (
        phases.chrome_events() if hasattr(phases, "chrome_events") else None
    )
    payload = {
        "schema_version": SCHEMA_VERSION,
        "kind": "bench_manifest",
        "artifact": artifact_name or p.name,
        "backend": (cd or {}).get("backend"),
        "platform": _platform(),
        "config": cd,
        "config_hash": config_hash(cd) if cd else None,
        "phases": {k: float(v) for k, v in phase_dict.items()},
        "provenance": provenance(),
        "spans": spans,
    }
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return out
