"""Per-worker mini-batch sampling with explicit JAX PRNG keys.

The reference samples each worker's batch from one *global* numpy RNG stream
(``np.random.choice`` at reference ``worker.py:27``, seeded once at
``main.py:24``), which makes batch draws order-dependent across workers. The
TPU-native design replaces that with counter-based PRNG: every (worker,
iteration) pair gets its own key via ``fold_in``, so sampling is
order-independent, reproducible, and embarrassingly parallel across the mesh.
Exact batch-sequence parity with the reference is impossible by construction
(documented in SURVEY.md §3.4); equivalence tests inject identical batches
instead.

Semantics preserved from the reference (``worker.py:15-28``):
- sampling is without replacement;
- the effective batch size is ``min(batch_size, n_valid)`` — encoded as a
  weight vector rather than a dynamic shape;
- a worker with zero valid samples yields an all-zero weight vector (its
  gradient contribution is then exactly the regularizer term, mirroring the
  empty-batch guard at ``obj_problems.py:14-15``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp



def _worker_keys(key: jax.Array, step: jax.Array, n_workers: int) -> jax.Array:
    """Per-(iteration, worker) keys: fold_in(fold_in(key, step), worker_id).

    SHARED by the gather and dense sampling paths — both must derive the
    identical key stream or their sampled subsets diverge (the dense==gather
    equivalence is structural, not just tested).
    """
    step_key = jax.random.fold_in(key, step)
    return jax.vmap(lambda i: jax.random.fold_in(step_key, i))(
        jnp.arange(n_workers)
    )


def _masked_scores(worker_key: jax.Array, n_local: int, n_valid: jax.Array) -> jax.Array:
    """One worker's uniform ranking scores with padding rows pushed to -inf.
    Shared by both sampling paths (same draw => same subset)."""
    scores = jax.random.uniform(worker_key, (n_local,))
    valid = jnp.arange(n_local) < n_valid
    return jnp.where(valid, scores, -jnp.inf)


def _effective_batch(batch_size: int, n_valid: jax.Array, n_local: int) -> jax.Array:
    """min(batch_size, n_valid, n_local) — the reference's batch clamp
    (worker.py:21), shared by both sampling paths."""
    return jnp.minimum(jnp.minimum(batch_size, n_valid), n_local)


def sample_batch_indices(
    key: jax.Array, n_local: int, n_valid: jax.Array, batch_size: int
) -> tuple[jax.Array, jax.Array]:
    """Draw ``batch_size`` row indices without replacement from the valid rows.

    Returns ``(indices [batch_size] int32, weights [batch_size] f32)`` where
    weights are ``1/min(batch_size, n_valid)`` on rows that represent real
    draws and 0 on padding rows. Uses the Gumbel-top-k trick (uniform scores +
    top-k) so shapes stay static under jit.
    """
    scores = _masked_scores(key, n_local, n_valid)
    # A shard can be smaller than the requested batch; keep static shapes by
    # tiling the top-k indices up to batch_size and zero-weighting the
    # surplus rows.
    k = min(batch_size, n_local)
    _, top_indices = jax.lax.top_k(scores, k)
    indices = jnp.resize(top_indices, (batch_size,))
    effective = _effective_batch(batch_size, n_valid, n_local)
    draw_is_real = jnp.arange(batch_size) < effective
    weights = jnp.where(draw_is_real, 1.0 / jnp.maximum(effective, 1), 0.0)
    return indices.astype(jnp.int32), weights.astype(jnp.float32)


def sample_worker_batch_weights(
    key: jax.Array,
    step: jax.Array,
    n_valid: jax.Array,  # [N] true shard sizes
    n_local: int,  # L, the padded shard length
    batch_size: int,
) -> jax.Array:
    """Dense-weights formulation of per-worker batch sampling: ``[N, L]``
    weights carrying ``1/b_eff`` on sampled rows and 0 elsewhere.

    Selects the SAME row subsets as :func:`sample_worker_batches` for the
    same key (same per-worker uniform draw; membership in the top
    ``b_eff`` scores computed by rank instead of ``lax.top_k``, with ties
    broken toward the lower index exactly like a stable top-k — ties have
    ~zero probability for float32 uniforms anyway). The gradient over the
    full shard with these weights equals the gathered mini-batch gradient.

    Why it exists: the gather path runs batched ``top_k`` + row gathers
    every iteration — serial latency-bound ops on TPU. This form trades
    them for one [L, L] comparison matrix and a full-shard weighted
    gradient: ~L/b more FLOPs, but fewer/larger ops, which wins when the
    step is latency-bound (measured: docs/perf/breakdown.json — the
    full-shard objective pass costs ~4µs while the sampling+gather
    machinery dominates the 84µs iteration).
    """
    worker_keys = _worker_keys(key, step, n_valid.shape[0])
    idx = jnp.arange(n_local)

    def one(worker_key, ni):
        u = _masked_scores(worker_key, n_local, ni)
        # rank[l] = #{m : u_m > u_l, or u_m == u_l with m < l} — the position
        # l would take in a stable descending sort (= lax.top_k order).
        beats = (u[None, :] > u[:, None]) | (
            (u[None, :] == u[:, None]) & (idx[None, :] < idx[:, None])
        )
        rank = jnp.sum(beats, axis=1)
        effective = _effective_batch(batch_size, ni, n_local)
        sel = (rank < effective) & (idx < ni)
        return jnp.where(sel, 1.0 / jnp.maximum(effective, 1), 0.0)

    return jax.vmap(one)(worker_keys, n_valid).astype(jnp.float32)


def sample_worker_batches(
    key: jax.Array,
    step: jax.Array,
    X: jax.Array,  # [N, L, d] stacked per-worker shards (padded)
    y: jax.Array,  # [N, L]
    n_valid: jax.Array,  # [N] true shard sizes
    batch_size: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sample one mini-batch per worker for iteration ``step``.

    Returns ``(Xb [N, b, d], yb [N, b], weights [N, b])``. Each worker's key is
    ``fold_in(fold_in(key, step), worker_id)`` — independent of every other
    worker and iteration.
    """
    worker_keys = _worker_keys(key, step, X.shape[0])

    def one(worker_key, Xi, yi, ni):
        idx, w = sample_batch_indices(worker_key, Xi.shape[0], ni, batch_size)
        return Xi[idx], yi[idx], w

    return jax.vmap(one)(worker_keys, X, y, n_valid)
