"""Per-worker mini-batch sampling with explicit JAX PRNG keys.

The reference samples each worker's batch from one *global* numpy RNG stream
(``np.random.choice`` at reference ``worker.py:27``, seeded once at
``main.py:24``), which makes batch draws order-dependent across workers. The
TPU-native design replaces that with counter-based PRNG: every (worker,
iteration) pair gets its own key via ``fold_in``, so sampling is
order-independent, reproducible, and embarrassingly parallel across the mesh.
Exact batch-sequence parity with the reference is impossible by construction
(documented in SURVEY.md §3.4); equivalence tests inject identical batches
instead.

Semantics preserved from the reference (``worker.py:15-28``):
- sampling is without replacement;
- the effective batch size is ``min(batch_size, n_valid)`` — encoded as a
  weight vector rather than a dynamic shape;
- a worker with zero valid samples yields an all-zero weight vector (its
  gradient contribution is then exactly the regularizer term, mirroring the
  empty-batch guard at ``obj_problems.py:14-15``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_batch_indices(
    key: jax.Array, n_local: int, n_valid: jax.Array, batch_size: int
) -> tuple[jax.Array, jax.Array]:
    """Draw ``batch_size`` row indices without replacement from the valid rows.

    Returns ``(indices [batch_size] int32, weights [batch_size] f32)`` where
    weights are ``1/min(batch_size, n_valid)`` on rows that represent real
    draws and 0 on padding rows. Uses the Gumbel-top-k trick (uniform scores +
    top-k) so shapes stay static under jit.
    """
    scores = jax.random.uniform(key, (n_local,))
    # Push invalid (padding) rows to the bottom of the ranking.
    valid = jnp.arange(n_local) < n_valid
    scores = jnp.where(valid, scores, -jnp.inf)
    # A shard can be smaller than the requested batch (reference worker.py:21
    # clamps the effective batch); keep static shapes by tiling the top-k
    # indices up to batch_size and zero-weighting the surplus rows.
    k = min(batch_size, n_local)
    _, top_indices = jax.lax.top_k(scores, k)
    indices = jnp.resize(top_indices, (batch_size,))
    effective = jnp.minimum(jnp.minimum(batch_size, n_valid), n_local)
    draw_is_real = jnp.arange(batch_size) < effective
    weights = jnp.where(draw_is_real, 1.0 / jnp.maximum(effective, 1), 0.0)
    return indices.astype(jnp.int32), weights.astype(jnp.float32)


def sample_worker_batches(
    key: jax.Array,
    step: jax.Array,
    X: jax.Array,  # [N, L, d] stacked per-worker shards (padded)
    y: jax.Array,  # [N, L]
    n_valid: jax.Array,  # [N] true shard sizes
    batch_size: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sample one mini-batch per worker for iteration ``step``.

    Returns ``(Xb [N, b, d], yb [N, b], weights [N, b])``. Each worker's key is
    ``fold_in(fold_in(key, step), worker_id)`` — independent of every other
    worker and iteration.
    """
    n_workers = X.shape[0]
    step_key = jax.random.fold_in(key, step)
    worker_keys = jax.vmap(lambda i: jax.random.fold_in(step_key, i))(
        jnp.arange(n_workers)
    )

    def one(worker_key, Xi, yi, ni):
        idx, w = sample_batch_indices(worker_key, Xi.shape[0], ni, batch_size)
        return Xi[idx], yi[idx], w

    return jax.vmap(one)(worker_keys, X, y, n_valid)
