"""Objective and gradient kernels (pure JAX, jit/vmap/grad-compatible).

Capability parity with the reference's objective library (reference
``obj_problems.py:3-69``): L2-regularized logistic regression with the
numerically stable ``max(0, -z) + log1p(exp(-|z|))`` formulation, and
L2-regularized least squares ("quadratic"). Both come in two forms:

- the *plain* form matching the reference signature ``f(w, X, y, reg)``, used
  by the numpy fidelity backend and parity tests;
- a *weighted* form taking per-sample weights, which is what the TPU path uses:
  static shapes + a weight vector subsume the reference's dynamic empty-batch /
  short-batch guards (reference ``obj_problems.py:4,14,40,47``,
  ``worker.py:17-23``) without data-dependent control flow, so everything
  stays traceable under ``jit``/``scan``.

All functions are closed-form (no autodiff needed at runtime), but tests check
them against ``jax.grad`` of the objectives and finite differences.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_optimization_tpu.config import DEFAULT_HUBER_DELTA


def _softplus_neg(z: jax.Array) -> jax.Array:
    """log(1 + exp(-z)) computed stably as max(0, -z) + log1p(exp(-|z|))."""
    return jnp.maximum(0.0, -z) + jnp.log1p(jnp.exp(-jnp.abs(z)))


# ---------------------------------------------------------------------------
# Logistic regression (convex):  f(w) = mean_i log(1+exp(-y_i x_i^T w)) + (λ/2)‖w‖²
# ---------------------------------------------------------------------------


def logistic_objective(w: jax.Array, X: jax.Array, y: jax.Array, lam: float) -> jax.Array:
    """Full-batch logistic objective. Parity: reference obj_problems.py:3-11."""
    margins = y * (X @ w)
    data_loss = jnp.mean(_softplus_neg(margins))
    return data_loss + 0.5 * lam * jnp.dot(w, w)


def logistic_gradient(w: jax.Array, X: jax.Array, y: jax.Array, lam: float) -> jax.Array:
    """Mini-batch (or full-batch) logistic gradient.

    Parity: reference obj_problems.py:13-20 (stochastic) and, applied to a full
    shard, obj_problems.py:22-36 (the reference's dead full-gradient code).
    """
    margins = y * (X @ w)
    coeff = -y * jax.nn.sigmoid(-margins)  # d/dlogit of the loss, per sample
    return X.T @ coeff / X.shape[0] + lam * w


def logistic_objective_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, lam: float
) -> jax.Array:
    """Weighted logistic objective: sum_i weights_i * loss_i + (λ/2)‖w‖².

    With ``weights = mask / count`` this equals the reference's mean over the
    valid rows; with all-zero weights it degrades to the pure regularizer
    (reference returns 0.0 for an empty batch, obj_problems.py:4-5 — the
    regularizer-only value is used here instead so the function stays smooth;
    the sampling layer guarantees nonempty batches whenever a worker has data).
    """
    margins = y * (X @ w)
    data_loss = jnp.sum(weights * _softplus_neg(margins))
    return data_loss + 0.5 * lam * jnp.dot(w, w)


def logistic_gradient_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, lam: float
) -> jax.Array:
    margins = y * (X @ w)
    coeff = weights * (-y) * jax.nn.sigmoid(-margins)
    return X.T @ coeff + lam * w


# ---------------------------------------------------------------------------
# Quadratic / least squares (strongly convex):
#   f(w) = ½ mean_i (x_i^T w − y_i)² + (μ/2)‖w‖²
# ---------------------------------------------------------------------------


def quadratic_objective(w: jax.Array, X: jax.Array, y: jax.Array, mu: float) -> jax.Array:
    """Parity: reference obj_problems.py:39-44."""
    residuals = X @ w - y
    return 0.5 * jnp.mean(residuals**2) + 0.5 * mu * jnp.dot(w, w)


def quadratic_gradient(w: jax.Array, X: jax.Array, y: jax.Array, mu: float) -> jax.Array:
    """Parity: reference obj_problems.py:46-53 (and dead code 55-69)."""
    residuals = X @ w - y
    return X.T @ residuals / X.shape[0] + mu * w


def quadratic_objective_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, mu: float
) -> jax.Array:
    residuals = X @ w - y
    return 0.5 * jnp.sum(weights * residuals**2) + 0.5 * mu * jnp.dot(w, w)


def quadratic_gradient_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, mu: float
) -> jax.Array:
    residuals = X @ w - y
    return X.T @ (weights * residuals) + mu * w


# ---------------------------------------------------------------------------
# Huber regression (convex, robust):
#   f(w) = mean_i H_δ(x_i^T w − y_i) + (λ/2)‖w‖²,
#   H_δ(r) = ½r² for |r| ≤ δ, else δ(|r| − ½δ)
#
# Not in the reference — the framework's third objective family: a robust
# regression between the study's two (quadratic tails hurt under the heavy
# noise make_regression injects; Huber caps the per-sample gradient at δ‖x‖).
# δ defaults to the synthetic data's noise scale (make_regression noise=10.0,
# utils/data.py), i.e. the transition sits at ~1σ of the residuals at the
# optimum — the classical choice — and is configurable
# (``ExperimentConfig.huber_delta``) because it is data-scale-dependent; the
# single source of the default is config.DEFAULT_HUBER_DELTA. Closed forms
# only: the gradient coefficient is clip(r, −δ, δ), smooth everywhere
# (H_δ is C¹).
# ---------------------------------------------------------------------------

# Backward-compatible alias; the definition lives in config (jax-free) so the
# numpy twins and the C-ABI default share it without importing this module.
HUBER_DELTA = DEFAULT_HUBER_DELTA


def _huber(r: jax.Array, delta: float) -> jax.Array:
    a = jnp.abs(r)
    return jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))


def huber_objective(
    w: jax.Array, X: jax.Array, y: jax.Array, lam: float,
    delta: float = DEFAULT_HUBER_DELTA,
) -> jax.Array:
    r = X @ w - y
    return jnp.mean(_huber(r, delta)) + 0.5 * lam * jnp.dot(w, w)


def huber_gradient(
    w: jax.Array, X: jax.Array, y: jax.Array, lam: float,
    delta: float = DEFAULT_HUBER_DELTA,
) -> jax.Array:
    r = X @ w - y
    coeff = jnp.clip(r, -delta, delta)
    return X.T @ coeff / X.shape[0] + lam * w


def huber_objective_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, lam: float,
    delta: float = DEFAULT_HUBER_DELTA,
) -> jax.Array:
    r = X @ w - y
    return jnp.sum(weights * _huber(r, delta)) + 0.5 * lam * jnp.dot(w, w)


def huber_gradient_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, lam: float,
    delta: float = DEFAULT_HUBER_DELTA,
) -> jax.Array:
    r = X @ w - y
    coeff = weights * jnp.clip(r, -delta, delta)
    return X.T @ coeff + lam * w


# ---------------------------------------------------------------------------
# Multinomial (softmax) logistic regression (convex):
#   f(W) = mean_i [logsumexp(x_i^T W) − (x_i^T W)_{y_i}] + (λ/2)‖W‖_F²,
#   W ∈ R^{d×K}, labels y_i ∈ {0, …, K−1}
#
# Not in the reference (its GLMs are scalar-output, reference
# obj_problems.py:3-69) — this is the framework's COMPUTE-BOUND tier: the
# scalar GLM gradients are matvecs (arithmetic intensity O(1), forever
# HBM-bound on TPU), while the softmax forward X @ W [b,K] and backward
# X^T @ (P − Y) [d,K] are real matmuls with 2·b·d·K FLOPs each that tile
# onto the MXU. docs/PERF.md §compute-bound measures the MFU this family
# reaches where the toy tier cannot.
#
# Parameters travel FLATTENED ([d·K] vectors) through the mixing/algorithm
# layers — gossip is elementwise over the parameter axis, so flattening is
# exact — and are reshaped here; K is inferred from the static shapes
# (w.size / X.shape[-1]), so the kernels need no bound class count.
# ---------------------------------------------------------------------------


def _softmax_ce(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Per-sample cross-entropy: logsumexp(logits) − logits[y] (stable)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(
        logits, y.astype(jnp.int32)[:, None], axis=-1
    )[:, 0]
    return lse - true


def softmax_objective(w: jax.Array, X: jax.Array, y: jax.Array, lam: float) -> jax.Array:
    logits = X @ w.reshape(X.shape[-1], -1)
    return jnp.mean(_softmax_ce(logits, y)) + 0.5 * lam * jnp.dot(w, w)


def softmax_gradient(w: jax.Array, X: jax.Array, y: jax.Array, lam: float) -> jax.Array:
    W = w.reshape(X.shape[-1], -1)
    logits = X @ W
    P = jax.nn.softmax(logits, axis=-1)
    Y = jax.nn.one_hot(y.astype(jnp.int32), W.shape[1], dtype=X.dtype)
    G = X.T @ (P - Y) / X.shape[0] + lam * W
    return G.reshape(-1)


def softmax_objective_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, lam: float
) -> jax.Array:
    logits = X @ w.reshape(X.shape[-1], -1)
    return jnp.sum(weights * _softmax_ce(logits, y)) + 0.5 * lam * jnp.dot(w, w)


def softmax_gradient_weighted(
    w: jax.Array, X: jax.Array, y: jax.Array, weights: jax.Array, lam: float
) -> jax.Array:
    W = w.reshape(X.shape[-1], -1)
    logits = X @ W
    P = jax.nn.softmax(logits, axis=-1)
    Y = jax.nn.one_hot(y.astype(jnp.int32), W.shape[1], dtype=X.dtype)
    G = X.T @ (weights[:, None] * (P - Y)) + lam * W
    return G.reshape(-1)


def batch_weights(mask: jax.Array) -> jax.Array:
    """Turn a validity mask into mean-weights: mask / max(1, sum(mask)).

    Encodes the reference's "effective batch = min(b, n_local)" semantics
    (reference worker.py:21) without dynamic shapes: invalid rows get weight 0
    and valid rows 1/count, so the weighted sum is the mean over valid rows.
    """
    count = jnp.sum(mask)
    return mask / jnp.maximum(count, 1.0)
