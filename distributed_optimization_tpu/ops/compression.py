"""Communication-compression operators for gossip algorithms.

Not present in the reference (its gossip always exchanges full d-vectors,
reference ``trainer.py:169-173``); this is the compressed-gossip capability
from the same literature line the reference's report builds on (Koloskova,
Stich & Jaggi '19 — report ref [13] authors — define CHOCO-SGD around exactly
these operators).

Each operator is a jittable contraction ``Q(key, v) -> v_compressed`` over
the last axis of an ``[N, d]`` stack, together with its per-edge float cost
(the analytic comms-accounting payload; index transmission is counted as one
float per index, the accounting convention of the sparsification literature):

- ``top_k``: keep the k largest-|magnitude| coordinates per row (biased,
  contraction factor delta = k/d); cost 2k (k values + k indices).
- ``random_k``: keep k uniformly random coordinates per row (unbiased after
  (d/k)-rescaling in expectation, but used UNscaled inside CHOCO, which
  requires only a contraction); cost 2k.
- ``none``: identity; cost d.

All operators satisfy the contraction property
E‖v − Q(v)‖² ≤ (1 − delta)‖v‖², delta > 0 — the condition CHOCO's
convergence proof needs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from distributed_optimization_tpu.config import COMPRESSIONS


@dataclasses.dataclass(frozen=True)
class Compressor:
    """A jittable row-wise compression operator with its comms payload."""

    name: str
    apply: Callable[[Optional[jax.Array], jax.Array], jax.Array]
    floats_per_edge: float  # payload replacing d in the float accounting
    delta: float  # contraction factor (k/d; 1 for identity)


def make_compressor(name: str, d: int, k: int = 0) -> Compressor:
    """Build a compressor for d-dimensional rows.

    ``k`` (coordinates kept) is required for top_k/random_k; 0 < k <= d.
    """
    if name == "none":
        return Compressor("none", lambda key, v: v, float(d), 1.0)
    if name not in COMPRESSIONS:
        raise ValueError(f"Unknown compression: {name!r}; known {COMPRESSIONS}")
    if not 0 < k <= d:
        raise ValueError(f"compression_k must be in (0, {d}], got {k}")

    def keep_top_scored(v, scores):
        # Row-wise mask keeping the k top-scored coordinates of each row.
        _, idx = jax.lax.top_k(scores, k)
        mask = jnp.zeros_like(v).at[
            jnp.arange(v.shape[0])[:, None], idx
        ].set(1.0)
        return v * mask

    if name == "top_k":

        def apply_topk(key, v):
            # Deterministic operator; key unused.
            return keep_top_scored(v, jnp.abs(v))

        return Compressor("top_k", apply_topk, 2.0 * k, k / d)

    def apply_randk(key, v):
        if key is None:
            raise ValueError("random_k compression needs a PRNG key")
        # Uniform scores = k uniformly random coordinates per row.
        return keep_top_scored(v, jax.random.uniform(key, v.shape))

    return Compressor("random_k", apply_randk, 2.0 * k, k / d)
