"""Communication-compression operators for gossip algorithms.

Not present in the reference (its gossip always exchanges full d-vectors,
reference ``trainer.py:169-173``); this is the compressed-gossip capability
from the same literature line the reference's report builds on (Koloskova,
Stich & Jaggi '19 — report ref [13] authors — define CHOCO-SGD around exactly
these operators).

Each operator is a jittable contraction ``Q(key, v) -> v_compressed`` over
the last axis of an ``[N, d]`` stack, together with its per-edge float cost
(the analytic comms-accounting payload; index transmission is counted as one
float per index, the accounting convention of the sparsification literature):

- ``top_k``: keep the k largest-|magnitude| coordinates per row (biased,
  contraction factor delta = k/d); cost 2k (k values + k indices).
- ``random_k``: keep k uniformly random coordinates per row (unbiased after
  (d/k)-rescaling in expectation, but used UNscaled inside CHOCO, which
  requires only a contraction); cost 2k.
- ``qsgd``: stochastic uniform quantization to s = 2^b levels per row
  (Alistarh et al. '17 as used by CHOCO: ‖v‖·sign(v)·ξ(v,s) with the
  1/(1+min(d/s², √d/s)) scaling that makes it a contraction); cost counted
  as d·(b+1)/32 + 1 floats per edge (b+1 bits per coordinate + the norm).
- ``none``: identity; cost d.

All operators satisfy the contraction property
E‖v − Q(v)‖² ≤ (1 − delta)‖v‖², delta > 0 — the condition CHOCO's
convergence proof needs.
"""

from __future__ import annotations

import dataclasses
from math import sqrt as np_sqrt
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from distributed_optimization_tpu.config import COMPRESSIONS

# Counter-based stream tag for the (possibly randomized) compressor draws,
# folded into the run seed: jax.random.fold_in(fold_in(key(seed), TAG), t).
# Single source shared by CHOCO and the generalized compressed dsgd /
# gradient-tracking steps — CHOCO's pre-refactor trajectories depend on
# exactly this derivation, so it must not drift.
_COMPRESSION_TAG = 0xC0C0


@dataclasses.dataclass(frozen=True)
class Compressor:
    """A jittable row-wise compression operator with its comms payload."""

    name: str
    apply: Callable[[Optional[jax.Array], jax.Array], jax.Array]
    floats_per_edge: float  # payload replacing d in the float accounting
    delta: float  # contraction factor (k/d; 1 for identity)


def make_compressor(name: str, d: int, k: int = 0) -> Compressor:
    """Build a compressor for d-dimensional rows.

    ``k``: coordinates kept for top_k/random_k (0 < k <= d); quantization
    BITS per coordinate for qsgd (1 <= k <= 16).
    """
    if name == "none":
        return Compressor("none", lambda key, v: v, float(d), 1.0)
    if name not in COMPRESSIONS:
        raise ValueError(f"Unknown compression: {name!r}; known {COMPRESSIONS}")

    if name == "qsgd":
        if not 1 <= k <= 16:
            raise ValueError(f"qsgd bits (compression_k) must be in [1, 16], got {k}")
        s = float(2 ** k)  # quantization levels
        # QSGD variance bound omega_var = min(d/s^2, sqrt(d)/s); scaling the
        # unbiased quantizer by omega = 1/(1 + omega_var) makes it a
        # contraction with delta = omega (Koloskova et al. '19, Sec. 2):
        # E||v - omega*xi(v)||^2 <= (1 - omega)||v||^2.
        omega = 1.0 / (1.0 + min(d / (s * s), np_sqrt(d) / s))

        def apply_qsgd(key, v):
            if key is None:
                raise ValueError("qsgd compression needs a PRNG key")
            norm = jnp.linalg.norm(v, axis=-1, keepdims=True)
            scale = jnp.where(norm > 0, norm, 1.0)
            level = jnp.abs(v) / scale * s  # in [0, s]
            low = jnp.floor(level)
            p_up = level - low  # stochastic rounding
            u = jax.random.uniform(key, v.shape)
            q = (low + (u < p_up)) / s
            return omega * norm * jnp.sign(v) * q

        bits_per_coord = k + 1  # sign + k magnitude bits
        floats_cost = d * bits_per_coord / 32.0 + 1.0  # + the row norm
        return Compressor("qsgd", apply_qsgd, floats_cost, omega)

    if not 0 < k <= d:
        raise ValueError(f"compression_k must be in (0, {d}], got {k}")

    def keep_top_scored(v, scores):
        # Row-wise mask keeping the k top-scored coordinates of each row.
        _, idx = jax.lax.top_k(scores, k)
        mask = jnp.zeros_like(v).at[
            jnp.arange(v.shape[0])[:, None], idx
        ].set(1.0)
        return v * mask

    if name == "top_k":

        def apply_topk(key, v):
            # Deterministic operator; key unused.
            return keep_top_scored(v, jnp.abs(v))

        return Compressor("top_k", apply_topk, 2.0 * k, k / d)

    def apply_randk(key, v):
        if key is None:
            raise ValueError("random_k compression needs a PRNG key")
        # Uniform scores = k uniformly random coordinates per row.
        return keep_top_scored(v, jax.random.uniform(key, v.shape))

    return Compressor("random_k", apply_randk, 2.0 * k, k / d)


# ------------------------------------------------- error-feedback machinery


def compression_key(seed: int, t, round: int = 0):
    """The counter-based PRNG key for iteration ``t``'s compressor draw.

    ``round`` distinguishes multiple exchanges within one iteration
    (gradient tracking compresses both its x and y gossip rounds); round 0
    is EXACTLY the pre-refactor CHOCO derivation, so single-exchange
    algorithms (choco, compressed dsgd) keep their historical draws.
    """
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.key(seed), _COMPRESSION_TAG), t
    )
    if round:
        key = jax.random.fold_in(key, round)
    return key


@dataclasses.dataclass(frozen=True)
class ErrorFeedbackGossip:
    """CHOCO-style error-feedback compressed gossip, algorithm-agnostic.

    Generalized out of ``algorithms/choco.py`` (ISSUE-6 tentpole) so
    D-SGD and gradient tracking can route their gossip exchanges through
    the same machinery. Each worker carries a public estimate x̂_i (the
    error-accumulator memory) that every neighbor holds a copy of; one
    exchange transmits only q_i = Q(v_i − x̂_i):

        x̂⁺ = x̂ + Q(v − x̂)                ← the ONLY bits on the wire
        v⁺  = v + γ [(W − I) X̂⁺]          (gossip over the estimates)

    The compression error v − x̂⁺ stays in the carry and is re-offered to
    the compressor next round — the error-feedback property that keeps
    the scheme convergent for any contraction operator (Koloskova, Stich
    & Jaggi '19). Identity compression at γ = 1 makes one exchange exactly
    the plain W-mix (v⁺ = W v), which is why uncompressed trajectories
    are unaffected. ``floats_per_edge`` (the compressor's payload) is the
    comms-accounting hook the backends consume.
    """

    compressor: Compressor
    gamma: float

    @property
    def floats_per_edge(self) -> float:
        return self.compressor.floats_per_edge

    def init(self, x0) -> jax.Array:
        """The estimate memory starts at 0 — every copy trivially agrees."""
        return jnp.zeros_like(x0)

    def exchange(
        self, key, v, memory, mix: Callable
    ) -> Tuple[jax.Array, jax.Array]:
        """One compressed gossip exchange: ``(v⁺, x̂⁺)``.

        ``mix``: the backend's x → W x collective (the estimates gossip
        through whatever mixing implementation the run selected). Ops are
        term-for-term the pre-refactor CHOCO step — trajectories are
        bitwise-unchanged (pinned in tests/test_choco.py).
        """
        q = self.compressor.apply(key, v - memory)
        memory_new = memory + q
        v_new = v + self.gamma * (mix(memory_new) - memory_new)
        return v_new, memory_new

    def exchange_sharded(
        self, key, v, memory, halo, compressed_mix: Callable
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """One compressed exchange over the worker mesh: ``(v⁺, x̂⁺, halo⁺)``.

        ``compressed_mix(q, x̂⁺, halo) -> (W x̂⁺, halo⁺)`` is the sharded
        wire form (``collectives.make_halo_compressed_mixing_op``): only
        the increment q's boundary rows cross devices, and ``halo`` is the
        persistent receiver-side copy of the neighbors' estimates that the
        q rows scatter-ADD into — the receiver replays the owner's
        ``x̂ ← x̂ + q`` update, which is what makes shipping q sufficient.
        The local algebra (q, x̂⁺, the γ-step) is term-for-term
        ``exchange``; the compressor runs OUTSIDE shard_map on the
        row-sharded stack (row-wise + shape-based draws, so sharding
        cannot change its output), keeping the historical per-row draws.
        """
        q = self.compressor.apply(key, v - memory)
        memory_new = memory + q
        mixed, halo_new = compressed_mix(q, memory_new, halo)
        v_new = v + self.gamma * (mixed - memory_new)
        return v_new, memory_new, halo_new


def make_error_feedback(
    name: str, d: int, k: int, gamma: float
) -> ErrorFeedbackGossip:
    """Build the shared error-feedback exchange for d-dimensional rows."""
    return ErrorFeedbackGossip(
        compressor=make_compressor(name, d, k), gamma=float(gamma)
    )
