"""Robust neighbor aggregation: Byzantine-tolerant replacements for W @ x.

Plain gossip is a linear map — one Byzantine neighbor sending an arbitrary
vector moves an honest worker's aggregate arbitrarily far (unbounded
sensitivity). These rules bound that sensitivity by SCREENING the received
neighbor messages before combining them; all three are jit-compatible pure
functions of (realized adjacency, stacked models), so they compose with the
fault machinery's per-iteration graphs (``parallel/faults.py``) inside the
scanned training loop:

- **coordinate-wise trimmed mean** (Yin et al. 2018, per neighborhood):
  node i sorts the values of its CLOSED neighborhood {x_j : j ∈ N(i)} ∪
  {x_i} per coordinate, drops the ``b`` largest and ``b`` smallest, and
  averages the rest. Tolerates up to b Byzantine neighbors per node: the
  kept values are bracketed by honest ones in every coordinate.
- **coordinate-wise median**: the midpoint of the closed-neighborhood
  values per coordinate — maximal trimming, tolerating any minority of a
  neighborhood (< (deg+1)/2 attackers).
- **self-centered clipping** (ClippedGossip, He-Karimireddy-Jaggi 2022):
  x_i + Σ_j W_ij · clip_τᵢ(x_j − x_i) with W the MH weights recomputed on
  the realized graph. Each received model moves a worker at most W_ij·τᵢ
  from its own state regardless of the payload. τᵢ is a fixed config
  radius, or adaptive: the (degᵢ − b)-th smallest neighbor-difference
  norm, so exactly the b most-distant messages are clipped down to the
  honest envelope. τ = ∞ (no clipping) IS plain MH gossip, which is why
  this rule degrades to the benign path exactly.

Budget semantics: ``b == 0`` means "assume no attackers" — the caller
(backends) short-circuits to plain MH gossip, bitwise identical to a run
with ``aggregation='gossip'``. ``validate_budget`` enforces 2·b ≤ min
degree: beyond that a node's trimmed neighborhood can be empty. Under
edge faults a REALIZED degree may still drop below 2b+1; the rules then
degrade per-node to the worker keeping its own model for that round (the
same identity-row convention an isolated node gets in ``FaultyMixing``).

The ``*_np`` twin is an independent per-node loop implementation written
directly from the rule definitions (numpy-oracle convention, see
``backends/numpy_backend.py``): equivalence between the vectorized jax
forms and this oracle is pinned in tests/test_byzantine.py.

Three jax implementations of every rule (``robust_impl`` knob):

- **dense** (``make_robust_aggregator``): materializes the [N, N, d]
  closed-neighborhood tensor and sorts over the full node axis —
  O(N²·d·log N) work, O(N²·d) memory, regardless of how sparse the
  topology is;
- **gather** (``make_gather_robust_aggregator``): precomputes a static
  padded neighbor-index table [N, k_max] from the topology
  (``parallel/topology.py::neighbor_table``), gathers neighbor models to
  [N, k_max, d] and per-incident-edge liveness bits to [N, k_max], and
  sorts/trims/medians/clips over the k_max axis — O(N·k_max·d·log k_max)
  work and O(N·k_max·d) memory, an ~N/k_max-fold reduction on
  degree-bounded graphs (measured 69-75× e2e for trimmed mean/median on
  an N=256 ring, docs/perf/robust_scale.json);
- **fused** (``ops/pallas_kernels.py::make_fused_robust_aggregator`` —
  lives with the other pallas kernels, not here): the gather math
  term-for-term as ONE VMEM-resident pallas kernel (plus the D-SGD
  update for dsgd), so the [N, k_max, d] stack never round-trips HBM
  between ops; bitwise the gather form for the count rules, ≤ 1e-12
  for clipping (tests/test_fused_robust.py, docs/perf/fused_robust.json).

The two are algebraically identical: the gather sort sees the same finite
values (+inf padding beyond the realized neighborhood, same convention),
neighbor slots are ordered ascending by index (the order a dense axis-1
reduction visits them), and f64 parity ≤ 1e-12 across dense / gather /
the numpy oracle is asserted in tests/test_robust_gather.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.config import AGGREGATIONS
from distributed_optimization_tpu.parallel.faults import (
    metropolis_hastings_weights,
)

RobustAggregator = Callable[[jax.Array, jax.Array], jax.Array]


def validate_budget(min_degree: int, budget: int, aggregation: str) -> None:
    """Reject trimming budgets the topology cannot support.

    Trimmed mean keeps deg+1−2b closed-neighborhood values, so the
    weakest node needs 2b ≤ min degree for at least one kept value beyond
    its own; the same bound keeps clipping's adaptive radius (deg−b ≥ 1
    unclipped reference) and the median's implicit minority assumption
    meaningful. Faults may still shrink REALIZED degrees below the bound —
    that degrades per-node to an identity row, not an error.
    """
    if aggregation not in AGGREGATIONS:
        raise ValueError(f"Unknown aggregation: {aggregation}")
    if 2 * budget > min_degree:
        raise ValueError(
            f"robust_b={budget} exceeds what the topology supports: "
            f"trimming {budget} from each tail needs 2*b <= min degree "
            f"({min_degree}), or the weakest node's screened neighborhood "
            "is empty — lower robust_b or use a better-connected topology"
        )


def _adaptive_clip_tau(mask, norms, budget: int, k_cap: int):
    """Adaptive ClippedGossip radius over masked neighbor distances: the
    (deg−b)-th smallest realized neighbor-difference norm, so exactly the
    ``b`` most-distant messages get clipped into the honest envelope;
    deg ≤ b ⇒ τ = 0 (identity row). ``mask``: realized adjacency/liveness
    weights (> 0 = live slot); ``k_cap``: the sortable axis length (N for
    the dense form, k_max for gather). ONE definition shared by both
    aggregator forms and their telemetry activity twins — the probe must
    see exactly the radius the rule uses.
    """
    deg = jnp.sum(mask, axis=1).astype(jnp.int32)
    masked = jnp.where(mask > 0, norms, jnp.inf)
    ranked = jnp.sort(masked, axis=1)
    k = jnp.clip(deg - budget - 1, 0, k_cap - 1)
    kth = jnp.take_along_axis(ranked, k[:, None], axis=1)[:, 0]
    return jnp.where(deg - budget >= 1, kth, 0.0)


def make_robust_aggregator(
    name: str, budget: int, clip_tau: float = 0.0
) -> RobustAggregator:
    """Build ``aggregate(A_t, x) -> x_new`` for one rule.

    ``A_t``: realized 0/1 adjacency (zero diagonal, convention
    ``A[i, j] = 1`` iff j's message reaches i this round); ``x``: the
    [N, d] stack of models AS TRANSMITTED (the adversary's corruption is
    applied upstream — honest rows carry true models). Internal math runs
    in at-least-float32 like the fault machinery; only the output is cast
    back to the input dtype.
    """
    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip is built by "
            "ops/mixing.py / parallel/faults.py"
        )
    if budget < 1:
        # b == 0 is the caller's short-circuit to plain gossip (for the
        # median, b only gates and sizes the validated assumption — the
        # rule itself is budget-free); reaching the screened path with an
        # empty budget is a wiring bug.
        raise ValueError(
            f"{name} needs a positive attack budget, got {budget}"
        )

    def _closed_sorted(A, x):
        """Ascending per-coordinate sort of the closed neighborhood.

        Returns (sorted [N, N, d] with +inf beyond each row's count,
        counts [N]): row i holds the values {x_j : A[i,j]=1} ∪ {x_i}.
        """
        n = A.shape[0]
        closed = A + jnp.eye(n, dtype=A.dtype)
        mask = closed > 0
        vals = jnp.where(mask[:, :, None], x[None, :, :], jnp.inf)
        return jnp.sort(vals, axis=1), jnp.sum(closed, axis=1)

    if name == "trimmed_mean":

        def aggregate(A, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            xa = x.astype(acc)
            s, counts = _closed_sorted(A.astype(acc), xa)
            # Valid entries occupy sorted positions [0, c_i); keep the
            # slice [b, c_i − b) — the +inf padding is never selected.
            pos = jnp.arange(A.shape[0], dtype=acc)
            keep = (pos[None, :] >= budget) & (
                pos[None, :] < (counts - budget)[:, None]
            )
            kept = jnp.maximum(counts - 2 * budget, 0.0)
            total = jnp.sum(jnp.where(keep[:, :, None], s, 0.0), axis=1)
            mean = total / jnp.maximum(kept, 1.0)[:, None]
            # Faulted-down neighborhoods (c_i ≤ 2b): identity row.
            return jnp.where(
                (kept >= 1.0)[:, None], mean, xa
            ).astype(x.dtype)

    elif name == "median":

        def aggregate(A, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            xa = x.astype(acc)
            s, counts = _closed_sorted(A.astype(acc), xa)
            c = counts.astype(jnp.int32)
            lo = jnp.maximum((c - 1) // 2, 0)[:, None, None]
            hi = jnp.maximum(c // 2, 0)[:, None, None]
            med = 0.5 * (
                jnp.take_along_axis(s, lo, axis=1)
                + jnp.take_along_axis(s, hi, axis=1)
            )
            return med[:, 0, :].astype(x.dtype)

    else:  # clipped_gossip
        # Adaptive vs fixed radius is a HOST decision: a traced clip_tau (a
        # replica-swept axis, run_batch-validated > 0) is always the fixed
        # form — only a concrete 0.0 selects the adaptive per-node radius.
        adaptive_tau = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0

        def aggregate(A, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            Aa = A.astype(acc)
            xa = x.astype(acc)
            W = metropolis_hastings_weights(Aa)
            diffs = xa[None, :, :] - xa[:, None, :]  # [recv i, send j, d]
            norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
            if not adaptive_tau:
                tau = jnp.full(A.shape[0], clip_tau, dtype=acc)
            else:
                tau = _adaptive_clip_tau(Aa, norms, budget, A.shape[0])
            factor = jnp.minimum(
                1.0, tau[:, None] / jnp.maximum(norms, jnp.finfo(acc).tiny)
            )
            # Off-graph entries have W_ij = 0; the diagonal difference is 0.
            moved = jnp.sum(W[:, :, None] * diffs * factor[:, :, None], axis=1)
            return (xa + moved).astype(x.dtype)

    return aggregate


def make_gather_robust_aggregator(
    name: str,
    budget: int,
    nbr_idx: np.ndarray,
    clip_tau: float = 0.0,
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Degree-bounded ``aggregate(live, x) -> x_new`` for one rule.

    ``nbr_idx``: the static [N, k_max] padded neighbor-index table of the
    BASE topology (``parallel/topology.py::neighbor_table``; padded slots
    point at self). ``live``: per-incident-edge 0/1 liveness bits
    [N, k_max] for this round — the gather-form realized adjacency
    (``FaultyMixing.neighbor_liveness``, or the static ``nbr_mask`` when
    fault-free); symmetric by construction, so a neighbor's realized
    degree is recoverable by gathering row sums. ``x``: the [N, d] stack
    AS TRANSMITTED, like the dense form.

    Each rule mirrors its dense twin term for term over the k_max axis —
    same +inf padding, same accumulation dtype floor, same identity-row
    degradation for faulted-down neighborhoods (realized closed
    neighborhood ≤ 2b, or deg ≤ b for adaptive clipping) — but the sort,
    rank selection, and neighbor reduction are O(k_max), not O(N).
    """
    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip is built by "
            "ops/mixing.py / parallel/faults.py"
        )
    if budget < 1:
        raise ValueError(
            f"{name} needs a positive attack budget, got {budget}"
        )
    nbr = jnp.asarray(nbr_idx, dtype=jnp.int32)  # [N, k_max]
    k_max = nbr.shape[1]

    def _closed_sorted(live, x):
        """Ascending per-coordinate sort of the realized closed
        neighborhood over the slot axis: [N, k_max+1, d] (self in slot 0
        pre-sort; +inf beyond each row's realized count) + counts [N]."""
        vals = jnp.where(live[:, :, None] > 0, x[nbr], jnp.inf)
        closed = jnp.concatenate([x[:, None, :], vals], axis=1)
        return jnp.sort(closed, axis=1), jnp.sum(live, axis=1) + 1.0

    if name == "trimmed_mean":

        def aggregate(live, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            xa = x.astype(acc)
            s, counts = _closed_sorted(live.astype(acc), xa)
            pos = jnp.arange(k_max + 1, dtype=acc)
            keep = (pos[None, :] >= budget) & (
                pos[None, :] < (counts - budget)[:, None]
            )
            kept = jnp.maximum(counts - 2 * budget, 0.0)
            total = jnp.sum(jnp.where(keep[:, :, None], s, 0.0), axis=1)
            mean = total / jnp.maximum(kept, 1.0)[:, None]
            # Faulted-down neighborhoods (c_i ≤ 2b): identity row.
            return jnp.where(
                (kept >= 1.0)[:, None], mean, xa
            ).astype(x.dtype)

    elif name == "median":

        def aggregate(live, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            xa = x.astype(acc)
            s, counts = _closed_sorted(live.astype(acc), xa)
            c = counts.astype(jnp.int32)
            lo = jnp.maximum((c - 1) // 2, 0)[:, None, None]
            hi = jnp.maximum(c // 2, 0)[:, None, None]
            med = 0.5 * (
                jnp.take_along_axis(s, lo, axis=1)
                + jnp.take_along_axis(s, hi, axis=1)
            )
            return med[:, 0, :].astype(x.dtype)

    else:  # clipped_gossip
        # Same host decision as the dense twin: traced clip_tau (a swept
        # replica axis) is the fixed form; concrete 0.0 is adaptive.
        adaptive_tau = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0

        def aggregate(live, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            xa = x.astype(acc)
            lv = live.astype(acc)
            deg = jnp.sum(lv, axis=1)  # realized degrees [N]
            diffs = xa[nbr] - xa[:, None, :]  # [recv i, slot, d]
            norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
            if not adaptive_tau:
                tau = jnp.full(nbr.shape[0], clip_tau, dtype=acc)
            else:
                tau = _adaptive_clip_tau(lv, norms, budget, k_max)
            # MH weights on realized degrees, gather form: the liveness is
            # symmetric, so a neighbor's realized degree is its row sum
            # gathered through the slot table; dead slots carry lv = 0.
            w = lv / (1.0 + jnp.maximum(deg[:, None], deg[nbr]))
            factor = jnp.minimum(
                1.0, tau[:, None] / jnp.maximum(norms, jnp.finfo(acc).tiny)
            )
            moved = jnp.sum(w[:, :, None] * diffs * factor[:, :, None], axis=1)
            return (xa + moved).astype(x.dtype)

    return aggregate


def _screening_fraction(name: str, budget: int, counts):
    """Fraction of received (open-neighborhood) messages a count-only rule
    screens out, given realized CLOSED-neighborhood counts ``counts``.

    trimmed_mean keeps max(c−2b, 1) values (1 = the identity-row
    degradation), the median keeps the middle one (two for even counts);
    everything else of the c−1 received messages is screened. Shared by the
    jax activity twins below; float32 like all fault-layer accounting.
    """
    c = counts.astype(jnp.float32)
    if name == "trimmed_mean":
        kept = jnp.maximum(c - 2.0 * budget, 1.0)
    else:  # median
        kept = 2.0 - jnp.mod(c, 2.0)
    return (c - kept) / jnp.maximum(c - 1.0, 1.0)


def make_robust_activity(
    name: str, budget: int, clip_tau: float = 0.0
) -> RobustAggregator:
    """Telemetry twin of ``make_robust_aggregator``: ``activity(A_t, x) ->
    scalar`` — the network-mean fraction of received neighbor messages the
    rule screened out this round (trimmed values for trimmed_mean/median;
    messages actually clipped — ‖diff‖ > τᵢ — for clipped_gossip, with τᵢ
    recomputed exactly as the aggregator computes it). Pure observability:
    nothing here feeds back into the step. float32 output.
    """
    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip screens "
            "nothing (activity is identically 0)"
        )
    if budget < 1:
        raise ValueError(f"{name} needs a positive attack budget, got {budget}")

    if name in ("trimmed_mean", "median"):

        def activity(A, x):
            counts = jnp.sum(A.astype(jnp.float32), axis=1) + 1.0
            return jnp.mean(_screening_fraction(name, budget, counts))

    else:  # clipped_gossip — same adaptive/fixed τ decision as the rule
        adaptive_tau = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0

        def activity(A, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            Aa = A.astype(acc)
            xa = x.astype(acc)
            diffs = xa[None, :, :] - xa[:, None, :]
            norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
            if not adaptive_tau:
                tau = jnp.full(A.shape[0], clip_tau, dtype=acc)
            else:
                tau = _adaptive_clip_tau(Aa, norms, budget, A.shape[0])
            clipped = jnp.sum(Aa * (norms > tau[:, None]))
            return (clipped / jnp.maximum(jnp.sum(Aa), 1.0)).astype(
                jnp.float32
            )

    return activity


def make_gather_robust_activity(
    name: str, budget: int, nbr_idx: np.ndarray, clip_tau: float = 0.0
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Degree-bounded twin of ``make_robust_activity``: ``activity(live, x)``
    over the static [N, k_max] neighbor table + per-slot liveness bits —
    the same realization the gather aggregator screens. float32 output.
    """
    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip screens "
            "nothing (activity is identically 0)"
        )
    if budget < 1:
        raise ValueError(f"{name} needs a positive attack budget, got {budget}")
    nbr = jnp.asarray(nbr_idx, dtype=jnp.int32)
    k_max = nbr.shape[1]

    if name in ("trimmed_mean", "median"):

        def activity(live, x):
            counts = jnp.sum(live.astype(jnp.float32), axis=1) + 1.0
            return jnp.mean(_screening_fraction(name, budget, counts))

    else:  # clipped_gossip

        adaptive_tau = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0

        def activity(live, x):
            acc = jnp.promote_types(jnp.float32, x.dtype)
            lv = live.astype(acc)
            xa = x.astype(acc)
            diffs = xa[nbr] - xa[:, None, :]
            norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
            if not adaptive_tau:
                tau = jnp.full(nbr.shape[0], clip_tau, dtype=acc)
            else:
                tau = _adaptive_clip_tau(lv, norms, budget, k_max)
            clipped = jnp.sum(lv * (norms > tau[:, None]))
            return (clipped / jnp.maximum(jnp.sum(lv), 1.0)).astype(
                jnp.float32
            )

    return activity


def robust_activity_np(
    name: str, A: np.ndarray, x: np.ndarray, budget: int, clip_tau: float = 0.0
) -> float:
    """Independent per-node oracle of the activity twins (float64 numpy,
    numpy-backend convention — written from the definitions, not the jax
    forms)."""
    n = x.shape[0]
    if name in ("trimmed_mean", "median"):
        fracs = []
        for i in range(n):
            c = int(A[i].sum()) + 1
            if name == "trimmed_mean":
                kept = max(c - 2 * budget, 1)
            else:
                kept = 2 - (c % 2)
            fracs.append((c - kept) / max(c - 1, 1))
        return float(np.mean(fracs))
    if name != "clipped_gossip":
        raise ValueError(f"no robust aggregator named {name!r}")
    clipped = 0.0
    total = 0.0
    for i in range(n):
        nbrs = np.nonzero(A[i])[0]
        if len(nbrs) == 0:
            continue
        norms = np.linalg.norm(x[nbrs] - x[i], axis=1)
        if clip_tau > 0.0:
            tau = clip_tau
        else:
            k = len(nbrs) - budget
            tau = float(np.sort(norms)[k - 1]) if k >= 1 else 0.0
        clipped += float(np.sum(norms > tau))
        total += float(len(nbrs))
    return clipped / total if total else 0.0


def robust_aggregate_np(
    name: str, A: np.ndarray, x: np.ndarray, budget: int, clip_tau: float = 0.0
) -> np.ndarray:
    """Independent per-node oracle of the rules above (float64 numpy).

    Written as explicit per-node loops from the definitions, not by
    transcribing the vectorized jax forms — the numpy-backend convention
    for everything the equivalence tests pin.
    """
    n = x.shape[0]
    degs = A.sum(axis=1)
    out = np.empty_like(x, dtype=np.float64)
    for i in range(n):
        nbrs = np.nonzero(A[i])[0]
        if name in ("trimmed_mean", "median"):
            vals = np.concatenate([x[nbrs], x[i : i + 1]], axis=0)
            s = np.sort(vals, axis=0)
            c = vals.shape[0]
            if name == "median":
                out[i] = 0.5 * (s[(c - 1) // 2] + s[c // 2])
            elif c - 2 * budget >= 1:
                out[i] = s[budget : c - budget].mean(axis=0)
            else:
                out[i] = x[i]
        elif name == "clipped_gossip":
            diffs = x[nbrs] - x[i]
            norms = np.linalg.norm(diffs, axis=1)
            if clip_tau > 0.0:
                tau = clip_tau
            else:
                k = len(nbrs) - budget
                tau = float(np.sort(norms)[k - 1]) if k >= 1 else 0.0
            w = 1.0 / (1.0 + np.maximum(degs[i], degs[nbrs]))
            fac = np.minimum(1.0, tau / np.maximum(norms, np.finfo(np.float64).tiny))
            out[i] = x[i] + (w[:, None] * diffs * fac[:, None]).sum(axis=0)
        else:
            raise ValueError(f"no robust aggregator named {name!r}")
    return out
