"""Compiled gossip/mixing operators: x -> W x and neighbor sums x -> A x.

The reference realizes gossip as a dense ``W @ models`` matmul in numpy
(reference ``trainer.py:173``) — a *simulation* of communication. Here the
same linear operator has three interchangeable compiled forms:

- ``dense``: an on-device matmul with the [N, N] mixing matrix. Works for any
  graph (Erdős–Rényi et al.). Under GSPMD sharding this becomes an
  all-gather + local contraction — fine for irregular graphs.
- ``sparse`` (round 5): a CSR-style edge-list contraction for irregular
  graphs — gather rows by edge source, scale by per-edge weight, and
  ``jax.ops.segment_sum`` into edge destinations (edges pre-sorted by
  destination host-side, so the segments are sorted). O(E·d) work instead
  of the dense form's O(N²·d) — and MEASURED SLOWER than dense at every
  cell tried (17 on-chip cells: N ∈ {256, 1024, 4096} × chain/star/ER/
  directed-ER at densities 0.05%–40%, ``docs/perf/sparse_mixing.json``;
  CPU spot-checks agree). On TPU the [N, N] matmul rides the MXU at a
  ~40–90 µs latency floor through N=4096 while gather+scatter pays
  per-row DMA that scales with E and catastrophically with density (200×
  slower at 40%) — asymptotic sparsity arguments lose to the systolic
  array at any scale a single chip holds. ``auto`` therefore keeps DENSE
  for irregular graphs; ``sparse`` stays as an explicit opt-in (exact for
  all graphs, directed included) for regimes beyond the measured envelope
  (N >> 4096 multi-chip, where the [N, N] weight replication itself
  becomes the bottleneck).
- ``stencil``: for ring / torus / fully-connected graphs, where MH weights are
  uniform by symmetry, W x is a weighted sum of circular shifts of x along the
  worker axis (ring: ±1; torus: ±1 along each grid axis; fc: the global mean).
  When x is sharded over the mesh, XLA compiles ``jnp.roll`` on the sharded
  axis into ``CollectivePermute`` over ICI and the fc mean into an
  ``AllReduce`` — the communication graph maps onto the pod topology, which is
  the north-star design (SURVEY.md §5.8).
- ``shard_map``: explicit-collective form of the same stencils using
  ``jax.lax.ppermute``/``psum`` (see ``parallel/collectives.py``), for when
  manual control over the collective schedule is wanted.
- ``gather`` (round 9): the matrix-free k_max-bounded form over padded
  ``[N, k_max]`` neighbor tables — O(N·k_max·d), no [N, N] object
  anywhere; the route that lifts the worker axis to N ≥ 10k. Its SHARDED
  twin is ``parallel/collectives.make_halo_mixing_op`` (impl tag
  ``'halo_gather'``, the ``worker_mesh`` axis, docs/PERF.md §16): the
  same per-row op sequence with the worker rows split over a device mesh
  and boundary rows ppermute-fetched at shard edges — bitwise this
  operator at matched N, selected by the backend (not here) because it
  needs the device mesh.

All forms agree to floating-point tolerance; property tests check stencil
and shard_map forms against the dense matrix.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from distributed_optimization_tpu.config import MATRIX_FREE_AUTO_N
from distributed_optimization_tpu.parallel.topology import (
    NEIGHBOR_TABLE_MAX_CELLS,
    Topology,
    gather_mixing_weights,
    neighbor_tables_for,
)

MixFn = Callable[[jax.Array], jax.Array]


@dataclasses.dataclass(frozen=True)
class MixingOp:
    """Jittable linear operators attached to one topology.

    ``apply``: x [N, ...] -> W x (the gossip averaging step).
    ``neighbor_sum``: x [N, ...] -> A x (sum over graph neighbors; used by
    ADMM-family algorithms whose updates need Σ_{j∈N(i)} x_j rather than the
    doubly-stochastic average).
    """

    topology_name: str
    impl: str
    apply: MixFn
    neighbor_sum: MixFn


def _supports_stencil(topo: Topology) -> bool:
    if topo.name == "fully_connected":
        return True
    if topo.name in ("ring", "directed_ring"):
        return topo.n >= 3
    if topo.name == "grid":
        return topo.grid_shape is not None and min(topo.grid_shape) >= 3
    return False


def make_mixing_op(topo: Topology, impl: str = "auto", dtype=jnp.float32) -> MixingOp:
    """Build the compiled mixing operator for a topology.

    ``impl``: 'auto' picks 'stencil' where the graph embeds into the mesh as
    shifts (ring/grid/fc), else 'dense' — the measured winner for irregular
    graphs at every cell tried, BOTH platforms (round 5,
    ``docs/perf/sparse_mixing.json``; see the module docstring for the
    mechanism). 'sparse' is opt-in only. 'shard_map' variants are built in
    ``parallel/collectives.py`` because they need a Mesh.
    """
    if impl == "auto":
        if _supports_stencil(topo):
            # Stencils are already matrix-free (rolls/means of the whole
            # block) and the measured winner where they apply.
            impl = "stencil"
        elif topo.is_matrix_free:
            impl = "gather"
        elif not topo.directed and topo.n >= MATRIX_FREE_AUTO_N:
            # The k_max-bounded gather route (docs/PERF.md §14): default
            # for matrix-backed irregular graphs above the measured
            # threshold — the [N, N] contraction's O(N²·d) work and the
            # matrix itself stop fitting where docs/perf/federated.json's
            # scale cells take over from sparse_mixing.json's. Gate on
            # the SAME degree bound build_neighbor_topology enforces:
            # gather's [N, k_max, d] transient beats dense only while
            # k_max ≪ N, so high-degree graphs (star, dense ER) keep the
            # dense contraction instead of allocating a near-quadratic
            # gather inside the scan.
            k_max = int(np.asarray(topo.degrees).max())
            degree_bounded = (
                k_max + 1 < topo.n
                and max(k_max, 1) * topo.n <= NEIGHBOR_TABLE_MAX_CELLS
            )
            impl = "gather" if degree_bounded else "dense"
        else:
            impl = "dense"
    if impl == "shard_map":
        raise ValueError(
            "shard_map mixing ops need a Mesh; build them via "
            "distributed_optimization_tpu.parallel.collectives instead"
        )
    if impl not in ("dense", "stencil", "pallas", "sparse", "gather"):
        raise ValueError(f"Unknown mixing impl: {impl!r}")
    if impl == "stencil" and not _supports_stencil(topo):
        raise ValueError(f"stencil mixing unsupported for {topo.name} (n={topo.n})")
    if topo.is_matrix_free and impl not in ("stencil", "gather"):
        raise ValueError(
            f"mixing_impl={impl!r} consumes the dense [N, N] matrices a "
            f"matrix-free topology ({topo.name}, n={topo.n}) never "
            "materializes — use 'gather' (or 'stencil' where the graph "
            "embeds as shifts)"
        )

    if impl == "gather":
        if topo.directed:
            raise ValueError(
                "gather mixing is undirected-only (MH weights per slot); "
                f"directed topology {topo.name!r} has no gather form"
            )
        nbr_idx_np, nbr_mask_np = neighbor_tables_for(topo)
        w_nbr_np, w_self_np = gather_mixing_weights(
            nbr_idx_np, nbr_mask_np, topo.degrees
        )
        nbr = jnp.asarray(nbr_idx_np, dtype=jnp.int32)
        mask = jnp.asarray(nbr_mask_np, dtype=dtype)
        w_nbr = jnp.asarray(w_nbr_np, dtype=dtype)
        w_self = jnp.asarray(w_self_np, dtype=dtype)

        def _bshape(x: jax.Array):
            return (x.shape[0], nbr.shape[1]) + (1,) * (x.ndim - 1)

        def apply(x: jax.Array) -> jax.Array:
            gathered = x[nbr]  # [N, k_max, ...]
            out = w_self.reshape((-1,) + (1,) * (x.ndim - 1)) * x + jnp.sum(
                w_nbr.reshape(_bshape(x)) * gathered, axis=1
            )
            return out.astype(x.dtype)

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return jnp.sum(
                mask.reshape(_bshape(x)) * x[nbr], axis=1
            ).astype(x.dtype)

        return MixingOp(topo.name, "gather", apply, neighbor_sum)

    if impl == "pallas":
        # Hand-fused VMEM kernels (ops/pallas_kernels.py). Ring and
        # fully-connected only — the graphs whose uniform-MH stencils reduce
        # to rolls/means of the whole [N, d] block.
        from distributed_optimization_tpu.ops import pallas_kernels as pk

        if topo.name == "ring" and topo.n >= 3:
            return MixingOp(
                topo.name, "pallas", pk.ring_mix, pk.ring_neighbor_sum
            )
        if topo.name == "fully_connected":
            return MixingOp(
                topo.name, "pallas", pk.fc_mix, pk.fc_neighbor_sum
            )
        raise ValueError(
            f"pallas mixing supports ring (n>=3) and fully_connected, "
            f"not {topo.name} (n={topo.n})"
        )

    if impl == "sparse":
        # CSR edge-list contraction: works for ANY graph, directed included
        # (the convention adjacency[i, j] = 1 iff j sends to i makes dst the
        # receiving row for both orientations). np.nonzero walks row-major,
        # so edges come out sorted by destination — segment_sum runs in its
        # sorted fast path. Weights/edge lists are built host-side once; the
        # device never materializes the [N, N] matrix.
        dst_np, src_np = np.nonzero(topo.adjacency)
        if dst_np.size == 0:
            raise ValueError(
                f"sparse mixing needs at least one edge ({topo.name}, "
                f"n={topo.n})"
            )
        dst = jnp.asarray(dst_np, dtype=jnp.int32)
        src = jnp.asarray(src_np, dtype=jnp.int32)
        w_edge = jnp.asarray(
            topo.mixing_matrix[dst_np, src_np], dtype=dtype
        )
        w_diag = jnp.asarray(np.diag(topo.mixing_matrix), dtype=dtype)
        n = topo.n

        def _bcast(v: jax.Array, x: jax.Array) -> jax.Array:
            return v.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)

        def apply(x: jax.Array) -> jax.Array:
            gathered = _bcast(w_edge, x) * x[src]
            agg = jax.ops.segment_sum(
                gathered, dst, num_segments=n, indices_are_sorted=True
            )
            return (_bcast(w_diag, x) * x + agg).astype(x.dtype)

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return jax.ops.segment_sum(
                x[src], dst, num_segments=n, indices_are_sorted=True
            ).astype(x.dtype)

        return MixingOp(topo.name, "sparse", apply, neighbor_sum)

    if impl == "dense":
        W = jnp.asarray(topo.mixing_matrix, dtype=dtype)
        A = jnp.asarray(topo.adjacency, dtype=dtype)

        def apply(x: jax.Array) -> jax.Array:
            return jnp.tensordot(W, x, axes=1).astype(x.dtype)

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return jnp.tensordot(A, x, axes=1).astype(x.dtype)

        return MixingOp(topo.name, "dense", apply, neighbor_sum)

    if topo.name == "fully_connected":
        # Degree N-1 everywhere ⇒ every MH weight (incl. diagonal) is 1/N:
        # mixing is exactly the global mean. Compiles to AllReduce when sharded.
        def apply(x: jax.Array) -> jax.Array:
            return jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape).astype(
                x.dtype
            )

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return (jnp.sum(x, axis=0, keepdims=True) - x).astype(x.dtype)

        return MixingOp(topo.name, "stencil", apply, neighbor_sum)

    if topo.name == "ring":
        # Degree 2 everywhere ⇒ all weights (self and both neighbors) are 1/3.
        w = 1.0 / 3.0

        def apply(x: jax.Array) -> jax.Array:
            return (w * (x + jnp.roll(x, 1, axis=0) + jnp.roll(x, -1, axis=0))).astype(
                x.dtype
            )

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return (jnp.roll(x, 1, axis=0) + jnp.roll(x, -1, axis=0)).astype(x.dtype)

        return MixingOp(topo.name, "stencil", apply, neighbor_sum)

    if topo.name == "directed_ring":
        # Out-degree 1 everywhere ⇒ column-stochastic weights are 1/2 on the
        # self-loop and the forward edge: (Ax)_i = (x_i + x_{i-1})/2. ONE
        # roll — when sharded this is a single forward CollectivePermute per
        # round, half the undirected ring's boundary traffic.
        def apply(x: jax.Array) -> jax.Array:
            return (0.5 * (x + jnp.roll(x, 1, axis=0))).astype(x.dtype)

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return jnp.roll(x, 1, axis=0).astype(x.dtype)

        return MixingOp(topo.name, "stencil", apply, neighbor_sum)

    if topo.name == "grid":
        rows, cols = topo.grid_shape  # type: ignore[misc]
        # Degree 4 everywhere ⇒ all five weights are 1/5. Worker i lives at
        # grid position (i // cols, i % cols) — row-major, matching the
        # reference's node indexing (trainer.py:104).
        w = 1.0 / 5.0

        def _shifts(x: jax.Array) -> jax.Array:
            g = x.reshape(rows, cols, *x.shape[1:])
            s = (
                jnp.roll(g, 1, axis=0)
                + jnp.roll(g, -1, axis=0)
                + jnp.roll(g, 1, axis=1)
                + jnp.roll(g, -1, axis=1)
            )
            return s.reshape(x.shape)

        def apply(x: jax.Array) -> jax.Array:
            return (w * (x + _shifts(x))).astype(x.dtype)

        def neighbor_sum(x: jax.Array) -> jax.Array:
            return _shifts(x).astype(x.dtype)

        return MixingOp(topo.name, "stencil", apply, neighbor_sum)

    raise ValueError(f"No stencil form for topology {topo.name!r}")
