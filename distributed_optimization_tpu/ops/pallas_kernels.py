"""Pallas TPU kernels for the hot gossip ops.

The framework's hot loop is elementwise-dominated (the model stack ``[N, d]``
is small enough to live in VMEM outright), so the win from hand-written
kernels is FUSION: one VMEM-resident kernel per gossip step instead of
several XLA ops bouncing through HBM. Two kernels:

- ``ring_mix`` — the ring stencil W x = (x + roll(x,+1) + roll(x,−1)) / 3
  (uniform Metropolis–Hastings weights for degree-2 rings, see
  ``ops/mixing.py``), one VMEM pass.
- ``fused_ring_dsgd_step`` — the ENTIRE D-SGD update
  x ← W x − η g (reference ``trainer.py:173-175``) in a single kernel:
  mixing + SGD step fused, x and g each read from HBM exactly once.

Both run in interpreter mode on CPU (tests / virtual-device CI) and compile
via Mosaic on real TPU. Selected with ``mixing_impl='pallas'`` (ring and
fully-connected topologies; other graphs fall back with a clear error).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _on_cpu() -> bool:
    return jax.devices()[0].platform == "cpu"


def _roll(x, shift: int):
    # pltpu.roll lowers to a VMEM rotate on TPU (it requires a non-negative
    # shift, so normalize modulo N); the interpreter path and non-TPU
    # backends use jnp.roll (identical semantics).
    if _on_cpu():
        return jnp.roll(x, shift, axis=0)
    return pltpu.roll(x, shift=shift % x.shape[0], axis=0)


THIRD = 1.0 / 3.0


def _ring_mix_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = (x + _roll(x, 1) + _roll(x, -1)) * THIRD


def _fused_ring_step_kernel(eta_ref, x_ref, g_ref, out_ref):
    x = x_ref[:]
    mixed = (x + _roll(x, 1) + _roll(x, -1)) * THIRD
    out_ref[:] = mixed - eta_ref[0] * g_ref[:]


def _fc_mix_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)


def _ring_neighbor_sum_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = _roll(x, 1) + _roll(x, -1)


def _fc_neighbor_sum_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.broadcast_to(jnp.sum(x, axis=0, keepdims=True), x.shape) - x


def ring_mix(x: jax.Array) -> jax.Array:
    """W x for a ring of N >= 3 workers; [N, d] -> [N, d], one VMEM pass."""
    return _unary_call(_ring_mix_kernel, x)


def fused_ring_dsgd_step(x: jax.Array, g: jax.Array, eta) -> jax.Array:
    """One fused D-SGD iteration on a ring: W x − eta g, single kernel."""
    eta_arr = jnp.asarray(eta, dtype=x.dtype).reshape(1)
    return pl.pallas_call(
        _fused_ring_step_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_on_cpu(),
    )(eta_arr, x, g)


def _unary_call(kernel, x: jax.Array) -> jax.Array:
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_on_cpu(),
    )(x)


def fc_mix(x: jax.Array) -> jax.Array:
    """W x for the fully-connected graph: the global mean, one VMEM pass."""
    return _unary_call(_fc_mix_kernel, x)


def ring_neighbor_sum(x: jax.Array) -> jax.Array:
    """A x for the ring: roll(+1) + roll(−1), computed directly (exact)."""
    return _unary_call(_ring_neighbor_sum_kernel, x)


def fc_neighbor_sum(x: jax.Array) -> jax.Array:
    """A x for the fully-connected graph: column sums minus self."""
    return _unary_call(_fc_neighbor_sum_kernel, x)
