"""Pallas TPU kernels for the hot gossip ops.

The framework's hot loop is elementwise-dominated (the model stack ``[N, d]``
is small enough to live in VMEM outright), so the win from hand-written
kernels is FUSION: one VMEM-resident kernel per gossip step instead of
several XLA ops bouncing through HBM. Kernel families:

- ``ring_mix`` — the ring stencil W x = (x + roll(x,+1) + roll(x,−1)) / 3
  (uniform Metropolis–Hastings weights for degree-2 rings, see
  ``ops/mixing.py``), one VMEM pass.
- ``fused_ring_dsgd_step`` — the ENTIRE D-SGD update
  x ← W x − η g (reference ``trainer.py:173-175``) in a single kernel:
  mixing + SGD step fused, x and g each read from HBM exactly once.
- ``make_fused_robust_aggregator`` / ``make_fused_robust_dsgd_step`` — the
  Byzantine/fault hot path (ISSUE-6 tentpole): neighbor-gather through the
  static ``[N, k_max]`` table + robust screen (trimmed mean / median via an
  odd-even transposition sort network; self-centered clipping) + mixing
  (+ the SGD update for D-SGD) in ONE kernel over the ``[N, d]`` stack and
  ``[N, k_max]`` liveness bits. The ``[N, k_max, d]`` neighbor stack exists
  only inside the kernel (VMEM), never as an HBM-materialized XLA buffer —
  the separate gather → sort → mix → update ops of the 'gather' path each
  round-trip it through HBM.

All kernels run in interpreter mode on CPU (tests / virtual-device CI) and
compile via Mosaic on real TPU. Interpreter-mode selection respects the
INPUT's committed platform — not the global ``jax.devices()[0]`` — so
routing stays correct under ``jax.default_device`` / mixed-platform setups;
pass ``interpret=`` to force either mode (tests).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_optimization_tpu.config import AGGREGATIONS

# Width bound for the in-kernel odd-even transposition sort network the
# count-based rules (trimmed mean / median) screen with: the network is
# width compare-exchange passes of jnp.minimum/maximum over the closed
# slot axis [N, k_max+1, d] — pure VPU elementwise ops Mosaic lowers
# everywhere, unlike a general jnp.sort. Quadratic in width, so past this
# bound the network's O(k_max²·N·d) work dominates the fusion win and the
# rule is not fused-eligible (``fused_robust_supported``); clipping sorts
# only the [N, k_max] norms and stays eligible at any degree.
FUSED_MAX_SORT_WIDTH = 16


def resolve_interpret(x=None, interpret: Optional[bool] = None) -> bool:
    """Should a pallas call interpret (CPU) or compile (Mosaic/TPU)?

    Precedence: the explicit ``interpret`` override (tests and callers
    that already resolved their platform) → the input array's COMMITTED
    device platform (concrete arrays carry one; tracers do not) → the
    ambient ``jax.default_device`` context → ``jax.default_backend()``.
    The old global ``jax.devices()[0]`` probe mis-routed under
    ``jax.default_device(cpu)`` on a TPU host (compiling Mosaic for
    arrays that live on CPU) and vice versa.
    """
    if interpret is not None:
        return bool(interpret)
    platform = None
    if x is not None and not isinstance(x, jax.core.Tracer):
        try:
            devices = x.devices()
            if devices:
                platform = next(iter(devices)).platform
        except Exception:
            platform = None
    if platform is None:
        default = getattr(jax.config, "jax_default_device", None)
        if default is None:
            platform = jax.default_backend()
        elif isinstance(default, str):
            # jax accepts jax.default_device("cpu") — the config then
            # holds the platform STRING, not a Device.
            platform = default
        else:
            platform = default.platform
    return platform == "cpu"


def _roll(x, shift: int, interp: bool):
    # pltpu.roll lowers to a VMEM rotate on TPU (it requires a non-negative
    # shift, so normalize modulo N); the interpreter path and non-TPU
    # backends use jnp.roll (identical semantics).
    if interp:
        return jnp.roll(x, shift, axis=0)
    return pltpu.roll(x, shift=shift % x.shape[0], axis=0)


THIRD = 1.0 / 3.0


def _make_ring_mix_kernel(interp: bool):
    def kernel(x_ref, out_ref):
        x = x_ref[:]
        out_ref[:] = (
            x + _roll(x, 1, interp) + _roll(x, -1, interp)
        ) * THIRD

    return kernel


def _make_fused_ring_step_kernel(interp: bool):
    def kernel(eta_ref, x_ref, g_ref, out_ref):
        x = x_ref[:]
        mixed = (x + _roll(x, 1, interp) + _roll(x, -1, interp)) * THIRD
        out_ref[:] = mixed - eta_ref[0] * g_ref[:]

    return kernel


def _fc_mix_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True), x.shape)


def _make_ring_neighbor_sum_kernel(interp: bool):
    def kernel(x_ref, out_ref):
        x = x_ref[:]
        out_ref[:] = _roll(x, 1, interp) + _roll(x, -1, interp)

    return kernel


def _fc_neighbor_sum_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.broadcast_to(jnp.sum(x, axis=0, keepdims=True), x.shape) - x


def ring_mix(x: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    """W x for a ring of N >= 3 workers; [N, d] -> [N, d], one VMEM pass."""
    interp = resolve_interpret(x, interpret)
    return _unary_call(_make_ring_mix_kernel(interp), x, interp)


def fused_ring_dsgd_step(
    x: jax.Array, g: jax.Array, eta, interpret: Optional[bool] = None
) -> jax.Array:
    """One fused D-SGD iteration on a ring: W x − eta g, single kernel."""
    interp = resolve_interpret(x, interpret)
    eta_arr = jnp.asarray(eta, dtype=x.dtype).reshape(1)
    return pl.pallas_call(
        _make_fused_ring_step_kernel(interp),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interp,
    )(eta_arr, x, g)


def _unary_call(kernel, x: jax.Array, interp: bool) -> jax.Array:
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interp,
    )(x)


def fc_mix(x: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    """W x for the fully-connected graph: the global mean, one VMEM pass."""
    return _unary_call(_fc_mix_kernel, x, resolve_interpret(x, interpret))


def ring_neighbor_sum(x: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    """A x for the ring: roll(+1) + roll(−1), computed directly (exact)."""
    interp = resolve_interpret(x, interpret)
    return _unary_call(_make_ring_neighbor_sum_kernel(interp), x, interp)


def fc_neighbor_sum(x: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    """A x for the fully-connected graph: column sums minus self."""
    return _unary_call(
        _fc_neighbor_sum_kernel, x, resolve_interpret(x, interpret)
    )


# ---------------------------------------------------------------------------
# Fused robust gather path (ISSUE-6 tentpole).
#
# Math is a term-for-term mirror of ops/robust_aggregation.py's
# ``make_gather_robust_aggregator`` — same +inf padding, same accumulation
# dtype floor, same identity-row degradation — so the fused form is an
# EXECUTION change only: bitwise-equal outputs for trimmed_mean/median
# (the sort network produces the identical sorted values jnp.sort does for
# finite inputs) and ≤ 1e-12 f64 for clipping, pinned in
# tests/test_fused_robust.py. The difference is WHERE the intermediates
# live: one pallas kernel holds the gathered neighbor stack, the sorted
# closed neighborhood, and the screened aggregate in VMEM and writes only
# the [N, d] result, where the gather path materializes each of them as an
# HBM-backed XLA buffer between ops.
# ---------------------------------------------------------------------------


def fused_robust_supported(name: str, k_max: int, clip_tau=0.0) -> bool:
    """Is ``name`` fused-eligible at this maximum degree?

    The count-based rules sort the closed [N, k_max+1, d] stack through
    the transposition network, which must fit ``FUSED_MAX_SORT_WIDTH``
    (see the constant's rationale). Clipping sorts nothing at a FIXED
    radius (eligible at any degree), but the ADAPTIVE radius
    (``clip_tau <= 0``, the default) ranks the [N, k_max] norms through
    the same quadratic network — the width bound applies to it equally
    (the same host fixed-vs-adaptive decision the aggregators make: a
    traced clip_tau is always the fixed form).
    """
    if name not in AGGREGATIONS or name == "gossip":
        return False
    if name == "clipped_gossip":
        adaptive = isinstance(clip_tau, (int, float)) and clip_tau <= 0.0
        return not adaptive or k_max <= FUSED_MAX_SORT_WIDTH
    return (k_max + 1) <= FUSED_MAX_SORT_WIDTH


def _sort_columns(v: jax.Array) -> jax.Array:
    """Ascending sort along axis 1 via odd-even transposition network.

    ``width`` compare-exchange passes of jnp.minimum/maximum — elementwise
    VPU ops at every stage, so the whole sort lowers on Mosaic where a
    general jnp.sort does not. For finite inputs the result is bitwise the
    multiset-sorted output jnp.sort produces (each min/max returns one of
    its operands exactly); the +inf padding of masked slots sorts to the
    tail like the gather form's. Width is static and small
    (``FUSED_MAX_SORT_WIDTH``), so the unrolled network stays cheap.
    """
    width = v.shape[1]
    cols = [v[:, i] for i in range(width)]
    for parity in range(width):
        for i in range(parity % 2, width - 1, 2):
            lo = jnp.minimum(cols[i], cols[i + 1])
            hi = jnp.maximum(cols[i], cols[i + 1])
            cols[i], cols[i + 1] = lo, hi
    return jnp.stack(cols, axis=1)


def _kernel_adaptive_clip_tau(lv, norms, budget: int, k_max: int):
    """In-kernel twin of robust_aggregation._adaptive_clip_tau: the
    (deg−b)-th smallest realized neighbor-distance norm per node, with the
    rank selection done over the network-sorted [N, k_max] norms via a
    one-hot contraction instead of take_along_axis (Mosaic-friendly)."""
    deg = jnp.sum(lv, axis=1)
    masked = jnp.where(lv > 0, norms, jnp.inf)
    ranked = _sort_columns(masked)
    k = jnp.clip(deg - budget - 1.0, 0.0, float(k_max - 1))
    pos = jnp.arange(k_max, dtype=ranked.dtype)[None, :]
    onehot = (pos == k[:, None]).astype(ranked.dtype)
    kth = jnp.sum(jnp.where(onehot > 0, ranked, 0.0), axis=1)
    return jnp.where(deg - budget >= 1.0, kth, 0.0)


def _fused_robust_body(name, budget, nbr, k_max, adaptive_tau,
                       lv_raw, x, tau_in):
    """The screen+mix math shared by the aggregate-only and fused-SGD
    kernels; runs entirely on VMEM-resident values. Returns the screened
    aggregate in the accumulation dtype (caller casts / applies the SGD
    update)."""
    acc = jnp.promote_types(jnp.float32, x.dtype)
    xa = x.astype(acc)
    lv = lv_raw.astype(acc)
    if name in ("trimmed_mean", "median"):
        gathered = jnp.take(xa, nbr, axis=0)  # [N, k_max, d], VMEM-only
        vals = jnp.where(lv[:, :, None] > 0, gathered, jnp.inf)
        closed = jnp.concatenate([xa[:, None, :], vals], axis=1)
        s = _sort_columns(closed)
        counts = jnp.sum(lv, axis=1) + 1.0
        if name == "trimmed_mean":
            pos = jnp.arange(k_max + 1, dtype=acc)
            keep = (pos[None, :] >= budget) & (
                pos[None, :] < (counts - budget)[:, None]
            )
            kept = jnp.maximum(counts - 2 * budget, 0.0)
            total = jnp.sum(jnp.where(keep[:, :, None], s, 0.0), axis=1)
            mean = total / jnp.maximum(kept, 1.0)[:, None]
            return jnp.where((kept >= 1.0)[:, None], mean, xa)
        # median: rank selection as one-hot contractions over the slot axis
        # (take_along_axis has no Mosaic lowering); 0.5·(s[lo] + s[hi]).
        c = counts  # float, exact for counts <= k_max+1
        lo = jnp.maximum(jnp.floor((c - 1.0) / 2.0), 0.0)
        hi = jnp.maximum(jnp.floor(c / 2.0), 0.0)
        pos = jnp.arange(k_max + 1, dtype=acc)[None, :]
        sel_lo = (pos == lo[:, None]).astype(acc)
        sel_hi = (pos == hi[:, None]).astype(acc)
        pick = lambda sel: jnp.sum(  # noqa: E731
            jnp.where(sel[:, :, None] > 0, s, 0.0), axis=1
        )
        return 0.5 * (pick(sel_lo) + pick(sel_hi))
    # clipped_gossip
    gathered = jnp.take(xa, nbr, axis=0)
    diffs = gathered - xa[:, None, :]
    norms = jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
    deg = jnp.sum(lv, axis=1)
    if adaptive_tau:
        tau = _kernel_adaptive_clip_tau(lv, norms, budget, k_max)
    else:
        tau = jnp.broadcast_to(tau_in[0].astype(acc), (nbr.shape[0],))
    w = lv / (1.0 + jnp.maximum(deg[:, None], jnp.take(deg, nbr)))
    factor = jnp.minimum(
        1.0, tau[:, None] / jnp.maximum(norms, jnp.finfo(acc).tiny)
    )
    moved = jnp.sum(w[:, :, None] * diffs * factor[:, :, None], axis=1)
    return xa + moved


def _make_fused_robust(
    name: str,
    budget: int,
    nbr_idx: np.ndarray,
    clip_tau,
    *,
    with_sgd: bool,
    interpret: Optional[bool],
):
    if name not in AGGREGATIONS or name == "gossip":
        raise ValueError(
            f"no robust aggregator named {name!r}; plain gossip is built by "
            "ops/mixing.py / parallel/faults.py"
        )
    if budget < 1:
        raise ValueError(f"{name} needs a positive attack budget, got {budget}")
    nbr_host = np.asarray(nbr_idx, dtype=np.int32)
    k_max = nbr_host.shape[1]
    if not fused_robust_supported(name, k_max, clip_tau):
        raise ValueError(
            f"robust_impl='fused' cannot screen {name!r} at k_max={k_max}: "
            f"the in-kernel sort network is bounded at width "
            f"{FUSED_MAX_SORT_WIDTH} (the closed neighborhood for the "
            "count rules; the adaptive-radius norm ranking for clipping) "
            "— use robust_impl='gather', or a fixed clip_tau for clipping"
        )
    # Same host decision as the gather twin: a traced clip_tau (a swept
    # replica axis) is the fixed form; only a concrete <= 0.0 is adaptive.
    adaptive_tau = (
        name == "clipped_gossip"
        and isinstance(clip_tau, (int, float))
        and clip_tau <= 0.0
    )
    nbr_dev = jnp.asarray(nbr_host)

    def make_kernel(dtype):
        acc = jnp.promote_types(jnp.float32, dtype)

        if with_sgd:
            def kernel(tau_ref, eta_ref, nbr_ref, live_ref, x_ref, g_ref,
                       out_ref):
                x = x_ref[:]
                agg = _fused_robust_body(
                    name, budget, nbr_ref[:], k_max,
                    adaptive_tau, live_ref[:], x, tau_ref,
                )
                # Cast-then-step in the run dtype: the same values as the
                # unfused ``aggregate(...) − eta·g`` two-op sequence (up
                # to XLA's FMA-contraction choice, ≤ 1 ulp).
                out_ref[:] = agg.astype(dtype) - eta_ref[0] * g_ref[:]
        else:
            def kernel(tau_ref, nbr_ref, live_ref, x_ref, out_ref):
                x = x_ref[:]
                agg = _fused_robust_body(
                    name, budget, nbr_ref[:], k_max,
                    adaptive_tau, live_ref[:], x, tau_ref,
                )
                out_ref[:] = agg.astype(dtype)

        return kernel, acc

    def call(live, x, g=None, eta=None):
        interp = resolve_interpret(x, interpret)
        kernel, acc = make_kernel(x.dtype)
        # Fixed-radius clipping threads tau as a [1] SMEM scalar (possibly
        # traced — the replica-swept axis); the count rules and adaptive
        # clipping ignore it (adaptive recomputes per node in-kernel).
        tau_val = clip_tau if not adaptive_tau else 0.0
        tau_arr = jnp.asarray(tau_val, dtype=acc).reshape(1)
        specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args = [tau_arr]
        if with_sgd:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            args.append(jnp.asarray(eta, dtype=x.dtype).reshape(1))
        specs += [
            pl.BlockSpec(memory_space=pltpu.VMEM),  # nbr table
            pl.BlockSpec(memory_space=pltpu.VMEM),  # liveness bits
            pl.BlockSpec(memory_space=pltpu.VMEM),  # model stack
        ]
        args += [nbr_dev, live, x]
        if with_sgd:
            specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
            args.append(g)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=specs,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interp,
        )(*args)

    return call


def make_fused_robust_aggregator(
    name: str,
    budget: int,
    nbr_idx: np.ndarray,
    clip_tau=0.0,
    *,
    interpret: Optional[bool] = None,
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Fused ``aggregate(live, x) -> x_new``: one pallas kernel performing
    the degree-bounded gather + screen + mix pass of
    ``make_gather_robust_aggregator`` without materializing the
    [N, k_max, d] neighbor stack in HBM. Drop-in for the gather form
    (same liveness/transmitted-stack contract, same outputs — bitwise for
    the count rules, ≤ 1e-12 f64 for clipping)."""
    call = _make_fused_robust(
        name, budget, nbr_idx, clip_tau, with_sgd=False, interpret=interpret
    )
    return lambda live, x: call(live, x)


def make_fused_robust_dsgd_step(
    name: str,
    budget: int,
    nbr_idx: np.ndarray,
    clip_tau=0.0,
    *,
    interpret: Optional[bool] = None,
) -> Callable[..., jax.Array]:
    """Fused ``step(live, x, g, eta) -> x_new``: the ENTIRE robust D-SGD
    update — gather + screen + mix + (− η g) — in one VMEM-resident kernel
    (the Byzantine twin of ``fused_ring_dsgd_step``). Bitwise the
    ``aggregate → subtract`` two-op sequence for the count rules."""
    call = _make_fused_robust(
        name, budget, nbr_idx, clip_tau, with_sgd=True, interpret=interpret
    )
    return lambda live, x, g, eta: call(live, x, g=g, eta=eta)
