"""Host-side float64 numpy twins of the objective/gradient kernels.

Used by the numpy fidelity backend, the sklearn oracle, and parity tests.
Semantics match reference ``obj_problems.py`` exactly, including the
empty-batch guards (return 0.0 / zeros for a zero-row batch,
``obj_problems.py:4-5,14-15,40,47-48``).
"""

from __future__ import annotations

import numpy as np


def _softplus_neg(z: np.ndarray) -> np.ndarray:
    return np.maximum(0.0, -z) + np.log1p(np.exp(-np.abs(z)))


def _sigmoid(z: np.ndarray) -> np.ndarray:
    out = np.empty_like(z)
    pos = z >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-z[pos]))
    ez = np.exp(z[~pos])
    out[~pos] = ez / (1.0 + ez)
    return out


def logistic_objective(w, X, y, lam):
    if X.shape[0] == 0:
        return 0.0
    margins = y * (X @ w)
    return float(np.mean(_softplus_neg(margins)) + 0.5 * lam * np.dot(w, w))


def logistic_gradient(w, X, y, lam):
    if X.shape[0] == 0:
        return np.zeros_like(w)
    margins = y * (X @ w)
    coeff = -y * _sigmoid(-margins)
    return X.T @ coeff / X.shape[0] + lam * w


def quadratic_objective(w, X, y, mu):
    if X.shape[0] == 0:
        return 0.0
    r = X @ w - y
    return float(0.5 * np.mean(r**2) + 0.5 * mu * np.dot(w, w))


def quadratic_gradient(w, X, y, mu):
    if X.shape[0] == 0:
        return np.zeros_like(w)
    r = X @ w - y
    return X.T @ r / X.shape[0] + mu * w


# Single-sourced default δ (config.DEFAULT_HUBER_DELTA); the jax twins in
# ops/losses.py and the native core's C-ABI argument share the same source.
from distributed_optimization_tpu.config import DEFAULT_HUBER_DELTA

HUBER_DELTA = DEFAULT_HUBER_DELTA  # backward-compatible alias


def huber_objective(w, X, y, lam, delta=DEFAULT_HUBER_DELTA):
    if X.shape[0] == 0:
        return 0.0
    r = X @ w - y
    a = np.abs(r)
    h = np.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return float(np.mean(h) + 0.5 * lam * np.dot(w, w))


def huber_gradient(w, X, y, lam, delta=DEFAULT_HUBER_DELTA):
    if X.shape[0] == 0:
        return np.zeros_like(w)
    r = X @ w - y
    coeff = np.clip(r, -delta, delta)
    return X.T @ coeff / X.shape[0] + lam * w


def softmax_objective(w, X, y, lam):
    """Multinomial logistic (cross-entropy) objective; K inferred from the
    flat parameter size (w.size // d — see ops/losses.py softmax section)."""
    if X.shape[0] == 0:
        return 0.0
    W = w.reshape(X.shape[1], -1)
    logits = X @ W
    m = logits.max(axis=1, keepdims=True)
    lse = (m[:, 0] + np.log(np.exp(logits - m).sum(axis=1)))
    true = logits[np.arange(X.shape[0]), y.astype(np.int64)]
    return float(np.mean(lse - true) + 0.5 * lam * np.dot(w, w))


def softmax_gradient(w, X, y, lam):
    if X.shape[0] == 0:
        return np.zeros_like(w)
    W = w.reshape(X.shape[1], -1)
    logits = X @ W
    m = logits.max(axis=1, keepdims=True)
    e = np.exp(logits - m)
    P = e / e.sum(axis=1, keepdims=True)
    P[np.arange(X.shape[0]), y.astype(np.int64)] -= 1.0
    G = X.T @ P / X.shape[0] + lam * W
    return G.reshape(-1)


OBJECTIVES = {"logistic": logistic_objective, "quadratic": quadratic_objective,
              "huber": huber_objective, "softmax": softmax_objective}
GRADIENTS = {"logistic": logistic_gradient, "quadratic": quadratic_gradient,
             "huber": huber_gradient, "softmax": softmax_gradient}
