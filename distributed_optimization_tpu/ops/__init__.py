"""Pure jittable math kernels: objectives, gradients, sampling, mixing."""

from distributed_optimization_tpu.ops import losses, mixing, sampling  # noqa: F401
