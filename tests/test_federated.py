"""Federated-scale execution (ISSUE 8): local steps, client sampling, and
the matrix-free neighbor-table path.

Four contracts are pinned here:

1. **Reductions** — ``local_steps=1`` and ``participation_rate=1.0`` are
   BITWISE the historical programs (no extra ops, no fault machinery), and
   the in-test hand-rolled recursions confirm the τ>1 semantics.
2. **Oracle parity** — sampled participation (composed with churn and the
   Byzantine layer) and τ>1 local steps agree between the jax backend and
   the independent numpy twins ≤ 1e-12 in float64 under injected batch
   schedules.
3. **Matrix-free equivalence** — neighbor-table topologies realize the
   bit-identical graph as their dense twins (the ER sampler consumes the
   same Generator stream), the gather mixing/fault forms match the dense
   trajectories ≤ 1e-12, and the k_max blow-up guards reject quadratic
   tables loudly.
4. **Serving-cache semantics** — the new fields are structural: configs
   differing in them hash apart (deliberate cache MISS, never a cohort
   collision).
"""

import numpy as np
import pytest

from distributed_optimization_tpu.config import (
    MATRIX_FREE_AUTO_N,
    NEIGHBOR_TOPOLOGIES,
    SWEEPABLE_FIELDS,
    ExperimentConfig,
)
from distributed_optimization_tpu.backends import jax_backend, numpy_backend
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

N = 8
T = 40
BASE = dict(
    n_workers=N, n_samples=200, n_features=10, n_informative_features=6,
    problem_type="quadratic", n_iterations=T, topology="ring",
    algorithm="dsgd", local_batch_size=8, dtype="float64", eval_every=10,
)


def make_cfg(**kw):
    return ExperimentConfig(**{**BASE, **kw})


@pytest.fixture(scope="module")
def problem():
    cfg = make_cfg()
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    rng = np.random.default_rng(0)
    sizes = [len(i) for i in ds.shard_indices]
    sched = np.stack([
        [rng.choice(sizes[i], size=BASE["local_batch_size"], replace=False)
         for i in range(N)]
        for _ in range(T)
    ])
    return ds, f_opt, sched


def run_jax(cfg, problem, **kw):
    ds, f_opt, sched = problem
    return jax_backend.run(
        cfg, ds, f_opt, batch_schedule=sched, use_mesh=False, **kw
    )


def run_np(cfg, problem):
    ds, f_opt, sched = problem
    return numpy_backend.run(cfg, ds, f_opt, batch_schedule=sched)


# ------------------------------------------------------------- reductions


@pytest.mark.parametrize("algorithm", ["dsgd", "gradient_tracking"])
@pytest.mark.parametrize("backend", ["jax", "numpy"])
def test_tau1_bitwise_reduces_to_current(problem, algorithm, backend):
    """local_steps=1 is the historical trajectory, bitwise, both backends."""
    cfg0 = make_cfg(algorithm=algorithm, backend=backend)
    cfg1 = cfg0.replace(local_steps=1)
    run = run_jax if backend == "jax" else run_np
    r0, r1 = run(cfg0, problem), run(cfg1, problem)
    np.testing.assert_array_equal(r0.final_models, r1.final_models)
    np.testing.assert_array_equal(r0.history.objective, r1.history.objective)


def test_participation_one_bitwise_and_no_fault_machinery(problem):
    """participation_rate=1.0 traces the identical no-sampling program."""
    from distributed_optimization_tpu.algorithms import get_algorithm
    from distributed_optimization_tpu.parallel import build_topology

    cfg = make_cfg(participation_rate=1.0)
    r0, r1 = run_jax(make_cfg(), problem), run_jax(cfg, problem)
    np.testing.assert_array_equal(r0.final_models, r1.final_models)
    topo = build_topology("ring", N)
    assert jax_backend._build_faulty(
        cfg, get_algorithm("dsgd"), topo, T
    ) is None


def test_dsgd_local_steps_manual_recursion(problem):
    """τ=2 D-SGD IS: x ← W x − η g(x); x ← x − η g(x) — checked against a
    hand-rolled float64 recursion (independent of both backends)."""
    ds, f_opt, sched = problem
    from distributed_optimization_tpu.parallel import build_topology
    from distributed_optimization_tpu.ops import losses_np

    cfg = make_cfg(local_steps=2, backend="numpy")
    W = build_topology("ring", N).mixing_matrix
    shards = [ds.shard(i) for i in range(N)]
    grad = losses_np.GRADIENTS["quadratic"]

    def g(params, t):
        out = np.zeros((N, 10 + 1))
        for i in range(N):
            Xi, yi = shards[i]
            idx = sched[t, i]
            out[i] = grad(params[i], Xi[idx], yi[idx], cfg.reg_param)
        return out

    x = np.zeros((N, 10 + 1))
    for t in range(T):
        eta = cfg.learning_rate_eta0 / np.sqrt(t + 1.0)
        x = W @ x - eta * g(x, t)   # round's gossip-fused step 0
        x = x - eta * g(x, t)       # local step 1 (same injected batch)
    r = run_np(cfg, problem)
    np.testing.assert_allclose(r.final_models, x, atol=1e-13, rtol=0)


def test_gt_local_steps_preserve_tracking_invariant(problem):
    """mean(y_t) == mean(g_prev_t) for every τ — the local descents touch
    only the model, never the tracker recursion."""
    cfg = make_cfg(algorithm="gradient_tracking", local_steps=3)
    r = run_jax(cfg, problem, return_state=True)
    y, g_prev = r.final_state["y"], r.final_state["g_prev"]
    np.testing.assert_allclose(
        y.mean(axis=0), g_prev.mean(axis=0), atol=1e-12, rtol=0
    )


# ----------------------------------------------------------- oracle parity


@pytest.mark.parametrize("algorithm", ["dsgd", "gradient_tracking"])
@pytest.mark.parametrize("tau", [2, 4])
def test_local_steps_jax_vs_numpy(problem, algorithm, tau):
    cj = make_cfg(algorithm=algorithm, local_steps=tau, backend="jax")
    cn = cj.replace(backend="numpy")
    rj, rn = run_jax(cj, problem), run_np(cn, problem)
    np.testing.assert_allclose(
        rj.final_models, rn.final_models, atol=1e-12, rtol=0
    )
    # Early-iteration gaps are O(10^3), so the history check is relative
    # (the 1e-12 f64 convention, scale-honest).
    np.testing.assert_allclose(
        rj.history.objective, rn.history.objective, rtol=1e-12, atol=1e-12
    )


def test_local_steps_fori_loop_path(problem):
    """τ−1 > LOCAL_UNROLL_MAX routes the jax body through lax.fori_loop;
    the numpy twin always Python-loops — same trajectory either way."""
    from distributed_optimization_tpu.algorithms.base import LOCAL_UNROLL_MAX

    tau = LOCAL_UNROLL_MAX + 3
    cfg = make_cfg(local_steps=tau, n_iterations=10, eval_every=10)
    rj = run_jax(cfg, problem)
    rn = run_np(cfg.replace(backend="numpy"), problem)
    dev = np.max(np.abs(rj.final_models[:, : T] - rn.final_models[:, : T]))
    assert dev < 1e-11, dev


@pytest.mark.parametrize("algorithm", ["dsgd", "gradient_tracking"])
def test_participation_jax_vs_numpy_under_churn(problem, algorithm):
    """Sampled participation composed with crash-recovery churn: ≤ 1e-12
    f64 parity against the independent numpy fault twins."""
    cj = make_cfg(
        algorithm=algorithm, participation_rate=0.6, mttf=8.0, mttr=3.0,
        backend="jax",
    )
    rj, rn = run_jax(cj, problem), run_np(cj.replace(backend="numpy"), problem)
    np.testing.assert_allclose(
        rj.final_models, rn.final_models, atol=1e-12, rtol=0
    )
    # Realized comms accounting agrees exactly (same realized edge count).
    assert rj.history.total_floats_transmitted == pytest.approx(
        rn.history.total_floats_transmitted
    )


def test_participation_composes_with_byzantine(problem):
    """Client sampling under attack: the screening rule runs over the
    sampled subgraph (realized_adjacency composition), matching the numpy
    twin ≤ 1e-12."""
    cj = make_cfg(
        participation_rate=0.7, attack="sign_flip", n_byzantine=1,
        aggregation="trimmed_mean", robust_b=1, partition="shuffled",
        backend="jax",
    )
    rj, rn = run_jax(cj, problem), run_np(cj.replace(backend="numpy"), problem)
    np.testing.assert_allclose(
        rj.final_models, rn.final_models, atol=1e-12, rtol=0
    )


def test_batch_replicas_match_sequential(problem):
    """run_batch with participation + local steps: replica r ==
    run(seed=seeds[r]) (the ISSUE-4 contract extended to the new regime)."""
    ds, f_opt, _ = problem
    cfg = make_cfg(
        participation_rate=0.5, local_steps=2, mttf=8.0, mttr=3.0,
        replicas=3,
    )
    br = jax_backend.run_batch(cfg, ds, f_opt)
    for r, s in enumerate(br.seeds):
        seq = jax_backend.run(
            cfg.replace(seed=s, replicas=1), ds, f_opt, use_mesh=False
        )
        np.testing.assert_allclose(
            br.results[r].final_models, seq.final_models,
            atol=1e-12, rtol=0,
        )


def test_batch_continuation_with_participation(problem):
    """The participation timeline is prefix-stable in the horizon: a batch
    split in two at t0 reproduces the one-shot run exactly."""
    ds, f_opt, _ = problem
    cfg = make_cfg(participation_rate=0.5, replicas=2)
    full = jax_backend.run_batch(cfg, ds, f_opt)
    half = cfg.replace(n_iterations=T // 2)
    first = jax_backend.run_batch(half, ds, f_opt)
    second = jax_backend.run_batch(
        half, ds, f_opt, state0=first.final_states, t0=T // 2
    )
    np.testing.assert_array_equal(
        full.final_states["x"], second.final_states["x"]
    )


# --------------------------------------------------------- matrix-free path


@pytest.mark.parametrize("name", NEIGHBOR_TOPOLOGIES)
def test_neighbor_tables_match_dense(name):
    """Matrix-free builds carry the bit-identical table ``neighbor_table``
    derives from the dense adjacency — ER included (same Generator
    stream)."""
    from distributed_optimization_tpu.parallel.topology import (
        build_topology, neighbor_table,
    )

    n = 16
    kw = dict(erdos_renyi_p=0.3, seed=7) if name == "erdos_renyi" else {}
    d = build_topology(name, n, **kw)
    m = build_topology(name, n, impl="neighbor", **kw)
    di, dm = neighbor_table(d.adjacency)
    np.testing.assert_array_equal(di, m.nbr_idx)
    np.testing.assert_array_equal(dm, m.nbr_mask)
    np.testing.assert_array_equal(d.degrees, m.degrees)
    assert m.is_matrix_free and m.adjacency is None and m.mixing_matrix is None
    assert abs(d.spectral_gap - m.spectral_gap) < 1e-6
    assert d.floats_per_iteration == m.floats_per_iteration


def test_gather_mixing_matches_dense():
    from distributed_optimization_tpu.parallel.topology import build_topology
    from distributed_optimization_tpu.ops.mixing import make_mixing_op
    import jax.numpy as jnp

    topo = build_topology("erdos_renyi", 12, erdos_renyi_p=0.4, seed=3)
    dense = make_mixing_op(topo, impl="dense", dtype=jnp.float32)
    gather = make_mixing_op(topo, impl="gather", dtype=jnp.float32)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((12, 5)), dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(dense.apply(x)), np.asarray(gather.apply(x)), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(dense.neighbor_sum(x)), np.asarray(gather.neighbor_sum(x)),
        atol=1e-6,
    )


def test_mixing_auto_routes_gather():
    """auto → gather for matrix-free graphs and above the measured dense
    threshold; stencil still wins where the graph embeds as shifts."""
    from distributed_optimization_tpu.parallel.topology import build_topology
    from distributed_optimization_tpu.ops.mixing import make_mixing_op

    er_free = build_topology("erdos_renyi", 16, seed=1, impl="neighbor")
    assert make_mixing_op(er_free).impl == "gather"
    ring_free = build_topology("ring", 16, impl="neighbor")
    assert make_mixing_op(ring_free).impl == "stencil"
    er_small = build_topology("erdos_renyi", 16, seed=1)
    assert make_mixing_op(er_small).impl == "dense"
    chain_big = build_topology("chain", MATRIX_FREE_AUTO_N)
    assert make_mixing_op(chain_big).impl == "gather"


def test_dense_mixing_rejected_on_matrix_free():
    from distributed_optimization_tpu.parallel.topology import build_topology
    from distributed_optimization_tpu.ops.mixing import make_mixing_op

    topo = build_topology("erdos_renyi", 16, seed=1, impl="neighbor")
    for impl in ("dense", "sparse"):
        with pytest.raises(ValueError, match="matrix-free"):
            make_mixing_op(topo, impl=impl)


@pytest.mark.parametrize("topology", ["erdos_renyi", "chain", "ring"])
def test_neighbor_trajectory_matches_dense(problem, topology):
    cd = make_cfg(topology=topology, topology_impl="dense")
    cn = make_cfg(topology=topology, topology_impl="neighbor")
    rd, rn = run_jax(cd, problem), run_jax(cn, problem)
    np.testing.assert_allclose(
        rd.final_models, rn.final_models, atol=1e-12, rtol=0
    )


def test_neighbor_faulty_trajectory_matches_dense(problem):
    """Gather-form node-process faults (participation + churn +
    neighbor_restart) realize the identical graphs and trajectories as the
    dense fault machinery."""
    kw = dict(
        topology="erdos_renyi", participation_rate=0.5, mttf=8.0, mttr=3.0,
        rejoin="neighbor_restart",
    )
    rd = run_jax(make_cfg(topology_impl="dense", **kw), problem)
    rn = run_jax(make_cfg(topology_impl="neighbor", **kw), problem)
    np.testing.assert_allclose(
        rd.final_models, rn.final_models, atol=1e-12, rtol=0
    )
    assert rd.history.total_floats_transmitted == pytest.approx(
        rn.history.total_floats_transmitted
    )


def test_neighbor_batch_replicas(problem):
    """Matrix-free topologies batch: replica r == sequential run."""
    ds, f_opt, _ = problem
    cfg = make_cfg(
        topology="erdos_renyi", topology_impl="neighbor",
        participation_rate=0.6, replicas=2,
    )
    br = jax_backend.run_batch(cfg, ds, f_opt)
    for r, s in enumerate(br.seeds):
        # The batch contract pins the random graph to the BASE config's
        # resolved topology seed (the graph is structural).
        seq = jax_backend.run(
            cfg.replace(
                seed=s, replicas=1,
                topology_seed=cfg.resolved_topology_seed(),
            ),
            ds, f_opt, use_mesh=False,
        )
        np.testing.assert_allclose(
            br.results[r].final_models, seq.final_models, atol=1e-12, rtol=0
        )


def test_kmax_blowup_guards():
    from distributed_optimization_tpu.parallel.topology import (
        build_neighbor_topology,
    )

    with pytest.raises(ValueError, match="dense"):
        build_neighbor_topology("fully_connected", 64)
    with pytest.raises(ValueError, match="dense"):
        build_neighbor_topology("star", 64)
    # A dense ER draw whose k_max reaches N−1 is routed back too.
    with pytest.raises(ValueError, match="degree bound"):
        build_neighbor_topology("erdos_renyi", 8, erdos_renyi_p=0.999, seed=0)


# ----------------------------------------------- config / serving semantics


def test_rejections():
    with pytest.raises(ValueError, match="local_steps"):
        make_cfg(algorithm="extra", local_steps=2)
    with pytest.raises(ValueError, match="local_steps"):
        make_cfg(local_steps=0)
    with pytest.raises(ValueError, match="compressed"):
        make_cfg(local_steps=2, compression="top_k", compression_k=3)
    with pytest.raises(ValueError, match="cpp"):
        make_cfg(local_steps=2, backend="cpp")
    with pytest.raises(ValueError, match="participation_rate"):
        make_cfg(participation_rate=0.0)
    with pytest.raises(ValueError, match="centralized|peer"):
        make_cfg(algorithm="centralized", participation_rate=0.5)
    with pytest.raises(ValueError, match="synchronous"):
        make_cfg(participation_rate=0.5, gossip_schedule="one_peer")
    with pytest.raises(ValueError, match="fully_connected|quadratic"):
        make_cfg(topology="fully_connected", topology_impl="neighbor")
    with pytest.raises(ValueError, match="jax"):
        make_cfg(topology_impl="neighbor", backend="numpy")
    # ISSUE-9 satellites: Byzantine screening and per-edge fault processes
    # are ACCEPTED on the matrix-free path now (gather form / [horizon, E]
    # chains through the slot table — tests/test_matrix_free_faults.py);
    # only the [N, N]-materializing robust execution forms stay rejected.
    make_cfg(
        topology_impl="neighbor", attack="sign_flip", n_byzantine=1,
        aggregation="trimmed_mean", robust_b=1,
    )
    make_cfg(topology_impl="neighbor", edge_drop_prob=0.1)
    with pytest.raises(ValueError, match="gather form"):
        make_cfg(
            topology_impl="neighbor", aggregation="trimmed_mean",
            robust_b=1, robust_impl="dense",
        )
    with pytest.raises(ValueError, match="matrices|mixing"):
        make_cfg(topology_impl="neighbor", mixing_impl="dense")


def test_federated_fields_are_structural():
    """The satellite contract: local_steps / participation_rate /
    topology_impl are structural — never sweepable, always hashed — so
    serving cohorts MISS across them instead of colliding."""
    c0 = make_cfg()
    assert "local_steps" not in SWEEPABLE_FIELDS
    assert "participation_rate" not in SWEEPABLE_FIELDS
    h0 = c0.structural_hash()
    assert h0 != c0.replace(local_steps=2).structural_hash()
    assert h0 != c0.replace(participation_rate=0.5).structural_hash()
    assert h0 != c0.replace(participation_rate=0.999).structural_hash()
    # Sweepable/seed variation still coheres into one cohort.
    assert h0 == c0.replace(seed=999, learning_rate_eta0=0.5).structural_hash()
    # The RESOLVED representation is hashed: explicit 'neighbor' and
    # auto-above-threshold name the same compiled program.
    big = dict(BASE, n_workers=MATRIX_FREE_AUTO_N)
    assert (
        ExperimentConfig(**big).resolved_topology_impl() == "neighbor"
    )
    assert (
        ExperimentConfig(**big).structural_hash()
        == ExperimentConfig(**big, topology_impl="neighbor").structural_hash()
    )
    # ... and below the threshold dense vs neighbor are distinct programs.
    assert c0.structural_hash() != c0.replace(
        topology_impl="neighbor"
    ).structural_hash()


def test_realized_bhat_matrix_free_with_node_faults():
    """health_summary's B̂ rebuild must not touch the dense adjacency on a
    matrix-free run (regression: windowed_connectivity dereferenced
    topo.adjacency.shape)."""
    from distributed_optimization_tpu.telemetry import realized_bhat

    cfg = make_cfg(
        topology_impl="neighbor", participation_rate=0.5, mttf=8.0, mttr=3.0,
    )
    out = realized_bhat(cfg)
    assert out is not None and out["horizon"] == T
    # At rate 0.5 over a ring some window is needed; B̂ is either a finite
    # int or None (disconnected union) — both are valid outputs, crashing
    # is not.
    assert out["bhat"] is None or out["bhat"] >= 1


def test_mixing_auto_keeps_dense_for_high_degree_graphs():
    """The large-N auto-gather rule applies the neighbor-table degree
    bound: star (k_max = N−1) and dense ER keep the dense contraction
    instead of allocating a near-quadratic gather (regression)."""
    from distributed_optimization_tpu.parallel.topology import build_topology
    from distributed_optimization_tpu.ops.mixing import make_mixing_op

    star = build_topology("star", MATRIX_FREE_AUTO_N)
    assert make_mixing_op(star).impl == "dense"


def test_batch_edge_sweep_resolution_is_consistent():
    """The per-replica configs of a swept edge_drop axis resolve to the
    SAME representation the base config resolves to — since ISSUE-9 the
    neighbor path carries per-edge fault processes, so the edge sweep no
    longer forks replicas onto a different program than their sequential
    twins (the invariant _run_batch's resolution consult protects)."""
    big = dict(BASE, n_workers=MATRIX_FREE_AUTO_N, topology="erdos_renyi")
    base_cfg = ExperimentConfig(**big)
    assert base_cfg.resolved_topology_impl() == "neighbor"
    rep = base_cfg.replace(edge_drop_prob=0.05)  # what each replica runs
    assert rep.resolved_topology_impl() == "neighbor"


def test_auto_stays_dense_for_dense_only_features():
    big = dict(BASE, n_workers=MATRIX_FREE_AUTO_N)
    # Edge-fault processes are matrix-free-capable since ISSUE-9: auto
    # keeps the neighbor route (the N >= 10k bursty-link headroom).
    assert ExperimentConfig(
        **big, edge_drop_prob=0.1
    ).resolved_topology_impl() == "neighbor"
    # Byzantine screening runs matrix-free but stays an explicit opt-in.
    assert ExperimentConfig(
        **big, aggregation="trimmed_mean", robust_b=1,
    ).resolved_topology_impl() == "dense"
    assert ExperimentConfig(
        **big, backend="numpy"
    ).resolved_topology_impl() == "dense"
    assert ExperimentConfig(
        **dict(big, topology="fully_connected")
    ).resolved_topology_impl() == "dense"


# ------------------------------------------------------------- observability


def test_participation_telemetry_and_report(problem):
    from distributed_optimization_tpu.telemetry import health_summary
    from distributed_optimization_tpu.reporting import format_report
    from distributed_optimization_tpu.metrics import summarize_run

    cfg = make_cfg(participation_rate=0.5, telemetry=True)
    r = run_jax(cfg, problem)
    nodes = np.asarray(r.history.trace["nodes_up"])
    assert 0.25 < nodes.mean() < 0.75  # realized fraction tracks the rate
    h = health_summary(cfg, r.history)
    assert h["participation"]["rate"] == 0.5
    assert h["participation"]["realized_frac_mean"] == pytest.approx(
        nodes.mean()
    )

    class Rec:
        label = "federated"
        skipped_reason = None
        replicate_stats = None
        health = h
        summary = summarize_run("federated", r.history, 0.08, N)

    report = format_report([Rec()], cfg, 0.0)
    assert "participation" in report
    assert "target 50%" in report


def test_local_steps_comms_accounting(problem):
    from distributed_optimization_tpu.telemetry import comms_summary

    cfg = make_cfg(local_steps=4)
    r = run_jax(cfg, problem)
    comms = comms_summary(cfg, r.history)
    assert comms["local_steps"] == 4
    assert comms["floats_per_gradient_step"] == pytest.approx(
        comms["floats_per_iteration_mean"] / 4
    )
    # Per-round analytic floats are UNCHANGED by τ (the whole point):
    r1 = run_jax(make_cfg(), problem)
    assert r.history.total_floats_transmitted == pytest.approx(
        r1.history.total_floats_transmitted
    )
