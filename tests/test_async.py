"""Event-driven asynchronous gossip tests (ISSUE 9 tentpole).

Covers the precomputed event timeline (``parallel/events.py``: purity,
prefix stability, ordering, staleness bookkeeping, latency models), the
scan-over-events execution paths (jax ``backends/async_scan.py`` + the
numpy per-event twin: injected-schedule parity ≤ 1e-12 f64,
checkpoint-mid-schedule resume-exactness on both backends), the
degenerate constant-latency behavior against synchronous one-peer gossip,
the telemetry health block, and the config/dispatch rejections. The
wall-clock-to-ε measurement lives in ``examples/bench_async.py``
(docs/perf/async.json).
"""

import numpy as np
import pytest

from distributed_optimization_tpu.backends import jax_backend, numpy_backend
from distributed_optimization_tpu.backends.async_scan import (
    run_async,
    timeline_for,
)
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel.events import (
    build_event_timeline,
    clock_skew,
    sample_durations,
    staleness_histogram,
    sync_round_times,
)
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

N = 8
T = 40
CFG = ExperimentConfig(
    execution="async", n_workers=N, n_iterations=T, eval_every=10,
    n_samples=400, n_features=12, n_informative_features=8,
    local_batch_size=8, dtype="float64", problem_type="quadratic",
    algorithm="dsgd", topology="ring",
)


@pytest.fixture(scope="module")
def setup():
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    return ds, f_opt


def event_schedule(cfg, ds, seed=0):
    """Fixed [E, b] per-event batch indices into the firing worker's shard
    — the async twin of conftest.batch_schedule."""
    _, tl = timeline_for(cfg)
    sizes = [ds.shard(i)[0].shape[0] for i in range(cfg.n_workers)]
    rng = np.random.default_rng(seed)
    return np.stack([
        rng.integers(0, sizes[int(w)], size=cfg.local_batch_size)
        for w in tl.worker
    ])


# --- timeline properties ---------------------------------------------------


def test_timeline_pure_and_prefix_stable():
    topo = build_topology("ring", N)
    kw = dict(latency_model="lognormal", latency_mean=2.0, latency_tail=1.0)
    a = build_event_timeline(topo, T, 7, **kw)
    b = build_event_timeline(topo, T, 7, **kw)
    for f in ("worker", "partner", "local_step", "t_virtual", "staleness",
              "durations"):
        assert np.array_equal(getattr(a, f), getattr(b, f)), f
    # Prefix stability in the horizon: the first T rounds of a longer
    # build are bit-identical draws (the build_fault_timeline contract).
    c = build_event_timeline(topo, 2 * T, 7, **kw)
    assert np.array_equal(c.durations[:T], a.durations)
    # A different seed realizes a different schedule.
    d = build_event_timeline(topo, T, 8, **kw)
    assert not np.array_equal(d.t_virtual, a.t_virtual)


def test_timeline_invariants():
    topo = build_topology("ring", N)
    tl = build_event_timeline(
        topo, T, 3, latency_model="exponential", latency_mean=1.0,
    )
    assert tl.n_events == N * T
    # Every worker fires exactly T events, in its own step order.
    for i in range(N):
        own = tl.local_step[tl.worker == i]
        assert np.array_equal(own, np.arange(T)), i
    # Event times are globally nondecreasing; matched (initiator) events
    # pair graph neighbors with the initiator as the pair minimum, and
    # each round's matched events form the round's one-peer matching.
    assert np.all(np.diff(tl.t_virtual) >= 0)
    A = np.asarray(topo.adjacency)
    m = tl.matched()
    assert m.any()
    assert np.all(A[tl.worker[m], tl.partner[m]] == 1)
    assert np.all(tl.worker[m] < tl.partner[m])
    for k in range(0, T, 7):
        rnd = m & (tl.local_step == k)
        pairs = set(zip(tl.worker[rnd].tolist(), tl.partner[rnd].tolist()))
        nodes = [v for p in pairs for v in p]
        assert len(nodes) == len(set(nodes))  # disjoint matching
    # The globally first event fired before anything could touch its row.
    assert tl.staleness[0] == 0
    # Staleness counts exactly the PASSIVE writes between a worker's
    # reads: summed over a worker's events it equals its passive
    # participations that fell inside read-fire windows — bounded by its
    # total passive participations, and positive somewhere (exponential
    # draws interleave events with probability ~1).
    total_stale = 0
    for i in range(N):
        passive = int(np.sum((tl.partner == i) & (tl.worker != i)))
        own_stale = int(tl.staleness[tl.worker == i].sum())
        assert own_stale <= passive, i
        total_stale += own_stale
    assert total_stale > 0


def test_constant_latency_degenerates_to_round_order():
    topo = build_topology("ring", N)
    tl = build_event_timeline(topo, T, 11, latency_mean=0.5)
    # Workers fire in id order at every tick k*c — the deterministic
    # tie-break the degenerate sync gate rests on.
    assert np.array_equal(tl.worker, np.tile(np.arange(N), T))
    assert np.array_equal(tl.local_step, np.repeat(np.arange(T), N))
    assert np.allclose(tl.t_virtual, np.repeat(np.arange(1, T + 1) * 0.5, N))
    # The synchronous twin's clock coincides: no straggler tax at
    # constant latency.
    assert np.allclose(sync_round_times(tl), np.arange(1, T + 1) * 0.5)
    assert clock_skew(tl)["rel_spread"] == 0.0


def test_latency_models_matched_mean_and_tails():
    topo = build_topology("ring", 16)
    draws = {}
    for model, tail in [("constant", 0.0), ("exponential", 0.0),
                        ("lognormal", 1.25), ("pareto", 1.3)]:
        d = sample_durations(
            4000, 16, 5, latency_model=model, latency_mean=2.0,
            latency_tail=tail,
        )
        assert np.all(d > 0)
        # Matched mean by construction (pareto's alpha=1.3 tail converges
        # slowly — only sanity-bounded here).
        if model == "pareto":
            assert 1.0 < d.mean() < 4.0
        else:
            assert d.mean() == pytest.approx(2.0, rel=0.05), model
        draws[model] = d
    # Tail ordering: the heavy-tailed models realize far larger extremes
    # at the same mean.
    assert draws["lognormal"].max() > 5 * draws["exponential"].mean()
    assert draws["pareto"].max() > draws["exponential"].max()
    # Heavy tails are what create staleness + clock skew.
    tl_h = build_event_timeline(
        topo, 50, 5, latency_model="lognormal", latency_tail=1.25,
    )
    tl_c = build_event_timeline(topo, 50, 5)
    assert staleness_histogram(tl_h)["max"] > staleness_histogram(
        tl_c)["max"]
    assert clock_skew(tl_h)["rel_spread"] > clock_skew(tl_c)["rel_spread"]
    with pytest.raises(ValueError, match="latency_tail > 0"):
        sample_durations(10, 4, 0, latency_model="lognormal",
                         latency_mean=1.0, latency_tail=0.0)
    with pytest.raises(ValueError, match="alpha"):
        sample_durations(10, 4, 0, latency_model="pareto",
                         latency_mean=1.0, latency_tail=1.0)
    with pytest.raises(ValueError, match="Unknown latency model"):
        sample_durations(10, 4, 0, latency_model="gamma",
                         latency_mean=1.0, latency_tail=0.0)


# --- backend parity --------------------------------------------------------


def test_jax_vs_numpy_per_event_parity(setup):
    """Injected per-event schedule ⇒ the two backends replay the identical
    event sequence: state prefixes and metric rows agree ≤ 1e-12 f64."""
    ds, f_opt = setup
    cfg = CFG.replace(eval_every=1)  # a metric row every N events
    sched = event_schedule(cfg, ds)
    rj = jax_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    rn = numpy_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    assert np.max(np.abs(rj.final_models - rn.final_models)) < 1e-12
    gap_dev = np.max(
        np.abs(rj.history.objective - rn.history.objective)
        / np.maximum(np.abs(rn.history.objective), 1.0)
    )
    assert gap_dev < 1e-12
    assert np.allclose(
        rj.history.consensus_error, rn.history.consensus_error,
        rtol=1e-12, atol=1e-12,
    )
    # Identical comms accounting (2·d floats per matched event) and the
    # round-based eval axis.
    assert (
        rj.history.total_floats_transmitted
        == rn.history.total_floats_transmitted
    )
    assert np.array_equal(
        rj.history.eval_iterations, rn.history.eval_iterations
    )
    # Per-event-granularity prefixes: state parity after 1 and 3 rounds.
    for rounds in (1, 3):
        pj = run_async(
            cfg, ds, f_opt, batch_schedule=sched,
            n_events=rounds * N, return_state=True, collect_metrics=False,
        )
        pn = numpy_backend.run_async(
            cfg, ds, f_opt, batch_schedule=sched,
            n_events=rounds * N, return_state=True, collect_metrics=False,
        )
        for k in ("x", "x_read"):
            assert np.max(
                np.abs(pj.final_state[k] - pn.final_state[k])
            ) < 1e-12, (rounds, k)


def test_resume_exactness_checkpoint_mid_schedule(setup, tmp_path):
    """Satellite: checkpoint mid-schedule, restore from disk, and the tail
    events replay bitwise on BOTH backends (the timeline and the
    counter-based batch draws rebuild from the config alone)."""
    ds, f_opt = setup
    E = N * T
    ckpt = tmp_path / "async_state.npz"
    for runner in (run_async, numpy_backend.run_async):
        full = runner(CFG, ds, f_opt, return_state=True)
        half = runner(
            CFG, ds, f_opt, n_events=E // 2, return_state=True,
        )
        np.savez(ckpt, **half.final_state)
        restored = dict(np.load(ckpt))
        tail = runner(
            CFG, ds, f_opt, state0=restored, start_event=E // 2,
            return_state=True,
        )
        for k in ("x", "x_read"):
            assert np.array_equal(
                tail.final_state[k], full.final_state[k]
            ), (runner.__module__, k)
        # The tail's metric rows are the full run's last rows, and the
        # eval axis continues in global round numbering.
        assert np.array_equal(
            tail.history.objective, full.history.objective[2:]
        )
        assert np.array_equal(
            tail.history.eval_iterations, full.history.eval_iterations[2:]
        )
    # A continuation slice's health block is scoped to ITS window: half
    # the events, the slice's own virtual duration, and a floats/virtual-
    # second rate consistent with the slice's realized accounting (never
    # slice floats over the full schedule's clock).
    from distributed_optimization_tpu.telemetry import health_summary

    h_full = health_summary(CFG, full.history)["async"]
    h_tail = health_summary(CFG, tail.history)["async"]
    assert h_tail["events"] == E // 2 and h_full["events"] == E
    assert h_tail["virtual_duration"] < h_full["virtual_duration"]
    assert h_tail["floats_per_virtual_second"] == pytest.approx(
        tail.history.total_floats_transmitted / h_tail["virtual_duration"]
    )


def test_misaligned_or_stateless_windows_rejected(setup):
    ds, f_opt = setup
    with pytest.raises(ValueError, match="align to eval boundaries"):
        run_async(CFG, ds, f_opt, n_events=N * 5)
    with pytest.raises(ValueError, match="needs the previous"):
        run_async(CFG, ds, f_opt, start_event=N * CFG.eval_every)
    with pytest.raises(ValueError, match="event rows"):
        run_async(CFG, ds, f_opt, batch_schedule=np.zeros((7, 4), int))
    with pytest.raises(ValueError, match="do not match the"):
        run_async(
            CFG, ds, f_opt,
            state0={"x": np.zeros((N, 12))}, start_event=0,
        )


# --- behavior --------------------------------------------------------------


def test_constant_latency_is_sync_one_peer(setup):
    """The degenerate sync-reduction gate, exactly: at constant latency
    the event schedule realizes ``x' = 0.5(I + P_t) x − η_t G(x)`` on the
    IDENTICAL matching draws the synchronous one-peer path samples (same
    sampler, same key stream), so with shared injected batches the two
    runs agree ≤ 1e-12 f64 — the only remaining difference is XLA
    program shape. Realized comms match exactly (one exchange per
    matched pair per round)."""
    from tests.conftest import batch_schedule

    ds, f_opt = setup
    Tg = 60
    async_cfg = CFG.replace(n_iterations=Tg, eval_every=10)
    sync_cfg = async_cfg.replace(
        execution="sync", gossip_schedule="one_peer",
        latency_mean=1.0,
    )
    # One batch realization per (worker, round), shared: the sync path
    # consumes it as [T, N, b] rows, the event path as the firing
    # worker's [E, b] rows.
    sync_sched = batch_schedule(ds, Tg, CFG.local_batch_size)
    _, tl = timeline_for(async_cfg)
    async_sched = sync_sched[tl.local_step, tl.worker]
    ra = jax_backend.run(async_cfg, ds, f_opt, batch_schedule=async_sched)
    rs = jax_backend.run(sync_cfg, ds, f_opt, batch_schedule=sync_sched)
    assert np.max(np.abs(ra.final_models - rs.final_models)) < 1e-12
    assert np.allclose(
        ra.history.objective, rs.history.objective,
        rtol=1e-12, atol=1e-9,
    )
    assert (
        ra.history.total_floats_transmitted
        == rs.history.total_floats_transmitted
    )


def test_async_converges_under_heavy_tail(setup):
    ds, f_opt = setup
    cfg = CFG.replace(
        n_iterations=300, eval_every=50, latency_model="lognormal",
        latency_tail=1.25,
    )
    r = jax_backend.run(cfg, ds, f_opt)
    gaps = r.history.objective
    assert np.all(np.isfinite(gaps))
    # Real optimization progress. The mean-over-workers gap decays more
    # slowly than a barriered run's per ROUND — heavy-tailed laggards drag
    # the average — which is exactly why the headline comparison is
    # wall-clock-to-ε on the virtual clock (bench_async), not iters-to-ε.
    assert gaps[-1] < 0.25 * gaps[0]
    assert r.history.iters_per_second > 0


# --- telemetry / serving surfaces ------------------------------------------


def test_health_summary_async_block(setup):
    from distributed_optimization_tpu.telemetry import (
        async_summary,
        health_summary,
    )

    ds, f_opt = setup
    cfg = CFG.replace(latency_model="lognormal", latency_tail=1.0)
    r = jax_backend.run(cfg, ds, f_opt)
    h = health_summary(cfg, r.history)
    a = h["async"]
    assert a["latency_model"] == "lognormal"
    assert a["events"] == N * T
    assert sum(a["staleness"]["buckets"].values()) == N * T
    assert a["staleness"]["mean"] >= 0.0
    assert a["virtual_clock"]["rel_spread"] > 0.0
    # The barrier twin on the same draws can only be slower.
    assert a["sync_virtual_duration"] >= a["virtual_duration"]
    assert a["floats_per_virtual_second"] > 0.0
    # Matched events bound: one exchange per event.
    assert a["matched_events"] <= a["events"]
    assert async_summary(CFG.replace(execution="sync")) is None
    # Sync runs carry no async block.
    assert "async" not in health_summary(
        CFG.replace(execution="sync"), r.history
    )


def test_simulator_and_runtrace_carry_async_health(setup):
    from distributed_optimization_tpu.simulator import Simulator

    ds, _ = setup
    sim = Simulator(CFG, dataset=ds)
    rec = sim.run_one("async smoke", verbose=False)
    assert rec.health is not None and "async" in rec.health
    traces = sim.run_traces()
    assert traces and traces[0].health["async"]["events"] == N * T
    text = sim.report_numerical_results()
    assert "async[constant]" in text


def test_structural_hash_distinguishes_execution_fields():
    """The serving cache/coalescer key must MISS across execution-mode and
    latency-model variants: the event schedule is baked into the traced
    program (ISSUE-9: 'all structural for the serving cache')."""
    base = CFG.replace(execution="sync")
    variants = [
        CFG,
        CFG.replace(latency_model="exponential"),
        CFG.replace(latency_mean=2.0),
        CFG.replace(latency_model="lognormal", latency_tail=1.0),
    ]
    hashes = {c.structural_hash() for c in [base] + variants}
    assert len(hashes) == len(variants) + 1


def test_executable_cache_hit_is_bitwise(setup):
    from distributed_optimization_tpu.serving.cache import ExecutableCache

    ds, f_opt = setup
    cache = ExecutableCache()
    r1 = jax_backend.run(CFG, ds, f_opt, executable_cache=cache)
    r2 = jax_backend.run(CFG, ds, f_opt, executable_cache=cache)
    assert cache.hits == 1
    assert r2.history.compile_seconds == 0.0
    assert np.array_equal(r1.final_models, r2.final_models)


# --- rejections ------------------------------------------------------------


def test_config_rejections():
    ok = dict(execution="async")
    for bad, match in [
        (dict(algorithm="extra"), "dsgd"),
        (dict(algorithm="push_sum"), "dsgd"),
        (dict(attack="sign_flip", n_byzantine=1), "pairwise exchange"),
        (dict(aggregation="trimmed_mean", robust_b=1), "pairwise exchange"),
        (dict(compression="top_k", compression_k=4, algorithm="dsgd"),
         "compressed"),
        (dict(replicas=2), "totally"),
        (dict(topology="directed_ring"), "one-way links"),
        (dict(topology_impl="neighbor", n_workers=8192,
              topology="ring"), "dense-"),
        (dict(backend="cpp"), "cpp backend"),
    ]:
        with pytest.raises(ValueError, match=match):
            ExperimentConfig(**{**ok, **bad})
    # ISSUE-17 composition closure: the event clock is a fault substrate
    # and the async scan carries trace buffers / fused local steps — these
    # all CONSTRUCT now (the former rejections are deleted in config and
    # scenarios/validity.py lockstep).
    for accepted in [
        dict(algorithm="gradient_tracking"),
        dict(edge_drop_prob=0.2),
        dict(participation_rate=0.5),
        dict(mttf=10.0, mttr=5.0),
        dict(mttf=10.0, mttr=5.0, rejoin="neighbor_restart"),
        dict(local_steps=2),
        dict(local_steps=3, algorithm="gradient_tracking"),
        dict(gossip_schedule="one_peer"),
        dict(gossip_schedule="round_robin"),
        dict(telemetry=True),
        dict(straggler_prob=0.1),
    ]:
        cfg = ExperimentConfig(**{**ok, **accepted})
        assert cfg.execution == "async"
    # latency knobs are async-only; tail knobs are model-specific.
    with pytest.raises(ValueError, match="silently ignore"):
        ExperimentConfig(latency_tail=1.0)
    with pytest.raises(ValueError, match="silently ignore"):
        ExperimentConfig(latency_mean=3.0)
    with pytest.raises(ValueError, match="latency_tail only shapes"):
        ExperimentConfig(
            execution="async", latency_model="exponential",
            latency_tail=1.0,
        )


def test_runner_rejections(setup):
    ds, f_opt = setup
    from distributed_optimization_tpu.utils.checkpoint import (
        CheckpointOptions,
    )

    # Checkpointing composes with async now (ISSUE-17); the remaining
    # exclusions are telemetry trace buffers (not checkpointed) and an
    # explicit state0/start_event cursor (the chunk IS the cursor).
    with pytest.raises(ValueError, match="not checkpointed"):
        jax_backend.run(
            CFG.replace(telemetry=True), ds, f_opt,
            checkpoint=CheckpointOptions(directory="/tmp/nope"),
        )
    with pytest.raises(ValueError, match="VIRTUAL clock"):
        jax_backend.run(CFG, ds, f_opt, measure_timestamps=True)
    with pytest.raises(ValueError, match="run seeds sequentially"):
        jax_backend.run_batch(CFG, ds, f_opt, seeds=[1, 2])
    assert jax_backend.batch_unsupported_reason(CFG) is not None


def test_auto_topology_impl_stays_dense_for_async():
    cfg = ExperimentConfig(
        execution="async", n_workers=8192, topology="ring",
        local_batch_size=4, n_samples=16384,
    )
    assert cfg.resolved_topology_impl() == "dense"
