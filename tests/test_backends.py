"""Backend tests: jax↔numpy equivalence, algorithm correctness vs hand-rolled
matrix-form recursions, convergence oracles, comms accounting."""

import numpy as np
import pytest

from conftest import batch_schedule as _schedule, small_backend_config as small_config
from distributed_optimization_tpu.backends import run_algorithm
from distributed_optimization_tpu.ops import losses_np
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.utils import (
    compute_reference_optimum,
    generate_synthetic_dataset,
)


@pytest.mark.parametrize("algorithm", ["centralized", "dsgd"])
def test_jax_numpy_equivalence_injected_batches(quad_setup, algorithm):
    """Identical batches ⇒ identical trajectories across backends (§4c)."""
    cfg, ds, f_opt = quad_setup
    T = 40
    sched = _schedule(ds, T, 8)
    rj = run_algorithm(
        cfg.replace(algorithm=algorithm, n_iterations=T), ds, f_opt, batch_schedule=sched
    )
    rn = run_algorithm(
        cfg.replace(algorithm=algorithm, n_iterations=T, backend="numpy"),
        ds,
        f_opt,
        batch_schedule=sched,
    )
    np.testing.assert_allclose(rj.final_models, rn.final_models, rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(
        rj.history.objective, rn.history.objective, rtol=2e-3, atol=5e-3
    )
    assert rj.total_floats_transmitted == rn.total_floats_transmitted


def test_centralized_rows_stay_identical(quad_setup):
    cfg, ds, f_opt = quad_setup
    r = run_algorithm(cfg.replace(algorithm="centralized"), ds, f_opt)
    spread = np.abs(r.final_models - r.final_models[0]).max()
    assert spread == 0.0
    assert r.history.consensus_error is None


def _hand_rolled(algorithm, ds, cfg, T, sched):
    """Matrix-form float64 recursions straight from the papers, as an oracle
    for the backend implementations (full-state, dense W)."""
    topo = build_topology(cfg.topology, cfg.n_workers)
    W = topo.mixing_matrix
    A = topo.adjacency
    deg = topo.degrees[:, None]
    n, d = cfg.n_workers, ds.n_features
    grad_f = losses_np.GRADIENTS[cfg.problem_type]
    reg = cfg.reg_param
    eta = cfg.learning_rate_eta0

    def grads(params, t):
        out = np.zeros((n, d))
        for i in range(n):
            Xi, yi = ds.shard(i)
            idx = sched[t, i]
            out[i] = grad_f(params[i], Xi[idx], yi[idx], reg)
        return out

    x = np.zeros((n, d))
    if algorithm == "gradient_tracking":
        y = np.zeros((n, d))
        g_prev = np.zeros((n, d))
        for t in range(T):
            x_new = W @ x - eta * y
            g_new = grads(x_new, t)
            y = W @ y + g_new - g_prev
            g_prev = g_new
            x = x_new
    elif algorithm == "extra":
        x_prev = x.copy()
        mix_prev = np.zeros((n, d))
        g_prev = np.zeros((n, d))
        for t in range(T):
            g = grads(x, t)
            mix_x = W @ x
            if t == 0:
                x_new = mix_x - eta * g
            else:
                x_new = x + mix_x - 0.5 * (x_prev + mix_prev) - eta * (g - g_prev)
            x_prev, mix_prev, g_prev, x = x, mix_x, g, x_new
    elif algorithm == "admm":
        c, rho = cfg.admm_c, cfg.admm_rho
        alpha = np.zeros((n, d))
        nbr = np.zeros((n, d))
        for t in range(T):
            g = grads(x, t)
            x = (rho * x + 0.5 * c * (deg * x + nbr) - g - alpha) / (rho + c * deg)
            nbr = A @ x
            alpha = alpha + 0.5 * c * (deg * x - nbr)
    else:
        raise ValueError(algorithm)
    return x


@pytest.mark.parametrize("algorithm", ["gradient_tracking", "extra", "admm"])
def test_extended_algorithms_match_matrix_form(quad_setup, algorithm):
    """Backend step rules ≡ the papers' matrix recursions on fixed batches."""
    cfg, ds, f_opt = quad_setup
    T = 12
    cfg = cfg.replace(algorithm=algorithm, n_iterations=T, learning_rate_eta0=0.01)
    sched = _schedule(ds, T, 8, seed=3)
    r = run_algorithm(cfg, ds, f_opt, batch_schedule=sched)
    expected = _hand_rolled(algorithm, ds, cfg, T, sched)
    np.testing.assert_allclose(r.final_models, expected, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("algorithm", ["gradient_tracking", "extra", "admm"])
def test_exact_methods_converge_where_dsgd_stalls(quad_setup, algorithm):
    """Constant-step GT/EXTRA/ADMM reach the exact optimum on non-IID data;
    constant-step D-SGD stalls at a bias floor — the study's core phenomenon."""
    cfg, ds, f_opt = quad_setup
    T = 600
    kw = dict(n_iterations=T, local_batch_size=50, lr_schedule="constant")
    exact = run_algorithm(
        cfg.replace(algorithm=algorithm, learning_rate_eta0=0.02, **kw), ds, f_opt
    )
    dsgd = run_algorithm(
        cfg.replace(algorithm="dsgd", learning_rate_eta0=0.02, **kw), ds, f_opt
    )
    assert exact.history.objective[-1] < 1.0
    assert exact.history.objective[-1] < 0.2 * dsgd.history.objective[-1]
    assert exact.history.consensus_error[-1] < 1e-2


def test_admm_on_erdos_renyi_logistic():
    """BASELINE.json config #3: decentralized ADMM, logistic, 16-worker ER."""
    cfg = small_config(
        problem_type="logistic",
        algorithm="admm",
        topology="erdos_renyi",
        n_workers=16,
        n_iterations=400,
        local_batch_size=25,
        admm_rho=2.0,
        admm_c=0.5,
    )
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    r = run_algorithm(cfg, ds, f_opt)
    assert r.history.objective[-1] < 0.01
    assert r.history.consensus_error[-1] < 1e-4


def test_gradient_tracking_on_torus():
    """BASELINE.json config #4 (scaled down): GT, quadratic, 2D torus."""
    cfg = small_config(
        algorithm="gradient_tracking",
        topology="grid",
        n_workers=16,
        n_iterations=500,
        local_batch_size=25,
        learning_rate_eta0=0.02,
    )
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    r = run_algorithm(cfg, ds, f_opt)
    assert r.history.objective[-1] < 0.5
    assert r.total_floats_transmitted == pytest.approx(2 * 4 * 16 * 11 * 500)


def test_shard_map_backend_path(quad_setup):
    """End-to-end run with explicit shard_map collectives on the 8-dev mesh."""
    cfg, ds, f_opt = quad_setup
    from distributed_optimization_tpu.parallel.mesh import make_worker_mesh

    mesh = make_worker_mesh(cfg.n_workers)
    r_sm = run_algorithm(
        cfg.replace(mixing_impl="shard_map", n_iterations=50), ds, f_opt, mesh=mesh
    )
    r_dense = run_algorithm(
        cfg.replace(mixing_impl="dense", n_iterations=50), ds, f_opt, use_mesh=False
    )
    np.testing.assert_allclose(
        r_sm.final_models, r_dense.final_models, rtol=5e-4, atol=5e-4
    )


@pytest.mark.parametrize("backend", ["jax", "numpy"])
def test_eval_every_subsamples_history(quad_setup, backend):
    """eval_every=k records metrics at iterations k, 2k, ... matching the
    k=1 history at those points (same trajectory, sparser evaluation)."""
    cfg, ds, f_opt = quad_setup
    T = 40
    sched = _schedule(ds, T, 8)
    dense = run_algorithm(
        cfg.replace(n_iterations=T, backend=backend), ds, f_opt, batch_schedule=sched
    )
    sparse = run_algorithm(
        cfg.replace(n_iterations=T, eval_every=10, backend=backend),
        ds,
        f_opt,
        batch_schedule=sched,
    )
    assert sparse.history.objective.shape == (4,)
    np.testing.assert_array_equal(sparse.history.eval_iterations, [10, 20, 30, 40])
    np.testing.assert_allclose(
        sparse.history.objective, dense.history.objective[9::10], rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(sparse.final_models, dense.final_models, rtol=1e-6)


def test_record_consensus_off(quad_setup):
    cfg, ds, f_opt = quad_setup
    r = run_algorithm(cfg.replace(record_consensus=False), ds, f_opt)
    assert r.history.consensus_error is None
    assert np.isfinite(r.history.objective[-1])


def test_numpy_backend_rejects_randomized_choco_compressors(quad_setup):
    """All six algorithms run on the numpy oracle; the only carve-out is
    CHOCO with a randomized compressor, whose draws live in the jax
    counter-based PRNG stream an independent host oracle cannot reproduce."""
    cfg, ds, f_opt = quad_setup
    with pytest.raises(ValueError, match="deterministic compressors"):
        run_algorithm(
            cfg.replace(algorithm="choco", backend="numpy",
                        compression="qsgd", compression_k=4),
            ds, f_opt,
        )


def test_sqrt_decay_matches_reference_schedule(quad_setup):
    """eta_t = eta0/sqrt(t+1) (reference trainer.py:17-19): one-step check."""
    cfg, ds, f_opt = quad_setup
    T = 1
    sched = _schedule(ds, T, 8)
    r = run_algorithm(cfg.replace(n_iterations=T), ds, f_opt, batch_schedule=sched)
    # After one step from x0 = 0: x1 = -eta0 * g0 (mix(0) = 0).
    grad_f = losses_np.GRADIENTS[cfg.problem_type]
    g0 = np.stack(
        [
            grad_f(np.zeros(ds.n_features), *[a[sched[0, i]] for a in ds.shard(i)], cfg.reg_param)
            for i in range(cfg.n_workers)
        ]
    )
    np.testing.assert_allclose(
        r.final_models, -cfg.learning_rate_eta0 * g0, rtol=1e-4, atol=1e-5
    )
