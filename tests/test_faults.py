"""Failure-injection tests (SURVEY.md §5.3 build target).

Properties: every realized W_t stays symmetric + doubly stochastic (average
preservation under faults); drop_prob=0 reduces exactly to the static MH
matrix; realizations are reproducible from (seed, t); D-SGD still converges
under moderate edge loss; the realized comms accounting is < the fault-free
closed form.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_optimization_tpu.backends import jax_backend, numpy_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel._compat import enable_x64
from distributed_optimization_tpu.parallel.faults import (
    make_faulty_mixing,
    metropolis_hastings_weights,
    sample_surviving_adjacency,
)
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum


@pytest.mark.parametrize("topology", ["ring", "grid", "fully_connected",
                                      "erdos_renyi"])
def test_realized_W_is_symmetric_doubly_stochastic(topology):
    topo = build_topology(topology, 9, erdos_renyi_p=0.5, seed=1)
    A = jnp.asarray(topo.adjacency, dtype=jnp.float32)
    for t in range(5):
        key = jax.random.fold_in(jax.random.key(7), t)
        At = sample_surviving_adjacency(key, A, 0.4)
        W = np.asarray(metropolis_hastings_weights(At))
        np.testing.assert_allclose(W, W.T, atol=1e-6)
        np.testing.assert_allclose(W.sum(axis=1), 1.0, atol=1e-5)
        assert np.all(W >= -1e-6)
        # Surviving edges are a subset of the base adjacency.
        assert np.all(np.asarray(At) <= np.asarray(A))


def test_zero_drop_prob_matches_static_matrix():
    topo = build_topology("ring", 8)
    fm = make_faulty_mixing(topo, 0.0, seed=3)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((8, 4)),
                    dtype=jnp.float32)
    got = np.asarray(fm.mix(jnp.asarray(0), x))
    want = topo.mixing_matrix @ np.asarray(x, dtype=np.float64)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert float(fm.realized_degree_sum(jnp.asarray(0))) == topo.degrees.sum()


def test_fault_realizations_reproducible_and_time_varying():
    topo = build_topology("fully_connected", 10)
    fm = make_faulty_mixing(topo, 0.5, seed=11)
    x = jnp.ones((10, 3), dtype=jnp.float32)
    a = np.asarray(fm.mix(jnp.asarray(4), x))
    b = np.asarray(fm.mix(jnp.asarray(4), x))
    np.testing.assert_array_equal(a, b)  # same t -> same realization
    sums = {float(fm.realized_degree_sum(jnp.asarray(t))) for t in range(8)}
    assert len(sums) > 1  # realizations vary over time


def test_mean_preserved_under_faults():
    # W_t doubly stochastic => the network average is invariant through mixing.
    topo = build_topology("grid", 9)
    fm = make_faulty_mixing(topo, 0.3, seed=5)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((9, 6)),
                    dtype=jnp.float32)
    for t in range(4):
        mixed = fm.mix(jnp.asarray(t), x)
        np.testing.assert_allclose(
            np.asarray(jnp.mean(mixed, axis=0)),
            np.asarray(jnp.mean(x, axis=0)),
            atol=1e-5,
        )


CFG = ExperimentConfig(
    n_workers=9, n_samples=360, n_features=10, n_informative_features=6,
    n_iterations=600, local_batch_size=8, problem_type="quadratic",
    algorithm="dsgd", topology="ring", eval_every=50,
)


def test_dsgd_converges_under_faults_and_floats_accounting():
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    clean = jax_backend.run(CFG, ds, f_opt)
    faulty = jax_backend.run(CFG.replace(edge_drop_prob=0.3), ds, f_opt)
    # Still optimizing (gap shrinks substantially from its start).
    assert faulty.history.objective[-1] < 0.2 * faulty.history.objective[0]
    # Realized communication < fault-free closed form, > half at p=0.3.
    clean_floats = clean.history.total_floats_transmitted
    assert faulty.history.total_floats_transmitted < clean_floats
    assert faulty.history.total_floats_transmitted > 0.5 * clean_floats


def test_numpy_backend_runs_synchronous_faults():
    # Synchronous failure injection became oracle-supported with the
    # fault-timeline refactor; matching schedules stay jax-only.
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    r = numpy_backend.run(CFG.replace(edge_drop_prob=0.3,
                                      backend="numpy"), ds, f_opt)
    assert r.history.objective[-1] < 0.2 * r.history.objective[0]
    with pytest.raises(ValueError, match="jax-backend capability"):
        numpy_backend.run(CFG.replace(gossip_schedule="one_peer"), ds, 0.0)


def test_shard_map_mixing_rejects_faults():
    ds = generate_synthetic_dataset(CFG)
    with pytest.raises(ValueError, match="dense or stencil"):
        jax_backend.run(
            CFG.replace(edge_drop_prob=0.1, mixing_impl="shard_map"), ds, 0.0
        )


def test_straggler_adjacency_and_mean_preservation():
    topo = build_topology("fully_connected", 10)
    fm = make_faulty_mixing(topo, 0.0, seed=4, straggler_prob=0.4)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((10, 3)),
                    dtype=jnp.float32)
    for t in range(4):
        m = np.asarray(fm.active(jnp.asarray(t)))
        assert set(np.unique(m)).issubset({0.0, 1.0})
        # Straggler exchanges nothing: its mixing row is identity.
        mixed = np.asarray(fm.mix(jnp.asarray(t), x))
        frozen = m == 0.0
        np.testing.assert_allclose(
            mixed[frozen], np.asarray(x)[frozen], atol=1e-6
        )
        # Doubly stochastic every realization: average preserved.
        np.testing.assert_allclose(mixed.mean(0), np.asarray(x).mean(0),
                                   atol=1e-5)


def test_straggler_rows_frozen_in_backend():
    from distributed_optimization_tpu.parallel.faults import make_faulty_mixing

    cfg = CFG.replace(straggler_prob=0.5, n_iterations=1, eval_every=1)
    ds = generate_synthetic_dataset(cfg)
    r = jax_backend.run(cfg, ds, 0.0)
    topo = build_topology("ring", cfg.n_workers)
    fm = make_faulty_mixing(topo, 0.0, seed=cfg.seed, straggler_prob=0.5)
    m = np.asarray(fm.active(jnp.asarray(0)))
    # x0 = 0: stragglers must still be exactly zero, active rows moved.
    assert np.all(r.final_models[m == 0.0] == 0.0)
    if (m == 1.0).any():
        assert np.all(np.abs(r.final_models[m == 1.0]).sum(axis=1) > 0)


def test_dsgd_converges_under_stragglers():
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    clean = jax_backend.run(CFG, ds, f_opt)
    lazy = jax_backend.run(CFG.replace(straggler_prob=0.3), ds, f_opt)
    assert lazy.history.objective[-1] < 0.2 * lazy.history.objective[0]
    # Stragglers reduce realized communication: (1-q)^2 per edge ≈ 0.49.
    assert (
        lazy.history.total_floats_transmitted
        < 0.7 * clean.history.total_floats_transmitted
    )


def test_straggler_rejected_for_centralized():
    ds = generate_synthetic_dataset(CFG)
    with pytest.raises(ValueError, match="decentralized"):
        jax_backend.run(
            CFG.replace(algorithm="centralized", straggler_prob=0.2), ds, 0.0
        )
    with pytest.raises(ValueError, match="decentralized"):
        numpy_backend.run(
            CFG.replace(algorithm="centralized", straggler_prob=0.2), ds, 0.0
        )
    with pytest.raises(ValueError):
        ExperimentConfig(straggler_prob=1.0)


def test_jax_numpy_fault_parity_iid():
    """Shared fault schedule + independent mask/weight math twins must
    agree on float64 trajectories to ~1e-12 (ISSUE 2 acceptance)."""
    cfg = CFG.replace(
        n_iterations=40, eval_every=4, dtype="float64",
        edge_drop_prob=0.3, straggler_prob=0.2,
    )
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    sched = _fault_batch_schedule(ds, cfg)
    rj = jax_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    rn = numpy_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    assert np.abs(rj.final_models - rn.final_models).max() < 1e-12
    assert rj.history.total_floats_transmitted == pytest.approx(
        rn.history.total_floats_transmitted
    )


def _fault_batch_schedule(ds, cfg, seed=0):
    """Fixed [T, N, b] injected batches so backend trajectories are
    comparable (same convention as tests/conftest.batch_schedule)."""
    rng = np.random.default_rng(seed)
    sizes = [ds.shard(i)[0].shape[0] for i in range(cfg.n_workers)]
    return np.stack([
        np.stack([
            rng.choice(sizes[i], size=cfg.local_batch_size, replace=False)
            for i in range(cfg.n_workers)
        ])
        for _ in range(cfg.n_iterations)
    ])


def test_one_peer_matching_properties():
    from distributed_optimization_tpu.parallel.faults import (
        sample_one_peer_matching,
    )

    topo = build_topology("grid", 16)
    A = jnp.asarray(topo.adjacency, dtype=jnp.float32)
    idx = np.arange(16)
    for t in range(6):
        p = np.asarray(sample_one_peer_matching(jax.random.key(t), A))
        np.testing.assert_array_equal(p[p], idx)  # involution
        matched = p != idx
        # Matched pairs are real edges of the base graph.
        assert np.all(np.asarray(A)[idx[matched], p[matched]] == 1.0)


def test_one_peer_mix_is_pairwise_average_and_mean_preserving():
    topo = build_topology("ring", 12)
    fm = make_faulty_mixing(topo, 0.0, seed=8, one_peer=True)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((12, 4)),
                    dtype=jnp.float32)
    for t in range(4):
        mixed = np.asarray(fm.mix(jnp.asarray(t), x))
        np.testing.assert_allclose(mixed.mean(0), np.asarray(x).mean(0),
                                   atol=1e-5)
        # Every row is either itself (unmatched) or a pairwise average.
        xs = np.asarray(x)
        for i in range(12):
            is_self = np.allclose(mixed[i], xs[i], atol=1e-6)
            is_avg = np.any([
                np.allclose(mixed[i], 0.5 * (xs[i] + xs[j]), atol=1e-6)
                for j in range(12) if j != i
            ])
            assert is_self or is_avg
        # Floats: one model per matched node, at most N.
        assert float(fm.realized_degree_sum(jnp.asarray(t))) <= 12


def test_one_peer_dsgd_converges_with_fraction_of_comm():
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    sync = jax_backend.run(CFG, ds, f_opt)
    op = jax_backend.run(CFG.replace(gossip_schedule="one_peer"), ds, f_opt)
    assert op.history.objective[-1] < 0.2 * op.history.objective[0]
    # <= N/sum(deg) = half the synchronous-ring traffic, strictly less.
    assert (
        op.history.total_floats_transmitted
        < 0.55 * sync.history.total_floats_transmitted
    )


def test_one_peer_rejections():
    ds = generate_synthetic_dataset(CFG)
    with pytest.raises(ValueError, match="decentralized"):
        jax_backend.run(
            CFG.replace(algorithm="centralized", gossip_schedule="one_peer"),
            ds, 0.0,
        )
    with pytest.raises(ValueError, match="time-varying"):
        jax_backend.run(
            CFG.replace(algorithm="admm", gossip_schedule="one_peer",
                        lr_schedule="constant"),
            ds, 0.0,
        )
    with pytest.raises(ValueError, match="jax-backend capability"):
        numpy_backend.run(CFG.replace(gossip_schedule="one_peer"), ds, 0.0)
    with pytest.raises(ValueError, match="Unknown gossip"):
        ExperimentConfig(gossip_schedule="async")


@pytest.mark.parametrize("topology,n", [
    ("ring", 8), ("ring", 9), ("chain", 7), ("chain", 8), ("grid", 16),
    ("grid", 36),
])
def test_round_robin_phases_cover_edges(topology, n):
    from distributed_optimization_tpu.parallel.matchings import (
        round_robin_partners,
        validate_partners,
    )

    topo = build_topology(topology, n)
    partners = round_robin_partners(topo)
    validate_partners(partners, topo)  # involutions, edges, exact coverage
    # Odd rings need the extra wrap phase.
    expected_phases = {("ring", 9): 3, ("grid", 16): 4, ("grid", 36): 4}
    assert partners.shape[0] == expected_phases.get((topology, n), 2)


def test_round_robin_rejects_unsupported():
    from distributed_optimization_tpu.parallel.matchings import (
        round_robin_partners,
    )

    with pytest.raises(ValueError, match="ring/chain/grid"):
        round_robin_partners(build_topology("fully_connected", 6))
    with pytest.raises(ValueError, match="even side"):
        round_robin_partners(build_topology("grid", 9))
    with pytest.raises(ValueError, match="deterministic"):
        ExperimentConfig(gossip_schedule="round_robin", edge_drop_prob=0.1)


def test_round_robin_dsgd_converges_with_third_of_traffic():
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    sync = jax_backend.run(CFG, ds, f_opt)
    rr = jax_backend.run(CFG.replace(gossip_schedule="round_robin"), ds, f_opt)
    assert rr.history.objective[-1] < 0.2 * rr.history.objective[0]
    # 9-ring: the 3 phases match 4+4+1 pairs -> 2*(4+4+1)/3 = 6 transmitting
    # nodes per iteration on average vs sum(deg) = 18 synchronous: exactly
    # one third — exact only when T divides evenly into whole phase cycles.
    assert CFG.n_iterations % 3 == 0, "ratio below assumes whole 3-phase cycles"
    assert rr.history.total_floats_transmitted == pytest.approx(
        sync.history.total_floats_transmitted / 3.0
    )


def test_admm_rejects_faults():
    ds = generate_synthetic_dataset(CFG)
    with pytest.raises(ValueError, match="static degree"):
        jax_backend.run(
            CFG.replace(algorithm="admm", edge_drop_prob=0.1,
                        lr_schedule="constant"),
            ds, 0.0,
        )


def test_centralized_rejects_faults():
    ds = generate_synthetic_dataset(CFG)
    with pytest.raises(ValueError, match="decentralized"):
        jax_backend.run(
            CFG.replace(algorithm="centralized", edge_drop_prob=0.1), ds, 0.0
        )


def test_invalid_drop_prob():
    with pytest.raises(ValueError):
        ExperimentConfig(edge_drop_prob=1.0)
    with pytest.raises(ValueError):
        ExperimentConfig(edge_drop_prob=-0.1)


def test_extra_rejects_faults():
    # EXTRA carries the previous iteration's mix (W_{t-1} x_{t-1}); its
    # exactness argument needs a static W, so time-varying gossip is refused.
    ds = generate_synthetic_dataset(CFG)
    with pytest.raises(ValueError, match="static W"):
        jax_backend.run(CFG.replace(algorithm="extra", edge_drop_prob=0.1),
                        ds, 0.0)
    with pytest.raises(ValueError, match="static W"):
        jax_backend.run(
            CFG.replace(algorithm="extra", gossip_schedule="one_peer"),
            ds, 0.0,
        )


def test_fault_accounting_is_float32_regardless_of_model_dtype():
    # Degree sums above 256 quantize in bfloat16 (8 mantissa bits); the
    # accounting must stay exact while mixed MODEL values keep the run dtype.
    topo = build_topology("fully_connected", 40)  # degree sum 40*39 = 1560
    fm = make_faulty_mixing(topo, 0.0, seed=2)
    ds0 = fm.realized_degree_sum(jnp.asarray(0))
    assert ds0.dtype == jnp.float32
    assert float(ds0) == 40 * 39  # exactly; bf16 would round to 1552/1568

    x16 = jnp.ones((40, 3), dtype=jnp.bfloat16)
    assert fm.mix(jnp.asarray(0), x16).dtype == jnp.bfloat16
    assert fm.neighbor_sum(jnp.asarray(0), x16).dtype == jnp.bfloat16
    assert fm.active(jnp.asarray(0)).dtype == jnp.float32

    one_peer = make_faulty_mixing(topo, 0.0, seed=2, one_peer=True)
    assert one_peer.realized_degree_sum(jnp.asarray(1)).dtype == jnp.float32
    assert one_peer.mix(jnp.asarray(1), x16).dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# Gradient tracking under faults: the claim at parallel/faults.py (GT remains
# convergent under time-varying gossip) is backed by exercising its tracking
# invariant through the REAL backend fault paths, not just by the DIGing
# citation. The invariant mean(y_t) = mean(g_prev_t) is an algebraic identity
# of the recursion whenever (a) every realized W_t is doubly stochastic
# (edge drops, one-peer matchings) and (b) a straggler's freeze covers ALL
# state leaves with its mixing row collapsed to identity — sum(y') =
# sum(W y) - sum_frozen y + sum_active(g_new - g_prev) + sum_frozen y =
# sum_frozen g_prev + sum_active g_new = sum(g_prev'). A partial freeze
# (e.g. freezing x but gossiping y) would break it; these tests pin the
# backend's freeze at jax_backend (straggler state-freeze) to the identity.
# ---------------------------------------------------------------------------

GT_CFG = CFG.replace(
    algorithm="gradient_tracking", lr_schedule="constant",
    learning_rate_eta0=0.02, dtype="float64", n_iterations=400,
    eval_every=50,
)


def _gt_invariant_residual(result):
    y_mean = result.final_state["y"].mean(axis=0)
    g_mean = result.final_state["g_prev"].mean(axis=0)
    assert np.linalg.norm(g_mean) > 1e-8  # nontrivial state
    return float(np.abs(y_mean - g_mean).max())


@pytest.mark.parametrize(
    "faults",
    [
        dict(edge_drop_prob=0.3),
        dict(straggler_prob=0.3),
        dict(edge_drop_prob=0.2, straggler_prob=0.2),
        dict(gossip_schedule="one_peer"),
        dict(gossip_schedule="one_peer", edge_drop_prob=0.2,
             straggler_prob=0.2),
    ],
    ids=["drops", "stragglers", "both", "one_peer", "one_peer_both"],
)
def test_gt_tracking_invariant_survives_faults(faults):
    ds = generate_synthetic_dataset(GT_CFG)
    _, f_opt = compute_reference_optimum(ds, GT_CFG.reg_param)
    r = jax_backend.run(GT_CFG.replace(**faults), ds, f_opt,
                        return_state=True)
    # float64 run, T=400: the identity holds to accumulation roundoff.
    assert _gt_invariant_residual(r) < 1e-10


def test_gt_converges_under_faults_with_honest_accounting():
    ds = generate_synthetic_dataset(GT_CFG)
    _, f_opt = compute_reference_optimum(ds, GT_CFG.reg_param)
    clean = jax_backend.run(GT_CFG, ds, f_opt)
    faulty = jax_backend.run(
        GT_CFG.replace(edge_drop_prob=0.3, straggler_prob=0.2), ds, f_opt
    )
    # Still optimizing under combined faults...
    assert faulty.history.objective[-1] < 0.2 * faulty.history.objective[0]
    # ...and the realized two-round (x and y) accounting shrinks with the
    # surviving edges: E[realized] = (1-p)(1-q)^2 * clean ≈ 0.448.
    ratio = (
        faulty.history.total_floats_transmitted
        / clean.history.total_floats_transmitted
    )
    assert 0.3 < ratio < 0.6


def test_gt_straggler_freeze_covers_all_state_leaves():
    """One straggler-heavy iteration from zero init: a frozen worker's x, y,
    AND g_prev must all remain at init (the invariant's proof needs the
    freeze to cover every leaf; freezing x alone would desynchronize y)."""
    from distributed_optimization_tpu.parallel.faults import (
        make_faulty_mixing,
    )

    cfg = GT_CFG.replace(straggler_prob=0.5, n_iterations=1, eval_every=1)
    ds = generate_synthetic_dataset(cfg)
    r = jax_backend.run(cfg, ds, 0.0, return_state=True)
    topo = build_topology("ring", cfg.n_workers)
    # Fault draws are explicit float32 since the timeline refactor, so the
    # mask no longer depends on x64 mode; the scope stays to pin exactly
    # the float64 run's context.
    with enable_x64():
        fm = make_faulty_mixing(topo, 0.0, seed=cfg.seed, straggler_prob=0.5)
        m = np.asarray(fm.active(jnp.asarray(0)))
    frozen = m == 0.0
    assert frozen.any() and (~frozen).any()
    # y_0 = 0, g_prev_0 = 0; after one GT step an ACTIVE worker's y equals
    # its first gradient (nonzero), a frozen worker's stays exactly 0.
    assert np.all(r.final_state["y"][frozen] == 0.0)
    assert np.all(r.final_state["g_prev"][frozen] == 0.0)
    assert np.all(
        np.abs(r.final_state["y"][~frozen]).sum(axis=1) > 0
    )


# ---------------------------------------------------------------------------
# Bitwise reductions of the persistent fault processes (ISSUE 2): the
# Gilbert-Elliott edge chain at burst_len=1 and crash-recovery churn at the
# iid-equivalent (mttf, mttr) point consume the SAME counter-based draws as
# the memoryless samplers against the SAME thresholds — different code path
# (precomputed timeline vs on-the-fly masks), identical realizations, so the
# reductions are asserted as exact array equality through the REAL backend
# trajectories, not just at the mask level.
# ---------------------------------------------------------------------------


def test_burst_len1_masks_bitwise_match_iid():
    from distributed_optimization_tpu.parallel.faults import (
        build_fault_timeline,
    )

    topo = build_topology("erdos_renyi", 10, erdos_renyi_p=0.5, seed=2)
    fm_iid = make_faulty_mixing(topo, 0.4, seed=11)
    tl = build_fault_timeline(topo, 60, 11, edge_drop_prob=0.4, burst_len=1.0)
    fm_tl = make_faulty_mixing(topo, 0.4, seed=11, burst_len=1.0, horizon=60)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((10, 3)),
                    dtype=jnp.float32)
    for t in range(60):
        np.testing.assert_array_equal(
            np.asarray(fm_iid.realized_adjacency(jnp.asarray(t))),
            np.asarray(fm_tl.realized_adjacency(jnp.asarray(t))),
        )
        np.testing.assert_array_equal(
            np.asarray(fm_iid.mix(jnp.asarray(t), x)),
            np.asarray(fm_tl.mix(jnp.asarray(t), x)),
        )
    # The timeline's marginal drop rate matches the iid sampler's target.
    assert abs((1.0 - tl.edge_up.mean()) - 0.4) < 0.05


def test_burst_len1_backend_trajectory_bitwise():
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    iid = jax_backend.run(CFG.replace(edge_drop_prob=0.3), ds, f_opt)
    b1 = jax_backend.run(
        CFG.replace(edge_drop_prob=0.3, burst_len=1.0), ds, f_opt
    )
    np.testing.assert_array_equal(b1.final_models, iid.final_models)
    np.testing.assert_array_equal(b1.history.objective, iid.history.objective)
    assert (
        b1.history.total_floats_transmitted
        == iid.history.total_floats_transmitted
    )


def test_churn_iid_point_backend_trajectory_bitwise():
    from distributed_optimization_tpu.parallel.faults import (
        iid_equivalent_churn,
    )

    q = 0.25
    mttf, mttr = iid_equivalent_churn(q)
    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    iid = jax_backend.run(CFG.replace(straggler_prob=q), ds, f_opt)
    churn = jax_backend.run(CFG.replace(mttf=mttf, mttr=mttr), ds, f_opt)
    np.testing.assert_array_equal(churn.final_models, iid.final_models)
    np.testing.assert_array_equal(
        churn.history.objective, iid.history.objective
    )


def test_churn_iid_point_bitwise_on_numpy_backend():
    """Same reduction through the numpy oracle's independent fault twins:
    the straggler timeline and the churn chain at mttf=1/q, mttr=1/(1-q)
    drive different branches of the builder but identical realizations."""
    from distributed_optimization_tpu.parallel.faults import (
        iid_equivalent_churn,
    )

    q = 0.3
    mttf, mttr = iid_equivalent_churn(q)
    cfg = CFG.replace(n_iterations=60, eval_every=10)
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
    iid = numpy_backend.run(cfg.replace(straggler_prob=q), ds, f_opt)
    churn = numpy_backend.run(cfg.replace(mttf=mttf, mttr=mttr), ds, f_opt)
    np.testing.assert_array_equal(churn.final_models, iid.final_models)


def test_jax_numpy_fault_parity_bursty_and_churn():
    """ISSUE 2 acceptance: jax-vs-numpy oracle trajectory parity (~1e-12)
    for bursty + churn fault schedules, both rejoin policies."""
    for rejoin in ("frozen", "neighbor_restart"):
        cfg = CFG.replace(
            n_iterations=40, eval_every=4, dtype="float64",
            edge_drop_prob=0.3, burst_len=4.0, mttf=10.0, mttr=5.0,
            rejoin=rejoin,
        )
        ds = generate_synthetic_dataset(cfg)
        _, f_opt = compute_reference_optimum(ds, cfg.reg_param)
        sched = _fault_batch_schedule(ds, cfg)
        rj = jax_backend.run(cfg, ds, f_opt, batch_schedule=sched)
        rn = numpy_backend.run(cfg, ds, f_opt, batch_schedule=sched)
        assert np.abs(rj.final_models - rn.final_models).max() < 1e-12, rejoin
        assert rj.history.total_floats_transmitted == pytest.approx(
            rn.history.total_floats_transmitted
        )
