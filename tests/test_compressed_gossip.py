"""Generalized error-feedback compressed gossip (ISSUE-6 tentpole).

The CHOCO machinery (per-worker estimate carry + Compressor) now lives in
``ops/compression.py::ErrorFeedbackGossip`` and serves three algorithms:
CHOCO itself (refactored, trajectories bitwise-unchanged), D-SGD, and
gradient tracking. Pinned here:

- compressed D-SGD IS the CHOCO recursion registered under dsgd: the two
  produce bitwise-identical trajectories for identical configs;
- jax-vs-numpy oracle parity for compressed dsgd/gt (deterministic
  compressors, the oracle convention);
- exact comms accounting: total floats == Σdeg · floats_per_edge ·
  rounds · T on both backends;
- the bytes-moved surfacing: RunTrace health carries the comms block and
  format_report prints floats/iter;
- resume exactness (the estimate carries checkpoint with the state);
- the composition rejections (faults, Byzantine, replicas, run_batch,
  tp) that would silently break the shared-estimate contract.
"""

import numpy as np
import pytest

from distributed_optimization_tpu.backends import jax_backend, numpy_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.ops.compression import make_compressor
from distributed_optimization_tpu.parallel import build_topology

CFG = ExperimentConfig(
    n_workers=10, n_samples=300, n_features=8, n_informative_features=5,
    n_iterations=60, local_batch_size=8, problem_type="quadratic",
    algorithm="dsgd", topology="ring", eval_every=20, dtype="float64",
    partition="shuffled",
)


@pytest.fixture(scope="module")
def data():
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    ds = generate_synthetic_dataset(CFG)
    _, f_opt = compute_reference_optimum(ds, CFG.reg_param)
    return ds, f_opt


@pytest.fixture(scope="module")
def sched(data):
    from conftest import batch_schedule

    ds, _ = data
    return batch_schedule(ds, CFG.n_iterations, CFG.local_batch_size)


# ------------------------------------------------------- oracle parity

@pytest.mark.parametrize("algo", ["dsgd", "gradient_tracking"])
def test_compressed_jax_matches_numpy_oracle(data, sched, algo):
    """top_k error-feedback runs agree with the independent float64
    matrix-form oracle at the backend-parity convention (~1e-13
    measured; asserted at the suite's 1e-9/1e-10 floor)."""
    ds, f_opt = data
    cfg = CFG.replace(algorithm=algo, compression="top_k", compression_k=3)
    rj = jax_backend.run(cfg, ds, f_opt, batch_schedule=sched,
                         use_mesh=False)
    rn = numpy_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    np.testing.assert_allclose(
        rj.final_models, rn.final_models, rtol=1e-9, atol=1e-10
    )
    np.testing.assert_allclose(
        rj.history.objective, rn.history.objective, rtol=1e-8
    )


def test_identity_compression_matches_uncompressed_gt(data, sched):
    """compression='none' at γ=1 makes one error-feedback exchange
    exactly the plain W-mix from a zero estimate... after the first
    round the estimate equals the previous value, so trajectories match
    the uncompressed rule only in the CHOCO adapt-then-combine sense —
    pinned against the oracle rather than the plain path."""
    ds, f_opt = data
    cfg = CFG.replace(
        algorithm="gradient_tracking", compression="none",
    )
    # compression='none' keeps gt on the PLAIN (no-estimate) path — the
    # state must not grow xhat leaves and trajectories are untouched.
    rj = jax_backend.run(cfg, ds, f_opt, batch_schedule=sched,
                         use_mesh=False, return_state=True)
    assert set(rj.final_state) == {"x", "y", "g_prev"}


def test_compressed_dsgd_is_choco(data, sched):
    """The generalization's anchor: compressed D-SGD and CHOCO run the
    SAME recursion off the SAME compressor key stream — bitwise-equal
    trajectories for identical configs (constant LR pins the schedules
    together)."""
    ds, f_opt = data
    cfg_d = CFG.replace(compression="top_k", compression_k=3,
                        lr_schedule="constant")
    cfg_c = cfg_d.replace(algorithm="choco")
    rd = jax_backend.run(cfg_d, ds, f_opt, batch_schedule=sched,
                         use_mesh=False)
    rc = jax_backend.run(cfg_c, ds, f_opt, batch_schedule=sched,
                         use_mesh=False)
    np.testing.assert_array_equal(rd.final_models, rc.final_models)
    np.testing.assert_array_equal(rd.history.objective, rc.history.objective)


def test_qsgd_runs_and_converges_direction(data):
    """The randomized quantizer has no host oracle; sanity-pin that a
    qsgd dsgd run stays finite and improves its gap."""
    ds, f_opt = data
    cfg = CFG.replace(compression="qsgd", compression_k=6,
                      n_iterations=200, eval_every=50)
    r = jax_backend.run(cfg, ds, f_opt, use_mesh=False)
    gaps = r.history.objective
    assert np.all(np.isfinite(gaps))
    assert gaps[-1] < gaps[0]


# --------------------------------------------------- comms accounting

@pytest.mark.parametrize("algo,rounds", [("dsgd", 1),
                                         ("gradient_tracking", 2)])
@pytest.mark.parametrize("comp,k", [("none", 0), ("top_k", 3),
                                    ("qsgd", 4)])
def test_floats_accounting_matches_hand_count(data, algo, rounds, comp, k):
    """total floats == Σdeg · floats_per_edge · rounds · T exactly, on
    the jax backend (and the numpy oracle for deterministic operators).
    The trained dimension is the dataset's (bias column included), so the
    payload is derived from the run's own reported uncompressed total."""
    ds, f_opt = data
    kw = dict(compression=comp, compression_k=k) if comp != "none" else {}
    cfg = CFG.replace(algorithm=algo, n_iterations=20, eval_every=20, **kw)
    r = jax_backend.run(cfg, ds, f_opt, use_mesh=False)
    topo = build_topology("ring", CFG.n_workers)
    deg_sum = float(topo.degrees.sum())
    d = ds.n_features  # the trained dimension (bias included)
    payload = make_compressor(comp, d, k).floats_per_edge
    expected = deg_sum * payload * rounds * 20
    assert r.history.total_floats_transmitted == pytest.approx(expected)
    if comp != "qsgd":
        rn = numpy_backend.run(cfg, ds, f_opt)
        assert rn.history.total_floats_transmitted == pytest.approx(expected)


def test_compression_shrinks_reported_floats(data):
    ds, f_opt = data
    r_full = jax_backend.run(CFG, ds, f_opt, use_mesh=False)
    r_comp = jax_backend.run(
        CFG.replace(compression="top_k", compression_k=2), ds, f_opt,
        use_mesh=False,
    )
    assert (
        r_comp.history.total_floats_transmitted
        < 0.5 * r_full.history.total_floats_transmitted
    )


# ------------------------------------------- health / report surfacing

def test_health_comms_block_and_report(data):
    """The RunTrace health block carries floats/iter (realized, from the
    run's own accounting) and format_report prints it with the operator
    tag — the compression win visible without opening bench JSON."""
    from distributed_optimization_tpu.telemetry import health_summary
    from distributed_optimization_tpu.reporting import format_report

    ds, f_opt = data
    cfg = CFG.replace(compression="top_k", compression_k=2, telemetry=True)
    r = jax_backend.run(cfg, ds, f_opt, use_mesh=False)
    h = health_summary(cfg, r.history)
    comms = h["comms"]
    assert comms["compression"] == "top_k"
    topo = build_topology("ring", CFG.n_workers)
    expected_round = float(topo.degrees.sum()) * 4.0  # 2k floats/edge
    assert comms["floats_per_iteration_mean"] == pytest.approx(expected_round)
    assert comms["floats_per_edge_per_iteration"] == pytest.approx(4.0)

    class Rec:
        label = "compressed"
        skipped_reason = None
        summary = None
        health = h

    # format_report's health section renders the comms part standalone.
    from distributed_optimization_tpu.reporting import _health_section

    lines = _health_section([Rec()])
    assert any("floats/iter" in ln and "top_k" in ln for ln in lines)


def test_health_comms_gt_edge_payload_counts_both_rounds(data):
    """Gradient tracking compresses both gossip rounds, so the per-edge
    per-iteration figure is 2x the compressor payload — the key name
    says per-iteration precisely so this doesn't read as a
    misconfigured compressor."""
    from distributed_optimization_tpu.telemetry import health_summary

    ds, f_opt = data
    cfg = CFG.replace(algorithm="gradient_tracking", compression="top_k",
                      compression_k=3, telemetry=True)
    r = jax_backend.run(cfg, ds, f_opt, use_mesh=False)
    comms = health_summary(cfg, r.history)["comms"]
    assert comms["floats_per_edge_per_iteration"] == pytest.approx(12.0)


def test_uncompressed_health_comms_still_reported(data):
    from distributed_optimization_tpu.telemetry import health_summary

    ds, f_opt = data
    r = jax_backend.run(CFG.replace(telemetry=True), ds, f_opt,
                        use_mesh=False)
    h = health_summary(CFG.replace(telemetry=True), r.history)
    assert h["comms"]["compression"] == "none"
    assert h["comms"]["floats_per_iteration_mean"] > 0


# ------------------------------------------------------ resume / state

def test_compressed_resume_exactness(data, tmp_path):
    """The estimate memories are state leaves, so checkpoint/resume
    rebuilds the identical compressed trajectory."""
    from distributed_optimization_tpu.utils.checkpoint import (
        CheckpointOptions,
    )

    ds, f_opt = data
    cfg = CFG.replace(compression="top_k", compression_k=3,
                      n_iterations=120, eval_every=20)
    full = jax_backend.run(cfg, ds, f_opt, use_mesh=False)
    ckdir = str(tmp_path / "comp_ck")
    jax_backend.run(
        cfg.replace(n_iterations=60), ds, f_opt, use_mesh=False,
        checkpoint=CheckpointOptions(ckdir, every_evals=3),
    )
    resumed = jax_backend.run(
        cfg, ds, f_opt, use_mesh=False,
        checkpoint=CheckpointOptions(ckdir, every_evals=3),
    )
    np.testing.assert_allclose(
        resumed.final_models, full.final_models, rtol=1e-12
    )


# ------------------------------------------------- composition guards

def test_config_rejections():
    ok = dict(compression="top_k", compression_k=3)
    with pytest.raises(ValueError, match="time-vary"):
        CFG.replace(edge_drop_prob=0.2, **ok)
    with pytest.raises(ValueError, match="time-vary"):
        CFG.replace(mttf=5.0, mttr=2.0, **ok)
    with pytest.raises(ValueError, match="Byzantine"):
        CFG.replace(attack="sign_flip", n_byzantine=2, **ok)
    with pytest.raises(ValueError, match="Byzantine"):
        CFG.replace(aggregation="trimmed_mean", robust_b=1, **ok)
    with pytest.raises(ValueError, match="replicas"):
        CFG.replace(replicas=2, **ok)
    with pytest.raises(ValueError, match="only takes effect"):
        CFG.replace(algorithm="push_sum", topology="ring", **ok)
    with pytest.raises(ValueError, match="choco_gamma"):
        CFG.replace(choco_gamma=0.0, **ok)


def test_run_batch_rejects_compression(data):
    ds, f_opt = data
    with pytest.raises(ValueError, match="compressed gossip"):
        jax_backend.run_batch(
            CFG.replace(compression="top_k", compression_k=3), ds, f_opt,
            seeds=[1, 2],
        )


def test_numpy_oracle_rejects_randomized_compressors(data):
    ds, f_opt = data
    with pytest.raises(ValueError, match="deterministic"):
        numpy_backend.run(
            CFG.replace(compression="qsgd", compression_k=4), ds, f_opt
        )


def test_cpp_backend_rejects_compressed_dsgd(data):
    """The native core's compression path covers CHOCO only; compressed
    dsgd/gt must raise (before any library load) rather than silently
    exchange full vectors."""
    from distributed_optimization_tpu.backends import cpp_backend

    ds, f_opt = data
    with pytest.raises(ValueError, match="CHOCO only"):
        cpp_backend.run(
            CFG.replace(backend="cpp", compression="top_k",
                        compression_k=3),
            ds, f_opt,
        )
