"""dtype-path regression tests: float64 fidelity and bfloat16 TPU dtype.

float64 requires a scoped x64 enable — without it jax silently truncates to
float32 (the bug this file pins); bfloat16 is the MXU-native storage dtype
and must run end to end.
"""

import warnings

import jax
import numpy as np
import pytest

from distributed_optimization_tpu.backends import jax_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

BASE = ExperimentConfig(
    n_workers=8, n_samples=320, n_features=8, n_informative_features=4,
    n_iterations=100, local_batch_size=8, problem_type="quadratic",
    algorithm="dsgd", topology="ring", eval_every=10,
)


@pytest.fixture(scope="module")
def data():
    ds = generate_synthetic_dataset(BASE)
    _, f_opt = compute_reference_optimum(ds, BASE.reg_param)
    return ds, f_opt


def test_float64_runs_without_truncation_warnings(data):
    ds, f_opt = data
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)  # truncation warns
        r = jax_backend.run(BASE.replace(dtype="float64"), ds, f_opt)
    assert np.all(np.isfinite(r.history.objective))
    assert not jax.config.jax_enable_x64  # scope restored


def test_float64_more_accurate_than_float32(data):
    ds, f_opt = data
    import numpy as onp

    from distributed_optimization_tpu.backends import numpy_backend

    T = 60
    sched = onp.stack([
        onp.stack([
            onp.random.default_rng(1000 * t + i).choice(40, size=8,
                                                        replace=False)
            for i in range(BASE.n_workers)
        ])
        for t in range(T)
    ]).astype(onp.int32)
    cfg = BASE.replace(n_iterations=T, eval_every=T)
    oracle = numpy_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    r64 = jax_backend.run(cfg.replace(dtype="float64"), ds, f_opt,
                          batch_schedule=sched)
    r32 = jax_backend.run(cfg, ds, f_opt, batch_schedule=sched)
    err64 = np.abs(r64.final_models - oracle.final_models).max()
    err32 = np.abs(r32.final_models - oracle.final_models).max()
    assert err64 < err32  # float64 tracks the float64 oracle more closely
    assert err64 < 1e-9


def test_bfloat16_runs_and_optimizes(data):
    ds, f_opt = data
    r = jax_backend.run(
        BASE.replace(dtype="bfloat16", n_iterations=300, eval_every=30),
        ds, f_opt,
    )
    assert np.all(np.isfinite(r.history.objective))
    assert r.history.objective[-1] < r.history.objective[0]
