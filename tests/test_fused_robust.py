"""Single-kernel fused robust gather path (ISSUE-6 tentpole).

The fused form (``ops/pallas_kernels.py::make_fused_robust_aggregator`` /
``make_fused_robust_dsgd_step`` behind ``robust_impl='fused'``) must be an
EXECUTION change only, exactly like the gather form it fuses: bitwise-equal
outputs for the count rules (trimmed mean / median — the in-kernel sort
network reproduces jnp.sort's values exactly for finite inputs), ≤ 1e-12
f64 for clipping, through unit calls AND real backend runs composed with
bursty links + crash-recovery churn + Byzantine injection, plus
checkpoint/resume exactness. Routing contract: 'auto' promotes to fused
exactly when eligible (static topology, supported rule, telemetry off,
no worker mesh), explicit 'fused' is honored beyond the auto gate but
rejected where the kernel cannot run (replica batches, over-wide sort
networks), and interpret-mode selection respects the input's committed
platform (the ``_on_cpu`` satellite fix).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_optimization_tpu.backends import jax_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.ops import pallas_kernels as pk
from distributed_optimization_tpu.ops.robust_aggregation import (
    make_gather_robust_aggregator,
    make_robust_aggregator,
    robust_aggregate_np,
)
from distributed_optimization_tpu.parallel import build_topology
from distributed_optimization_tpu.parallel._compat import enable_x64
from distributed_optimization_tpu.parallel.topology import neighbor_table

RULES = ("trimmed_mean", "median", "clipped_gossip")
COUNT_RULES = ("trimmed_mean", "median")


def _gather_live(A, nbr_idx, nbr_mask):
    return np.take_along_axis(np.asarray(A), nbr_idx, axis=1) * nbr_mask


def _faulted_instance(n=14, seed=3, d=7):
    """An irregular fault-realized graph with wild (attack-like) rows."""
    topo = build_topology("erdos_renyi", n, erdos_renyi_p=0.5, seed=seed)
    rng = np.random.default_rng(11)
    A = np.array(topo.adjacency, copy=True)
    ei, ej = np.nonzero(np.triu(A, 1))
    drop = rng.random(len(ei)) < 0.3
    A[ei[drop], ej[drop]] = A[ej[drop], ei[drop]] = 0.0
    x = rng.standard_normal((n, d))
    x[[1, 5]] *= 1e4
    nbr_idx, nbr_mask = neighbor_table(topo.adjacency)
    live = _gather_live(A, nbr_idx, nbr_mask)
    return A, x, nbr_idx, live


# ------------------------------------------------------ unit kernel parity

@pytest.mark.parametrize("rule", RULES)
def test_fused_matches_gather_dense_and_oracle_f64(rule):
    """The acceptance parity: bitwise vs gather for the count rules,
    ≤ 1e-12 (f64) for clipping; dense and the per-node numpy oracle agree
    to the gather path's own pinned tolerance."""
    A, x, nbr_idx, live = _faulted_instance()
    with enable_x64():
        gather = make_gather_robust_aggregator(rule, 1, nbr_idx)
        fused = pk.make_fused_robust_aggregator(rule, 1, nbr_idx)
        dense = make_robust_aggregator(rule, budget=1)
        lv = jnp.asarray(live, jnp.float64)
        xv = jnp.asarray(x, jnp.float64)
        g_out = np.asarray(gather(lv, xv))
        f_out = np.asarray(fused(lv, xv))
        d_out = np.asarray(
            dense(jnp.asarray(A, jnp.float64), xv)
        )
    if rule in COUNT_RULES:
        np.testing.assert_array_equal(f_out, g_out)
    else:
        np.testing.assert_allclose(f_out, g_out, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(f_out, d_out, rtol=1e-12, atol=1e-12)
    o_out = robust_aggregate_np(rule, A, x, budget=1)
    np.testing.assert_allclose(f_out, o_out, rtol=1e-12, atol=1e-12)


def test_fused_fixed_clip_tau_matches_gather():
    A, x, nbr_idx, live = _faulted_instance(n=12, seed=9, d=5)
    with enable_x64():
        gather = make_gather_robust_aggregator(
            "clipped_gossip", 1, nbr_idx, clip_tau=0.7
        )
        fused = pk.make_fused_robust_aggregator(
            "clipped_gossip", 1, nbr_idx, clip_tau=0.7
        )
        lv = jnp.asarray(live, jnp.float64)
        xv = jnp.asarray(x, jnp.float64)
        np.testing.assert_allclose(
            np.asarray(fused(lv, xv)), np.asarray(gather(lv, xv)),
            rtol=0, atol=1e-12,
        )


@pytest.mark.parametrize("rule", RULES)
def test_fused_dsgd_step_is_aggregate_then_subtract(rule):
    """The whole-update kernel == the two-op sequence it fuses. Not
    asserted bitwise: XLA may contract the − η·g multiply-subtract into
    an FMA inside one program shape and not the other, a 1-ulp
    discrepancy — the tolerance admits exactly that (≪ the 1e-12
    acceptance floor)."""
    A, x, nbr_idx, live = _faulted_instance()
    rng = np.random.default_rng(21)
    g = rng.standard_normal(x.shape)
    with enable_x64():
        fused_step = pk.make_fused_robust_dsgd_step(rule, 1, nbr_idx)
        fused_agg = pk.make_fused_robust_aggregator(rule, 1, nbr_idx)
        lv = jnp.asarray(live, jnp.float64)
        xv = jnp.asarray(x, jnp.float64)
        gv = jnp.asarray(g, jnp.float64)
        eta = jnp.asarray(0.05, jnp.float64)
        got = np.asarray(fused_step(lv, xv, gv, eta))
        want = np.asarray(fused_agg(lv, xv) - eta * gv)
    np.testing.assert_allclose(got, want, rtol=1e-14, atol=1e-14)


def test_fused_f32_matches_gather_f32():
    """Same accumulation-dtype floor as the gather form: f32 inputs agree
    bitwise for the count rules (both run the identical op sequence in
    f32)."""
    _, x, nbr_idx, live = _faulted_instance()
    for rule in COUNT_RULES:
        gather = make_gather_robust_aggregator(rule, 1, nbr_idx)
        fused = pk.make_fused_robust_aggregator(rule, 1, nbr_idx)
        lv = jnp.asarray(live, jnp.float32)
        xv = jnp.asarray(x, jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(fused(lv, xv)), np.asarray(gather(lv, xv))
        )


def test_identity_row_degradation_matches_gather():
    """Faulted-down neighborhoods (realized closed count ≤ 2b / deg ≤ b)
    keep the worker's own model in the fused form exactly like gather."""
    topo = build_topology("ring", 10)
    rng = np.random.default_rng(8)
    x = rng.standard_normal((10, 4))
    A = np.array(topo.adjacency, copy=True)
    A[0, :] = A[:, 0] = 0.0
    A[3, 4] = A[4, 3] = 0.0
    nbr_idx, nbr_mask = neighbor_table(topo.adjacency)
    live = _gather_live(A, nbr_idx, nbr_mask)
    with enable_x64():
        for rule in RULES:
            fused = pk.make_fused_robust_aggregator(rule, 1, nbr_idx)
            out = np.asarray(
                fused(jnp.asarray(live, jnp.float64),
                      jnp.asarray(x, jnp.float64))
            )
            np.testing.assert_array_equal(out[0], x[0])
            gather = make_gather_robust_aggregator(rule, 1, nbr_idx)
            g_out = np.asarray(
                gather(jnp.asarray(live, jnp.float64),
                       jnp.asarray(x, jnp.float64))
            )
            if rule in COUNT_RULES:
                np.testing.assert_array_equal(out, g_out)
            else:
                np.testing.assert_allclose(out, g_out, rtol=0, atol=1e-12)


def test_sort_network_matches_jnp_sort():
    """The in-kernel odd-even transposition network is bitwise jnp.sort
    for finite inputs, +inf padding included (the property the count-rule
    bitwise parity rests on)."""
    rng = np.random.default_rng(5)
    v = rng.standard_normal((40, 9, 6))
    v[rng.random(v.shape) < 0.2] = np.inf  # masked-slot padding
    with enable_x64():
        got = np.asarray(pk._sort_columns(jnp.asarray(v, jnp.float64)))
        want = np.asarray(jnp.sort(jnp.asarray(v, jnp.float64), axis=1))
    np.testing.assert_array_equal(got, want)


# ------------------------------------------------ e2e backend equivalence

E2E_CFG = ExperimentConfig(
    n_workers=12, n_samples=360, n_features=8, n_informative_features=5,
    n_iterations=80, local_batch_size=8, problem_type="quadratic",
    algorithm="dsgd", topology="erdos_renyi", erdos_renyi_p=0.6,
    eval_every=20, dtype="float64", partition="shuffled",
    attack="sign_flip", n_byzantine=2, attack_scale=2.0,
    aggregation="trimmed_mean", robust_b=1,
)


@pytest.fixture(scope="module")
def e2e_data():
    from distributed_optimization_tpu.utils.data import (
        generate_synthetic_dataset,
    )
    from distributed_optimization_tpu.utils.oracle import (
        compute_reference_optimum,
    )

    ds = generate_synthetic_dataset(E2E_CFG)
    _, f_opt = compute_reference_optimum(ds, E2E_CFG.reg_param)
    return ds, f_opt


@pytest.mark.parametrize("rule", RULES)
def test_e2e_fused_matches_gather_under_composed_faults(e2e_data, rule):
    """The full composition — bursty links + crash-recovery churn +
    Byzantine sign-flip — through real backend runs: robust_impl='fused'
    consumes the per-iteration gather-form liveness inside the kernel, so
    the trajectory must match the gather path's at the repo's e2e parity
    floor, ≤ 1e-12 in f64 (the same convention as gather-vs-dense:
    kernel-level parity IS bitwise for the count rules — the unit tests
    above — but across two differently-shaped compiled programs XLA's
    FMA-contraction choices for the surrounding step ops admit ulp-level
    trajectory drift)."""
    ds, f_opt = e2e_data
    cfg = E2E_CFG.replace(
        aggregation=rule, edge_drop_prob=0.2, burst_len=3.0,
        mttf=8.0, mttr=3.0,
    )
    from conftest import batch_schedule

    sched = batch_schedule(ds, cfg.n_iterations, cfg.local_batch_size)
    rg = jax_backend.run(
        cfg.replace(robust_impl="gather"), ds, f_opt, batch_schedule=sched,
        use_mesh=False,
    )
    rf = jax_backend.run(
        cfg.replace(robust_impl="fused"), ds, f_opt, batch_schedule=sched,
        use_mesh=False,
    )
    np.testing.assert_allclose(
        rf.final_models, rg.final_models, rtol=1e-12, atol=1e-12
    )
    np.testing.assert_allclose(
        rf.history.objective, rg.history.objective, rtol=1e-12
    )


def test_e2e_gt_fused_aggregate(e2e_data):
    """Non-dsgd byzantine algorithms (gradient tracking) take the fused
    AGGREGATOR (screen+mix kernel; the SGD fusion is dsgd's) — same
    trajectory as gather."""
    ds, f_opt = e2e_data
    cfg = E2E_CFG.replace(algorithm="gradient_tracking")
    rg = jax_backend.run(
        cfg.replace(robust_impl="gather"), ds, f_opt, use_mesh=False
    )
    rf = jax_backend.run(
        cfg.replace(robust_impl="fused"), ds, f_opt, use_mesh=False
    )
    # e2e parity floor (see the composed-faults test's docstring).
    np.testing.assert_allclose(
        rf.final_models, rg.final_models, rtol=1e-12, atol=1e-12
    )


def test_fused_resume_exactness(e2e_data, tmp_path):
    """Killed-and-resumed fused run == uninterrupted fused run (the kernel
    is stateless; liveness and corruption derive from (seed, t))."""
    from distributed_optimization_tpu.utils.checkpoint import (
        CheckpointOptions,
    )

    ds, f_opt = e2e_data
    cfg = E2E_CFG.replace(
        robust_impl="fused", n_iterations=120, eval_every=20,
    )
    full = jax_backend.run(cfg, ds, f_opt, use_mesh=False)
    ckdir = str(tmp_path / "fused_ck")
    jax_backend.run(
        cfg.replace(n_iterations=60), ds, f_opt, use_mesh=False,
        checkpoint=CheckpointOptions(ckdir, every_evals=3),
    )
    resumed = jax_backend.run(
        cfg, ds, f_opt, use_mesh=False,
        checkpoint=CheckpointOptions(ckdir, every_evals=3),
    )
    np.testing.assert_allclose(
        resumed.final_models, full.final_models, rtol=1e-12
    )


# ------------------------------------------------------- routing contract

def test_auto_promotes_to_fused_when_eligible(e2e_data):
    """Static topology + supported rule + telemetry off + no mesh: 'auto'
    runs the fused kernel — same compiled trajectory as forcing it (and
    the count-rule path is bitwise, so equality is exact)."""
    ds, f_opt = e2e_data
    ra = jax_backend.run(E2E_CFG, ds, f_opt, use_mesh=False)
    rf = jax_backend.run(
        E2E_CFG.replace(robust_impl="fused"), ds, f_opt, use_mesh=False
    )
    np.testing.assert_array_equal(ra.final_models, rf.final_models)


def test_auto_stays_gather_under_faults_and_telemetry(e2e_data):
    """The auto gate is conservative: time-varying graphs or an active
    telemetry activity probe keep the measured gather routing."""
    ds, f_opt = e2e_data
    faulty = E2E_CFG.replace(edge_drop_prob=0.2)
    ra = jax_backend.run(faulty, ds, f_opt, use_mesh=False)
    rg = jax_backend.run(
        faulty.replace(robust_impl="gather"), ds, f_opt, use_mesh=False
    )
    np.testing.assert_array_equal(ra.final_models, rg.final_models)
    tele = E2E_CFG.replace(telemetry=True)
    rt = jax_backend.run(tele, ds, f_opt, use_mesh=False)
    rtg = jax_backend.run(
        tele.replace(robust_impl="gather"), ds, f_opt, use_mesh=False
    )
    np.testing.assert_array_equal(rt.final_models, rtg.final_models)


def test_resolved_robust_impl_fused_gate():
    cfg = E2E_CFG
    assert cfg.resolved_robust_impl(4, fused_eligible=True) == "fused"
    assert cfg.resolved_robust_impl(4, fused_eligible=False) == "gather"
    # Fully connected keeps dense regardless of eligibility.
    assert cfg.resolved_robust_impl(11, fused_eligible=True) == "dense"
    # Explicit forms are never overridden.
    assert cfg.replace(robust_impl="gather").resolved_robust_impl(
        4, fused_eligible=True
    ) == "gather"


def test_fused_rejects_over_wide_sort_network():
    """Rules whose in-kernel sort would exceed the network width bound
    are not fused-eligible: explicit 'fused' raises, and
    fused_robust_supported gates auto. Clipping sorts nothing at a FIXED
    radius (any degree), but the ADAPTIVE radius ranks the [N, k_max]
    norms through the same quadratic network, so it carries the bound
    too."""
    topo = build_topology("fully_connected", 24)
    nbr_idx, _ = neighbor_table(topo.adjacency)
    assert not pk.fused_robust_supported("median", 23)
    assert not pk.fused_robust_supported("clipped_gossip", 23)  # adaptive
    assert pk.fused_robust_supported("clipped_gossip", 23, clip_tau=0.7)
    assert pk.fused_robust_supported("clipped_gossip", 12)
    with pytest.raises(ValueError, match="sort network"):
        pk.make_fused_robust_aggregator("median", 1, nbr_idx)
    with pytest.raises(ValueError, match="sort network"):
        pk.make_fused_robust_aggregator("clipped_gossip", 1, nbr_idx)
    # Fixed-radius clipping stays constructible at the same degree.
    pk.make_fused_robust_aggregator("clipped_gossip", 1, nbr_idx,
                                    clip_tau=0.7)


def test_run_batch_rejects_fused(e2e_data):
    ds, f_opt = e2e_data
    with pytest.raises(ValueError, match="robust_impl='fused'"):
        jax_backend.run_batch(
            E2E_CFG.replace(robust_impl="fused"), ds, f_opt,
            seeds=[1, 2],
        )


def test_config_rejects_fused_with_replicas_and_without_rule():
    with pytest.raises(ValueError, match="fused"):
        E2E_CFG.replace(robust_impl="fused", replicas=2)
    with pytest.raises(ValueError, match="robust_impl"):
        ExperimentConfig(robust_impl="fused")


# ------------------------------------- interpret-mode selection satellite

def test_resolve_interpret_explicit_override_wins():
    x = jnp.zeros((4, 4))
    assert pk.resolve_interpret(x, interpret=True) is True
    assert pk.resolve_interpret(x, interpret=False) is False


def test_resolve_interpret_uses_committed_platform():
    """On this CPU-only container every committed array lives on cpu, and
    the resolver must read THAT (not the global devices list) — including
    under an explicit jax.default_device scope, in BOTH forms jax
    accepts (a Device object and a platform string — the latter leaves a
    plain str in jax.config.jax_default_device)."""
    x = jax.device_put(jnp.zeros((4, 4)), jax.devices("cpu")[0])
    assert pk.resolve_interpret(x) is True
    with jax.default_device(jax.devices("cpu")[0]):
        assert pk.resolve_interpret(None) is True
    with jax.default_device("cpu"):
        assert pk.resolve_interpret(None) is True


def test_resolve_interpret_handles_tracers():
    """Inside jit the operand is a tracer with no committed device; the
    resolver must fall back to the ambient platform instead of raising."""
    seen = {}

    @jax.jit
    def probe(x):
        seen["interp"] = pk.resolve_interpret(x)
        return x

    probe(jnp.zeros((2, 2)))
    assert seen["interp"] is True  # cpu container
