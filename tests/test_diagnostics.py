"""Tests for the profiling/diagnostics subsystems (SURVEY.md §5.1-5.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_optimization_tpu.utils.diagnostics import (
    check_collectives,
    check_determinism,
    nan_debugging,
)
from distributed_optimization_tpu.utils.profiling import PhaseTimer


def test_phase_timer_accumulates_and_reports():
    timer = PhaseTimer()
    with timer.phase("a"):
        pass
    with timer.phase("a"):
        pass
    with timer.phase("b"):
        pass
    assert set(timer.phases) == {"a", "b"}
    assert timer.phases["a"] >= 0.0
    report = timer.report()
    assert "a" in report and "total" in report


def test_nan_debugging_raises_on_nan():
    with nan_debugging(True):
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()
    # Config restored: same op silently yields NaN outside the scope.
    out = jax.jit(lambda x: jnp.log(x + 0.0))(jnp.asarray(-1.0))
    assert np.isnan(out)


def test_nan_debugging_disabled_is_noop():
    with nan_debugging(False):
        out = jnp.log(jnp.asarray(-1.0))
    assert np.isnan(out)


def test_check_determinism_passes_for_pure_fn():
    fn = jax.jit(lambda x: {"y": x * 2, "z": jnp.cumsum(x)})
    check_determinism(fn, jnp.arange(8.0))


def test_check_determinism_catches_impure_fn():
    rng = np.random.default_rng(0)

    def impure(x):
        return x + rng.standard_normal(x.shape)

    with pytest.raises(AssertionError, match="not bitwise reproducible"):
        check_determinism(impure, np.zeros(4))


def test_check_collectives_all_devices():
    check_collectives()  # 8 virtual CPU devices via conftest


def test_check_collectives_subset_mesh():
    from distributed_optimization_tpu.parallel.mesh import make_worker_mesh

    check_collectives(make_worker_mesh(4, devices=jax.devices()[:4]))
    check_collectives(make_worker_mesh(1, devices=jax.devices()[:1]))
