"""Serving subsystem tests (ISSUE-7; docs/SERVING.md).

The contracts pinned here:

1. **Served == standalone.** A request resolved from a coalesced
   ``run_batch`` cohort is the SAME run as a standalone ``run(cfg)`` over
   the service's dataset — ≤ 1e-12 in float64 (the PR-4 replica-
   equivalence convention, extended to the serving path), and a cached
   executable re-executed for a new request produces bitwise the result a
   fresh compile would have.
2. **Structural hash.** Sweep (eta0 / clip_tau>0 / edge_drop>0) and seed
   variants hash together; ANY non-sweepable difference — including the
   zero/nonzero boundaries inside the sweepables — hashes apart, and two
   configs differing only in a non-sweepable field MISS the cache (the
   collision guard).
3. **Cache mechanics.** LRU eviction by count, hit/miss/compile-seconds-
   saved counters, reuse across seed variants with different datasets
   (f* and data are traced inputs of the batched program).
4. **Robustness.** Malformed/unknown/invalid configs are rejected with
   structured errors at the submission boundary; a poison request that
   passes field validation but fails in the backend takes down only its
   own plan — in-flight cohorts complete and the service keeps serving.
5. **Re-compile fix.** ``Simulator.run_one`` in one process compiles each
   distinct program once (the process executable cache), and the report
   carries the one-line serving summary.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_optimization_tpu.backends import jax_backend
from distributed_optimization_tpu.config import ExperimentConfig
from distributed_optimization_tpu.serving.cache import (
    ExecutableCache,
    process_executable_cache,
)
from distributed_optimization_tpu.serving.coalescer import (
    plan_cohorts,
    structural_group_key,
    sweep_fields_for,
    unbatchable_reason,
)
from distributed_optimization_tpu.serving.service import (
    ServingError,
    ServingOptions,
    SimulationService,
    parse_config,
)
from distributed_optimization_tpu.utils.data import generate_synthetic_dataset
from distributed_optimization_tpu.utils.oracle import compute_reference_optimum

TOL = dict(rtol=1e-12, atol=1e-12)


def _cfg(**kw) -> ExperimentConfig:
    defaults = dict(
        n_workers=8, n_samples=400, n_features=10, n_informative_features=6,
        problem_type="logistic", n_iterations=40, topology="ring",
        algorithm="dsgd", backend="jax", local_batch_size=8, eval_every=10,
        dtype="float64",
    )
    defaults.update(kw)
    return ExperimentConfig(**defaults)


def _setup(cfg):
    ds = generate_synthetic_dataset(cfg)
    _, f_opt = compute_reference_optimum(
        ds, cfg.reg_param, huber_delta=cfg.huber_delta,
        n_classes=cfg.n_classes,
    )
    return ds, f_opt


def _service(**opts) -> SimulationService:
    """A service with its OWN executable cache (never the process-global
    one) so hit/miss assertions are deterministic under any test order."""
    defaults = dict(window_s=0.0)
    defaults.update(opts)
    return SimulationService(
        ServingOptions(**defaults), cache=ExecutableCache()
    )


# ---------------------------------------------------------- structural hash


def test_structural_hash_ignores_seed_and_sweepables():
    base = _cfg()
    assert base.structural_hash() == _cfg(seed=999).structural_hash()
    assert (
        base.structural_hash()
        == _cfg(learning_rate_eta0=0.31).structural_hash()
    )
    assert (
        _cfg(edge_drop_prob=0.1).structural_hash()
        == _cfg(edge_drop_prob=0.25, seed=7).structural_hash()
    )
    robust = dict(
        aggregation="clipped_gossip", robust_b=1, attack="sign_flip",
        n_byzantine=1,
    )
    assert (
        _cfg(clip_tau=0.5, **robust).structural_hash()
        == _cfg(clip_tau=2.0, **robust).structural_hash()
    )
    # data_seed only picks dataset VALUES (traced inputs), never the program.
    assert base.structural_hash() == _cfg(data_seed=7).structural_hash()


def test_structural_hash_zero_boundaries_and_structure():
    base = _cfg()
    # The sweepables' zero boundaries ARE structural: 0 traces a different
    # program (no fault machinery / adaptive clipping radius).
    assert (
        base.structural_hash() != _cfg(edge_drop_prob=0.1).structural_hash()
    )
    robust = dict(
        aggregation="clipped_gossip", robust_b=1, attack="sign_flip",
        n_byzantine=1,
    )
    assert (
        _cfg(clip_tau=0.0, **robust).structural_hash()
        != _cfg(clip_tau=0.5, **robust).structural_hash()
    )
    # Non-sweepable fields hash apart.
    for ov in (
        dict(n_iterations=80, eval_every=10),
        dict(topology="fully_connected"),
        dict(algorithm="gradient_tracking"),
        dict(telemetry=True),
        dict(n_workers=10),
    ):
        assert base.structural_hash() != _cfg(**ov).structural_hash(), ov
    # Random topologies bake the realized graph; deterministic ones don't.
    er = _cfg(topology="erdos_renyi", n_workers=10)
    assert (
        er.structural_hash()
        != er.replace(topology_seed=123).structural_hash()
    )
    assert (
        base.structural_hash()
        == base.replace(topology_seed=123).structural_hash()
    )


def test_collision_guard_nonsweepable_diff_misses_cache():
    """Two configs differing only in a NON-sweepable field must MISS —
    same-hash-but-different-program would serve wrong executables."""
    cfg_a = _cfg()
    cfg_b = _cfg(eval_every=20)
    ds, f_opt = _setup(cfg_a)
    cache = ExecutableCache()
    jax_backend.run_batch(cfg_a, ds, f_opt, executable_cache=cache)
    jax_backend.run_batch(cfg_b, ds, f_opt, executable_cache=cache)
    assert cache.misses == 2 and cache.hits == 0


# ------------------------------------------------------------ cache mechanics


class _FakeExec:
    def memory_analysis(self):
        raise NotImplementedError


def test_cache_lru_eviction_and_counters():
    cache = ExecutableCache(max_entries=2)
    for key in ("a", "b", "c"):
        cache.put((key,), _FakeExec(), compile_seconds=1.5)
    assert len(cache) == 2 and cache.evictions == 1
    assert cache.get(("a",)) is None  # LRU'd out
    entry = cache.get(("c",))
    assert entry is not None and entry.hits == 1
    assert cache.misses == 1 and cache.hits == 1
    assert cache.compile_seconds_saved == pytest.approx(1.5)
    stats = cache.stats()
    assert stats["entries"] == 2 and stats["hit_rate"] == 0.5
    cache.clear()
    assert len(cache) == 0


def test_sequential_run_cache_hit_is_bitwise():
    cfg = _cfg()
    ds, f_opt = _setup(cfg)
    cache = ExecutableCache()
    cold = jax_backend.run(cfg, ds, f_opt, executable_cache=cache)
    warm = jax_backend.run(cfg, ds, f_opt, executable_cache=cache)
    uncached = jax_backend.run(cfg, ds, f_opt, executable_cache=False)
    assert cache.hits == 1 and cache.misses == 1
    assert cold.history.compile_seconds > 0.0
    assert warm.history.compile_seconds == 0.0
    np.testing.assert_array_equal(
        cold.history.objective, warm.history.objective
    )
    np.testing.assert_array_equal(cold.final_models, warm.final_models)
    np.testing.assert_array_equal(cold.final_models, uncached.final_models)


def test_batch_cache_reuse_across_seed_variants_same_bits():
    """Seed variants generate DIFFERENT datasets and optima, yet reuse one
    batched executable (data and f* are traced inputs) — and the reused
    program computes exactly what a fresh compile computes."""
    cfg_a = _cfg()
    cfg_b = _cfg(seed=99)
    ds_a, f_a = _setup(cfg_a)
    ds_b, f_b = _setup(cfg_b)
    cache = ExecutableCache()
    jax_backend.run_batch(cfg_a, ds_a, f_a, executable_cache=cache)
    warm = jax_backend.run_batch(cfg_b, ds_b, f_b, executable_cache=cache)
    cold = jax_backend.run_batch(cfg_b, ds_b, f_b, executable_cache=False)
    assert cache.misses == 1 and cache.hits == 1
    assert warm.compile_seconds == 0.0
    np.testing.assert_array_equal(cold.objective, warm.objective)
    np.testing.assert_array_equal(
        cold.final_states["x"], warm.final_states["x"]
    )


# --------------------------------------------------------------- coalescing


class _Shim:
    def __init__(self, config):
        self.config = config


def test_plan_cohorts_groups_and_chunks():
    reqs = [
        _Shim(_cfg(learning_rate_eta0=e)) for e in (0.05, 0.08, 0.1, 0.2)
    ] + [_Shim(_cfg(topology="fully_connected"))]
    plans = plan_cohorts(reqs, max_cohort=2)
    sizes = sorted(p.size for p in plans)
    assert sizes == [1, 2, 2]
    assert all(p.sequential_reason is None for p in plans)
    # Unbatchable configs become sequential singletons with the
    # run_batch rejection text.
    choco = _Shim(_cfg(algorithm="choco", lr_schedule="constant"))
    plans = plan_cohorts([choco, _Shim(_cfg())], max_cohort=8)
    seq = [p for p in plans if p.sequential_reason is not None]
    assert len(seq) == 1 and "choco" in seq[0].sequential_reason


def test_sweep_fields_follow_structural_class():
    assert sweep_fields_for(_cfg()) == ("learning_rate_eta0",)
    assert sweep_fields_for(_cfg(edge_drop_prob=0.1)) == (
        "learning_rate_eta0", "edge_drop_prob",
    )
    robust = _cfg(
        aggregation="clipped_gossip", robust_b=1, clip_tau=0.5,
        attack="sign_flip", n_byzantine=1,
    )
    assert "clip_tau" in sweep_fields_for(robust)
    assert unbatchable_reason(_cfg()) is None
    assert "choco" in unbatchable_reason(
        _cfg(algorithm="choco", lr_schedule="constant")
    )


def test_served_cohort_matches_standalone_run():
    """The headline parity gate (tier-1): every request sliced from a
    coalesced cohort — eta0 variants AND an identical repeat — equals the
    standalone sequential run of its own config over the service's
    dataset, ≤ 1e-12 in f64."""
    svc = _service()
    etas = (0.05, 0.08, 0.05)  # repeat included: duplicates may coalesce
    ids = [
        svc.submit(_cfg(learning_rate_eta0=e).to_dict()) for e in etas
    ]
    svc.drain()
    reqs = [svc.result(i, timeout=5) for i in ids]
    assert [r.cohort_size for r in reqs] == [3, 3, 3]
    assert all(r.coalesced for r in reqs)
    ds, f_opt = svc._dataset_for(reqs[0].config)
    for req in reqs:
        seq = jax_backend.run(req.config, ds, f_opt, executable_cache=False)
        np.testing.assert_allclose(
            req.result.history.objective, seq.history.objective, **TOL
        )
        np.testing.assert_allclose(
            req.result.final_models, seq.final_models, **TOL
        )
        np.testing.assert_allclose(
            req.result.history.consensus_error,
            seq.history.consensus_error, **TOL,
        )
    # The two identical submissions must agree exactly (same replica
    # program, same inputs).
    np.testing.assert_array_equal(
        reqs[0].result.final_models, reqs[2].result.final_models
    )


def test_served_faulty_byzantine_cohort_matches_standalone():
    """Parity holds through the fault + Byzantine + robust-aggregation
    composition with per-request edge_drop_prob on the sweep axis."""
    mk = lambda p: _cfg(  # noqa: E731
        edge_drop_prob=p, attack="sign_flip", n_byzantine=1,
        aggregation="trimmed_mean", robust_b=1, partition="shuffled",
    )
    svc = _service()
    ids = [svc.submit(mk(p)) for p in (0.1, 0.2)]
    svc.drain()
    reqs = [svc.result(i, timeout=5) for i in ids]
    assert reqs[0].cohort_size == 2
    ds, f_opt = svc._dataset_for(reqs[0].config)
    for req in reqs:
        seq = jax_backend.run(req.config, ds, f_opt, executable_cache=False)
        np.testing.assert_allclose(
            req.result.history.objective, seq.history.objective, **TOL
        )
        np.testing.assert_allclose(
            req.result.final_models, seq.final_models, **TOL
        )


def test_seed_variants_separate_cohorts_shared_executable():
    """Requests differing only in seed name DIFFERENT datasets (the seed
    is sklearn's random_state), so they cannot share a cohort — but they
    hash together and reuse one compiled executable."""
    svc = _service()
    ids = [svc.submit(_cfg(seed=s)) for s in (203, 99)]
    svc.drain()
    reqs = [svc.result(i, timeout=5) for i in ids]
    assert [r.cohort_size for r in reqs] == [1, 1]
    assert reqs[0].cache_hit is False and reqs[1].cache_hit is True
    assert svc.cache.stats()["compile_seconds_saved"] > 0.0
    for req in reqs:
        ds, f_opt = svc._dataset_for(req.config)
        seq = jax_backend.run(req.config, ds, f_opt, executable_cache=False)
        np.testing.assert_allclose(
            req.result.history.objective, seq.history.objective, **TOL
        )


def test_data_seed_pins_dataset_and_coalesces_seed_variants():
    """With data_seed pinned, seed variants share the problem instance —
    one cohort, one program execution — and each equals the standalone
    run of its config over that shared dataset (the --seeds semantics,
    now explicit)."""
    svc = _service()
    ids = [svc.submit(_cfg(seed=s, data_seed=7)) for s in (1, 2)]
    svc.drain()
    reqs = [svc.result(i, timeout=5) for i in ids]
    assert [r.cohort_size for r in reqs] == [2, 2]
    assert all(r.coalesced for r in reqs)
    ds, f_opt = svc._dataset_for(reqs[0].config)
    for req in reqs:
        seq = jax_backend.run(req.config, ds, f_opt, executable_cache=False)
        np.testing.assert_allclose(
            req.result.final_models, seq.final_models, **TOL
        )
    # Different seeds really did run: trajectories differ.
    assert not np.array_equal(
        reqs[0].result.final_models, reqs[1].result.final_models
    )


def test_unbatchable_request_falls_back_sequential():
    svc = _service()
    cfg = _cfg(
        algorithm="choco", lr_schedule="constant", compression="top_k",
        compression_k=3,
    )
    rid = svc.submit(cfg)
    svc.drain()
    req = svc.result(rid, timeout=5)
    assert req.status == "done" and not req.coalesced
    assert "choco" in req.sequential_reason
    assert svc.stats()["requests_sequential_fallback"] == 1
    ds, f_opt = svc._dataset_for(cfg)
    seq = jax_backend.run(cfg, ds, f_opt, executable_cache=False)
    np.testing.assert_allclose(
        req.result.history.objective, seq.history.objective, **TOL
    )


# ---------------------------------------------------------------- robustness


def test_submit_rejects_structured():
    svc = _service()
    with pytest.raises(ServingError, match="unknown config fields"):
        svc.submit({"bogus_field": 1})
    with pytest.raises(ServingError, match="Unknown topology"):
        svc.submit(_cfg().to_dict() | {"topology": "moebius"})
    with pytest.raises(ServingError, match="JSON object"):
        svc.submit([1, 2, 3])
    with pytest.raises(ServingError, match="one request per seed"):
        svc.submit(_cfg(replicas=4))
    assert svc.queue_depth() == 0  # nothing poisoned the queue
    with pytest.raises(ServingError, match="from_dict|unknown config"):
        parse_config({"no_such": True})


def test_queue_bound_rejects_not_buffers():
    svc = _service(max_pending=1)
    svc.submit(_cfg())
    with pytest.raises(ServingError, match="queue full"):
        svc.submit(_cfg(seed=5))
    svc.drain()


def test_done_history_is_bounded():
    """A long-lived daemon rotates finished results out past max_done —
    old ids answer 'unknown request' instead of pinning their payloads."""
    svc = _service(max_done=2)
    ids = [
        svc.submit(_cfg(learning_rate_eta0=e)) for e in (0.05, 0.07, 0.09)
    ]
    svc.drain()
    assert svc.result(ids[-1], timeout=5).status == "done"
    with pytest.raises(KeyError, match=ids[0]):
        svc.get(ids[0])
    assert len(svc._requests) == 2


def test_kill_switch_serves_uncached(monkeypatch):
    """DOPT_EXEC_CACHE=0 must be honored by the serving layer too: no
    explicit cache means COLD compiles, not a silent private cache."""
    monkeypatch.setenv("DOPT_EXEC_CACHE", "0")
    svc = SimulationService(ServingOptions(window_s=0.0))
    assert svc.cache is None
    ids = [svc.submit(_cfg()), svc.submit(_cfg())]
    svc.drain()
    reqs = [svc.result(i, timeout=5) for i in ids]
    # Identical repeats still coalesce (one cohort, one compile) — but
    # nothing is cached across plans and no hit is claimed.
    assert all(r.status == "done" and r.cache_hit is None for r in reqs)
    # The status shape contract (ISSUE-10 satellite): even with the cache
    # disabled, the counter block keeps its full shape — zeros, plus the
    # disabled flag — so dashboards never special-case a cold daemon.
    cache_stats = svc.stats()["cache"]
    assert cache_stats["disabled"] is True
    assert cache_stats["hits"] == 0 and cache_stats["misses"] == 0
    assert cache_stats["compile_seconds_saved"] == 0.0


def test_poison_request_does_not_kill_inflight_cohorts():
    """A config that passes field validation but is rejected by the
    backend (robust budget > min degree) fails ALONE; the healthy cohort
    cut in the same scheduling pass completes, and the service keeps
    accepting work."""
    svc = _service()
    good = [
        svc.submit(_cfg(learning_rate_eta0=e)) for e in (0.05, 0.08)
    ]
    poison = svc.submit(_cfg(
        attack="sign_flip", n_byzantine=1, aggregation="trimmed_mean",
        robust_b=3, partition="shuffled",  # 2*3 > ring min degree 2
    ))
    svc.drain()
    preq = svc.result(poison, timeout=5)
    assert preq.status == "failed" and "robust_b" in preq.error
    for rid in good:
        req = svc.result(rid, timeout=5)
        assert req.status == "done" and req.cohort_size == 2
    # Still serving after the poison.
    rid = svc.submit(_cfg())
    svc.drain()
    assert svc.result(rid, timeout=5).status == "done"
    stats = svc.stats()
    assert stats["requests_failed"] == 1 and stats["requests_done"] == 3


# ------------------------------------------------------------------- daemon


def _post(url, body, timeout=120.0, raw=False):
    req = urllib.request.Request(
        url,
        data=body if raw else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, timeout=30.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture()
def daemon():
    from distributed_optimization_tpu.serving.daemon import ServingDaemon

    d = ServingDaemon(
        "127.0.0.1", 0, ServingOptions(window_s=0.01),
        service=SimulationService(
            ServingOptions(window_s=0.01), cache=ExecutableCache()
        ),
    )
    d.start()
    try:
        yield d
    finally:
        d.stop()


def test_daemon_run_submit_status_and_errors(daemon):
    base = _cfg().to_dict()
    # Submit-and-wait streams the manifest back as strict JSON(L).
    code, manifest = _post(daemon.url + "/v1/run?timeout=120", base)
    assert code == 200 and manifest["kind"] == "run_trace"
    assert manifest["health"]["serving"]["cohort_size"] == 1
    assert manifest["config"]["n_workers"] == base["n_workers"]
    # Async submit + poll.
    code, sub = _post(daemon.url + "/v1/submit",
                      {"config": base | {"seed": 11}})
    assert code == 202 and sub["status"] == "queued"
    code, res = _get(
        daemon.url + f"/v1/result/{sub['id']}?timeout=120"
    )
    assert code == 200 and res["kind"] == "run_trace"
    assert res["label"] == sub["id"]
    # Status carries queue + cache counters.
    code, st = _get(daemon.url + "/v1/status")
    assert code == 200 and st["status"] == "serving"
    assert st["cache"]["misses"] >= 1
    # Structured rejections: malformed JSON, unknown field, bad value,
    # unknown id/endpoint — all without killing the daemon.
    code, err = _post(daemon.url + "/v1/submit", b"{not json", raw=True)
    assert code == 400 and err["error"] == "malformed_json"
    code, err = _post(daemon.url + "/v1/submit", base | {"bogus": 1})
    assert code == 400 and "bogus" in err["detail"]
    code, err = _post(daemon.url + "/v1/submit",
                      base | {"topology": "moebius"})
    assert code == 400 and "Unknown topology" in err["detail"]
    code, err = _get(daemon.url + "/v1/result/req-999999")
    assert code == 404 and err["error"] == "unknown_request"
    code, err = _get(daemon.url + "/v1/nope")
    assert code == 404
    # ... and the daemon still serves after all of them.
    code, manifest = _post(
        daemon.url + "/v1/run?timeout=120", base | {"seed": 12}
    )
    assert code == 200 and manifest["kind"] == "run_trace"


def test_daemon_poison_run_returns_500_with_reason(daemon):
    bad = _cfg(
        attack="sign_flip", n_byzantine=1, aggregation="trimmed_mean",
        robust_b=3, partition="shuffled",
    ).to_dict()
    code, err = _post(daemon.url + "/v1/run?timeout=120", bad)
    assert code == 500 and err["error"] == "run_failed"
    assert "robust_b" in err["detail"]
    # In-flight capability intact.
    code, manifest = _post(
        daemon.url + "/v1/run?timeout=120", _cfg().to_dict()
    )
    assert code == 200 and manifest["kind"] == "run_trace"


# -------------------------------------------------- re-compile waste fixed


def test_simulator_compiles_each_program_once(capsys):
    """Satellite: repeated identical run_one calls (and repeated CLI
    invocations in one process) hit the process executable cache — the
    second run's compile phase is gone and the report says so."""
    from distributed_optimization_tpu.simulator import Simulator

    cache = process_executable_cache()
    assert cache is not None, "process cache must be on by default"
    cfg = _cfg(n_iterations=30, eval_every=10, n_samples=360, seed=31337)
    sim = Simulator(cfg)
    rec1 = sim.run_one(verbose=False)
    rec2 = sim.run_one(verbose=False)
    assert rec1.result.history.compile_seconds > 0.0
    assert rec2.result.history.compile_seconds == 0.0
    text = sim.report_numerical_results()
    capsys.readouterr()
    assert "serving: cache" in text and "compile saved" in text


def test_process_cache_env_kill_switch(monkeypatch):
    import distributed_optimization_tpu.serving.cache as cache_mod

    monkeypatch.setenv("DOPT_EXEC_CACHE", "0")
    assert cache_mod.process_executable_cache() is None
    monkeypatch.delenv("DOPT_EXEC_CACHE")
    assert cache_mod.process_executable_cache() is not None


# ------------------------------------------------------- graceful drain


def test_service_drain_finishes_accepted_work():
    """ISSUE-15 satellite: begin_drain refuses NEW submissions but every
    request accepted before the drain — queued or in flight — completes
    normally."""
    from distributed_optimization_tpu.serving.service import DrainingError

    service = _service()
    try:
        base = _cfg()
        accepted = [
            service.submit(base.replace(seed=s).to_dict()) for s in (1, 2)
        ]
        service.begin_drain()
        assert service.draining
        with pytest.raises(DrainingError):
            service.submit(base.replace(seed=3).to_dict())
        # The scheduler (here: explicit processing) still runs the
        # accepted cohort to completion.
        service.process_once()
        assert service.wait_drained(timeout=30.0)
        for rid in accepted:
            assert service.result(rid, timeout=30.0).status == "done"
    finally:
        service.close()


def test_daemon_drain_survives_inflight_cohort(daemon):
    """``/v1/shutdown?drain=1``: an in-flight cohort survives the drain
    (its results stay fetchable through the held-open shutdown), new
    submissions answer 503, and the daemon then exits."""
    base = _cfg().to_dict()
    code, sub = _post(daemon.url + "/v1/submit", {"config": base})
    assert code == 202
    box = {}

    def drain():
        box["shutdown"] = _post(
            daemon.url + "/v1/shutdown?drain=1&deadline=120", None,
            timeout=150.0,
        )

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    # New submissions are refused with the retryable 503 while draining.
    deadline = time.time() + 30.0
    refused = None
    while time.time() < deadline:
        code, err = _post(
            daemon.url + "/v1/submit", {"config": base | {"seed": 9}}
        )
        if code == 503:
            refused = err
            break
        assert code == 202, err  # drain not begun yet — request accepted
        time.sleep(0.02)
    assert refused is not None and refused["error"] == "draining"
    # The in-flight request from before the drain still completes and
    # its manifest is fetchable while the daemon holds the drain open.
    code, res = _get(daemon.url + f"/v1/result/{sub['id']}?timeout=120")
    assert code == 200 and res["kind"] == "run_trace"
    t.join(timeout=150.0)
    assert not t.is_alive()
    code, body = box["shutdown"]
    assert code == 200
    assert body["status"] == "shutting_down" and body["drained"] is True


def test_daemon_shutdown_default_unchanged(daemon):
    """Without ?drain=1 the PR-7 contract is untouched: immediate stop,
    no drained field."""
    code, body = _post(daemon.url + "/v1/shutdown", None)
    assert code == 200
    assert body == {"status": "shutting_down"}
